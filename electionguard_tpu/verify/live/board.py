"""BulletinBoardService: the public audit surface of the live verifier.

A tiny read-only gRPC service over a ``LiveVerifier``'s commitment
ledger and audit state, served through ``rpc_util.generic_service`` so
the whole remote-plane substrate (tracing interceptors, fault
injection, metrics, deadline classes) rides along for free.  Observers:

* ``getRoot`` — current Merkle root + hash-chain head (poll this; a
  root that ever contradicts an earlier inclusion proof is evidence).
* ``getInclusionProof(chunk_index)`` — log-sized membership proof for
  one committed chunk, checkable with
  ``CommitmentLedger.verify_proof`` against the served root.
* ``getAuditState`` — the verifier's running verdict, frame/chunk
  counters, and audit lag (frames published but not yet verified).

The board serves *between* the driver's ``poll()`` calls — handlers
only read ledger/result state, they never advance the verifier, so a
slow auditor can't stall verification.
"""

from __future__ import annotations

import threading

from electionguard_tpu.obs import REGISTRY
from electionguard_tpu.publish import pb
from electionguard_tpu.remote import rpc_util

_SERVICE = "BulletinBoardService"


class BulletinBoard:
    """Serve one ``LiveVerifier``'s ledger on ``port`` (0 = ephemeral).

    ``lock`` (optional) serializes handler reads against the driver's
    ``poll()`` mutations; the single-threaded CLI driver passes one so
    a getRoot never reads a ledger mid-append."""

    def __init__(self, live, port: int = 0, lock=None):
        self.live = live
        self._lock = lock or threading.Lock()
        self.server, self.port = rpc_util.make_server(port)
        self.server.add_generic_rpc_handlers((rpc_util.generic_service(
            _SERVICE,
            {"getRoot": self._get_root,
             "getInclusionProof": self._get_inclusion_proof,
             "getAuditState": self._get_audit_state,
             "getMetrics": self._get_metrics}),))
        self.server.start()

    # ---- handlers -----------------------------------------------------
    def _get_root(self, request, context):
        with self._lock:
            led = self.live.ledger
            return pb.msg("BulletinRootResponse")(
                root=led.root(), chain_head=led.head,
                n_chunks=len(led.chunks),
                n_frames=self.live.verified_frames)

    def _get_inclusion_proof(self, request, context):
        with self._lock:
            led = self.live.ledger
            idx = int(request.chunk_index)
            if not 0 <= idx < len(led.chunks):
                return pb.msg("InclusionProofResponse")(
                    error=f"no chunk {idx}: ledger has "
                          f"{len(led.chunks)} chunk(s)")
            c = led.chunks[idx]
            path, right = led.prove(idx)
            return pb.msg("InclusionProofResponse")(
                leaf=c.leaf, start_frame=c.start_frame,
                n_frames=c.n_frames, chunk_digest=c.chunk_digest,
                accepted=c.accepted, path=path, right=right,
                root=led.root())

    def _get_audit_state(self, request, context):
        with self._lock:
            s = self.live.audit_state()
        return pb.msg("AuditStateResponse")(
            status=s["status"],
            frames_published=s["frames_published"],
            frames_verified=s["frames_verified"],
            ballots_admitted=s["ballots_admitted"],
            chunks_accepted=s["chunks_accepted"],
            chunks_rejected=s["chunks_rejected"],
            audit_lag_frames=s["audit_lag_frames"],
            verdict_ok=s["verdict_ok"],
            errors=s["errors"])

    def _get_metrics(self, request, context):
        return REGISTRY.to_proto()

    def shutdown(self, grace: float = 1.0) -> None:
        self.server.stop(grace=grace)


class BulletinBoardClient:
    """Observer-side stub (CLIs, tests, the e2e epilogue)."""

    def __init__(self, url: str):
        self._channel = rpc_util.make_channel(url)
        self._stub = rpc_util.Stub(self._channel, _SERVICE)

    def root(self, timeout: float = 30.0):
        return self._stub.call("getRoot",
                               pb.msg("BulletinRootRequest")(),
                               timeout=timeout)

    def inclusion_proof(self, chunk_index: int, timeout: float = 30.0):
        resp = self._stub.call(
            "getInclusionProof",
            pb.msg("InclusionProofRequest")(chunk_index=chunk_index),
            timeout=timeout)
        if resp.error:
            raise ValueError(resp.error)
        return resp

    def audit_state(self, timeout: float = 30.0):
        return self._stub.call("getAuditState",
                               pb.msg("AuditStateRequest")(),
                               timeout=timeout)

    def metrics(self, timeout: float = 30.0):
        return self._stub.call("getMetrics", pb.msg("MetricsRequest")(),
                               timeout=timeout)

    def close(self) -> None:
        self._channel.close()

"""Live verification plane: audit the election WHILE it runs.

``LiveVerifier`` tails the framed record streams and the admission
journal, folds each landed chunk through the batch verification plane,
and checkpoints a resumable cursor + commitment ledger;
``CommitmentLedger`` is the hash-chain/Merkle structure over verified
chunks; ``BulletinBoard`` serves it mid-election over gRPC.  See
README "Live verification".
"""

from electionguard_tpu.verify.live.commitment import (ChunkCommit,
                                                      CommitmentLedger,
                                                      chunk_leaf,
                                                      frames_digest)
from electionguard_tpu.verify.live.verifier import (CHECKPOINT_NAME,
                                                    DONE, FINALIZING,
                                                    TAILING,
                                                    LiveVerifier)
from electionguard_tpu.verify.live.board import (BulletinBoard,
                                                 BulletinBoardClient)

__all__ = [
    "ChunkCommit", "CommitmentLedger", "chunk_leaf", "frames_digest",
    "LiveVerifier", "CHECKPOINT_NAME", "TAILING", "FINALIZING", "DONE",
    "BulletinBoard", "BulletinBoardClient",
]

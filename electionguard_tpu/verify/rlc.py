"""Random-linear-combination (RLC) batch verification checks.

N proof equations of the form ``com_i == base1^{x_i} base2^{y_i} ...``
collapse into ONE equation by raising each side to a fresh
verifier-sampled 128-bit randomizer ``s_i`` and multiplying everything
together: the single check

    ∏_i com_i^{s_i}  ==  g^{E_g} · K^{E_K} · ∏_i var_i^{s_i·c_i}

costs one variable-base MSM per side (``JaxGroupOps.msm``, Pippenger
bucketed) plus two fixed-base powers, instead of ~4-6 full 256-bit
ladders per proof.  The commitments ``com_i`` are the prover's
*unserialized hints* (see crypto/chaum_pedersen.py); callers MUST
hash-check each hint row against the proof's Fiat–Shamir challenge
before calling these functions — the hash check is what binds the hint
to the published (challenge, response) record and catches any post-
proving tampering deterministically.

Soundness budget (documented per the batch-verification literature,
Bellare–Garay–Rabin's small-exponents test):

* Within the order-q subgroup G_q, a batch containing at least one
  false equation passes with probability ≤ 2^-127 over the verifier's
  randomizers (128-bit odd randomizers give 2^127 equally likely
  values; the standard BGR argument bounds the escape probability by
  1/#randomizers).
* The ambient group Z_p^* has even cofactor r = (p-1)/q, so an
  adversarial *hint* could sit outside G_q (Boyd–Pavlovski).  The
  randomizers here are sampled ODD, which deterministically exposes any
  single order-2 defect; an even number of colluding order-2 defects
  still cancels with probability 1/2 per extra defect pair.  Because
  hints are unserialized and hash-bound, only the record *producer* can
  craft such defects, and the naive verifier (which recomputes
  commitments from scratch) remains the authoritative semantics: every
  RLC reject falls back to the naive path, so batch verification is a
  sound *accept screen*, never a new accept path — a record accepted
  under EGTPU_VERIFY_BATCH satisfies the RLC equation AND the per-row
  hash binding, and any record the batch path rejects is re-judged
  naively before being reported.

Exponent handling: only the certified order-q bases (g, and the
election key K, whose subgroup membership verifier check V2 pins) get
exponents reduced mod q.  Untrusted bases (ciphertext elements, hints,
guardian keys pre-V2) carry EXACT integer exponents (~384-bit s·c
products) — ``msm`` takes arbitrary-width host ints, so no reduction
argument is needed for them.
"""

from __future__ import annotations

import secrets
from typing import Sequence

from electionguard_tpu.core.group_jax import JaxGroupOps

RLC_BITS = 128


def sample_randomizers(n: int) -> list[int]:
    """n independent ODD 128-bit randomizers from the OS CSPRNG.

    Odd exponents never annihilate an order-2 component of a defective
    hint (see module docstring); 2^127 possible values bound the G_q
    escape probability at 2^-127."""
    return [2 * secrets.randbits(RLC_BITS - 1) + 1 for _ in range(n)]


def rlc_check_v4(ops: JaxGroupOps, K: int,
                 alphas: Sequence[int], betas: Sequence[int],
                 c0s: Sequence[int], v0s: Sequence[int],
                 c1s: Sequence[int], v1s: Sequence[int],
                 hints: Sequence[tuple]) -> bool:
    """One RLC check over N disjunctive (V4) proofs.

    Per row the four commitment equations are
      a0 = g^{v0} α^{c0}     b0 = K^{v0} β^{c0}
      a1 = g^{v1} α^{c1}     b1 = K^{v1} β^{c1} g^{-c1}
    Each gets its own randomizer (s0..s3), giving
      msm(hints, s) == g^{Σ s0·v0 + s2·v1 - s3·c1} · K^{Σ s1·v0 + s3·v1}
                       · msm(α‖β, [s0·c0 + s2·c1, s1·c0 + s3·c1])
    with the α/β exponents kept as exact ints."""
    g = ops.group
    p, q, n = g.p, g.q, len(alphas)
    if n == 0:
        return True
    s = sample_randomizers(4 * n)
    hint_bases: list[int] = []
    var_exps: list[int] = []
    e_g = e_k = 0
    for i in range(n):
        s0, s1, s2, s3 = s[4 * i:4 * i + 4]
        hint_bases.extend(hints[i])
        var_exps.append(s0 * c0s[i] + s2 * c1s[i])
        var_exps.append(s1 * c0s[i] + s3 * c1s[i])
        e_g += s0 * v0s[i] + s2 * v1s[i] - s3 * c1s[i]
        e_k += s1 * v0s[i] + s3 * v1s[i]
    var_bases = [x for ab in zip(alphas, betas) for x in ab]
    lhs = ops.msm_ints(hint_bases, s, exp_bits=RLC_BITS)
    rhs = (pow(g.g, e_g % q, p) * pow(K, e_k % q, p)
           * ops.msm_ints(var_bases, var_exps)) % p
    return lhs == rhs


def rlc_check_v5(ops: JaxGroupOps, K: int,
                 alphas: Sequence[int], betas: Sequence[int],
                 limits: Sequence[int], ccs: Sequence[int],
                 cvs: Sequence[int],
                 hints: Sequence[tuple]) -> bool:
    """One RLC check over N constant (V5) contest proofs:
      a = g^{v} α^{c}        b = K^{v} β^{c} g^{-L·c}
    -> msm(hints, s‖t) == g^{Σ s·v - t·L·c} · K^{Σ t·v}
                          · msm(α‖β, [s·c, t·c])."""
    g = ops.group
    p, q, n = g.p, g.q, len(alphas)
    if n == 0:
        return True
    s = sample_randomizers(2 * n)
    hint_bases: list[int] = []
    var_exps: list[int] = []
    e_g = e_k = 0
    for i in range(n):
        si, ti = s[2 * i], s[2 * i + 1]
        hint_bases.extend(hints[i])
        var_exps.append(si * ccs[i])
        var_exps.append(ti * ccs[i])
        e_g += si * cvs[i] - ti * limits[i] * ccs[i]
        e_k += ti * cvs[i]
    var_bases = [x for ab in zip(alphas, betas) for x in ab]
    lhs = ops.msm_ints(hint_bases, s, exp_bits=RLC_BITS)
    rhs = (pow(g.g, e_g % q, p) * pow(K, e_k % q, p)
           * ops.msm_ints(var_bases, var_exps)) % p
    return lhs == rhs


def rlc_check_schnorr(ops: JaxGroupOps, keys: Sequence[int],
                      cs: Sequence[int], vs: Sequence[int],
                      hints: Sequence[int]) -> bool:
    """One RLC check over N Schnorr equations h = g^{v} K^{c}:
      msm(hints, s) == g^{Σ s·v} · msm(keys, [s·c]).
    The keys are untrusted at this point (V2 has not accepted them yet)
    so their exponents stay exact."""
    g = ops.group
    p, q, n = g.p, g.q, len(keys)
    if n == 0:
        return True
    s = sample_randomizers(n)
    e_g = sum(si * vi for si, vi in zip(s, vs))
    lhs = ops.msm_ints(list(hints), s, exp_bits=RLC_BITS)
    rhs = (pow(g.g, e_g % q, p)
           * ops.msm_ints(list(keys), [si * ci for si, ci in zip(s, cs)])
           ) % p
    return lhs == rhs


def membership_rlc(ops: JaxGroupOps, elems: Sequence[int]) -> bool:
    """Batched subgroup screen: every element in canonical range and
    (∏ x_i^{r_i})^q == 1 with odd 128-bit r_i.  A single non-member
    escapes with probability ≤ 2^-127 (order-2 defects: caught
    deterministically by the odd exponents unless they arrive in
    cancelling pairs — see module docstring).  Callers fall back to the
    exact per-element ``is_valid_residue`` on failure for attribution."""
    g = ops.group
    if not elems:
        return True
    if any(not 0 < x < g.p for x in elems):
        return False
    acc = ops.msm_ints(list(elems), sample_randomizers(len(elems)),
                       exp_bits=RLC_BITS)
    return pow(acc, g.q, g.p) == 1

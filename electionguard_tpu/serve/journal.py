"""Write-ahead admission journal: the serving plane's crash-safety spine.

The durability contract of the online encryption service is *admitted ⇒
published*: once a ballot is accepted into the admission queue (the
client will eventually see a confirmation code for it), a crash of the
service process must not lose it.  The batcher queue is memory; the
growing record stream is written only when a batch drains through the
device — everything in between dies with the process.

So admission appends one fsync'd record to this journal BEFORE the
ballot enters the queue.  On restart, ``EncryptionService`` replays the
journal against the published record: every journaled ballot that never
reached the record is re-encrypted (in admission order, chained onto the
last published confirmation code), so the recovered record is exactly
the record an uncrashed service would have produced — bit-for-bit, chain
contiguous, verifier green.

Format: one JSON line per admission (``{"id", "spoil", "ballot"}``).
A SIGKILL can tear the final line; ``replay`` ignores a trailing partial
line (its admission never ack'd — the fsync had not returned, so the
client never saw the ballot accepted).  On a clean drain the service
``reset()``s the journal: a non-empty journal is itself the crash marker.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from electionguard_tpu.ballot.plaintext import PlaintextBallot
from electionguard_tpu.publish import framing

JOURNAL_NAME = "admission_journal.wal"


@dataclass(frozen=True)
class JournalEntry:
    ballot: PlaintextBallot
    spoil: bool


class AdmissionJournal:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    def append(self, ballot: PlaintextBallot, spoil: bool) -> None:
        """Durably record one admission (write + flush + fsync) — must
        return before the ballot enters the admission queue."""
        self._write({"id": ballot.ballot_id, "spoil": bool(spoil),
                     "ballot": json.loads(ballot.to_json())})

    def append_drop(self, ballot_id: str) -> None:
        """Tombstone: the admission journaled just before was REJECTED
        (queue full / draining) and the client told so — replay must not
        resurrect it.  Append-only, like everything else in a WAL."""
        self._write({"id": ballot_id, "drop": True})

    def _write(self, rec: dict) -> None:
        self._f.write(json.dumps(rec).encode() + b"\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def reset(self) -> None:
        """Truncate after a clean drain: everything journaled has been
        resolved (published or rejected in-band)."""
        self._f.truncate(0)
        self._f.seek(0)
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()


def replay(path: str) -> list[JournalEntry]:
    """Journaled admissions in admission order; a torn trailing line
    (crash mid-append, admission never ack'd) is ignored — the shared
    torn-tail policy of ``publish.framing.complete_lines``, the same
    rule the framed record streams use."""
    if not os.path.exists(path):
        return []
    entries: list[JournalEntry] = []
    with open(path, "rb") as f:
        data = f.read()
    # the torn tail (bytes past the last newline: the fsync of that
    # append never returned, so the client never saw the admission
    # ack'd) is dropped here exactly like a torn trailing frame is
    # dropped by repair_frame_stream
    lines, _torn = framing.complete_lines(data)
    for i, raw in enumerate(lines):
        try:
            rec = json.loads(raw)
            if rec.get("drop"):
                # tombstone: remove the latest pending entry for this id
                for k in range(len(entries) - 1, -1, -1):
                    if entries[k].ballot.ballot_id == rec["id"]:
                        del entries[k]
                        break
                continue
            ballot = PlaintextBallot.from_json(json.dumps(rec["ballot"]))
        except (ValueError, KeyError):
            raise IOError(f"corrupt journal line {i} in {path}")
        entries.append(JournalEntry(ballot, bool(rec["spoil"])))
    return entries

"""gRPC ``BallotEncryptionService``: the online encryption front end.

Built on the same runtime-descriptor plumbing as the trustee planes
(``remote/rpc_util.py``): no generated stubs, the .proto stays the
contract.  Request threads only parse, submit to the batcher, and block
on futures — all device work happens on the one ``EncryptionWorker``.

Backpressure is explicit: a full admission queue aborts the rpc with
RESOURCE_EXHAUSTED, a draining service with UNAVAILABLE.  Invalid
ballots (unknown contest, overvote, duplicate id, ...) travel in-band as
``error`` strings, like every other response in the rpc plane.

Graceful drain (``drain()``, wired to SIGTERM in
``cli/run_encryption_service.py``): stop admitting, flush every admitted
request through the device, close the record stream so the partial
record is publishable, then stop the server.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

import grpc

from electionguard_tpu.ballot.plaintext import PlaintextBallot
from electionguard_tpu.core.group import ElementModQ, GroupContext
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.publish import pb, serialize
from electionguard_tpu.publish.election_record import ElectionInitialized
from electionguard_tpu.publish.publisher import Publisher
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.serve.batcher import (DrainingError, DynamicBatcher,
                                             QueueFullError)
from electionguard_tpu.serve.metrics import ServiceMetrics
from electionguard_tpu.serve.worker import EncryptionWorker, InvalidBallotError

log = logging.getLogger("serve.service")

_SERVICE = "BallotEncryptionService"
#: request-thread wait on the worker: generous — the batcher bounds the
#: queue, so a healthy worker clears any admitted request in
#: queue/throughput time; this only fires if the device owner died.
_RESULT_TIMEOUT = 300.0


class EncryptionService:
    """One serving process: gRPC server + batcher + device-owner worker,
    optionally publishing the growing record to ``out_dir``."""

    def __init__(self, init: ElectionInitialized,
                 group: Optional[GroupContext] = None,
                 port: int = 0,
                 out_dir: Optional[str] = None,
                 max_batch: int = 64,
                 max_wait_ms: float = 25.0,
                 max_queue: int = 256,
                 buckets: Optional[Sequence[int]] = None,
                 seed: Optional[ElementModQ] = None,
                 timestamp: Optional[int] = None,
                 prewarm: bool = True,
                 mesh=None,
                 max_workers: int = 16,
                 hold: Optional[threading.Event] = None):
        self.init = init
        self.group = group if group is not None else \
            init.joint_public_key.group
        self.publisher = Publisher(out_dir) if out_dir else None
        self._stream = None
        if self.publisher is not None:
            # the record dir is self-contained from the first ballot on:
            # init lands before serving starts, ballots append as batches
            # drain, so a SIGTERM drain only has to close the stream
            self.publisher.write_election_initialized(init)
            self._stream = self.publisher.open_encrypted_ballots()
        self.batcher = DynamicBatcher(max_batch=max_batch,
                                      max_wait_ms=max_wait_ms,
                                      max_queue=max_queue, buckets=buckets)
        self.metrics = ServiceMetrics(queue_depth=self.batcher.depth)
        self.worker = EncryptionWorker(
            self.batcher, BatchEncryptor(init, self.group, mesh=mesh),
            self.metrics, seed=seed, timestamp=timestamp,
            stream=self._stream, hold=hold)
        if prewarm:
            # compile every (program, bucket) pair before the first
            # request: under load the compile counter stays flat
            self.worker.prewarm()
        self.worker.start()
        self.server, self.port = rpc_util.make_server(
            port, max_workers=max_workers)
        self.server.add_generic_rpc_handlers((rpc_util.generic_service(
            _SERVICE,
            {"encryptBallot": self._encrypt_ballot,
             "encryptBallotBatch": self._encrypt_ballot_batch,
             "getMetrics": self._get_metrics}),))
        self.server.start()
        self._drained = threading.Event()
        log.info("encryption service on port %d (max_batch=%d "
                 "max_wait=%.0fms max_queue=%d buckets=%s)", self.port,
                 max_batch, max_wait_ms, max_queue,
                 list(self.batcher.buckets))

    # ---- rpc impls ---------------------------------------------------
    def _submit(self, ballot_msg, spoil: bool, context):
        """Parse + admit one request; returns the future or aborts."""
        ballot = serialize.import_plaintext_ballot(ballot_msg)
        if ballot.ballot_id.startswith("__pad-"):
            # the filler namespace is the worker's, not the client's
            return None, "ballot id prefix '__pad-' is reserved"
        try:
            self.metrics.inc("requests_admitted")
            return self.batcher.submit(ballot, spoil=spoil), None
        except QueueFullError as e:
            self.metrics.inc("requests_admitted", -1)
            self.metrics.inc("requests_rejected_queue_full")
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except DrainingError as e:
            self.metrics.inc("requests_admitted", -1)
            self.metrics.inc("requests_rejected_draining")
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    def _resolve(self, future, error):
        Resp = pb.msg("EncryptBallotResponse")
        if future is None:
            return Resp(error=error)
        try:
            b = future.result(timeout=_RESULT_TIMEOUT)
        except InvalidBallotError as e:
            return Resp(error=f"invalid ballot: {e}")
        except Exception as e:  # noqa: BLE001 — in-band, like the planes
            self.metrics.inc("requests_failed")
            return Resp(error=f"encryption failed: {type(e).__name__}: {e}")
        return Resp(
            encrypted_ballot=serialize.publish_encrypted_ballot(b),
            confirmation_code=b.code)

    def _encrypt_ballot(self, request, context):
        future, err = self._submit(request.ballot, request.spoil, context)
        return self._resolve(future, err)

    def _encrypt_ballot_batch(self, request, context):
        # admit everything first (one flush can take the whole batch),
        # then gather; admission failures for a batch rpc go in-band so
        # the accepted prefix still completes exactly once
        pending = []
        for bm in request.ballots:
            ballot = serialize.import_plaintext_ballot(bm)
            if ballot.ballot_id.startswith("__pad-"):
                pending.append((None, "ballot id prefix '__pad-' is "
                                      "reserved"))
                continue
            try:
                self.metrics.inc("requests_admitted")
                pending.append((self.batcher.submit(ballot), None))
            except QueueFullError as e:
                self.metrics.inc("requests_admitted", -1)
                self.metrics.inc("requests_rejected_queue_full")
                pending.append((None, f"RESOURCE_EXHAUSTED: {e}"))
            except DrainingError as e:
                self.metrics.inc("requests_admitted", -1)
                self.metrics.inc("requests_rejected_draining")
                pending.append((None, f"UNAVAILABLE: {e}"))
        return pb.msg("EncryptBallotBatchResponse")(
            results=[self._resolve(f, err) for f, err in pending])

    def _get_metrics(self, request, context):
        return self.metrics.to_proto()

    # ---- lifecycle ---------------------------------------------------
    def drain(self, grace: float = 5.0) -> None:
        """Graceful shutdown: stop admitting, flush in-flight batches,
        publish the partial record, stop the server.  Idempotent."""
        if self._drained.is_set():
            return
        self._drained.set()
        log.info("draining: %d requests queued", self.batcher.depth())
        self.batcher.close()
        self.worker.join(timeout=_RESULT_TIMEOUT)
        if self._stream is not None:
            self._stream.close()
            self._stream = None
        # request threads blocked in _resolve still hold completed
        # futures; give them `grace` to serialize their responses
        self.server.stop(grace=grace).wait(grace)
        log.info("drained: %s", self.metrics.summary())

    def shutdown(self) -> None:
        self.drain(grace=1.0)


class EncryptionClient:
    """Client stub: ``encrypt`` one ballot, ``encrypt_batch`` many,
    ``metrics`` for the live counters/histograms.  Raises grpc.RpcError
    with RESOURCE_EXHAUSTED on backpressure (callers decide whether to
    retry) and ValueError on in-band invalid-ballot errors."""

    def __init__(self, url: str, group: GroupContext):
        self.group = group
        self._channel = rpc_util.make_channel(url)
        self._stub = rpc_util.Stub(self._channel, _SERVICE)

    def encrypt(self, ballot: PlaintextBallot, spoil: bool = False,
                timeout: float = 120.0):
        resp = self._stub.call(
            "encryptBallot",
            pb.msg("EncryptBallotRequest")(
                ballot=serialize.publish_plaintext_ballot(ballot),
                spoil=spoil),
            timeout=timeout)
        if resp.error:
            raise ValueError(resp.error)
        return serialize.import_encrypted_ballot(self.group,
                                                 resp.encrypted_ballot)

    def encrypt_batch(self, ballots: Sequence[PlaintextBallot],
                      timeout: float = 300.0):
        """Returns [(EncryptedBallot | None, error_str | None)] aligned
        with the request."""
        resp = self._stub.call(
            "encryptBallotBatch",
            pb.msg("EncryptBallotBatchRequest")(
                ballots=[serialize.publish_plaintext_ballot(b)
                         for b in ballots]),
            timeout=timeout)
        out = []
        for r in resp.results:
            if r.error:
                out.append((None, r.error))
            else:
                out.append((serialize.import_encrypted_ballot(
                    self.group, r.encrypted_ballot), None))
        return out

    def metrics(self, timeout: float = 30.0):
        return self._stub.call("getMetrics", pb.msg("MetricsRequest")(),
                               timeout=timeout)

    def close(self) -> None:
        self._channel.close()

"""gRPC ``BallotEncryptionService``: the online encryption front end.

Built on the same runtime-descriptor plumbing as the trustee planes
(``remote/rpc_util.py``): no generated stubs, the .proto stays the
contract.  Request threads only parse, submit to the batcher, and block
on futures — all device work happens on the one ``EncryptionWorker``.

Backpressure is explicit: a full admission queue aborts the rpc with
RESOURCE_EXHAUSTED, a draining service with UNAVAILABLE.  Invalid
ballots (unknown contest, overvote, duplicate id, ...) travel in-band as
``error`` strings, like every other response in the rpc plane.

Graceful drain (``drain()``, wired to SIGTERM in
``cli/run_encryption_service.py``): stop admitting, flush every admitted
request through the device, close the record stream so the partial
record is publishable, then stop the server.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional, Sequence

import grpc

from electionguard_tpu import obs
from electionguard_tpu.ballot.plaintext import PlaintextBallot
from electionguard_tpu.core.group import ElementModQ, GroupContext
from electionguard_tpu.crypto import validate
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.publish import pb, serialize
from electionguard_tpu.publish.election_record import ElectionInitialized
from electionguard_tpu.publish.publisher import (Publisher,
                                                 repair_frame_stream)
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.serve import journal as wal
from electionguard_tpu.serve.batcher import (DrainingError, DynamicBatcher,
                                             QueueFullError)
from electionguard_tpu.serve.metrics import ServiceMetrics
from electionguard_tpu.serve.tenants import (TenantQuota, TenantQuotaError,
                                             TenantRegistry)
from electionguard_tpu.serve.worker import EncryptionWorker, InvalidBallotError
from electionguard_tpu.utils import clock, errors

log = logging.getLogger("serve.service")

_SERVICE = "BallotEncryptionService"
#: request-thread wait on the worker: generous — the batcher bounds the
#: queue, so a healthy worker clears any admitted request in
#: queue/throughput time; this only fires if the device owner died.
_RESULT_TIMEOUT = 300.0


class EncryptionService:
    """One serving process: gRPC server + batcher + device-owner worker,
    optionally publishing the growing record to ``out_dir``."""

    def __init__(self, init: ElectionInitialized,
                 group: Optional[GroupContext] = None,
                 port: int = 0,
                 out_dir: Optional[str] = None,
                 max_batch: int = 64,
                 max_wait_ms: float = 25.0,
                 max_queue: int = 256,
                 buckets: Optional[Sequence[int]] = None,
                 seed: Optional[ElementModQ] = None,
                 timestamp: Optional[int] = None,
                 prewarm: bool = True,
                 mesh=None,
                 max_workers: int = 16,
                 hold: Optional[threading.Event] = None,
                 hold_after: Optional[int] = None,
                 metrics_http_port: Optional[int] = None,
                 shard_id: Optional[int] = None,
                 worker_id: Optional[str] = None,
                 chain_seed: Optional[bytes] = None,
                 skip_ballot_ids: Sequence[str] = (),
                 manifest_keypair=None,
                 tenants: Optional[TenantRegistry] = None):
        self.init = init
        self.group = group if group is not None else \
            init.joint_public_key.group
        # ingestion gate at serve admission: the joint key and every
        # guardian commitment are screened ONCE at startup — a smuggled
        # non-subgroup key never reaches the encryptor, and the per-
        # ballot admission path pays nothing (plaintext requests carry
        # no group elements)
        validate.gate_elements(
            self.group,
            [("joint public key", init.joint_public_key.value)]
            + [(f"{gr.guardian_id} commitment[{j}]", k.value)
               for gr in init.guardians
               for j, k in enumerate(gr.coefficient_commitments)],
            "serve")
        # fabric shard mode: this worker owns ONE shard of the fleet's
        # ballot-code chain, anchored at ``chain_seed`` instead of the
        # single-worker anchor; ``skip_ballot_ids`` are admissions the
        # router already requeued to surviving shards while this worker
        # was down — replaying them would double-publish.
        self.shard_id = shard_id
        self.worker_id = worker_id or (f"worker-{shard_id}"
                                       if shard_id is not None else None)
        self._chain_seed = chain_seed
        self._manifest_keypair = manifest_keypair
        self._skip_ballot_ids = set(skip_ballot_ids)
        self._published_base = 0
        self._status = "STARTING"
        self.publisher = Publisher(out_dir) if out_dir else None
        self._stream = None
        self.journal: Optional[wal.AdmissionJournal] = None
        self._adm_lock = threading.Lock()
        self.recovered_ballots = 0
        gap: list[wal.JournalEntry] = []
        code_seed: Optional[bytes] = None
        if self.publisher is not None:
            # the record dir is self-contained from the first ballot on:
            # init lands before serving starts, ballots append as batches
            # drain, so a SIGTERM drain only has to close the stream.
            # A restart first repairs a possibly-torn ballot stream and
            # diffs the admission journal against it: the difference is
            # exactly the admitted-but-unpublished gap a crash lost.
            self.publisher.write_election_initialized(init)
            jpath = os.path.join(out_dir, wal.JOURNAL_NAME)
            gap, code_seed = self._plan_recovery(jpath)
            self.journal = wal.AdmissionJournal(jpath)
            skipped = [e for e in gap
                       if e.ballot.ballot_id in self._skip_ballot_ids]
            if skipped:
                # the router moved these admissions to surviving shards
                # while we were dead; tombstone them so neither this
                # replay nor any future one resurrects a double-publish
                gap = [e for e in gap
                       if e.ballot.ballot_id not in self._skip_ballot_ids]
                for e in skipped:
                    self.journal.append_drop(e.ballot.ballot_id)
                log.warning("dropping %d journaled admissions requeued "
                            "to other shards", len(skipped))
            self._stream = self.publisher.open_encrypted_ballots(
                append=True)
        self.batcher = DynamicBatcher(max_batch=max_batch,
                                      max_wait_ms=max_wait_ms,
                                      max_queue=max_queue, buckets=buckets)
        self.metrics = ServiceMetrics(queue_depth=self.batcher.depth)
        # multi-tenant mode: tenant lanes ride the SAME batcher, worker,
        # and compiled bucket programs (the election key is a traced
        # argument — encrypt/fused.py); what each lane adds is its own
        # encryptor/seed/stream/code chain.  Per-tenant admission is
        # bounded by EGTPU_TENANT_QUOTA in-flight requests so one
        # flooding election sheds ITS OWN load, not the fleet's.
        self.tenants = tenants
        self._tenant_quota = TenantQuota()
        self.worker = EncryptionWorker(
            self.batcher, BatchEncryptor(init, self.group, mesh=mesh),
            self.metrics, seed=seed, timestamp=timestamp,
            stream=self._stream, hold=hold,
            code_seed=(code_seed if code_seed is not None
                       else self._chain_seed),
            hold_after=hold_after,
            lanes=tenants.lanes() if tenants is not None else None)
        if prewarm:
            # compile every (program, bucket) pair before the first
            # request: under load the compile counter stays flat
            self.worker.prewarm()
        clock.start_thread(self.worker)
        if gap:
            self._status = "RECOVERING"
            self._replay_gap(gap)
        self.server, self.port = rpc_util.make_server(
            port, max_workers=max_workers)
        self.server.add_generic_rpc_handlers((rpc_util.generic_service(
            _SERVICE,
            {"encryptBallot": self._encrypt_ballot,
             "encryptBallotBatch": self._encrypt_ballot_batch,
             "getMetrics": self._get_metrics,
             "health": self._health}),))
        self.server.start()
        self.metrics_http_port: Optional[int] = None
        self._metrics_httpd = None
        if metrics_http_port is not None:
            # Prometheus text endpoint (0 = ephemeral); the scrape serves
            # this service's registry merged with the process default
            # (rpc server counters, compile counters, ...)
            from electionguard_tpu.obs import httpd
            self._metrics_httpd, self.metrics_http_port = \
                httpd.start(metrics_http_port)
        self._drained = threading.Event()
        self._status = "SERVING"
        self._set_serving_phase()
        log.info("encryption service on port %d (max_batch=%d "
                 "max_wait=%.0fms max_queue=%d buckets=%s recovered=%d)",
                 self.port, max_batch, max_wait_ms, max_queue,
                 list(self.batcher.buckets), self.recovered_ballots)

    # ---- crash recovery ----------------------------------------------
    def _plan_recovery(self, jpath: str
                       ) -> tuple[list[wal.JournalEntry], Optional[bytes]]:
        """Repair the published stream's tail, then compute the replay
        gap (journaled admissions never published) and the code-chain
        head (last published ballot's confirmation code)."""
        entries = wal.replay(jpath)
        ballots_path = os.path.join(self.publisher.dir,
                                    "encrypted_ballots.pb")
        n_pub, last_frame = repair_frame_stream(ballots_path)
        self._published_base = n_pub
        code_seed = None
        published: set[str] = set()
        if n_pub:
            from electionguard_tpu.publish.publisher import _read_frames
            for frame in _read_frames(ballots_path):
                m = pb.EncryptedBallot()
                m.ParseFromString(frame)
                published.add(m.ballot_id)
            m = pb.EncryptedBallot()
            m.ParseFromString(last_frame)
            code_seed = serialize.import_u256(m.code)
        gap = [e for e in entries if e.ballot.ballot_id not in published]
        if entries and not gap:
            log.info("journal fully published (%d entries); nothing to "
                     "recover", len(entries))
        return gap, code_seed

    def _replay_gap(self, gap: list[wal.JournalEntry]) -> None:
        """Re-encrypt the crash gap through the normal worker path, in
        admission order, BEFORE the server accepts new requests — the
        recovered stream continues the code chain exactly where the
        published record stops."""
        log.warning("recovering %d admitted-but-unpublished ballots "
                    "from the journal", len(gap))
        futures = []
        for e in gap:
            while True:   # a gap larger than the queue drains in waves
                try:
                    futures.append((e.ballot.ballot_id,
                                    self.batcher.submit(e.ballot,
                                                        spoil=e.spoil)))
                    break
                except QueueFullError:
                    clock.sleep(0.05)
        for bid, fut in futures:
            try:
                clock.wait_future(fut, _RESULT_TIMEOUT)
                self.recovered_ballots += 1
                self.metrics.inc("ballots_recovered")
            except InvalidBallotError as e:
                # it was invalid the first time too: the original run
                # would have answered in-band; resolution is identical
                log.warning("recovered ballot %s invalid: %s", bid, e)

    # ---- shard bookkeeping -------------------------------------------
    def published_count(self) -> int:
        """Ballots durably in this worker's stream (pre-crash + since)."""
        return self._published_base + \
            (self._stream.n if self._stream is not None else 0)

    def chain_head(self) -> Optional[bytes]:
        """Current head of this worker's code chain (None = single-worker
        mode with no publisher and nothing encrypted yet)."""
        head = self.worker.code_seed
        return head if head is not None else self._chain_seed

    def _set_serving_phase(self) -> None:
        """The obs heartbeat's free-form phase carries the shard facts
        egtop renders per-shard rows from — no proto change needed."""
        if self.shard_id is None:
            obs.set_phase("serving")
            return
        head = self.chain_head()
        obs.set_phase(f"serving shard={self.shard_id} "
                      f"head={head.hex()[:16] if head else '-'} "
                      f"admitted={self.published_count()}")

    # ---- rpc impls ---------------------------------------------------
    def _admit(self, ballot: PlaintextBallot, spoil: bool):
        """Journal-then-enqueue, atomically w.r.t. other admissions: the
        WAL line is durable BEFORE the ballot enters the queue, so a
        crash can lose the queue but never an admitted ballot.  A
        rejected enqueue appends a tombstone so replay won't resurrect a
        ballot whose client saw the rejection."""
        with self._adm_lock:
            if self.journal is not None:
                self.journal.append(ballot, spoil)
            try:
                return self.batcher.submit(ballot, spoil=spoil)
            except (QueueFullError, DrainingError):
                if self.journal is not None:
                    self.journal.append_drop(ballot.ballot_id)
                raise

    def _submit(self, ballot_msg, spoil: bool, context):
        """Parse + admit one request; returns the future or aborts."""
        ballot = serialize.import_plaintext_ballot(ballot_msg)
        if ballot.ballot_id.startswith("__pad-"):
            # the filler namespace is the worker's, not the client's
            msg = "ballot id prefix '__pad-' is reserved"
            errors.reject("serve.reserved_id", msg)
            return None, errors.named("serve.reserved_id", msg)
        try:
            # per-tenant quota BEFORE the fleet-wide queue: a flooding
            # election hits ITS cap (RESOURCE_EXHAUSTED naming it) while
            # other tenants' admissions keep flowing
            release = self._tenant_quota.acquire()
        except TenantQuotaError as e:
            self.metrics.inc("requests_rejected_queue_full")
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        try:
            self.metrics.inc("requests_admitted")
            fut = self._admit(ballot, spoil)
            if release is not None:
                fut.add_done_callback(release)
            return fut, None
        except QueueFullError as e:
            if release is not None:
                release()
            self.metrics.inc("requests_admitted", -1)
            self.metrics.inc("requests_rejected_queue_full")
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except DrainingError as e:
            if release is not None:
                release()
            self.metrics.inc("requests_admitted", -1)
            self.metrics.inc("requests_rejected_draining")
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    def _resolve(self, future, error):
        Resp = pb.msg("EncryptBallotResponse")
        sid = self.shard_id if self.shard_id is not None else -1
        if future is None:
            return Resp(error=error, shard_id=sid)
        try:
            b = clock.wait_future(future, _RESULT_TIMEOUT)
        except InvalidBallotError as e:
            # stable named class for the soundness oracle: duplicates
            # (in-batch or cross-batch replays) are their own class,
            # everything else is a malformed submission
            cls = ("serve.duplicate_ballot" if "duplicate" in str(e)
                   else "serve.invalid_ballot")
            errors.reject(cls, str(e))
            return Resp(error=errors.named(cls, f"invalid ballot: {e}"),
                        shard_id=sid)
        except Exception as e:  # noqa: BLE001 — in-band, like the planes
            self.metrics.inc("requests_failed")
            return Resp(error=f"encryption failed: {type(e).__name__}: {e}",
                        shard_id=sid)
        if self.shard_id is not None:
            self._set_serving_phase()
        return Resp(
            encrypted_ballot=serialize.publish_encrypted_ballot(b),
            confirmation_code=b.code, shard_id=sid)

    def _encrypt_ballot(self, request, context):
        future, err = self._submit(request.ballot, request.spoil, context)
        return self._resolve(future, err)

    def _encrypt_ballot_batch(self, request, context):
        # admit everything first (one flush can take the whole batch),
        # then gather; admission failures for a batch rpc go in-band so
        # the accepted prefix still completes exactly once
        pending = []
        for bm in request.ballots:
            ballot = serialize.import_plaintext_ballot(bm)
            if ballot.ballot_id.startswith("__pad-"):
                pending.append((None, "ballot id prefix '__pad-' is "
                                      "reserved"))
                continue
            release = None
            try:
                release = self._tenant_quota.acquire()
                self.metrics.inc("requests_admitted")
                fut = self._admit(ballot, False)
                if release is not None:
                    fut.add_done_callback(release)
                pending.append((fut, None))
            except TenantQuotaError as e:
                self.metrics.inc("requests_rejected_queue_full")
                pending.append((None, f"RESOURCE_EXHAUSTED: {e}"))
            except QueueFullError as e:
                if release is not None:
                    release()
                self.metrics.inc("requests_admitted", -1)
                self.metrics.inc("requests_rejected_queue_full")
                pending.append((None, f"RESOURCE_EXHAUSTED: {e}"))
            except DrainingError as e:
                if release is not None:
                    release()
                self.metrics.inc("requests_admitted", -1)
                self.metrics.inc("requests_rejected_draining")
                pending.append((None, f"UNAVAILABLE: {e}"))
        return pb.msg("EncryptBallotBatchResponse")(
            results=[self._resolve(f, err) for f, err in pending])

    def _get_metrics(self, request, context):
        return self.metrics.to_proto()

    def _health(self, request, context):
        depth = self.batcher.depth()
        return pb.msg("HealthResponse")(
            status=self._status,
            ready=(self._status == "SERVING"
                   and depth < self.batcher.max_queue),
            queue_depth=depth,
            recovered_ballots=self.recovered_ballots,
            shard_id=self.shard_id if self.shard_id is not None else -1)

    # ---- lifecycle ---------------------------------------------------
    def drain(self, grace: float = 5.0) -> None:
        """Graceful shutdown: stop admitting, flush in-flight batches,
        publish the partial record, stop the server.  Idempotent."""
        if self._drained.is_set():
            return
        self._drained.set()
        self._status = "DRAINING"
        obs.set_phase("draining")
        log.info("draining: %d requests queued", self.batcher.depth())
        self.batcher.close()
        clock.join_thread(self.worker, _RESULT_TIMEOUT)
        if self._stream is not None:
            n_published = self.published_count()
            self._stream.close()
            self._stream = None
            if self.shard_id is not None:
                self._write_shard_manifest(n_published)
        if self.tenants is not None:
            # tenant lanes own their streams; the worker has exited, so
            # each per-election record is complete and publishable
            self.tenants.close()
        with self._adm_lock:
            # the admission lock keeps a straggler _admit from appending
            # to a journal we are about to close
            if self.journal is not None and not self.worker.is_alive():
                # everything admitted is now resolved (published or
                # answered in-band); an empty journal marks the
                # shutdown as clean
                self.journal.reset()
                self.journal.close()
                self.journal = None
        # request threads blocked in _resolve still hold completed
        # futures; give them `grace` to serialize their responses
        clock.wait_event(self.server.stop(grace=grace), grace)
        if self._metrics_httpd is not None:
            self._metrics_httpd.shutdown()
            self._metrics_httpd = None
        log.info("drained: %s", self.metrics.summary())

    def _write_shard_manifest(self, n_published: int) -> None:
        """The shard's signed claim, written at drain next to its ballot
        stream; ``fabric/merge.py`` publishes all of them and the
        verifier's V.shard_manifest family holds them to account."""
        from electionguard_tpu.fabric import manifest as fab_manifest

        head = self.chain_head()
        if head is None or self._chain_seed is None:
            log.warning("shard %s drained without a chain seed; no "
                        "manifest written", self.shard_id)
            return
        m = fab_manifest.ShardManifest(
            shard_id=self.shard_id, worker_id=self.worker_id,
            chain_seed=self._chain_seed, head_hash=head,
            admitted_count=n_published,
            public_key=(self._manifest_keypair.public.value
                        if self._manifest_keypair is not None else 0))
        if self._manifest_keypair is not None:
            m = fab_manifest.sign_manifest(self.group,
                                           self._manifest_keypair, m)
        fab_manifest.write_shard_manifest(self.publisher.dir, m)
        log.info("shard %d manifest: %d ballots, head %s",
                 self.shard_id, n_published, head.hex()[:16])

    def shutdown(self) -> None:
        self.drain(grace=1.0)


class EncryptionClient:
    """Client stub: ``encrypt`` one ballot, ``encrypt_batch`` many,
    ``metrics`` for the live counters/histograms.  Raises grpc.RpcError
    with RESOURCE_EXHAUSTED on backpressure (callers decide whether to
    retry) and ValueError on in-band invalid-ballot errors."""

    def __init__(self, url: str, group: GroupContext):
        self.group = group
        self._channel = rpc_util.make_channel(url)
        self._stub = rpc_util.Stub(self._channel, _SERVICE)
        #: shard that answered the last encrypt/encrypt_batch (-1 = the
        #: single-worker plane); loadgen joins latencies to shards on it
        self.last_shard_id = -1

    def encrypt(self, ballot: PlaintextBallot, spoil: bool = False,
                timeout: float = 120.0):
        resp = self._stub.call(
            "encryptBallot",
            pb.msg("EncryptBallotRequest")(
                ballot=serialize.publish_plaintext_ballot(ballot),
                spoil=spoil),
            timeout=timeout)
        self.last_shard_id = resp.shard_id
        if resp.error:
            raise ValueError(resp.error)
        self._gate_ballot(resp.encrypted_ballot)
        return serialize.import_encrypted_ballot(self.group,
                                                 resp.encrypted_ballot)

    def encrypt_batch(self, ballots: Sequence[PlaintextBallot],
                      timeout: float = 300.0):
        """Returns [(EncryptedBallot | None, error_str | None)] aligned
        with the request."""
        resp = self._stub.call(
            "encryptBallotBatch",
            pb.msg("EncryptBallotBatchRequest")(
                ballots=[serialize.publish_plaintext_ballot(b)
                         for b in ballots]),
            timeout=timeout)
        out = []
        for r in resp.results:
            self.last_shard_id = r.shard_id
            if r.error:
                out.append((None, r.error))
            else:
                self._gate_ballot(r.encrypted_ballot)
                out.append((serialize.import_encrypted_ballot(
                    self.group, r.encrypted_ballot), None))
        return out

    def _gate_ballot(self, bm) -> None:
        """Ingestion gate on a returned encrypted ballot: every
        ciphertext element is screened (range + RLC subgroup) before
        the ballot object is built.  Raises crypto.validate.GateError
        with its named class on a defective element."""
        validate.gate_wire_p(
            self.group,
            [(f"{bm.ballot_id} {c.contest_id}/{s.selection_id}.{fld}",
              bytes(getattr(s.ciphertext, fld).value))
             for c in bm.contests for s in c.selections
             for fld in ("pad", "data")],
            "serve")

    def metrics(self, timeout: float = 30.0):
        return self._stub.call("getMetrics", pb.msg("MetricsRequest")(),
                               timeout=timeout)

    def health(self, timeout: float = 30.0):
        return self._stub.call("health", pb.msg("HealthRequest")(),
                               timeout=timeout)

    def close(self) -> None:
        self._channel.close()

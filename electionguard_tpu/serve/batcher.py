"""Dynamic micro-batcher: the admission queue between request threads and
the single device-owner worker.

Inference-server semantics rather than offline-loop semantics:

* **Bounded admission with explicit backpressure.**  ``submit`` either
  enqueues and returns a future, or raises ``QueueFullError`` — the
  service maps that to gRPC RESOURCE_EXHAUSTED so clients see load
  instead of unbounded latency.
* **Flush on size OR age.**  A batch leaves the queue the moment it
  reaches ``max_batch`` pending requests, or when the OLDEST pending
  request has waited ``max_wait_ms`` — the classic dynamic-batching
  latency/occupancy trade.
* **Bucketed shapes.**  ``bucket_for`` rounds a flush up to the next
  power-of-two bucket ≤ ``max_batch``; the worker pads with filler
  ballots to exactly that size, so the device program compiles once per
  bucket and never again under load.  Power-of-two buckets bound padding
  waste: a bucket is always < 2× the real batch, so per-batch occupancy
  is structurally > 50%.
* **Graceful drain.**  ``close`` stops admission (``submit`` raises
  ``DrainingError``); everything already admitted is still handed out —
  promptly, ignoring ``max_wait_ms`` — and ``next_batch`` returns None
  only once the queue is empty, so every admitted request is delivered
  exactly once.
"""

from __future__ import annotations

import threading
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Optional, Sequence

from electionguard_tpu.ballot.plaintext import PlaintextBallot
from electionguard_tpu.obs import tenant as _tenant
from electionguard_tpu.utils import clock


class QueueFullError(Exception):
    """Admission queue at capacity — shed load (RESOURCE_EXHAUSTED)."""


class DrainingError(Exception):
    """The batcher is draining/closed — no new admissions."""


@dataclass
class PendingRequest:
    """One admitted request: the ballot, its completion future, the
    admission time (t_enqueue) the latency histogram measures from, and
    the election the request belongs to — captured HERE, on the request
    thread, because the worker thread that later processes the batch
    has no ambient tenant context of its own."""

    ballot: PlaintextBallot
    spoil: bool = False
    future: Future = field(default_factory=Future)
    t_enqueue: float = field(default_factory=clock.monotonic)
    tenant: str = field(default_factory=_tenant.current_election)


def _default_buckets(max_batch: int) -> tuple[int, ...]:
    """Powers of two up to (and including) max_batch — the "small fixed
    set of batch shapes"."""
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b <<= 1
    buckets.append(max_batch)
    return tuple(buckets)


class DynamicBatcher:
    def __init__(self, max_batch: int = 64, max_wait_ms: float = 25.0,
                 max_queue: int = 256,
                 buckets: Optional[Sequence[int]] = None):
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1000.0
        self.max_queue = max_queue
        self.buckets = tuple(sorted(set(buckets))) if buckets else \
            _default_buckets(max_batch)
        if self.buckets[-1] < max_batch:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} < max_batch {max_batch}")
        self._q: deque[PendingRequest] = deque()
        self._cv = threading.Condition()
        self._closed = False

    # ---- request side ------------------------------------------------
    def submit(self, ballot: PlaintextBallot,
               spoil: bool = False) -> Future:
        """Admit one ballot; returns the future its EncryptedBallot will
        land on.  Raises QueueFullError (backpressure) or DrainingError
        (shutdown) instead of blocking the request thread."""
        req = PendingRequest(ballot, spoil)
        with self._cv:
            if self._closed:
                raise DrainingError("service is draining")
            if len(self._q) >= self.max_queue:
                raise QueueFullError(
                    f"admission queue full ({self.max_queue})")
            self._q.append(req)
            self._cv.notify_all()
        return req.future

    def depth(self) -> int:
        with self._cv:
            return len(self._q)

    # ---- worker side -------------------------------------------------
    def next_batch(self,
                   timeout: Optional[float] = None
                   ) -> Optional[list[PendingRequest]]:
        """Block until a batch is due, then pop it (≤ max_batch, FIFO).

        A batch is due when ``max_batch`` requests are pending, when the
        oldest pending request is ``max_wait_ms`` old, or immediately
        once ``close`` was called.  Returns None when closed AND empty
        (the worker's exit signal); an idle ``timeout`` (seconds) returns
        [] so callers can interleave housekeeping.
        """
        deadline = None if timeout is None else clock.monotonic() + timeout
        with self._cv:
            while True:
                if self._q:
                    if (len(self._q) >= self.max_batch or self._closed):
                        break
                    due = self._q[0].t_enqueue + self.max_wait
                    wait = due - clock.monotonic()
                    if wait <= 0:
                        break
                else:
                    if self._closed:
                        return None
                    if deadline is not None and clock.monotonic() >= deadline:
                        return []
                    wait = None if deadline is None else \
                        deadline - clock.monotonic()
                clock.cv_wait(self._cv, wait)
            n = min(self.max_batch, len(self._q))
            batch = [self._q.popleft() for _ in range(n)]
            self._cv.notify_all()
            return batch

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket ≥ n (n ≤ max_batch always holds
        for batches this batcher produced)."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"batch of {n} exceeds largest bucket "
                         f"{self.buckets[-1]}")

    # ---- lifecycle ---------------------------------------------------
    def close(self) -> None:
        """Stop admitting; wake the worker so it drains what remains."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed

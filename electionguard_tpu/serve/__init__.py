"""Online ballot-encryption serving layer.

The inference-server-shaped front end for the fused TPU encryptor: a gRPC
``BallotEncryptionService`` (service.py) admits plaintext ballots into a
bounded queue with explicit backpressure, a dynamic micro-batcher
(batcher.py) aggregates them into a small fixed set of bucket shapes, and
one device-owner worker thread (worker.py) drains batches through the
existing ``encrypt.encryptor.BatchEncryptor`` / ``encrypt.fused``
pipeline, keeping host↔device transfer off the request threads.
Counters and histograms (metrics.py) travel over a ``getMetrics`` rpc.

Every prior entry point was offline (ballots staged in a record dir
before the encryptor runs); this subsystem is the host-side glue that the
ROADMAP's "heavy traffic from millions of users" requires — aggregation
into large fixed-shape batches is what makes the accelerator pay off for
online traffic (PAPERS.md: SZKP, if-ZKP make the same point for
accelerator ZKP provers).
"""

from electionguard_tpu.serve.batcher import (DrainingError, DynamicBatcher,
                                             QueueFullError)
from electionguard_tpu.serve.metrics import ServiceMetrics
from electionguard_tpu.serve.service import EncryptionClient, EncryptionService
from electionguard_tpu.serve.worker import EncryptionWorker

__all__ = [
    "DrainingError", "DynamicBatcher", "EncryptionClient",
    "EncryptionService", "EncryptionWorker", "QueueFullError",
    "ServiceMetrics",
]

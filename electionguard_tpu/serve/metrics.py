"""Serving metrics — a thin client of the obs metrics registry.

Every ``EncryptionService`` owns one ``MetricsRegistry`` (so per-service
counts never bleed between instances in tests or multi-service
processes) and registers it for process-wide exposition: the Prometheus
endpoint (``obs.httpd``) and the default ``metrics`` rpc serve the merged
view automatically.  Exposed three ways: the ``getMetrics`` rpc
(``to_proto``), the Prometheus text endpoint, and the one-line drain log
(``summary``).

``device_compiles`` counts actual backend compilations process-wide via
the ``jax.monitoring`` listener in ``obs.jaxmon`` — the live twin of the
``compile_cache_entries`` accounting bench.py does against the
persistent cache dir.  A serving process that buckets its batch shapes
correctly shows this counter flat after warmup: one compile per
(program, bucket shape) and never again under load.
"""

from __future__ import annotations

from typing import Callable, Optional

from electionguard_tpu.obs import jaxmon
from electionguard_tpu.obs.registry import (Histogram,  # noqa: F401
                                            MetricsRegistry,
                                            election_labels, expose)

# default latency edges (ms): log-ish spacing from sub-ms to minutes
_LATENCY_MS_BOUNDS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 15000.0, 60000.0)
_OCCUPANCY_BOUNDS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)
_DEPTH_BOUNDS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0)


def install_compile_listener() -> None:
    """Back-compat alias: the listener now lives in obs.jaxmon."""
    jaxmon.install()


def device_compile_count() -> int:
    return jaxmon.compile_count()


class ServiceMetrics:
    """All counters/gauges/histograms of one EncryptionService."""

    COUNTERS = ("requests_admitted", "requests_rejected_queue_full",
                "requests_rejected_draining", "requests_failed",
                "ballots_encrypted", "ballots_invalid", "ballots_spoiled",
                "ballots_recovered", "batches_flushed", "padded_slots")

    def __init__(self, queue_depth: Optional[Callable[[], int]] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = expose(registry if registry is not None
                               else MetricsRegistry("serve"))
        # every ballot-flow counter carries the election tenant label
        # (EGTPU_ELECTION; "default" when a deployment serves one
        # election) so a shared fleet's scrape stays per-tenant
        labels = election_labels()
        self._counters = {name: self.registry.counter(name, labels)
                          for name in self.COUNTERS}
        self._queue_depth = queue_depth
        self.latency_ms = self.registry.histogram("request_latency_ms",
                                                  _LATENCY_MS_BOUNDS)
        self.batch_occupancy = self.registry.histogram("batch_occupancy",
                                                       _OCCUPANCY_BOUNDS)
        self.queue_depth_at_flush = self.registry.histogram(
            "queue_depth_at_flush", _DEPTH_BOUNDS)
        install_compile_listener()
        self._compiles_at_start = device_compile_count()
        if queue_depth is not None:
            self.registry.gauge("queue_depth", fn=queue_depth)
        self.registry.gauge("device_compiles", fn=device_compile_count)
        self.registry.gauge(
            "device_compiles_since_start",
            fn=lambda: device_compile_count() - self._compiles_at_start)

    # ---- writers -----------------------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        self._counters[name].inc(by)

    def get(self, name: str) -> int:
        return self._counters[name].value

    def observe_flush(self, n_real: int, bucket: int,
                      queue_depth: int) -> None:
        self.inc("batches_flushed")
        self.inc("padded_slots", bucket - n_real)
        self.batch_occupancy.observe(n_real / bucket)
        self.queue_depth_at_flush.observe(float(queue_depth))

    # ---- readers -----------------------------------------------------
    def counters(self) -> dict:
        """Counters + point-in-time gauges, as one flat map."""
        out = {name: c.value for name, c in self._counters.items()}
        out["queue_depth"] = (self._queue_depth()
                              if self._queue_depth else 0)
        out["device_compiles"] = device_compile_count()
        out["device_compiles_since_start"] = \
            device_compile_count() - self._compiles_at_start
        return out

    def to_proto(self):
        from electionguard_tpu.publish import pb
        resp = pb.msg("MetricsResponse")(counters=self.counters())
        for h in (self.latency_ms, self.batch_occupancy,
                  self.queue_depth_at_flush):
            s = h.snapshot()
            resp.histograms.add(name=s["name"], bounds=s["bounds"],
                                counts=s["counts"], sum=s["sum"],
                                count=s["count"])
        return resp

    def summary(self) -> str:
        c = self.counters()
        return (f"admitted={c['requests_admitted']} "
                f"encrypted={c['ballots_encrypted']} "
                f"invalid={c['ballots_invalid']} "
                f"failed={c['requests_failed']} "
                f"rejected={c['requests_rejected_queue_full']} "
                f"recovered={c['ballots_recovered']} "
                f"batches={c['batches_flushed']} "
                f"occupancy_mean={self.batch_occupancy.mean():.2f} "
                f"latency_p50={self.latency_ms.quantile(0.5):.0f}ms "
                f"p99={self.latency_ms.quantile(0.99):.0f}ms "
                f"queue_depth={c['queue_depth']} "
                f"compiles={c['device_compiles_since_start']}")

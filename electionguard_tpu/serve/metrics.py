"""Serving metrics — a thin client of the obs metrics registry.

Every ``EncryptionService`` owns one ``MetricsRegistry`` (so per-service
counts never bleed between instances in tests or multi-service
processes) and registers it for process-wide exposition: the Prometheus
endpoint (``obs.httpd``) and the default ``metrics`` rpc serve the merged
view automatically.  Exposed three ways: the ``getMetrics`` rpc
(``to_proto``), the Prometheus text endpoint, and the one-line drain log
(``summary``).

``device_compiles`` counts actual backend compilations process-wide via
the ``jax.monitoring`` listener in ``obs.jaxmon`` — the live twin of the
``compile_cache_entries`` accounting bench.py does against the
persistent cache dir.  A serving process that buckets its batch shapes
correctly shows this counter flat after warmup: one compile per
(program, bucket shape) and never again under load.
"""

from __future__ import annotations

from typing import Callable, Optional

from electionguard_tpu.obs import jaxmon
from electionguard_tpu.obs import tenant as _tenant
from electionguard_tpu.obs.registry import (Histogram,  # noqa: F401
                                            MetricsRegistry,
                                            election_labels, expose)

_current_election = _tenant.current_election

# default latency edges (ms): log-ish spacing from sub-ms to minutes
_LATENCY_MS_BOUNDS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 15000.0, 60000.0)
_OCCUPANCY_BOUNDS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)
_DEPTH_BOUNDS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0)


def install_compile_listener() -> None:
    """Back-compat alias: the listener now lives in obs.jaxmon."""
    jaxmon.install()


def device_compile_count() -> int:
    return jaxmon.compile_count()


class ServiceMetrics:
    """All counters/gauges/histograms of one EncryptionService."""

    COUNTERS = ("requests_admitted", "requests_rejected_queue_full",
                "requests_rejected_draining", "requests_failed",
                "ballots_encrypted", "ballots_invalid", "ballots_spoiled",
                "ballots_recovered", "batches_flushed", "padded_slots")

    #: histogram families and their bucket edges — every instance is
    #: election-labeled (one histogram per family per tenant)
    HISTOGRAMS = {"request_latency_ms": _LATENCY_MS_BOUNDS,
                  "batch_occupancy": _OCCUPANCY_BOUNDS,
                  "queue_depth_at_flush": _DEPTH_BOUNDS}

    def __init__(self, queue_depth: Optional[Callable[[], int]] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = expose(registry if registry is not None
                               else MetricsRegistry("serve"))
        # every ballot-flow series carries the election tenant label,
        # resolved at WRITE time from the ambient tenant context (the
        # EGTPU_ELECTION knob when no request scope is active) — one
        # service instance serving N elections keeps N disjoint series
        # sets.  The small (name, election) cache keeps the hot path at
        # one dict probe instead of a registry lock per increment.
        el = _current_election()
        self._counters = {(name, el): self.registry.counter(
                              name, election_labels({"election": el}))
                          for name in self.COUNTERS}
        self._hists = {(name, el): self.registry.histogram(
                           name, bounds,
                           election_labels({"election": el}))
                       for name, bounds in self.HISTOGRAMS.items()}
        self._device_ms: dict = {}
        self._queue_depth = queue_depth
        self.latency_ms = self.histogram_for("request_latency_ms")
        self.batch_occupancy = self.histogram_for("batch_occupancy")
        self.queue_depth_at_flush = self.histogram_for(
            "queue_depth_at_flush")
        install_compile_listener()
        self._compiles_at_start = device_compile_count()
        if queue_depth is not None:
            self.registry.gauge("queue_depth", fn=queue_depth)
        self.registry.gauge("device_compiles", fn=device_compile_count)
        self.registry.gauge(
            "device_compiles_since_start",
            fn=lambda: device_compile_count() - self._compiles_at_start)

    # ---- writers -----------------------------------------------------
    def inc(self, name: str, by: int = 1,
            election: Optional[str] = None) -> None:
        if election is None:
            election = _current_election()
        c = self._counters.get((name, election))
        if c is None:
            if name not in self.COUNTERS:
                raise KeyError(name)
            c = self._counters[(name, election)] = self.registry.counter(
                name, election_labels({"election": election}))
        c.inc(by)

    def get(self, name: str) -> int:
        """Counter total summed across every tenant's series."""
        return sum(c.value for (n, _), c in self._counters.items()
                   if n == name)

    def inc_device_ms(self, ms: float,
                      election: Optional[str] = None) -> None:
        """Per-tenant device-time attribution: cumulative milliseconds
        the device owner spent on this election's batches — the
        ``tenant_device_ms_total{election=...}`` series the
        noisy-neighbor detector (obs/slo) reads."""
        if election is None:
            election = _current_election()
        c = self._device_ms.get(election)
        if c is None:
            c = self._device_ms[election] = self.registry.counter(
                "tenant_device_ms_total",
                election_labels({"election": election}))
        c.inc(ms)

    def histogram_for(self, name: str,
                      election: Optional[str] = None) -> Histogram:
        """The ``name`` histogram of one tenant (ambient by default)."""
        if election is None:
            election = _current_election()
        h = self._hists.get((name, election))
        if h is None:
            h = self._hists[(name, election)] = self.registry.histogram(
                name, self.HISTOGRAMS[name],
                election_labels({"election": election}))
        return h

    def latency_quantile(self, q: float) -> float:
        """Cross-tenant q-quantile of request latency (upper-bound
        estimate over the merged per-tenant buckets)."""
        hists = [h.snapshot() for (n, _), h in self._hists.items()
                 if n == "request_latency_ms"]
        total = sum(h["count"] for h in hists)
        if total == 0:
            return 0.0
        bounds = hists[0]["bounds"]
        counts = [0] * (len(bounds) + 1)
        for h in hists:
            for i, c in enumerate(h["counts"]):
                counts[i] += c
        target, seen = q * total, 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                return bounds[i] if i < len(bounds) else bounds[-1]
        return bounds[-1]

    def observe_flush(self, n_real: int, bucket: int, queue_depth: int,
                      election: Optional[str] = None) -> None:
        self.inc("batches_flushed", election=election)
        self.inc("padded_slots", bucket - n_real, election=election)
        self.histogram_for("batch_occupancy",
                           election).observe(n_real / bucket)
        self.histogram_for("queue_depth_at_flush",
                           election).observe(float(queue_depth))

    # ---- readers -----------------------------------------------------
    def counters(self) -> dict:
        """Counters + point-in-time gauges, as one flat map (counter
        values summed across tenants — per-tenant series live in the
        registry snapshot under their {election=...} flat names)."""
        out = {name: self.get(name) for name in self.COUNTERS}
        out["queue_depth"] = (self._queue_depth()
                              if self._queue_depth else 0)
        out["device_compiles"] = device_compile_count()
        out["device_compiles_since_start"] = \
            device_compile_count() - self._compiles_at_start
        return out

    def to_proto(self):
        from electionguard_tpu.publish import pb
        resp = pb.msg("MetricsResponse")(counters=self.counters())
        for h in list(self._hists.values()):
            s = h.snapshot()
            resp.histograms.add(name=s["name"], bounds=s["bounds"],
                                counts=s["counts"], sum=s["sum"],
                                count=s["count"])
        return resp

    def summary(self) -> str:
        c = self.counters()
        occ = [h for (n, _), h in self._hists.items()
               if n == "batch_occupancy"]
        occ_n = sum(h.snapshot()["count"] for h in occ)
        occ_sum = sum(h.snapshot()["sum"] for h in occ)
        return (f"admitted={c['requests_admitted']} "
                f"encrypted={c['ballots_encrypted']} "
                f"invalid={c['ballots_invalid']} "
                f"failed={c['requests_failed']} "
                f"rejected={c['requests_rejected_queue_full']} "
                f"recovered={c['ballots_recovered']} "
                f"batches={c['batches_flushed']} "
                f"occupancy_mean={(occ_sum / occ_n) if occ_n else 0:.2f} "
                f"latency_p50={self.latency_quantile(0.5):.0f}ms "
                f"p99={self.latency_quantile(0.99):.0f}ms "
                f"queue_depth={c['queue_depth']} "
                f"compiles={c['device_compiles_since_start']}")

"""Serving metrics: counters + fixed-bucket histograms + compile tracking.

Exposed two ways: over the ``getMetrics`` rpc (``to_proto``) and as a
one-line drain log (``summary``).  Everything is lock-protected and cheap
enough to update per request on the hot path.

``device_compiles`` counts actual backend compilations process-wide via
``jax.monitoring`` — the live twin of the ``compile_cache_entries``
accounting bench.py does against the persistent cache dir.  A serving
process that buckets its batch shapes correctly shows this counter flat
after warmup: one compile per (program, bucket shape) and never again
under load.
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Optional, Sequence

# default latency edges (ms): log-ish spacing from sub-ms to minutes
_LATENCY_MS_BOUNDS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 15000.0, 60000.0)
_OCCUPANCY_BOUNDS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)
_DEPTH_BOUNDS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0)

# -- process-wide backend-compile counter (jax.monitoring) -------------
_compile_lock = threading.Lock()
_compile_count = 0
_listener_installed = False


def _on_event_duration(event: str, duration: float, **kw) -> None:
    global _compile_count
    if event == "/jax/core/compile/backend_compile_duration":
        with _compile_lock:
            _compile_count += 1


def install_compile_listener() -> None:
    """Idempotently hook jax.monitoring so every backend compile in this
    process is counted (works on every platform and group, unlike the
    persistent-cache dir count, which only sees compiles ≥ the persist
    threshold)."""
    global _listener_installed
    with _compile_lock:
        if _listener_installed:
            return
        _listener_installed = True
    import jax.monitoring
    jax.monitoring.register_event_duration_secs_listener(_on_event_duration)


def device_compile_count() -> int:
    with _compile_lock:
        return _compile_count


class Histogram:
    """Fixed-bound histogram: counts[i] observations ≤ bounds[i], last
    bucket is overflow.  Snapshot-able without stopping writers."""

    def __init__(self, name: str, bounds: Sequence[float]):
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._n += 1

    def snapshot(self) -> dict:
        with self._lock:
            return dict(name=self.name, bounds=list(self.bounds),
                        counts=list(self._counts), sum=self._sum,
                        count=self._n)

    def mean(self) -> float:
        with self._lock:
            return self._sum / self._n if self._n else 0.0

    def quantile(self, q: float) -> float:
        """Upper bucket-bound estimate of the q-quantile (q in [0,1])."""
        with self._lock:
            n, counts = self._n, list(self._counts)
        if n == 0:
            return 0.0
        target = q * n
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                return (self.bounds[i] if i < len(self.bounds)
                        else self.bounds[-1])
        return self.bounds[-1]


class ServiceMetrics:
    """All counters/gauges/histograms of one EncryptionService."""

    COUNTERS = ("requests_admitted", "requests_rejected_queue_full",
                "requests_rejected_draining", "requests_failed",
                "ballots_encrypted", "ballots_invalid", "ballots_spoiled",
                "ballots_recovered", "batches_flushed", "padded_slots")

    def __init__(self, queue_depth: Optional[Callable[[], int]] = None):
        self._lock = threading.Lock()
        self._counters = {name: 0 for name in self.COUNTERS}
        self._queue_depth = queue_depth
        self.latency_ms = Histogram("request_latency_ms",
                                    _LATENCY_MS_BOUNDS)
        self.batch_occupancy = Histogram("batch_occupancy",
                                         _OCCUPANCY_BOUNDS)
        self.queue_depth_at_flush = Histogram("queue_depth_at_flush",
                                              _DEPTH_BOUNDS)
        install_compile_listener()
        self._compiles_at_start = device_compile_count()

    # ---- writers -----------------------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] += by

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters[name]

    def observe_flush(self, n_real: int, bucket: int,
                      queue_depth: int) -> None:
        self.inc("batches_flushed")
        self.inc("padded_slots", bucket - n_real)
        self.batch_occupancy.observe(n_real / bucket)
        self.queue_depth_at_flush.observe(float(queue_depth))

    # ---- readers -----------------------------------------------------
    def counters(self) -> dict:
        """Counters + point-in-time gauges, as one flat map."""
        with self._lock:
            out = dict(self._counters)
        out["queue_depth"] = (self._queue_depth()
                              if self._queue_depth else 0)
        out["device_compiles"] = device_compile_count()
        out["device_compiles_since_start"] = \
            device_compile_count() - self._compiles_at_start
        return out

    def to_proto(self):
        from electionguard_tpu.publish import pb
        resp = pb.msg("MetricsResponse")(counters=self.counters())
        for h in (self.latency_ms, self.batch_occupancy,
                  self.queue_depth_at_flush):
            s = h.snapshot()
            resp.histograms.add(name=s["name"], bounds=s["bounds"],
                                counts=s["counts"], sum=s["sum"],
                                count=s["count"])
        return resp

    def summary(self) -> str:
        c = self.counters()
        return (f"admitted={c['requests_admitted']} "
                f"encrypted={c['ballots_encrypted']} "
                f"invalid={c['ballots_invalid']} "
                f"rejected={c['requests_rejected_queue_full']} "
                f"batches={c['batches_flushed']} "
                f"occupancy_mean={self.batch_occupancy.mean():.2f} "
                f"latency_p50={self.latency_ms.quantile(0.5):.0f}ms "
                f"p99={self.latency_ms.quantile(0.99):.0f}ms "
                f"queue_depth={c['queue_depth']} "
                f"compiles={c['device_compiles_since_start']}")

"""Per-tenant election contexts over one shared serving process.

The multi-tenant serving model: ONE process (one device owner, one
admission queue, one compiled program set) serves N overlapping
elections.  What is per-tenant is deliberately small and listed here —

* an ``ElectionContext``: the election's ``ElectionInitialized`` record
  (its joint key, base hash, guardians), a ``BatchEncryptor`` bound to
  it, an optional publisher/record stream, and the worker ``Lane``
  carrying the tenant's seed and confirmation-code chain;
* metric series: every counter/histogram carries ``election=<id>``
  (resolved ambiently — ``obs.tenant``);
* an admission quota (``EGTPU_TENANT_QUOTA``): the max in-flight
  requests ONE election may hold, so a flooding tenant exhausts its own
  quota (RESOURCE_EXHAUSTED naming it) instead of the fleet.

Everything else is shared.  In particular the compiled device programs:
the election key table, seed row, and hash prefix are traced runtime
arguments of the fused encrypt programs (``encrypt/fused.py``), and the
PowRadix/NTT setup tables are cached by group digest alone
(``core/table_cache``), so N tenants over one group cause ZERO
cross-tenant compile churn — the N-tenant drill pins ``device_compiles``
flat after warmup.
"""

from __future__ import annotations

import hashlib
import os
import re
import threading
from typing import Optional

from electionguard_tpu.crypto import validate
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.obs import tenant as _tenant
from electionguard_tpu.publish.election_record import ElectionInitialized
from electionguard_tpu.publish.publisher import Publisher
from electionguard_tpu.serve.worker import Lane
from electionguard_tpu.utils import errors, knobs


class TenantQuotaError(Exception):
    """One election's in-flight admission quota is exhausted — shed THAT
    tenant's load (RESOURCE_EXHAUSTED naming it), not the fleet's."""


def tenant_record_dir(base: str, election_id: str) -> str:
    """A filesystem-safe per-election record dir under ``base``: a
    sanitized slug for humans plus an id digest for uniqueness (hostile
    election ids — quotes, newlines, path separators — collapse to the
    digest, never to a path traversal)."""
    slug = re.sub(r"[^A-Za-z0-9_-]+", "_", election_id)[:24].strip("_")
    digest = hashlib.sha256(election_id.encode()).hexdigest()[:12]
    return os.path.join(base, f"{slug or 'election'}-{digest}")


class ElectionContext:
    """One tenant's election state over the shared serving process."""

    def __init__(self, election_id: str, init: ElectionInitialized,
                 group=None, out_dir: Optional[str] = None,
                 seed=None, mesh=None,
                 encryptor: Optional[BatchEncryptor] = None):
        _tenant.admit(election_id)
        self.election_id = election_id
        self.init = init
        self.group = group if group is not None else \
            init.joint_public_key.group
        # same ingestion gate the single-tenant service runs at startup:
        # a smuggled non-subgroup key in ANY tenant's record is rejected
        # before its encryptor exists
        validate.gate_elements(
            self.group,
            [("joint public key", init.joint_public_key.value)]
            + [(f"{gr.guardian_id} commitment[{j}]", k.value)
               for gr in init.guardians
               for j, k in enumerate(gr.coefficient_commitments)],
            "serve")
        # shares jax_ops(group)/the fused program set with every other
        # tenant on this group; only the key table is per-election
        self.encryptor = encryptor if encryptor is not None else \
            BatchEncryptor(init, self.group, mesh=mesh)
        self.publisher = Publisher(out_dir) if out_dir else None
        self.stream = None
        if self.publisher is not None:
            self.publisher.write_election_initialized(init)
            self.stream = self.publisher.open_encrypted_ballots(
                append=True)
        self.seed = seed if seed is not None else self.group.rand_q()
        self.lane = Lane(election_id, self.encryptor, self.seed,
                         self.stream)

    @property
    def record_dir(self) -> Optional[str]:
        return self.publisher.dir if self.publisher is not None else None

    def close(self) -> None:
        """Flush and close the tenant's record stream (idempotent)."""
        if self.stream is not None:
            self.stream.close()
            self.stream = None
            self.lane.stream = None


class TenantRegistry:
    """The elections one serving process hosts, keyed by election id.
    Bounded implicitly by ``EGTPU_TENANT_MAX`` (every ``add`` runs the
    ``obs.tenant`` cardinality guard via ElectionContext)."""

    def __init__(self):
        self._by_id: dict[str, ElectionContext] = {}

    def add(self, ctx: ElectionContext) -> ElectionContext:
        if ctx.election_id in self._by_id:
            raise ValueError(errors.named(
                "tenant.duplicate",
                f"election {ctx.election_id!r} already registered"))
        self._by_id[ctx.election_id] = ctx
        return ctx

    def get(self, election_id: str) -> Optional[ElectionContext]:
        return self._by_id.get(election_id)

    def elections(self) -> tuple:
        return tuple(self._by_id)

    def lanes(self) -> dict:
        """{election_id: Lane} for the EncryptionWorker."""
        return {eid: ctx.lane for eid, ctx in self._by_id.items()}

    def close(self) -> None:
        for ctx in self._by_id.values():
            ctx.close()


class TenantQuota:
    """Per-election in-flight admission accounting.

    ``acquire()`` charges the AMBIENT election one in-flight slot and
    returns a release callable (attach it to the request future), or
    raises ``TenantQuotaError`` at the cap.  Quota 0 (the default)
    disables accounting entirely — ``acquire`` returns None."""

    def __init__(self, quota: Optional[int] = None):
        self.quota = quota if quota is not None else \
            knobs.get_int("EGTPU_TENANT_QUOTA")
        self._lock = threading.Lock()
        self._inflight: dict[str, int] = {}

    def inflight(self, election: str) -> int:
        with self._lock:
            return self._inflight.get(election, 0)

    def acquire(self, election: Optional[str] = None):
        if self.quota <= 0:
            return None
        if election is None:
            election = _tenant.current_election()
        with self._lock:
            n = self._inflight.get(election, 0)
            if n >= self.quota:
                raise TenantQuotaError(errors.named(
                    "tenant.quota",
                    f"election {election!r} has {n} in-flight requests "
                    f"(quota {self.quota})"))
            self._inflight[election] = n + 1

        released = threading.Event()

        def release(_fut=None) -> None:
            # idempotent: a future resolved twice (or released by both
            # an error path and a done-callback) must not undercount
            if released.is_set():
                return
            released.set()
            with self._lock:
                left = self._inflight.get(election, 1) - 1
                if left <= 0:
                    self._inflight.pop(election, None)
                else:
                    self._inflight[election] = left

        return release

"""The device-owner loop: drains batches into the batch encryptor.

Exactly ONE worker thread talks to the device, so request threads never
touch host↔device transfer — they block on futures while the worker runs
the fused pipeline (``encrypt/fused.py`` on the production group, the
batched host-hash fallback elsewhere) over padded, bucket-shaped batches.

Padding and the code chain
--------------------------
Each flush is padded to its bucket with filler ballots appended AFTER the
real requests.  Because nonces are keyed by ballot identity, fillers
change nothing about the real ballots' ciphertexts; and because the
confirmation-code chain runs through the batch in order, the real
ballots' codes form a contiguous chain prefix.  The worker advances its
cross-batch ``code_seed`` to the LAST REAL ballot's code and discards the
filler tail, so the published stream is bit-for-bit what the offline
``BatchEncryptor`` would produce for the same ballots in the same order
(given the same seed and timestamp) — the serving layer adds batching,
not a second crypto path.

``prewarm()`` encrypts one all-filler batch per bucket at startup, so
every device program is compiled before the first request arrives and the
``device_compiles`` metric stays flat under load.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from electionguard_tpu.ballot.plaintext import (PlaintextBallot,
                                                PlaintextBallotContest,
                                                PlaintextBallotSelection)
from electionguard_tpu.core.group import ElementModQ
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.obs import trace
from electionguard_tpu.serve.batcher import DynamicBatcher, PendingRequest
from electionguard_tpu.serve.metrics import ServiceMetrics
from electionguard_tpu.utils import clock

log = logging.getLogger("serve.worker")


class InvalidBallotError(Exception):
    """The ballot failed admission validation inside the encryptor
    (unknown contest/selection, overvote, duplicate id, ...)."""


class Lane:
    """Per-election encryption state over the SHARED device programs:
    the tenant's encryptor (same group and manifest shapes — so the
    same jitted bucket programs, only the traced key table differs),
    its record stream, and its own seed and confirmation-code chain.
    One worker drains one batcher into N lanes; each device batch is
    single-lane, so every tenant's published stream stays exactly what
    the offline BatchEncryptor would produce for its ballots."""

    __slots__ = ("election", "enc", "seed", "stream", "code_seed")

    def __init__(self, election, enc, seed, stream=None, code_seed=None):
        self.election = election
        self.enc = enc
        self.seed = seed
        self.stream = stream
        self.code_seed = code_seed


class EncryptionWorker(threading.Thread):
    def __init__(self, batcher: DynamicBatcher, encryptor: BatchEncryptor,
                 metrics: ServiceMetrics,
                 seed: Optional[ElementModQ] = None,
                 timestamp: Optional[int] = None,
                 stream=None,
                 hold: Optional[threading.Event] = None,
                 code_seed: Optional[bytes] = None,
                 hold_after: Optional[int] = None,
                 lanes: Optional[dict] = None):
        """``stream``: optional ``EncryptedBallotStream`` every real
        encrypted ballot is appended to (the growing record).
        ``timestamp``: pin the ballot timestamp (tests/differential runs);
        None stamps each batch with encryption time.
        ``hold``: when given, the worker waits on it before each pull —
        a test hook to force queue buildup deterministically.
        ``code_seed``: continue the confirmation-code chain from this
        code (crash recovery: the last PUBLISHED ballot's code).
        ``hold_after``: chaos hook — once this many ballots are
        encrypted, the worker stops pulling forever (a deterministic
        stand-in for "the device owner wedged/died mid-stream" that the
        SIGKILL chaos test arms via EGTPU_CHAOS_HOLD_AFTER_BALLOTS).
        ``lanes``: {election_id: Lane} for multi-tenant serving — a
        drained flush is regrouped by each request's election and every
        group encrypts on its own lane; requests whose election has no
        lane run on the default lane (this worker's own encryptor/
        stream/chain), which is the entire story when ``lanes`` is
        None (single-tenant, the legacy behavior)."""
        super().__init__(name="encryption-worker", daemon=True)
        self.batcher = batcher
        self.enc = encryptor
        self.metrics = metrics
        self.seed = seed if seed is not None else encryptor.group.rand_q()
        self.timestamp = timestamp
        self.stream = stream
        self.hold = hold
        self.hold_after = hold_after
        from electionguard_tpu.utils import knobs
        self._emulate_device_s = knobs.get_float(
            "EGTPU_FABRIC_EMULATE_DEVICE_MS") / 1e3
        self._default_lane = Lane("", encryptor, self.seed, stream,
                                  code_seed)
        self.lanes: dict[str, Lane] = dict(lanes) if lanes else {}
        self._pad_counter = 0
        self._filler_proto = self._make_filler_proto()
        self.error: Optional[BaseException] = None

    # ---- filler ballots ---------------------------------------------
    def _make_filler_proto(self):
        """Contests of the manifest's first ballot style, all votes 0 —
        a structurally valid undervote the encryptor pads internally."""
        manifest = self.enc.manifest
        style = manifest.ballot_styles[0]
        contests = tuple(
            PlaintextBallotContest(
                contest_id=c.object_id,
                selections=tuple(PlaintextBallotSelection(s.object_id, 0)
                                 for s in c.selections))
            for c in manifest.contests_for_style(style.object_id))
        return style.object_id, contests

    def _filler(self) -> PlaintextBallot:
        self._pad_counter += 1
        style_id, contests = self._filler_proto
        return PlaintextBallot(f"__pad-{self._pad_counter:09d}",
                               style_id, contests)

    # ---- lifecycle ---------------------------------------------------
    def prewarm(self) -> None:
        """Encrypt one all-filler batch per bucket: compiles every
        (program, bucket shape) pair up front.  Filler-only batches have
        no real ballots, so neither the code chain nor the record stream
        moves.  One prewarm covers EVERY lane: the election key is a
        traced argument of the fused programs, so tenant lanes reuse
        the same compiled bucket set (device_compiles stays flat)."""
        for bucket in self.batcher.buckets:
            self._encrypt([], bucket, self._default_lane)

    def run(self) -> None:
        while True:
            if self.hold is not None:
                clock.wait_event(self.hold)
            if (self.hold_after is not None
                    and self.metrics.get("ballots_encrypted")
                    >= self.hold_after):
                log.warning("chaos hold: %d ballots encrypted, worker "
                            "wedged", self.hold_after)
                clock.wait_event(threading.Event())   # wedge until SIGKILL
            batch = self.batcher.next_batch()
            if batch is None:
                return
            try:
                self._process(batch, clock.monotonic)
            except BaseException as e:  # noqa: BLE001 — keep serving
                # _process already failed the batch's futures; a raise
                # here would kill the one device owner and wedge every
                # future request
                self.error = e
                log.exception("batch processing failed")

    # ---- the hot path ------------------------------------------------
    def _encrypt(self, real: list[PendingRequest], bucket: int,
                 lane: Lane):
        ballots = [p.ballot for p in real]
        fillers = [self._filler() for _ in range(bucket - len(ballots))]
        spoiled = {p.ballot.ballot_id for p in real if p.spoil}
        encrypted, invalid = lane.enc.encrypt_ballots(
            ballots + fillers, seed=lane.seed, code_seed=lane.code_seed,
            spoiled_ids=spoiled, timestamp=self.timestamp)
        filler_ids = {f.ballot_id for f in fillers}
        # fillers sit at the tail of the valid list, so the real prefix
        # is chain-contiguous; keep it, discard the filler tail
        real_encrypted = []
        for b in encrypted:
            if b.ballot_id in filler_ids:
                break
            real_encrypted.append(b)
        if self._emulate_device_s:
            # scale-evidence hook (EGTPU_FABRIC_EMULATE_DEVICE_MS): pad
            # the device leg to a fixed wall-clock duration — the
            # per-chip-device-time regime of a real fleet, where the
            # host core is NOT the bottleneck — so a single-host fabric
            # curve measures routing-plane scaling, the analogue of
            # scale_run's virtual 8-device mesh for the shuffle plane
            clock.sleep(self._emulate_device_s)
        return real_encrypted, invalid, spoiled

    def _process(self, batch: list[PendingRequest], clock) -> None:
        depth = self.batcher.depth()
        # regroup one drained flush by election (first-seen tenant
        # order, FIFO within a tenant): each group is a single-lane
        # device batch, so every tenant's code chain and record stream
        # stay contiguous.  Single-tenant services see exactly one
        # group — the legacy path.
        groups: dict[str, list[PendingRequest]] = {}
        for p in batch:
            groups.setdefault(p.tenant, []).append(p)
        err: Optional[BaseException] = None
        for election, group in groups.items():
            try:
                self._process_group(election, group, depth, clock)
            except BaseException as e:  # noqa: BLE001 — per-lane blast
                # radius: one lane's failure must not strand the other
                # lanes' futures in the same flush
                if err is None:
                    err = e
        if err is not None:
            raise err

    def _process_group(self, election: str,
                       group: list[PendingRequest], depth: int,
                       clock) -> None:
        lane = self.lanes.get(election, self._default_lane)
        bucket = self.batcher.bucket_for(len(group))
        t0 = clock()
        try:
            # the device leg of one flush: compile time inside this span
            # is attributed to it by the obs.jaxmon listener; when
            # tracing is off this is the shared no-op (zero allocation
            # beyond the guarded attrs dict)
            attrs = ({"bucket": bucket, "n_real": len(group),
                      "election": election or lane.election or "default"}
                     if trace.enabled() else None)
            with trace.span("worker.batch", attrs):
                real_encrypted, invalid, spoiled = \
                    self._encrypt(group, bucket, lane)
        except BaseException as e:
            for p in group:
                if not p.future.set_running_or_notify_cancel():
                    continue
                p.future.set_exception(e)
            self.metrics.inc("requests_failed", len(group),
                             election=election)
            raise
        # per-tenant device-time attribution: the raw material the
        # noisy-neighbor detector joins against per-tenant SLO burn
        self.metrics.inc_device_ms((clock() - t0) * 1e3, election)
        if real_encrypted:
            lane.code_seed = real_encrypted[-1].code
            # the default lane reads ``self.stream`` at flush time, not
            # the handle captured at construction — callers (the sim
            # harness) rebind ``worker.stream`` after the fact; tenant
            # lanes own their stream for their whole lifetime
            stream = (self.stream if lane is self._default_lane
                      else lane.stream)
            if stream is not None:
                for b in real_encrypted:
                    stream.write(b)
                # batch-boundary durability: a crash after this point
                # loses nothing from this batch; a crash before it is
                # covered by the admission journal's replay
                stream.flush()
        by_id = {b.ballot_id: b for b in real_encrypted}
        inv_by_id = {b.ballot_id: reason for b, reason in invalid}
        now = clock()
        latency = self.metrics.histogram_for("request_latency_ms",
                                             election)
        for p in group:
            latency.observe((now - p.t_enqueue) * 1e3)
            if not p.future.set_running_or_notify_cancel():
                continue
            # pop, not get: of two same-id requests in one batch, only
            # the first owns the encrypted ballot; the second is the
            # duplicate the encryptor rejected
            b = by_id.pop(p.ballot.ballot_id, None)
            if b is not None:
                p.future.set_result(b)
            else:
                reason = inv_by_id.get(p.ballot.ballot_id,
                                       "not returned by encryptor")
                self.metrics.inc("ballots_invalid", election=election)
                p.future.set_exception(InvalidBallotError(reason))
        self.metrics.inc("ballots_encrypted", len(real_encrypted),
                         election=election)
        self.metrics.inc("ballots_spoiled",
                         sum(1 for b in real_encrypted
                             if b.ballot_id in spoiled),
                         election=election)
        self.metrics.observe_flush(len(group), bucket, depth,
                                   election=election)

    @property
    def code_seed(self) -> Optional[bytes]:
        """The last real ballot's confirmation code on the DEFAULT lane
        (the chain head the next batch continues from); None before any
        real ballot.  Tenant lanes hold their own chain heads."""
        return self._default_lane.code_seed

"""Trace analytics: critical path, wall-clock attribution, anti-patterns.

The obs plane *records* a run (obs/trace.py exports spans, obs/assemble
merges them); this module *answers* the capacity-planning questions over
that record:

* **critical path** — the single chain of span self-segments that the
  run's end-to-end wall-clock actually waited on.  At any instant the
  critical path is inside the deepest span active at that instant that
  finishes last; the decomposition below covers the root envelope
  exactly, so the per-hop durations sum to the run's wall-clock by
  construction (what the flight report's coverage line asserts);
* **attribution buckets** — every span's *self time* (its duration
  minus the union of its children) lands in a phase x process x
  category bucket, where category is device compute, queue wait, RPC,
  serialization, recompile (``device.compile`` events joined in from
  the obs/jaxmon listener) or host;
* **anti-patterns** — mid-run recompiles (a ``device.compile`` after a
  process's first device batch completed: the prewarm contract was
  violated), queue saturation against the SLO engine's
  ``queue_depth_max`` threshold, and straggler shards (a fabric worker
  whose mean device-batch duration is a multiple of the fleet median,
  from the ``worker.batch`` spans and the collector's persisted
  heartbeats).

Everything degrades: orphaned spans (a SIGKILL'd worker never closes
its root), clock-skewed processes and truncated JSONL lines produce a
partial analysis with ``warnings``, never a crash — the assembler's
tolerant loader (obs/assemble.load_spans) is the single parsing path.

``obs/flight.py`` renders one of these into ``FLIGHT_REPORT.md``;
``tools/egreport.py`` is the CLI; ``tools/egtop.py`` feeds its live
critical-path pane from the same ``analyze()``.
"""

from __future__ import annotations

import glob
import json
import os
from dataclasses import dataclass, field
from typing import Optional

from electionguard_tpu.obs import assemble
from electionguard_tpu.obs import slo as slo_mod
from electionguard_tpu.utils import knobs

#: span names that are one device dispatch (the "device" category);
#: everything under ``device.`` counts too
_DEVICE_BATCHES = frozenset(
    {"worker.batch", "encrypt.batch", "decrypt.batch", "tally.batch",
     "verify.batch"})
_SERIALIZATION_TOKENS = ("publish", "serialize", "journal", "merge",
                         "record")
_QUEUE_TOKENS = ("wait", "queue", "batcher")

CATEGORIES = ("device", "queue-wait", "rpc", "serialization",
              "recompile", "host")


def category_of(name: str) -> str:
    """Wall-clock bucket for one span name (see CATEGORIES)."""
    if name == "device.compile":
        return "recompile"
    if name in _DEVICE_BATCHES or name.startswith("device."):
        return "device"
    if name.startswith("rpc."):
        return "rpc"
    if any(t in name for t in _QUEUE_TOKENS):
        return "queue-wait"
    if any(t in name for t in _SERIALIZATION_TOKENS):
        return "serialization"
    return "host"


def _end(s: dict) -> int:
    return s["ts"] + s.get("dur", 0)


@dataclass(frozen=True)
class Hop:
    """One self-segment of one span on the critical path: the interval
    ``[t0, t1)`` during which ``span`` itself (no child of it) was the
    thing the run waited on."""

    span: dict
    t0: int
    t1: int

    @property
    def dur_us(self) -> int:
        return self.t1 - self.t0


@dataclass
class ShardStat:
    """Device-batch balance of one serving/fabric worker process."""

    proc: str
    n_batches: int
    total_us: int
    mean_us: float
    max_us: int
    shard: Optional[int] = None
    queue_max: Optional[int] = None


@dataclass
class RunAnalysis:
    """Everything analyze() learned about one trace dir."""

    trace_dir: str
    spans: list[dict] = field(default_factory=list)
    warnings: list[str] = field(default_factory=list)
    validation: dict = field(default_factory=dict)
    root: Optional[dict] = None
    wall_us: int = 0
    hops: list[Hop] = field(default_factory=list)       # time order
    path: list[dict] = field(default_factory=list)      # merged rows
    #: (phase, proc, category) -> self-time us
    buckets: dict = field(default_factory=dict)
    top_self: list[tuple[dict, int]] = field(default_factory=list)
    shards: list[ShardStat] = field(default_factory=list)
    stragglers: list[dict] = field(default_factory=list)
    recompiles_total: int = 0
    recompile_us: int = 0
    midrun_recompiles: list[dict] = field(default_factory=list)
    heartbeats: list[dict] = field(default_factory=list)
    queue_max: dict = field(default_factory=dict)       # proc -> depth
    #: election -> {n_batches, device_us, share}: device time attributed
    #: per tenant from the ``election`` attr on device-batch spans
    tenants: dict = field(default_factory=dict)
    alerts: list[dict] = field(default_factory=list)    # slo.alert spans
    antipatterns: list[dict] = field(default_factory=list)

    @property
    def path_total_us(self) -> int:
        return sum(h.dur_us for h in self.hops)

    @property
    def coverage(self) -> float:
        """Critical-path total over root wall-clock (1.0 = exact)."""
        if not self.wall_us:
            return 0.0
        return self.path_total_us / self.wall_us

    def to_json(self) -> dict:
        return {
            "trace_dir": self.trace_dir,
            "n_spans": len(self.spans),
            "wall_us": self.wall_us,
            "path_total_us": self.path_total_us,
            "coverage": round(self.coverage, 4),
            "critical_path": self.path,
            "buckets": [{"phase": p, "proc": pr, "category": c,
                         "self_us": us}
                        for (p, pr, c), us in sorted(self.buckets.items())],
            "top_self": [{"name": s["name"], "proc": s["proc"],
                          "self_us": us} for s, us in self.top_self],
            "shards": [{"proc": s.proc, "shard": s.shard,
                        "n_batches": s.n_batches, "total_us": s.total_us,
                        "mean_us": round(s.mean_us, 1),
                        "max_us": s.max_us, "queue_max": s.queue_max}
                       for s in self.shards],
            "stragglers": self.stragglers,
            "tenants": [{"election": el, **stats}
                        for el, stats in sorted(self.tenants.items())],
            "recompiles_total": self.recompiles_total,
            "recompile_us": self.recompile_us,
            "midrun_recompiles": self.midrun_recompiles,
            "queue_max": self.queue_max,
            "alerts": [{"subject": a.get("attrs", {}).get("subject", ""),
                        "kind": a.get("attrs", {}).get("kind", "")}
                       for a in self.alerts],
            "antipatterns": self.antipatterns,
            "warnings": self.warnings,
            "validation": {k: v for k, v in self.validation.items()
                           if k in ("trace_ids", "processes", "rpc_pairs",
                                    "rpc_server_unpaired")},
        }


# ---------------------------------------------------------------------------
# critical path
# ---------------------------------------------------------------------------

def _children_index(spans: list[dict]) -> dict[str, list[dict]]:
    kids: dict[str, list[dict]] = {}
    for s in spans:
        if s["parent_id"]:
            kids.setdefault(s["parent_id"], []).append(s)
    for v in kids.values():
        v.sort(key=lambda s: s["ts"])
    return kids


def _critical_hops(span: dict, lo: int, hi: int,
                   kids_of: dict[str, list[dict]],
                   out: list[Hop]) -> None:
    """Cover ``[lo, hi)`` with Hops: descend into whichever child is
    active at the cursor and finishes LAST (the one the parent actually
    waits on); the uncovered remainder is the span's own self time."""
    cursor = lo
    kids = kids_of.get(span["span_id"], ())
    while cursor < hi:
        active = [c for c in kids
                  if c["ts"] <= cursor and _end(c) > cursor]
        if active:
            c = max(active, key=_end)
            seg_end = min(_end(c), hi)
            _critical_hops(c, cursor, seg_end, kids_of, out)
            cursor = seg_end
        else:
            nxt = min([hi] + [c["ts"] for c in kids
                              if cursor < c["ts"] < hi])
            out.append(Hop(span=span, t0=cursor, t1=nxt))
            cursor = nxt


def critical_path(spans: list[dict],
                  root: Optional[dict] = None) -> list[Hop]:
    """The run's critical path as time-ordered self-segments; their
    durations sum exactly to the root span's duration."""
    closed = [s for s in spans if not assemble.is_open(s)]
    if root is None:
        root = find_root(closed)
    if root is None:
        return []
    kids_of = _children_index(closed)
    out: list[Hop] = []
    _critical_hops(root, root["ts"], _end(root), kids_of, out)
    return out


def find_root(spans: list[dict]) -> Optional[dict]:
    """The run's envelope span: prefer the workflow driver's ``process``
    root, else the longest process root whose parent is unresolved."""
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans
             if s["name"] == "process"
             and (not s["parent_id"] or s["parent_id"] not in ids)]
    if not roots:
        return None
    drivers = [s for s in roots if s["proc"] == "workflow-driver"]
    pool = drivers or roots
    return max(pool, key=lambda s: s.get("dur", 0))


def merge_hops(hops: list[Hop]) -> list[dict]:
    """Adjacent hops of the same span merged into display rows."""
    rows: list[dict] = []
    for h in hops:
        if rows and rows[-1]["span_id"] == h.span["span_id"] \
                and rows[-1]["_t1"] == h.t0:
            rows[-1]["dur_us"] += h.dur_us
            rows[-1]["_t1"] = h.t1
            continue
        rows.append({"span_id": h.span["span_id"],
                     "name": h.span["name"], "proc": h.span["proc"],
                     "t0": h.t0, "_t1": h.t1, "dur_us": h.dur_us})
    for r in rows:
        del r["_t1"]
    return rows


# ---------------------------------------------------------------------------
# attribution + anti-patterns
# ---------------------------------------------------------------------------

def _self_time_us(s: dict, kids_of: dict[str, list[dict]]) -> int:
    """Span duration minus the union of its children's intervals
    (clipped into the span; robust to small cross-process clock skew)."""
    lo, hi = s["ts"], _end(s)
    covered = 0
    cursor = lo
    for c in kids_of.get(s["span_id"], ()):
        c0, c1 = max(c["ts"], cursor), min(_end(c), hi)
        if c1 > c0:
            covered += c1 - c0
            cursor = c1
    return max(s.get("dur", 0) - covered, 0)


def _phase_of(s: dict, by_id: dict[str, dict],
              cache: dict[str, str]) -> str:
    """Nearest ancestor ``phase.*`` span name; "(run)" when none."""
    chain: list[str] = []
    cur: Optional[dict] = s
    seen: set[str] = set()
    phase = "(run)"
    while cur is not None and cur["span_id"] not in seen:
        sid = cur["span_id"]
        if sid in cache:
            phase = cache[sid]
            break
        seen.add(sid)
        chain.append(sid)
        if cur["name"].startswith("phase."):
            phase = cur["name"]
            break
        cur = by_id.get(cur["parent_id"])
    for sid in chain:
        cache[sid] = phase
    return phase


def load_heartbeats(trace_dir: str,
                    warnings: Optional[list[str]] = None) -> list[dict]:
    """The collector's persisted heartbeat stream
    (``heartbeats.jsonl`` in the receive dir), tolerant of torn lines;
    empty when the run had no collector.  Looks in the trace dir itself,
    its ``recv/`` subdir (when analyzing a collector's obs dir), and the
    workflow layout's sibling ``obs/recv/`` (``<out>/trace`` next to
    ``<out>/obs``)."""
    base = trace_dir.rstrip("/")
    candidates: list[str] = []
    for d in (base, os.path.join(base, "recv"),
              os.path.join(os.path.dirname(base) or ".", "obs", "recv")):
        candidates += glob.glob(os.path.join(d, "heartbeats*.jsonl"))
    out: list[dict] = []
    for path in sorted(set(candidates)):
        with open(path, errors="replace") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    if warnings is not None:
                        warnings.append(
                            f"{os.path.basename(path)}:{lineno}: "
                            f"malformed heartbeat line skipped")
                    continue
                if isinstance(rec, dict) and "proc" in rec:
                    out.append(rec)
    return out


def _parse_shard_id(phase: str) -> Optional[int]:
    """Shard id from a serving heartbeat phase string
    (``serving shard=<id> ...``; see tools/egtop.parse_shard)."""
    if not phase or "shard=" not in phase:
        return None
    for tok in phase.split():
        if tok.startswith("shard="):
            try:
                return int(tok.split("=", 1)[1])
            except ValueError:
                return None
    return None


def analyze(trace_dir: str, top_n: Optional[int] = None,
            straggler_ratio: Optional[float] = None,
            slo_config: Optional[dict] = None) -> RunAnalysis:
    """Full analysis of one trace dir (a run's ``EGTPU_OBS_TRACE`` dir
    or a collector's ``obs/recv`` dir).  Never raises on a damaged
    trace: everything partial lands in ``warnings``."""
    if top_n is None:
        top_n = knobs.get_int("EGTPU_FLIGHT_TOP_N")
    if straggler_ratio is None:
        straggler_ratio = knobs.get_float("EGTPU_FLIGHT_STRAGGLER_RATIO")
    cfg = slo_config or slo_mod.load_config()

    a = RunAnalysis(trace_dir=trace_dir)
    raw = assemble.load_spans(trace_dir, a.warnings)
    spans = assemble.dedupe(raw)
    a.spans = spans
    if not spans:
        a.warnings.append(f"no spans found under {trace_dir}")
        return a
    a.validation = assemble.validate(spans)
    if a.validation["orphans"]:
        a.warnings.append(
            f"{len(a.validation['orphans'])} orphaned span(s) (parents "
            f"never exported — a killed process?): partial attribution")
    if a.validation["open_spans"]:
        a.warnings.append(
            f"{len(a.validation['open_spans'])} span(s) still open: "
            f"mid-run or died-run trace")
    if len(a.validation["trace_ids"]) > 1:
        a.warnings.append(
            f"multiple trace ids {a.validation['trace_ids']}: dir mixes "
            f"runs; analyzing all spans together")

    closed = [s for s in spans if not assemble.is_open(s)]
    by_id = {s["span_id"]: s for s in closed}
    kids_of = _children_index(closed)

    # ---- critical path ------------------------------------------------
    root = find_root(closed)
    a.root = root
    if root is None:
        a.warnings.append("no process root span: critical path "
                          "unavailable (partial report)")
    else:
        a.wall_us = root.get("dur", 0)
        a.hops = []
        _critical_hops(root, root["ts"], _end(root), kids_of, a.hops)
        a.path = merge_hops(a.hops)

    # ---- attribution buckets + top self-time --------------------------
    phase_cache: dict[str, str] = {}
    self_us: list[tuple[dict, int]] = []
    for s in closed:
        us = _self_time_us(s, kids_of)
        self_us.append((s, us))
        key = (_phase_of(s, by_id, phase_cache), s["proc"],
               category_of(s["name"]))
        a.buckets[key] = a.buckets.get(key, 0) + us
    self_us.sort(key=lambda t: -t[1])
    a.top_self = self_us[:top_n]

    # ---- recompile attribution (obs/jaxmon compile events) ------------
    compiles = [s for s in closed if s["name"] == "device.compile"]
    a.recompiles_total = len(compiles)
    a.recompile_us = sum(s.get("dur", 0) for s in compiles)
    first_batch_end: dict[str, int] = {}
    for s in closed:
        if category_of(s["name"]) == "device":
            e = _end(s)
            cur = first_batch_end.get(s["proc"])
            if cur is None or e < cur:
                first_batch_end[s["proc"]] = e
    for s in compiles:
        cutoff = first_batch_end.get(s["proc"])
        if cutoff is not None and s["ts"] > cutoff:
            a.midrun_recompiles.append(
                {"proc": s["proc"], "ts": s["ts"],
                 "dur_us": s.get("dur", 0)})
    if a.midrun_recompiles:
        a.antipatterns.append({
            "kind": "midrun-recompile",
            "subject": ",".join(sorted({m["proc"]
                                        for m in a.midrun_recompiles})),
            "detail": f"{len(a.midrun_recompiles)} compile(s) after the "
                      f"first device batch — prewarm missed a shape"})

    # ---- heartbeats: queue saturation + shard ids ---------------------
    a.heartbeats = load_heartbeats(trace_dir, a.warnings)
    shard_of: dict[str, int] = {}
    for hb in a.heartbeats:
        proc = hb["proc"]
        depth = int(hb.get("queue_depth", 0))
        if depth > a.queue_max.get(proc, -1):
            a.queue_max[proc] = depth
        sid = _parse_shard_id(hb.get("phase", ""))
        if sid is not None:
            shard_of[proc] = sid
    depth_max = int(cfg.get("queue_depth_max", 256))
    for proc, depth in sorted(a.queue_max.items()):
        if depth >= depth_max:
            a.antipatterns.append({
                "kind": "queue-saturation", "subject": proc,
                "detail": f"admission queue hit {depth} "
                          f"(SLO queue_depth_max={depth_max})"})

    # ---- per-shard balance + stragglers -------------------------------
    per_proc: dict[str, list[int]] = {}
    for s in closed:
        if s["name"] == "worker.batch":
            per_proc.setdefault(s["proc"], []).append(s.get("dur", 0))
    for proc in sorted(per_proc):
        durs = per_proc[proc]
        a.shards.append(ShardStat(
            proc=proc, n_batches=len(durs), total_us=sum(durs),
            mean_us=sum(durs) / len(durs), max_us=max(durs),
            shard=shard_of.get(proc), queue_max=a.queue_max.get(proc)))
    if len(a.shards) >= 2:
        means = sorted(s.mean_us for s in a.shards)
        median = means[len(means) // 2] if len(means) % 2 \
            else (means[len(means) // 2 - 1] + means[len(means) // 2]) / 2
        for s in a.shards:
            if median > 0 and s.mean_us > straggler_ratio * median:
                entry = {"proc": s.proc, "shard": s.shard,
                         "mean_us": round(s.mean_us, 1),
                         "fleet_median_us": round(median, 1),
                         "ratio": round(s.mean_us / median, 2)}
                a.stragglers.append(entry)
                a.antipatterns.append({
                    "kind": "straggler-shard", "subject": s.proc,
                    "detail": f"mean device batch "
                              f"{s.mean_us / 1e3:.1f} ms vs fleet median "
                              f"{median / 1e3:.1f} ms "
                              f"({s.mean_us / median:.1f}x)"})

    # ---- per-tenant device-time attribution ---------------------------
    # device-batch spans carry an ``election`` attr (serve/worker stamps
    # it per lane); bucketing by it answers "who used the device" even
    # for runs with no metrics snapshot — hostile election ids are plain
    # JSON attr values here, no exposition escaping involved
    per_tenant: dict[str, list[int]] = {}
    for s in closed:
        if s["name"] in _DEVICE_BATCHES:
            el = (s.get("attrs") or {}).get("election")
            if el is not None:
                per_tenant.setdefault(str(el), []).append(s.get("dur", 0))
    tenant_total = sum(sum(v) for v in per_tenant.values())
    for el, durs in per_tenant.items():
        a.tenants[el] = {
            "n_batches": len(durs), "device_us": sum(durs),
            "share": (round(sum(durs) / tenant_total, 4)
                      if tenant_total else 0.0)}

    # ---- slo.alert spans recorded in the timeline ---------------------
    a.alerts = [s for s in closed if s["name"] == "slo.alert"]
    return a

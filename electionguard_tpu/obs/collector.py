"""Live telemetry collector + fleet health/SLO engine (server and client).

One ``ObsCollector`` per run receives ``pushTelemetry`` batches from
every process — span JSONL lines, structured-log lines, a full
CUMULATIVE metrics ``snapshot()``, and a liveness heartbeat — and turns
them into:

* one fleet-wide metrics registry: the latest snapshot per (proc, pid),
  each series relabeled with ``proc=<role>``, merged with
  ``MetricsRegistry.merge`` and served on a single ``/metrics`` scrape
  (``obs.httpd`` with the collector's ``fleet_text`` as ``text_fn``) and
  the ``getMetrics`` rpc;
* a mid-run strict-valid timeline: received spans land in the
  collector's own receive dir (same ``spans-<proc>-<pid>.jsonl`` layout
  ``obs.assemble`` reads) and ``trace_live.json`` is re-assembled every
  few seconds with the fleet's in-flight spans merged in as ``open``
  markers — so the timeline exists DURING the run and survives
  processes that die without flushing;
* an SLO evaluation loop (``obs.slo``): every tick emits a ``slo.eval``
  span; every violation that fires emits a first-class ``slo.alert``
  span carrying the alert attrs (``detection_s`` for liveness), so
  alerts are part of the same timeline as the work they judge;
* ``getFleetStatus``: the one rpc ``tools/egtop.py`` polls for the
  mission-control board.

The client half (``TelemetryClient``) is wired by ``obs.init_from_env``
when ``EGTPU_OBS_COLLECTOR=<host:port>`` is set.  Its contract with the
caller's hot path: trace/slog hooks only append to a bounded in-process
buffer (drop-oldest, counted by ``obs_dropped_total``); a background
thread drains it over a PLAIN channel (``rpc_util.make_plain_channel``
— no fault injection, no self-tracing) through ``rpc_util.Stub`` for
the retry/deadline-class stack.  A clean exit pushes a final EXITING
goodbye (atexit), which is how the collector tells a shutdown from a
death: missed heartbeats WITHOUT a goodbye turn the process DEAD.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Optional

from electionguard_tpu.obs import assemble, registry, slog, trace
from electionguard_tpu.obs import slo as slo_mod
from electionguard_tpu.utils import clock

log = logging.getLogger("egtpu.obs.collector")

#: client-side bounded buffer (span+log lines awaiting push)
DEFAULT_BUFFER = 5000
#: max lines drained into one TelemetryBatch
BATCH_LINES = 1000

_SIZE_SUFFIX = {"KB": 1024, "MB": 1024 ** 2, "GB": 1024 ** 3}
_AGE_SUFFIX = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_retain(spec: str) -> tuple[Optional[int], Optional[float]]:
    """Parse an ``EGTPU_OBS_RETAIN`` value into
    ``(max_bytes, max_age_s)`` — either may be None (unbounded).

    Grammar: ``"SIZE[,AGE]"`` where SIZE takes a KB/MB/GB suffix
    (plain number = bytes) and AGE takes s/m/h/d.  A leading comma
    (``",24h"``) caps age only; empty spec disables retention.
    Raises ValueError on anything else.
    """
    spec = (spec or "").strip()
    if not spec:
        return None, None
    parts = spec.split(",")
    if len(parts) > 2:
        raise ValueError(f"retain spec wants SIZE[,AGE], got {spec!r}")
    size_part = parts[0].strip()
    age_part = parts[1].strip() if len(parts) == 2 else ""
    max_bytes: Optional[int] = None
    max_age_s: Optional[float] = None
    if size_part:
        up = size_part.upper()
        mult = 1
        for suf, m in _SIZE_SUFFIX.items():
            if up.endswith(suf):
                mult, up = m, up[: -len(suf)]
                break
        try:
            max_bytes = int(float(up) * mult)
        except ValueError:
            raise ValueError(f"bad retain size {size_part!r}") from None
    if age_part:
        suf, num = age_part[-1].lower(), age_part[:-1]
        if suf not in _AGE_SUFFIX:
            raise ValueError(f"bad retain age {age_part!r} "
                             f"(want s/m/h/d suffix)")
        try:
            max_age_s = float(num) * _AGE_SUFFIX[suf]
        except ValueError:
            raise ValueError(f"bad retain age {age_part!r}") from None
    return max_bytes, max_age_s


def _label_proc(snap: dict, proc: str) -> dict:
    """Relabel every series in one ``snapshot()`` dict with a
    ``proc=<role>`` label, so the fleet merge keeps per-role series
    distinct while still aggregating within a role."""
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    for kind in ("counters", "gauges", "histograms"):
        for flat, v in snap.get(kind, {}).items():
            name, labels = slo_mod.parse_labels(flat)
            labels["proc"] = proc
            out[kind][registry.flat_name(name, labels)] = v
    return out


def _sum_gauge(snap: dict, base: str) -> float:
    total = 0.0
    for flat, v in snap.get("gauges", {}).items():
        if slo_mod.parse_labels(flat)[0] == base:
            total += v
    return total


# ---------------------------------------------------------------------------
# server half
# ---------------------------------------------------------------------------

@dataclass
class _ProcState:
    """Everything the collector knows about one pushing process."""

    proc: str
    pid: int
    status: str = "STARTING"
    state: str = "ALIVE"            # ALIVE | EXITED | DEAD
    first_seen: float = 0.0
    last_seen: float = 0.0
    seq: int = 0
    lost_batches: int = 0
    spans: int = 0
    dropped: int = 0
    queue_depth: int = 0
    phase: str = ""
    phase_since: float = 0.0
    metrics: dict = field(default_factory=dict)   # latest raw snapshot
    open_spans: list = field(default_factory=list)
    span_file: Optional[object] = None


class ObsCollector:
    """The collector service impl plus its background evaluation loop.

    Thread-safety: gRPC handler threads mutate per-process state under
    ``_lock``; the eval loop reads under the same lock and does its
    span/file I/O outside it.
    """

    def __init__(self, out_dir: str, slo_config: Optional[dict] = None,
                 tick_s: float = 0.5, assemble_every_s: float = 2.0):
        self.out_dir = out_dir
        self.recv_dir = os.path.join(out_dir, "recv")
        os.makedirs(self.recv_dir, exist_ok=True)
        self.engine = slo_mod.SLOEngine(slo_config)
        self.tick_s = tick_s
        self.assemble_every_s = assemble_every_s
        self._lock = threading.Lock()
        self._procs: dict[tuple[str, int], _ProcState] = {}
        self._spans_total = 0
        self._ingest_drops = 0
        self._red_until = 0.0       # monotonic deadline of the red window
        self._red_reason = ""
        self._health = "green"
        self._stop = threading.Event()
        self._eval_thread: Optional[threading.Thread] = None
        self._own_file = None
        self.live_path = os.path.join(out_dir, "trace_live.json")
        self.live_report: dict = {}
        from electionguard_tpu.utils import knobs
        try:
            self.retain_bytes, self.retain_age_s = parse_retain(
                knobs.get_str("EGTPU_OBS_RETAIN"))
        except ValueError as e:
            log.warning("EGTPU_OBS_RETAIN ignored: %s", e)
            self.retain_bytes = self.retain_age_s = None
        self._rotated = registry.REGISTRY.counter("obs_rotated_files_total")

    # ---- ingest ------------------------------------------------------

    def push_telemetry(self, batch, context=None):
        from electionguard_tpu.publish import pb
        now = clock.monotonic()
        key = (batch.proc, int(batch.pid))
        hb = batch.heartbeat
        with self._lock:
            p = self._procs.get(key)
            if p is None:
                p = self._procs[key] = _ProcState(
                    proc=batch.proc, pid=int(batch.pid), first_seen=now)
                log.info("fleet: %s:%d joined", batch.proc, batch.pid)
            if batch.seq and p.seq and batch.seq > p.seq + 1:
                p.lost_batches += batch.seq - p.seq - 1
            p.seq = max(p.seq, int(batch.seq))
            p.last_seen = now
            if p.state == "DEAD":
                # a flagged-dead process pushing again was only slow —
                # resurrect it (the alert span stays in the timeline)
                log.warning("fleet: %s:%d heartbeats again after being "
                            "declared dead", p.proc, p.pid)
            p.state = "ALIVE"
            if hb.status:
                p.status = hb.status
            p.queue_depth = int(hb.queue_depth)
            p.dropped = int(hb.dropped_total)
            if hb.phase != p.phase:
                p.phase = hb.phase
                p.phase_since = now
            if batch.metrics_json:
                try:
                    p.metrics = json.loads(batch.metrics_json)
                except ValueError:
                    self._ingest_drops += 1
            closed, open_markers, drops = self._split_spans(
                batch.span_lines)
            self._ingest_drops += drops
            p.open_spans = open_markers
            p.spans += len(closed)
            self._spans_total += len(closed)
        # file I/O outside the lock: per-(proc,pid) files, one writer each
        if closed:
            self._append(p, "spans", closed)
        if batch.log_lines:
            self._append(p, "log", list(batch.log_lines))
        # persist the heartbeat stream too (single shared file: this is
        # the only writer): post-run trace analytics reads queue depths
        # and shard phases from it (obs/analyze.load_heartbeats)
        self._append_heartbeat(batch, hb)
        return pb.msg("TelemetryAck")(ok=True)

    def _split_spans(self, lines) -> tuple[list[str], list[dict], int]:
        """Pure split: also returns the unparseable-line count so the
        caller can account for it under the ingest lock."""
        closed: list[str] = []
        open_markers: list[dict] = []
        drops = 0
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                drops += 1
                continue
            if assemble.is_open(rec):
                open_markers.append(rec)
            else:
                closed.append(line)
        return closed, open_markers, drops

    def _append(self, p: _ProcState, kind: str, lines: list[str]) -> None:
        path = os.path.join(self.recv_dir,
                            f"{kind}-{p.proc}-{p.pid}.jsonl")
        try:
            with open(path, "a") as f:
                f.write("\n".join(lines) + "\n")
        except OSError as e:
            log.warning("receive dir write failed: %s", e)

    def _append_heartbeat(self, batch, hb) -> None:
        rec = {"t_us": int(clock.now() * 1e6), "proc": batch.proc,
               "pid": int(batch.pid), "status": hb.status,
               "phase": hb.phase, "queue_depth": int(hb.queue_depth),
               "uptime_s": round(float(hb.uptime_s), 3)}
        path = os.path.join(self.recv_dir, "heartbeats.jsonl")
        try:
            with open(path, "a") as f:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        except OSError as e:
            log.warning("receive dir write failed: %s", e)

    def _ingest_own_span(self, line: dict) -> None:
        """Trace export hook: the collector's OWN spans (slo.eval,
        slo.alert, rpc.server.*) join the receive dir too, so the live
        assembly covers the whole fleet including this process."""
        with self._lock:
            if self._own_file is None:
                self._own_file = open(os.path.join(
                    self.recv_dir,
                    f"spans-{trace.proc_name()}-{os.getpid()}.jsonl"), "a")
            self._own_file.write(
                json.dumps(line, separators=(",", ":")) + "\n")
            self._own_file.flush()

    # ---- read paths --------------------------------------------------

    def fleet_snapshot(self) -> dict:
        """The fleet-merged metrics snapshot: latest per process (series
        relabeled ``proc=<role>``) plus the collector's own registries."""
        with self._lock:
            per_proc = [(p.proc, p.metrics) for p in self._procs.values()
                        if p.metrics]
        snaps = [_label_proc(m, proc) for proc, m in per_proc]
        snaps.append(_label_proc(registry.merged_snapshot(),
                                 trace.proc_name()))
        return registry.MetricsRegistry.merge(snaps)

    def fleet_text(self) -> str:
        """Prometheus exposition of the fleet snapshot (the collector's
        ``/metrics`` — ONE scrape for the whole run)."""
        return registry.prometheus_text_of(self.fleet_snapshot())

    def get_metrics(self, request=None, context=None):
        return registry.proto_of(self.fleet_snapshot())

    def get_fleet_status(self, request=None, context=None):
        from electionguard_tpu.publish import pb
        now = clock.monotonic()
        with self._lock:
            resp = pb.msg("FleetStatusResponse")(
                health=self._health,
                spans_total=self._spans_total,
                dropped_total=self._ingest_drops,
                slo_evals=self.engine.evals)
            procs = sorted(self._procs.values(),
                           key=lambda p: (p.proc, p.pid))
            for p in procs:
                resp.processes.add(
                    proc=p.proc, pid=p.pid, state=p.state, status=p.status,
                    heartbeat_age_s=round(now - p.last_seen, 3),
                    queue_depth=p.queue_depth, phase=p.phase,
                    p99_ms=self._proc_p99(p), spans=p.spans,
                    dropped=p.dropped)
        for a in self.engine.fired[-16:]:
            resp.alerts.append(a.summary())
        return resp

    @staticmethod
    def _proc_p99(p: _ProcState) -> float:
        worst = 0.0
        for flat, h in p.metrics.get("histograms", {}).items():
            if slo_mod.parse_labels(flat)[0] == "request_latency_ms":
                worst = max(worst, slo_mod.histogram_quantile(h, 0.99))
        return worst

    def finish(self, request=None, context=None):
        from electionguard_tpu.publish import pb
        self.stop()
        return pb.msg("BoolResponse")(ok=True)

    # ---- evaluation loop ---------------------------------------------

    def start(self) -> None:
        trace.add_export_hook(self._ingest_own_span)
        self._eval_thread = threading.Thread(
            target=self._eval_loop, daemon=True, name="obs-collector-eval")
        clock.start_thread(self._eval_thread)

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        t = self._eval_thread
        if t is not None and t is not threading.current_thread():
            clock.join_thread(t, timeout=5.0)
        self._assemble_live()
        trace.remove_export_hook(self._ingest_own_span)

    def _eval_loop(self) -> None:
        last_assemble = 0.0
        while not clock.wait_event(self._stop, self.tick_s):
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("slo evaluation failed")
            try:
                self._enforce_retention()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("retention enforcement failed")
            now = clock.monotonic()
            if now - last_assemble >= self.assemble_every_s:
                last_assemble = now
                try:
                    self._assemble_live()
                except Exception:  # noqa: BLE001
                    log.exception("live assembly failed")

    def evaluate_once(self, now: Optional[float] = None) -> list:
        """One SLO tick (public for tests and the chaos harness):
        evaluate, emit the ``slo.eval`` span, turn fired alerts into
        ``slo.alert`` spans and fleet-state transitions."""
        now = clock.monotonic() if now is None else now
        hb_cfg = self.engine.config["heartbeat"]
        window = hb_cfg["interval_s"] * hb_cfg["miss_threshold"]
        with self._lock:
            rows = []
            for p in self._procs.values():
                age = now - p.last_seen
                if (p.state == "ALIVE" and p.status == "EXITING"
                        and age > window):
                    p.state = "EXITED"   # clean goodbye, then silence
                    log.info("fleet: %s:%d exited cleanly", p.proc, p.pid)
                rows.append({"proc": p.proc, "pid": p.pid,
                             "state": p.state, "status": p.status,
                             "heartbeat_age_s": age,
                             "queue_depth": p.queue_depth,
                             "phase": p.phase,
                             "phase_age_s": now - p.phase_since})
        metrics = self.fleet_snapshot()
        with trace.span("slo.eval") as s:
            fired = self.engine.evaluate(now, metrics, rows)
            s.set("evals", self.engine.evals)
            s.set("procs", len(rows))
            s.set("fired", len(fired))
        for a in fired:
            self._on_alert(a, now)
        color, reasons = self.engine.health(now)
        if now < self._red_until:
            color = "red"
            if self._red_reason and self._red_reason not in reasons:
                reasons.append(self._red_reason)
        if color != self._health:
            log.warning("fleet: health %s -> %s%s", self._health, color,
                        f" ({'; '.join(reasons)})" if reasons else "")
            self._health = color
        return fired

    def _on_alert(self, alert, now: float) -> None:
        log.warning("slo alert %s", alert.summary())
        with trace.span("slo.alert",
                        {"kind": alert.kind, "subject": alert.subject,
                         "detail": alert.detail, **alert.attrs}):
            pass
        if alert.kind == "heartbeat_miss":
            with self._lock:
                for p in self._procs.values():
                    if p.proc == alert.subject and p.state == "ALIVE":
                        p.state = "DEAD"
                        log.warning("fleet: %s:%d declared dead "
                                    "(detection %.2fs)", p.proc, p.pid,
                                    alert.attrs.get("detection_s", 0.0))
            self._red_until = max(
                self._red_until,
                now + self.engine.config["heartbeat"]["dead_red_for_s"])
            self._red_reason = alert.summary()

    def _enforce_retention(self, now: Optional[float] = None) -> int:
        """Apply the ``EGTPU_OBS_RETAIN`` cap to the receive dir:
        delete every ``*.jsonl`` past the age cap, then the oldest
        files (by mtime) until total size fits the size cap.  Deleted
        streams reopen on their next append, so a long sweep keeps its
        retention-window tail.  Returns the number of files rotated
        (also counted by ``obs_rotated_files_total``)."""
        if self.retain_bytes is None and self.retain_age_s is None:
            return 0
        now = clock.now() if now is None else now
        files = []
        try:
            names = os.listdir(self.recv_dir)
        except OSError:
            return 0
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(self.recv_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            files.append((st.st_mtime, st.st_size, path))
        files.sort()                      # oldest first
        total = sum(sz for _, sz, _ in files)
        rotated = 0
        with self._lock:
            own_path = (None if self._own_file is None
                        else self._own_file.name)
        for mtime, size, path in files:
            too_old = (self.retain_age_s is not None
                       and now - mtime > self.retain_age_s)
            over_cap = (self.retain_bytes is not None
                        and total > self.retain_bytes)
            if not too_old and not over_cap:
                break                     # everything newer fits too
            try:
                os.remove(path)
            except OSError as e:
                log.warning("retention remove failed: %s", e)
                continue
            if path == own_path:
                # reopen on next own-span export instead of writing to
                # the unlinked inode forever
                with self._lock:
                    if self._own_file is not None:
                        self._own_file.close()
                        self._own_file = None
            total -= size
            rotated += 1
        if rotated:
            self._rotated.inc(rotated)
            log.info("retention: rotated %d receive-dir file(s) "
                     "(cap %s bytes / %s s)", rotated,
                     self.retain_bytes, self.retain_age_s)
        return rotated

    def _assemble_live(self) -> None:
        """Re-merge the receive dir plus every process's in-flight span
        markers into ``trace_live.json`` — a strict-valid mid-run
        timeline (open spans are reported, not failed, by the
        assembler)."""
        with self._lock:
            extra = [rec for p in self._procs.values()
                     for rec in p.open_spans]
        extra += trace.open_span_records()   # the collector's own
        # persist the in-flight markers as a spans file too, so a PLAIN
        # file-based assembly of the receive dir (tools/assemble_trace.py
        # -dir <out>/obs/recv, mid-run or after a died run) resolves
        # every in-flight parent without this process's memory
        marker_path = os.path.join(self.recv_dir,
                                   "spans-open-markers.jsonl")
        tmp = marker_path + ".tmp"
        with open(tmp, "w") as f:
            for rec in extra:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        os.replace(tmp, marker_path)
        self.live_report = assemble.merge_dir(
            self.recv_dir, self.live_path, extra_spans=extra)
        # persist the validation report beside the timeline so a dead
        # run's consumers (and the chaos tests) can check strictness
        # without reconstructing the in-memory open markers
        report_path = os.path.join(self.out_dir, "trace_live_report.json")
        with open(report_path, "w") as f:
            json.dump(self.live_report, f, indent=2, sort_keys=True)

    # ---- wiring ------------------------------------------------------

    def service(self):
        from electionguard_tpu.remote import rpc_util
        return rpc_util.generic_service("ObsCollectorService", {
            "pushTelemetry": self.push_telemetry,
            "getFleetStatus": self.get_fleet_status,
            "finish": self.finish,
            "getMetrics": self.get_metrics,
        })


def serve(port: int = 0, out_dir: str = ".",
          slo_config: Optional[dict] = None,
          http_port: Optional[int] = None):
    """Build + start a collector server; returns
    (collector, grpc_server, bound_port, http_bound_or_None)."""
    from electionguard_tpu.obs import httpd
    from electionguard_tpu.remote import rpc_util
    collector = ObsCollector(out_dir, slo_config)
    server, bound = rpc_util.make_server(port)
    server.add_generic_rpc_handlers((collector.service(),))
    server.start()
    collector.start()
    http_bound = None
    if http_port is not None:
        _, http_bound = httpd.start(http_port,
                                    text_fn=collector.fleet_text)
    log.info("obs collector on :%d (fleet /metrics on %s)", bound,
             http_bound)
    return collector, server, bound, http_bound


# ---------------------------------------------------------------------------
# client half
# ---------------------------------------------------------------------------

class TelemetryClient:
    """Streams this process's telemetry to the collector.

    Hot-path contract: the trace/slog hooks only append to a bounded
    deque under a lock (drop-oldest, counted in ``obs_dropped_total``);
    everything else happens on the pusher thread.
    """

    def __init__(self, addr: str, interval_s: float = 1.0,
                 max_buffer: int = DEFAULT_BUFFER):
        from electionguard_tpu.remote import rpc_util
        self.addr = addr
        self.interval_s = interval_s
        self.max_buffer = max_buffer
        self._buf: list[tuple[str, str]] = []   # (kind, jsonl line)
        self._buf_lock = threading.Lock()
        self._dropped = registry.REGISTRY.counter("obs_dropped_total")
        self._stub = rpc_util.Stub(
            rpc_util.make_plain_channel(addr), "ObsCollectorService")
        self._seq = 0
        self._t0 = clock.monotonic()
        self._status = "STARTING"
        self._phase = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._push_failures = 0

    # ---- hooks (exporting threads: bounded append only) --------------

    def _on_span(self, line: dict) -> None:
        self._enqueue("span", json.dumps(line, separators=(",", ":")))

    def _on_log(self, line: dict) -> None:
        self._enqueue("log", json.dumps(line, separators=(",", ":")))

    def _enqueue(self, kind: str, line: str) -> None:
        with self._buf_lock:
            if len(self._buf) >= self.max_buffer:
                del self._buf[0]
                self._dropped.inc()
            self._buf.append((kind, line))

    # ---- control -----------------------------------------------------

    def set_phase(self, phase: str) -> None:
        self._phase = phase
        self._status = "SERVING"

    def start(self) -> None:
        trace.add_export_hook(self._on_span)
        trace.track_open_spans(True)
        slog.ensure_forwarding()
        slog.add_hook(self._on_log)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="obs-telemetry-push")
        clock.start_thread(self._thread)
        atexit.register(self.close)

    def close(self) -> None:
        """Final flush with the EXITING goodbye — how a clean shutdown
        differs from a death the collector must alert on."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._status = "EXITING"
        trace.remove_export_hook(self._on_span)
        slog.remove_hook(self._on_log)
        t = self._thread
        if t is not None and t is not threading.current_thread():
            clock.join_thread(t, timeout=2.0)
        try:
            self._push_once(timeout=3.0)
        except Exception:  # noqa: BLE001 — exit must not fail on telemetry
            pass

    # ---- pusher thread -----------------------------------------------

    def _run(self) -> None:
        while not clock.wait_event(self._stop, self.interval_s):
            try:
                self._push_once()
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                self._push_failures += 1

    def _push_once(self, timeout: Optional[float] = None) -> None:
        from electionguard_tpu.publish import pb
        with self._buf_lock:
            batch_lines = self._buf[:BATCH_LINES]
            del self._buf[:BATCH_LINES]
        if self._status == "STARTING" and self._seq > 0:
            self._status = "SERVING"
        snap = registry.merged_snapshot()
        self._seq += 1
        span_lines = [ln for k, ln in batch_lines if k == "span"]
        span_lines += [json.dumps(rec, separators=(",", ":"))
                       for rec in trace.open_span_records()]
        msg = pb.msg("TelemetryBatch")(
            proc=trace.proc_name(), pid=os.getpid(),
            trace_id=trace.trace_id(), seq=self._seq,
            span_lines=span_lines,
            log_lines=[ln for k, ln in batch_lines if k == "log"],
            metrics_json=json.dumps(snap),
            heartbeat=pb.msg("ObsHeartbeat")(
                status=self._status,
                uptime_s=clock.monotonic() - self._t0,
                queue_depth=int(_sum_gauge(snap, "queue_depth")),
                phase=self._phase,
                dropped_total=self._dropped.value))
        try:
            # short default deadline: a wedged collector must cost the
            # pusher loop seconds, not the control class's full 30
            self._stub.call("pushTelemetry", msg,
                            timeout=5.0 if timeout is None else timeout)
        except Exception:
            # push the drained lines back (front), bounded: cumulative
            # metrics lose nothing, but span/log lines would
            with self._buf_lock:
                room = self.max_buffer - len(self._buf)
                restored = batch_lines[-room:] if room > 0 else []
                self._dropped.inc(len(batch_lines) - len(restored))
                self._buf[:0] = restored
            raise


_client: Optional[TelemetryClient] = None
_client_lock = threading.Lock()


def client_from_env() -> Optional[TelemetryClient]:
    """Start the per-process telemetry client when
    ``EGTPU_OBS_COLLECTOR=<host:port>`` is set (idempotent)."""
    global _client
    addr = os.environ.get("EGTPU_OBS_COLLECTOR", "")
    if not addr:
        return None
    with _client_lock:
        if _client is None:
            from electionguard_tpu.utils import knobs
            interval = knobs.get_float("EGTPU_OBS_PUSH_INTERVAL")
            _client = TelemetryClient(addr, interval_s=interval)
            _client.start()
        return _client


def set_phase(phase: str) -> None:
    """Report a progress phase on this process's heartbeat (no-op when
    no collector is configured) — the mission-control board and the
    stage-lag SLO read it."""
    c = _client
    if c is not None:
        c.set_phase(phase)


def _reset_for_tests() -> None:
    global _client
    c = _client
    _client = None
    if c is not None:
        c.close()

"""Prometheus text exposition endpoint.

A tiny threaded HTTP server serving ``/metrics`` (the merged exposition
of the default registry plus every ``expose()``d one — see
``obs.registry``) and ``/healthz``.  One per process; port 0 binds an
ephemeral port (the bound port is returned and logged, so multi-process
runs on one host never collide).

Enable per process with ``EGTPU_OBS_HTTP=<port>`` (``obs.init_from_env``)
or programmatically with ``start()``; then::

    curl -s localhost:<port>/metrics
"""

from __future__ import annotations

import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from electionguard_tpu.obs import registry

log = logging.getLogger("egtpu.obs.httpd")


class _Handler(BaseHTTPRequestHandler):
    #: what /metrics serves; overridable per server instance (the obs
    #: collector serves the FLEET-merged exposition instead of this
    #: process's own registries)
    text_fn = staticmethod(registry.prometheus_text_all)

    def do_GET(self):  # noqa: N802 — http.server API
        if self.path.split("?", 1)[0] == "/metrics":
            body = self.text_fn().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path.split("?", 1)[0] == "/healthz":
            body, ctype = b"ok\n", "text/plain"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes are not stdout events
        log.debug("http %s", fmt % args)


def start(port: int = 0, addr: str = "127.0.0.1",
          text_fn=None) -> tuple[ThreadingHTTPServer, int]:
    """Serve /metrics on ``addr:port`` (0 = ephemeral) from a daemon
    thread; returns (server, bound_port).  ``text_fn`` overrides what
    /metrics serves (default: this process's merged exposition)."""
    handler = _Handler
    if text_fn is not None:
        handler = type("_Handler", (_Handler,),
                       {"text_fn": staticmethod(text_fn)})
    server = ThreadingHTTPServer((addr, port), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="obs-metrics-http")
    t.start()
    bound = server.server_address[1]
    log.info("metrics endpoint on http://%s:%d/metrics", addr, bound)
    return server, bound


_started: Optional[tuple[ThreadingHTTPServer, int]] = None
_start_lock = threading.Lock()


def maybe_start_from_env() -> Optional[int]:
    """Start the endpoint when ``EGTPU_OBS_HTTP=<port>`` is set
    (idempotent); returns the bound port or None."""
    global _started
    spec = os.environ.get("EGTPU_OBS_HTTP", "")
    if not spec:
        return None
    with _start_lock:
        if _started is None:
            try:
                _started = start(int(spec))
            except (ValueError, OSError) as e:
                log.warning("EGTPU_OBS_HTTP=%r: endpoint not started: %s",
                            spec, e)
                return None
        return _started[1]

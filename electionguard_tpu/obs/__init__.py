"""Observability: metrics registry, distributed tracing, structured logs.

The one import surface for the rest of the codebase::

    from electionguard_tpu import obs
    obs.init_from_env()           # CLI startup (cli/common.setup_logging)
    with obs.span("phase.encrypt", {"n": n}): ...
    obs.REGISTRY.counter("things_total").inc()

Env vars (all off by default; see README "Observability"):

* ``EGTPU_OBS_TRACE=<dir>``   — export spans as JSONL under <dir>
* ``EGTPU_OBS_TRACE_ID=<hex>``— join an existing trace (set by e2e)
* ``EGTPU_OBS_PARENT_SPAN=<id>`` — parent of this process's root span
* ``EGTPU_OBS_PROC=<name>``   — process name in spans/logs
* ``EGTPU_OBS_HTTP=<port>``   — Prometheus /metrics endpoint (0=ephemeral)
* ``EGTPU_OBS_LOG=<dir>``     — JSONL log mirror (defaults to trace dir)
* ``EGTPU_OBS_COLLECTOR=<host:port>`` — stream spans/logs/metrics/
  heartbeats to the run's obs collector (obs.collector)
"""

from __future__ import annotations

from electionguard_tpu.obs.registry import (REGISTRY,  # noqa: F401
                                            MetricsRegistry,
                                            election_labels, expose,
                                            merged_snapshot,
                                            merged_to_proto,
                                            prometheus_text_all)
from electionguard_tpu.obs.trace import (enable_from_env,  # noqa: F401
                                         enabled, span)


def init_from_env() -> dict:
    """Light up every env-selected obs surface (idempotent); called once
    per process from ``cli/common.setup_logging``.  Returns what was
    enabled, for the caller's startup log line."""
    from electionguard_tpu.obs import collector, httpd, jaxmon, slog, trace
    info: dict = {}
    if trace.enable_from_env():
        info["trace_dir"] = trace._dir
        info["trace_id"] = trace.trace_id()
        jaxmon.install()   # compile spans need the listener
    handler = slog.install_from_env()
    if handler is not None:
        info["log"] = handler.path
    port = httpd.maybe_start_from_env()
    if port is not None:
        info["metrics_port"] = port
    client = collector.client_from_env()
    if client is not None:
        info["collector"] = client.addr
    return info


def set_phase(phase: str) -> None:
    """Report a progress phase on this process's collector heartbeat
    (no-op without ``EGTPU_OBS_COLLECTOR``)."""
    from electionguard_tpu.obs import collector
    collector.set_phase(phase)

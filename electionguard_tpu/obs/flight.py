"""Render a RunAnalysis into FLIGHT_REPORT.md.

A flight report is the post-run evidence bundle for one traced
election: where the wall-clock went (critical path + attribution
buckets), whether the fleet was balanced (per-shard table, straggler
section), whether the run obeyed its SLOs, and what the device spent
compiling vs computing.  ``workflow/e2e.py -flightReport`` drops one
next to ``trace.json`` after every run; ``tools/egreport.py`` produces
one from any trace dir after the fact.

The renderer is pure (analysis in, markdown out) so tests can assert
on sections without touching the filesystem.
"""

from __future__ import annotations

import os
from typing import Optional

from electionguard_tpu.obs import analyze as analyze_mod


def _ms(us: float) -> str:
    if us >= 10_000_000:
        return f"{us / 1e6:.1f} s"
    return f"{us / 1e3:.1f} ms"


def _pct(part: float, whole: float) -> str:
    return f"{100.0 * part / whole:.1f}%" if whole else "n/a"


def render(a: analyze_mod.RunAnalysis) -> str:
    """Markdown flight report for one analyzed run."""
    lines: list[str] = []
    w = lines.append
    w("# Flight report")
    w("")
    w(f"Trace dir: `{a.trace_dir}`")
    w("")

    # ---- run summary --------------------------------------------------
    w("## Run summary")
    w("")
    val = a.validation or {}
    w(f"- spans: **{len(a.spans)}** across "
      f"{len(val.get('processes', []))} process(es)")
    if val.get("trace_ids"):
        w(f"- trace id(s): {', '.join(val['trace_ids'])}")
    if a.root is not None:
        w(f"- root: `{a.root['name']}` in `{a.root['proc']}` — "
          f"wall-clock **{_ms(a.wall_us)}**")
    if val.get("rpc_pairs") or val.get("rpc_server_unpaired"):
        w(f"- rpc: {val.get('rpc_pairs', 0)} paired, "
          f"{val.get('rpc_server_unpaired', 0)} unpaired server span(s)")
    if a.warnings:
        w(f"- **partial report** — {len(a.warnings)} warning(s):")
        for msg in a.warnings:
            w(f"  - {msg}")
    w("")

    # ---- critical path ------------------------------------------------
    w("## Critical path")
    w("")
    if not a.path:
        w("_Critical path unavailable (no closed process-root span)._")
        w("")
    else:
        w("| # | span | process | self on path |")
        w("|--:|------|---------|-------------:|")
        for i, row in enumerate(a.path, 1):
            w(f"| {i} | `{row['name']}` | {row['proc']} | "
              f"{_ms(row['dur_us'])} |")
        w("")
        w(f"Critical path total: **{_ms(a.path_total_us)}** "
          f"({_pct(a.path_total_us, a.wall_us)} of run wall-clock "
          f"{_ms(a.wall_us)}).")
        w("")

    # ---- attribution --------------------------------------------------
    if a.buckets:
        w("## Wall-clock attribution (self time)")
        w("")
        w("| phase | process | category | self time | % of wall |")
        w("|-------|---------|----------|----------:|----------:|")
        total_self = sum(a.buckets.values())
        rows = sorted(a.buckets.items(), key=lambda kv: -kv[1])
        for (phase, proc, cat), us in rows:
            if us == 0:
                continue
            w(f"| {phase} | {proc} | {cat} | {_ms(us)} | "
              f"{_pct(us, a.wall_us)} |")
        w("")
        by_cat: dict[str, int] = {}
        for (_, _, cat), us in a.buckets.items():
            by_cat[cat] = by_cat.get(cat, 0) + us
        cats = ", ".join(f"{c} {_pct(us, total_self)}"
                         for c, us in sorted(by_cat.items(),
                                             key=lambda kv: -kv[1]) if us)
        w(f"Category split of all self time: {cats}.")
        w("")

    # ---- top self-time spans ------------------------------------------
    if a.top_self:
        w(f"## Top {len(a.top_self)} self-time spans")
        w("")
        w("| span | process | self time |")
        w("|------|---------|----------:|")
        for s, us in a.top_self:
            w(f"| `{s['name']}` | {s['proc']} | {_ms(us)} |")
        w("")

    # ---- shard balance ------------------------------------------------
    w("## Shard balance")
    w("")
    if not a.shards:
        w("_No device-batch spans (run had no serving/fabric workers)._")
        w("")
    else:
        w("| process | shard | batches | total | mean | max | "
          "queue max |")
        w("|---------|------:|--------:|------:|-----:|----:|"
          "----------:|")
        for s in a.shards:
            shard = "-" if s.shard is None else str(s.shard)
            qmax = "-" if s.queue_max is None else str(s.queue_max)
            w(f"| {s.proc} | {shard} | {s.n_batches} | "
              f"{_ms(s.total_us)} | {_ms(s.mean_us)} | {_ms(s.max_us)} "
              f"| {qmax} |")
        w("")
        if a.stragglers:
            w("### Stragglers")
            w("")
            for st in a.stragglers:
                w(f"- **{st['proc']}**"
                  + (f" (shard {st['shard']})"
                     if st.get("shard") is not None else "")
                  + f": mean device batch {_ms(st['mean_us'])} vs fleet "
                    f"median {_ms(st['fleet_median_us'])} "
                    f"({st['ratio']}x)")
            w("")
        else:
            w("No stragglers (all workers within "
              "EGTPU_FLIGHT_STRAGGLER_RATIO of the fleet median).")
            w("")

    # ---- compile / device-time summary --------------------------------
    w("## Compile & device time")
    w("")
    device_us = sum(us for (_, _, c), us in a.buckets.items()
                    if c == "device")
    w(f"- device compute self time: {_ms(device_us)} "
      f"({_pct(device_us, a.wall_us)} of wall)")
    w(f"- compiles: {a.recompiles_total} event(s), "
      f"{_ms(a.recompile_us)} total")
    if a.midrun_recompiles:
        w(f"- **mid-run recompiles: {len(a.midrun_recompiles)}** "
          f"(after the first device batch) in: "
          + ", ".join(sorted({m['proc'] for m in a.midrun_recompiles})))
    else:
        w("- no mid-run recompiles (prewarm covered every shape)")
    w("")

    # ---- SLO verdicts -------------------------------------------------
    w("## SLO verdicts")
    w("")
    if a.queue_max:
        worst = max(a.queue_max.values())
        verdict = "FAIL" if any(p["kind"] == "queue-saturation"
                                for p in a.antipatterns) else "PASS"
        w(f"- queue depth: **{verdict}** (max observed {worst})")
    else:
        w("- queue depth: no heartbeat data")
    if a.alerts:
        w(f"- alerts recorded during the run: **{len(a.alerts)}**")
        for al in a.alerts:
            attrs = al.get("attrs") or {}
            w(f"  - {attrs.get('kind', '?')} on "
              f"{attrs.get('subject', '?')}")
    else:
        w("- alerts recorded during the run: none")
    mid = "FAIL" if a.midrun_recompiles else "PASS"
    w(f"- recompile discipline: **{mid}**")
    strag = "FAIL" if a.stragglers else \
        ("PASS" if len(a.shards) >= 2 else "n/a (single worker)")
    w(f"- shard balance: **{strag}**")
    w("")

    # ---- anti-patterns ------------------------------------------------
    if a.antipatterns:
        w("## Anti-patterns")
        w("")
        for p in a.antipatterns:
            w(f"- `{p['kind']}` on **{p['subject']}**: {p['detail']}")
        w("")

    # ---- predicted vs actual (capacity model) -------------------------
    # best-effort: needs a tracked CAPACITY.json (tools/egplan.py) AND
    # phase-attributable buckets in this run; silent otherwise
    cmp_rows = None
    try:
        from electionguard_tpu.obs import capacity
        cmp_rows = capacity.phase_comparison(a)
    except Exception:  # noqa: BLE001 — the report never fails on this
        cmp_rows = None
    if cmp_rows:
        w("## Predicted vs actual (capacity model)")
        w("")
        w(f"Model: `{cmp_rows['source']}` — shares of pipeline "
          f"wall-clock, this run vs the tracked prediction.")
        w("")
        w("| phase | predicted share | actual share | delta |")
        w("|-------|----------------:|-------------:|------:|")
        for r in cmp_rows["rows"]:
            w(f"| {r['phase']} | {r['predicted_share'] * 100:.1f}% | "
              f"{r['actual_share'] * 100:.1f}% | "
              f"{r['delta_pp']:+.1f}pp |")
        w("")
        val2 = cmp_rows.get("validation")
        if val2 and val2.get("max_err_pct") is not None:
            w(f"Last model validation: max err "
              f"{val2['max_err_pct']:.1f}% over {val2['n_checked']} "
              f"measured config(s) within a "
              f"{val2['tolerance_pct']:.0f}% band "
              f"(**{'PASS' if val2.get('pass') else 'FAIL'}**).")
            w("")

    return "\n".join(lines) + "\n"


def write_report(trace_dir: str, out_path: Optional[str] = None,
                 top_n: Optional[int] = None) -> tuple[str, "analyze_mod.RunAnalysis"]:
    """Analyze ``trace_dir`` and write FLIGHT_REPORT.md; returns
    ``(out_path, analysis)``."""
    a = analyze_mod.analyze(trace_dir, top_n=top_n)
    if out_path is None:
        out_path = os.path.join(os.path.dirname(trace_dir.rstrip("/"))
                                or ".", "FLIGHT_REPORT.md")
    with open(out_path, "w") as f:
        f.write(render(a))
    return out_path, a

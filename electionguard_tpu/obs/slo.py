"""Declarative fleet SLOs evaluated with fast+slow burn-rate windows.

The collector (obs/collector.py) calls ``SLOEngine.evaluate`` once per
tick with the fleet-merged metrics snapshot and the per-process
heartbeat table; the engine returns the alerts that FIRED this tick
(edge-triggered: an alert fires when its condition first becomes true
and cannot re-fire until the condition has cleared).  The collector
turns each fired alert into a first-class ``slo.alert`` span in the run
timeline and folds active alerts into the fleet health rollup.

Config is a plain dict (JSON-able), deep-merged over ``DEFAULT_SLO``;
``load_config`` accepts inline JSON, ``@file``, or the ``EGTPU_OBS_SLO``
env var.  Objectives:

* ``availability`` — rpc success ratio per deadline class
  (registration/control/exchange/data), alerting on the standard
  multiwindow multi-burn-rate rule: the error budget must be burning
  faster than ``fast_burn`` over the fast window AND faster than
  ``slow_burn`` over the slow window (Google SRE workbook ch. 5) — the
  fast window gives detection latency, the slow window stops a single
  blip from paging;
* ``serving_p99_ms`` — p99 of the serving latency histograms in the
  merged snapshot;
* ``queue_depth_max`` — any process heartbeating a deeper admission
  queue alerts;
* ``stage_lag_s`` — a SERVING process whose reported phase has not
  advanced for this long alerts (a wedged mix/verify stage);
* ``audit_lag_frames`` — the live verification plane (verify/live)
  reports ``live_audit_lag_frames`` (ballot frames published but not
  yet verified); a lag past the objective means the auditor has fallen
  behind the election it is supposed to be watching.  ``objective:
  null`` (the default) resolves the ``EGTPU_LIVE_AUDIT_LAG_MAX`` knob;
* ``noisy_neighbor`` — multi-tenant attribution: per-election device
  time (``tenant_device_ms_total{election=...}``, written by the serve
  worker) is joined against per-election SLO burn.  When some election
  is burning a tenant-scoped objective (a VICTIM) while ANOTHER
  election holds more than ``share`` of the fleet's device time over
  the trailing ``window_s`` (the OFFENDER), the alert names the
  offender — the tenant to throttle — not the victim that paged.
  ``share``/``window_s`` default to the ``EGTPU_TENANT_NOISY_SHARE`` /
  ``EGTPU_TENANT_NOISY_WINDOW`` knobs;
* ``heartbeat`` — liveness: a process that misses ``miss_threshold``
  consecutive heartbeat intervals without having said goodbye
  (status EXITING) is declared dead.  This fires in
  ``interval_s * miss_threshold`` seconds — far inside any rpc deadline
  class, so the fleet learns about a SIGKILL'd trustee before its next
  rpc would time out.
"""

from __future__ import annotations

import json
import os
import re
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

#: one k="v" pair in a flat series name, value possibly escaped
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:\\.|[^"\\])*)"')

DEFAULT_SLO: dict = {
    "availability": {
        # success-ratio objective per rpc deadline class
        "objective": {"registration": 0.99, "control": 0.99,
                      "exchange": 0.99, "data": 0.99},
        "fast_window_s": 30.0,
        "slow_window_s": 300.0,
        "fast_burn": 14.0,
        "slow_burn": 6.0,
    },
    "serving_p99_ms": {
        "objective": 5000.0,
        # histogram base names checked against the merged snapshot
        "histograms": ["request_latency_ms"],
        # tenant-scoped overrides: {election_id: objective_ms}.  Every
        # election-labeled latency series is already checked separately
        # (one SLO instance per tenant); this pins a DIFFERENT objective
        # for specific elections on the same fleet.
        "per_election": {},
    },
    "noisy_neighbor": {
        # None -> resolved from EGTPU_TENANT_NOISY_SHARE /
        # EGTPU_TENANT_NOISY_WINDOW at evaluation time
        "share": None,
        "window_s": None,
    },
    "queue_depth_max": 256,
    "stage_lag_s": 300.0,
    "audit_lag_frames": {
        # None -> resolved from the EGTPU_LIVE_AUDIT_LAG_MAX knob at
        # evaluation time (config JSON may still pin a number)
        "objective": None,
    },
    "heartbeat": {
        "interval_s": 1.0,
        "miss_threshold": 3,
        # a dead process keeps the fleet red for this long after its
        # alert fires, then becomes recorded history (the alert span
        # stays in the timeline; a requeued/replaced role turns green)
        "dead_red_for_s": 10.0,
    },
}


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def load_config(spec: Optional[str] = None) -> dict:
    """SLO config: ``spec`` (or ``EGTPU_OBS_SLO``) is inline JSON or
    ``@path`` to a JSON file, deep-merged over ``DEFAULT_SLO``."""
    spec = spec if spec is not None else os.environ.get("EGTPU_OBS_SLO", "")
    if not spec:
        return _deep_merge(DEFAULT_SLO, {})
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            spec = f.read()
    return _deep_merge(DEFAULT_SLO, json.loads(spec))


def parse_labels(flat: str) -> tuple[str, dict]:
    """Invert ``registry.flat_name``: ``name{k="v",...}`` -> (name, {k: v}).

    Values are unescaped (flat_name escapes ``\\``, ``"`` and newline),
    so a round trip through a snapshot preserves arbitrary label
    values — including ones containing ``,`` or ``"``."""
    if "{" not in flat:
        return flat, {}
    from electionguard_tpu.obs import registry as _reg
    name, rest = flat.split("{", 1)
    labels = {k: _reg.unescape_label_value(v)
              for k, v in _LABEL_RE.findall(rest.rstrip("}"))}
    return name, labels


def histogram_quantile(hist: dict, q: float) -> float:
    """Upper bucket-bound estimate of the q-quantile of one histogram
    snapshot dict ({bounds, counts, count})."""
    n = hist.get("count", 0)
    if not n:
        return 0.0
    target = q * n
    seen = 0
    bounds = hist["bounds"]
    for i, c in enumerate(hist["counts"]):
        seen += c
        if seen >= target:
            return bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1] if bounds else 0.0


@dataclass
class Alert:
    """One fired SLO violation.  ``key`` dedupes re-fires; ``attrs``
    lands verbatim on the alert span."""

    kind: str       # heartbeat_miss | availability_burn | serving_p99 |
    #                 queue_depth | stage_lag | audit_lag | noisy_neighbor
    subject: str    # process role / deadline class / histogram name
    detail: str
    t: float
    attrs: dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.kind}:{self.subject}"

    def summary(self) -> str:
        return f"[{self.kind}] {self.subject}: {self.detail}"


class SLOEngine:
    """Stateful evaluator: keeps the availability sample history the
    burn-rate windows need, the edge-trigger state per alert key, and
    the fired-alert history the fleet rollup reads."""

    def __init__(self, config: Optional[dict] = None,
                 method_class: Optional[Callable[[str], str]] = None):
        self.config = config if config is not None else load_config()
        self.evals = 0
        self.fired: list[Alert] = []      # full history, in fire order
        self._active: dict[str, Alert] = {}
        #: per deadline class: deque[(t, calls, failures)] cumulative
        self._avail: dict[str, deque] = {}
        #: per election: deque[(t, cumulative device ms)] — the trailing
        #: window the noisy-neighbor share is computed over
        self._device_ms: dict[str, deque] = {}
        self._method_class = method_class or _default_method_class

    # ---- evaluation --------------------------------------------------

    def evaluate(self, t: float, metrics: dict,
                 processes: list[dict]) -> list[Alert]:
        """One tick.  ``metrics`` is the fleet-merged ``snapshot()``
        dict; ``processes`` rows carry {proc, state, status,
        heartbeat_age_s, queue_depth, phase_age_s}.  Returns the alerts
        that FIRED this tick (edge-triggered)."""
        self.evals += 1
        fired: list[Alert] = []
        fired += self._check_heartbeats(t, processes)
        fired += self._check_availability(t, metrics)
        fired += self._check_serving_p99(t, metrics)
        fired += self._check_queues(t, processes)
        fired += self._check_stage_lag(t, processes)
        fired += self._check_audit_lag(t, metrics)
        # last, so it sees this tick's victim alerts in self._active
        fired += self._check_noisy_neighbor(t, metrics)
        self.fired.extend(fired)
        return fired

    def _fire(self, cond: bool, alert_fn) -> list[Alert]:
        """Edge-trigger plumbing: fire when ``cond`` rises, clear (and
        re-arm) when it falls.  ``alert_fn()`` builds the Alert lazily."""
        alert = alert_fn()
        key = alert.key
        if cond:
            if key in self._active:
                return []
            self._active[key] = alert
            return [alert]
        self._active.pop(key, None)
        return []

    def _check_heartbeats(self, t: float, processes) -> list[Alert]:
        cfg = self.config["heartbeat"]
        window = cfg["interval_s"] * cfg["miss_threshold"]
        out = []
        for p in processes:
            dead = (p["state"] == "ALIVE"
                    and p["status"] != "EXITING"
                    and p["heartbeat_age_s"] > window)
            out += self._fire(dead, lambda p=p: Alert(
                "heartbeat_miss", p["proc"],
                f"no heartbeat for {p['heartbeat_age_s']:.2f}s "
                f"(> {window:.2f}s = {cfg['miss_threshold']} x "
                f"{cfg['interval_s']}s)", t,
                attrs={"detection_s": round(p["heartbeat_age_s"], 3),
                       "window_s": window, "pid": p.get("pid", 0)}))
        return out

    def _check_availability(self, t: float, metrics) -> list[Alert]:
        cfg = self.config["availability"]
        # cumulative calls/failures per deadline class from the merged
        # counters (calls are labeled with class=; failures with method=)
        calls: dict[str, float] = {}
        fails: dict[str, float] = {}
        for flat, v in metrics.get("counters", {}).items():
            name, labels = parse_labels(flat)
            if name == "rpc_client_calls_total":
                cls = labels.get("class", "exchange")
                calls[cls] = calls.get(cls, 0) + v
            elif name == "rpc_client_failures_total":
                cls = self._method_class(labels.get("method", ""))
                fails[cls] = fails.get(cls, 0) + v
        out = []
        for cls, objective in cfg["objective"].items():
            hist = self._avail.setdefault(cls, deque())
            hist.append((t, calls.get(cls, 0), fails.get(cls, 0)))
            while hist and hist[0][0] < t - cfg["slow_window_s"] - 1:
                hist.popleft()
            budget = max(1e-9, 1.0 - objective)
            fast = _window_error_rate(hist, t, cfg["fast_window_s"])
            slow = _window_error_rate(hist, t, cfg["slow_window_s"])
            burning = (fast is not None and slow is not None
                       and fast / budget > cfg["fast_burn"]
                       and slow / budget > cfg["slow_burn"])
            out += self._fire(burning, lambda cls=cls, fast=fast,
                              slow=slow, budget=budget: Alert(
                "availability_burn", cls,
                f"error budget burning {0 if fast is None else fast / budget:.1f}x "
                f"(fast) / {0 if slow is None else slow / budget:.1f}x (slow) "
                f"against {objective}", t,
                attrs={"fast_burn": round((fast or 0) / budget, 2),
                       "slow_burn": round((slow or 0) / budget, 2),
                       "objective": objective}))
        return out

    def _check_serving_p99(self, t: float, metrics) -> list[Alert]:
        cfg = self.config["serving_p99_ms"]
        out = []
        for flat, hist in metrics.get("histograms", {}).items():
            name, labels = parse_labels(flat)
            if name not in cfg["histograms"]:
                continue
            # one SLO instance per series: an election-labeled latency
            # histogram is ONE tenant's p99, checked against that
            # tenant's objective (per_election override, else fleet)
            election = labels.get("election", "")
            objective = cfg.get("per_election", {}).get(election,
                                                        cfg["objective"])
            p99 = histogram_quantile(hist, 0.99)
            out += self._fire(p99 > objective,
                              lambda flat=flat, p99=p99,
                              objective=objective, election=election:
                              Alert(
                "serving_p99", flat,
                f"p99 {p99:.0f}ms > objective {objective:.0f}ms",
                t, attrs={"p99_ms": p99,
                          "objective_ms": objective,
                          "election": election}))
        return out

    def _check_noisy_neighbor(self, t: float, metrics) -> list[Alert]:
        """Attribution, not detection: the per-tenant checks say WHO is
        hurting; this one says who is CAUSING it.  An offender is an
        election holding ≥ ``share`` of the fleet's device time over
        the trailing window while a DIFFERENT election (the victim)
        burns a tenant-scoped SLO."""
        cfg = self.config["noisy_neighbor"]
        share_min, window = cfg["share"], cfg["window_s"]
        if share_min is None or window is None:
            from electionguard_tpu.utils import knobs
            if share_min is None:
                share_min = knobs.get_float("EGTPU_TENANT_NOISY_SHARE")
            if window is None:
                window = knobs.get_float("EGTPU_TENANT_NOISY_WINDOW")
        # cumulative per-election device time from the merged counters
        cum: dict[str, float] = {}
        for flat, v in metrics.get("counters", {}).items():
            name, labels = parse_labels(flat)
            if name == "tenant_device_ms_total":
                el = labels.get("election", "")
                cum[el] = cum.get(el, 0.0) + v
        deltas: dict[str, float] = {}
        for el, v in cum.items():
            hist = self._device_ms.setdefault(el, deque())
            hist.append((t, v))
            while hist and hist[0][0] < t - window - 1:
                hist.popleft()
            start = next((s for s in hist if s[0] >= t - window), None)
            if start is not None:
                deltas[el] = max(0.0, v - start[1])
        total = sum(deltas.values())
        # victims: elections currently burning a tenant-scoped alert
        victims = {a.attrs["election"] for a in self._active.values()
                   if a.attrs.get("election")}
        out = []
        for offender in sorted(self._device_ms):
            share = (deltas.get(offender, 0.0) / total) if total > 0 \
                else 0.0
            victs = sorted(v for v in victims if v != offender)
            noisy = bool(victs) and share >= share_min
            out += self._fire(noisy, lambda offender=offender,
                              share=share, victs=victs: Alert(
                "noisy_neighbor", offender,
                f"election {offender!r} holds {share:.0%} of fleet "
                f"device time over the last {window:.0f}s while "
                f"{', '.join(repr(v) for v in victs)} burns its SLO",
                t, attrs={"offender": offender,
                          "victim": victs[0] if victs else "",
                          "victims": list(victs),
                          "share": round(share, 3),
                          "window_s": window}))
        return out

    def _check_queues(self, t: float, processes) -> list[Alert]:
        limit = self.config["queue_depth_max"]
        out = []
        for p in processes:
            deep = p["state"] == "ALIVE" and p.get("queue_depth", 0) > limit
            out += self._fire(deep, lambda p=p: Alert(
                "queue_depth", p["proc"],
                f"queue depth {p.get('queue_depth', 0)} > {limit}", t,
                attrs={"queue_depth": p.get("queue_depth", 0),
                       "limit": limit}))
        return out

    def _check_stage_lag(self, t: float, processes) -> list[Alert]:
        limit = self.config["stage_lag_s"]
        out = []
        for p in processes:
            lag = p.get("phase_age_s", 0.0)
            wedged = (p["state"] == "ALIVE" and p.get("phase")
                      and p["status"] == "SERVING" and lag > limit)
            out += self._fire(wedged, lambda p=p, lag=lag: Alert(
                "stage_lag", p["proc"],
                f"phase {p.get('phase')!r} unchanged for {lag:.0f}s "
                f"(> {limit:.0f}s)", t,
                attrs={"phase": p.get("phase"), "lag_s": round(lag, 1)}))
        return out

    def _check_audit_lag(self, t: float, metrics) -> list[Alert]:
        limit = self.config["audit_lag_frames"]["objective"]
        if limit is None:
            from electionguard_tpu.utils import knobs
            limit = knobs.get_int("EGTPU_LIVE_AUDIT_LAG_MAX")
        out = []
        for flat, v in metrics.get("gauges", {}).items():
            name, _ = parse_labels(flat)
            if name != "live_audit_lag_frames":
                continue
            out += self._fire(v > limit, lambda flat=flat, v=v,
                              limit=limit: Alert(
                "audit_lag", flat,
                f"live verification is {v:.0f} frames behind the "
                f"published stream (> {limit})", t,
                attrs={"lag_frames": v, "limit": limit}))
        return out

    # ---- rollup ------------------------------------------------------

    def health(self, t: float) -> tuple[str, list[str]]:
        """Fleet color from the alert state: red while any non-liveness
        alert is active, or within ``dead_red_for_s`` of a liveness
        alert firing (after that the death is recorded history — the
        fleet is green again once the work requeued elsewhere)."""
        red_for = self.config["heartbeat"]["dead_red_for_s"]
        reasons = []
        for key, a in self._active.items():
            if a.kind == "heartbeat_miss":
                if t - a.t <= red_for:
                    reasons.append(a.summary())
            else:
                reasons.append(a.summary())
        return ("red" if reasons else "green"), reasons

    def active(self) -> list[Alert]:
        return list(self._active.values())


def _window_error_rate(hist: deque, t: float,
                       window_s: float) -> Optional[float]:
    """Failure ratio over the trailing window from cumulative samples;
    None when the window has no calls (no verdict, never alert)."""
    start = None
    for sample in hist:
        if sample[0] >= t - window_s:
            start = sample
            break
    if start is None:
        return None
    end = hist[-1]
    d_calls = end[1] - start[1]
    d_fails = end[2] - start[2]
    if d_calls <= 0:
        return None
    return min(1.0, max(0.0, d_fails / d_calls))


def _default_method_class(method: str) -> str:
    from electionguard_tpu.remote import rpc_util
    return rpc_util._DEADLINE_CLASS_OF.get(method, "exchange")

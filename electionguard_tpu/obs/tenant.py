"""Ambient per-request tenant (election) context.

One process serving N overlapping elections must label every metric,
span, and log line with the election the CURRENT request belongs to —
without threading an election id through every call signature.  This
module is that ambient channel, built exactly like ``obs.trace``:

* across **threads/frames**: a ``contextvars`` var — ``tenant_scope``
  sets the election id for everything the enclosed code does, and
  ``current_election()`` resolves it (falling back to the
  ``EGTPU_ELECTION`` knob, so a single-tenant deployment never touches
  a contextvar);
* across **processes over gRPC**: the client interceptor stamps the
  active election id onto the call metadata (binary key, so hostile
  ids with newlines survive) and the server wrapper adopts it — hooked
  at the same ``rpc_util.make_channel``/``generic_service`` points as
  the trace/fault interceptors, zero call-site changes;
* under the **sim transport** nothing is needed: the sim dispatches
  handlers inline on the caller's task, so the contextvar itself
  propagates client → server.

``registry.election_labels()`` resolves through ``current_election``,
so every call site that already labels its series per election becomes
multi-tenant-correct the moment a router/service wraps request
handling in a ``tenant_scope``.

Cardinality guard: the set of distinct election ids one process will
label series with is bounded by ``EGTPU_TENANT_MAX``.  A hostile or
misconfigured client cycling fresh ids would otherwise mint unbounded
metric series (the classic label-cardinality explosion); past the
bound, ``tenant_scope`` raises the named ``tenant.cardinality`` error
instead of admitting the id.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Iterator, Optional

import grpc

from electionguard_tpu.utils import errors

#: gRPC metadata key carrying the election id.  The ``-bin`` suffix
#: makes it binary-valued metadata: arbitrary utf-8 (commas, quotes,
#: newlines — hostile-id tests exercise all of them) round-trips where
#: ASCII metadata would be rejected by the transport.
MD_ELECTION = "egtpu-election-bin"

_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "egtpu_tenant", default=None)
_lock = threading.Lock()
#: distinct election ids this process has labeled anything with
_seen: set[str] = set()


class TenantCardinalityError(RuntimeError):
    """Raised when a process would exceed ``EGTPU_TENANT_MAX`` distinct
    election ids — the bounded-label-set guard."""


def current_election() -> str:
    """The election id of the ambient request context, falling back to
    the ``EGTPU_ELECTION`` knob (``default`` out of the box)."""
    t = _ctx.get()
    if t is not None:
        return t
    from electionguard_tpu.utils import knobs
    return knobs.get_str("EGTPU_ELECTION")


def seen_elections() -> frozenset:
    """The distinct election ids admitted by this process so far."""
    with _lock:
        return frozenset(_seen)


def admit(election_id: str) -> str:
    """Count ``election_id`` against the per-process tenant bound;
    raises the named ``tenant.cardinality`` error past
    ``EGTPU_TENANT_MAX`` distinct ids.  Idempotent per id."""
    from electionguard_tpu.utils import knobs
    with _lock:
        if election_id in _seen:
            return election_id
        cap = knobs.get_int("EGTPU_TENANT_MAX")
        if len(_seen) >= cap:
            raise TenantCardinalityError(errors.named(
                "tenant.cardinality",
                f"election id {election_id!r} would be distinct tenant "
                f"#{len(_seen) + 1} in this process but EGTPU_TENANT_MAX"
                f"={cap}; raise the knob or fix the client"))
        _seen.add(election_id)
    return election_id


@contextlib.contextmanager
def tenant_scope(election_id: str) -> Iterator[str]:
    """Make ``election_id`` the ambient election for the enclosed code
    (and everything it calls, including onward rpcs).  Applies the
    cardinality guard on entry."""
    admit(election_id)
    token = _ctx.set(election_id)
    try:
        yield election_id
    finally:
        _ctx.reset(token)


def _reset_for_tests() -> None:
    """Clear the seen-tenant set (tests only)."""
    with _lock:
        _seen.clear()


# ---------------------------------------------------------------------------
# gRPC propagation (real transport only — the sim dispatches inline and
# the contextvar flows by itself)
# ---------------------------------------------------------------------------

class _CallDetails(grpc.ClientCallDetails):
    __slots__ = ("method", "timeout", "metadata", "credentials",
                 "wait_for_ready", "compression")

    def __init__(self, base, metadata):
        self.method = base.method
        self.timeout = base.timeout
        self.metadata = metadata
        self.credentials = base.credentials
        self.wait_for_ready = getattr(base, "wait_for_ready", None)
        self.compression = getattr(base, "compression", None)


class TenantClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    """Stamps the ambient election id (when one is set — a single-tenant
    caller with no scope active stamps nothing) onto outgoing rpc
    metadata for the server wrapper to adopt."""

    def intercept_unary_unary(self, continuation, client_call_details,
                              request):
        election = _ctx.get()
        if election is None:
            outcome = continuation(client_call_details, request)
        else:
            md = list(client_call_details.metadata or ())
            md.append((MD_ELECTION, election.encode("utf-8")))
            outcome = continuation(_CallDetails(client_call_details, md),
                                   request)
        # grpc's continuation wrapper converts an error RAISED by an
        # inner interceptor (the fault injector) into a returned
        # outcome; a raw RpcError is not a call — re-raise it so it
        # propagates to the caller exactly as it did before this layer
        # existed, instead of dying on ``outcome.result()`` upstream
        if isinstance(outcome, grpc.RpcError) \
                and not hasattr(outcome, "result"):
            raise outcome
        return outcome


def intercept_channel(channel: grpc.Channel) -> grpc.Channel:
    """Wrap ``channel`` with the tenant interceptor."""
    return grpc.intercept_channel(channel, TenantClientInterceptor())


def wrap_server_method(fn):
    """Wrap one ``fn(request, context)`` impl so it runs under the
    caller's election scope when the rpc metadata carries one.  With no
    tenant metadata the impl runs unchanged — in particular the sim's
    inline dispatch keeps whatever scope the caller already holds."""

    def scoped(request, context):
        election: Optional[str] = None
        for k, v in (context.invocation_metadata() or ()):
            if k == MD_ELECTION:
                election = (v.decode("utf-8")
                            if isinstance(v, (bytes, bytearray)) else str(v))
        if election is None:
            return fn(request, context)
        try:
            with tenant_scope(election):
                return fn(request, context)
        except TenantCardinalityError as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))

    return scoped

"""Capacity planning: empirical cost models over the obs-plane artifacts.

The obs plane records and attributes wall-clock (obs/trace, obs/analyze);
this module makes those recordings *predictive*.  Three layers:

* **fitters** — ingest the artifacts the repo already produces and turn
  them into per-term cost estimates with uncertainty bands:

  - ``BENCH_BIGNUM.json`` → per-backend modexp rooflines (variable-base
    ladder rows/s normalized to the full 256-bit exponent, fixed-base
    rows/s as measured at 256 bits);
  - ``SCALE.json`` → tiny-group streaming per-ballot host costs (the
    repeated 100k-ballot rows are the uncertainty samples), the fabric
    worker-scaling curve (fit to Amdahl's law: rate(w) = w·r1 /
    (1 + σ·(w−1)), σ the serial fraction), and the production-group
    measured verify anchor;
  - a trace forest (``obs/analyze.RunAnalysis``) → per-phase ×
    per-category self-time shares, incl. the rpc overhead share;
  - a collector/serving metrics snapshot → mean batch occupancy from the
    ``batch_occupancy`` histogram.

  Every ``Estimate`` carries ``rel_band``: the relative sample std when
  repeated samples exist, else the prior band bench_diff already uses
  for that metric class.

* **an analytic pipeline model** — ``predict`` composes per-phase costs
  (serve-encrypt → K mix stages → compensated decrypt → RLC batch
  verify / live-verify residual) into end-to-end wall-clock as a
  function of a ``Plan`` (ballots, workers, chips, mix stages, backend,
  batch knobs), names the bottleneck phase, and reports the worker-
  scaling knee (the worker count where Amdahl efficiency crosses 50%).
  ``chips_for_deadline`` inverts it: the smallest chip count whose
  predicted wall-clock meets a deadline, with optimistic/pessimistic
  bounds from the band.

* **validation** — the model must reproduce *measured* configurations:
  ``validate_fabric`` holds out the last point of the SCALE.json fabric
  curve and predicts it from the rest; ``validate_e2e`` runs a traced
  tiny-group election end-to-end (a real flight-report trace), fits
  per-phase linear costs on two calibration sizes, and predicts a third,
  larger measured run.  ``validate`` aggregates both and fails when any
  error exceeds the tolerance (``EGTPU_CAPACITY_TOL``).

Modexp-row counts per ballot come from the fused-program op mix pinned
in ``TPU_RESULTS.md`` (2 selections + 1 placeholder): ~18 full-ladder
rows/ballot for naive verify, ~4 with the RLC batch screen, ~12
fixed-base rows for encryption, ~8 variable rows per mix stage (width-2
re-encryption + Chaum-Pedersen), ~0.5 rows/ballot amortized compensated
decrypt (tally selections + ~10% spoiled).  ``tools/egplan.py`` renders
the tracked ``CAPACITY.md``/``CAPACITY.json`` from here; ``bench.py``'s
``capacity`` phase re-validates per bench run and emits
``capacity_model_err_pct`` so model drift gates like any perf
regression.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from electionguard_tpu.utils import clock

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

#: full-ladder exponent width the rooflines are normalized to
LADDER_BITS = 256

#: full-ladder modexp rows per ballot per phase (TPU_RESULTS.md op mix
#: at 2 selections + 1 placeholder; encrypt rows are fixed-base)
ROWS_PER_BALLOT = {
    "encrypt": 12.0,
    "mix_stage": 8.0,
    "decrypt": 0.5,
    "verify": 18.0,
    "verify_batch": 4.0,
}

#: the live-verify residual contract: ≤5% of record verify left at close
LIVE_RESIDUAL_FRACTION = 0.05

#: prior relative band when a term has a single sample (the bench_diff
#: noise band for the powmod metric class)
PRIOR_REL_BAND = 0.15


# ---------------------------------------------------------------------------
# estimates with uncertainty bands
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Estimate:
    """A fitted scalar with a relative 1-sigma band and sample count."""

    mean: float
    rel_band: float = PRIOR_REL_BAND
    n: int = 1

    @property
    def lo(self) -> float:
        return self.mean * (1.0 - self.rel_band)

    @property
    def hi(self) -> float:
        return self.mean * (1.0 + self.rel_band)

    def scaled(self, factor: float) -> "Estimate":
        return Estimate(self.mean * factor, self.rel_band, self.n)

    def to_json(self) -> dict:
        return {"mean": self.mean, "rel_band": round(self.rel_band, 4),
                "n": self.n}

    @classmethod
    def from_json(cls, d: dict) -> "Estimate":
        return cls(float(d["mean"]), float(d.get("rel_band",
                                                 PRIOR_REL_BAND)),
                   int(d.get("n", 1)))

    @classmethod
    def from_samples(cls, samples: list[float],
                     prior: float = PRIOR_REL_BAND) -> "Estimate":
        vals = [float(v) for v in samples if v is not None]
        if not vals:
            raise ValueError("no samples")
        mean = sum(vals) / len(vals)
        if len(vals) < 2 or mean == 0:
            return cls(mean, prior, len(vals))
        var = sum((v - mean) ** 2 for v in vals) / (len(vals) - 1)
        return cls(mean, math.sqrt(var) / abs(mean), len(vals))


# ---------------------------------------------------------------------------
# the fitted cost model
# ---------------------------------------------------------------------------

@dataclass
class CostModel:
    """Per-term device/host/rpc costs fitted from measured artifacts."""

    platform: str = "unknown"
    #: backend -> variable-base full-ladder modexp rows/s (one chip)
    powmod_per_s: dict = field(default_factory=dict)
    #: backend -> fixed-base 256-bit rows/s (one chip)
    fixed_per_s: dict = field(default_factory=dict)
    #: tiny-group streaming host path, per-ballot seconds per phase
    stream_per_ballot_s: dict = field(default_factory=dict)
    #: production-group measured verify anchor (ballots/s/chip)
    prod_verify_per_s_per_chip: Optional[Estimate] = None
    #: serving service time per ballot at 1 fabric worker (admission +
    #: device emulation + merge), from the fabric curve's first point
    rpc_per_ballot_s: Optional[Estimate] = None
    #: Amdahl serial fraction of the fabric worker-scaling curve
    serial_fraction: Estimate = field(
        default_factory=lambda: Estimate(0.15, PRIOR_REL_BAND, 0))
    #: mean batch occupancy from serving histograms (0..1]
    occupancy: Estimate = field(
        default_factory=lambda: Estimate(0.85, PRIOR_REL_BAND, 0))
    #: per-phase × per-category self-time profile from a trace forest
    phase_profile: dict = field(default_factory=dict)
    sources: dict = field(default_factory=dict)
    warnings: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "platform": self.platform,
            "powmod_per_s": {k: v.to_json()
                             for k, v in self.powmod_per_s.items()},
            "fixed_per_s": {k: v.to_json()
                            for k, v in self.fixed_per_s.items()},
            "stream_per_ballot_s": {
                k: v.to_json()
                for k, v in self.stream_per_ballot_s.items()},
            "prod_verify_per_s_per_chip": (
                self.prod_verify_per_s_per_chip.to_json()
                if self.prod_verify_per_s_per_chip else None),
            "rpc_per_ballot_s": (self.rpc_per_ballot_s.to_json()
                                 if self.rpc_per_ballot_s else None),
            "serial_fraction": self.serial_fraction.to_json(),
            "occupancy": self.occupancy.to_json(),
            "phase_profile": self.phase_profile,
            "rows_per_ballot": dict(ROWS_PER_BALLOT),
            "sources": self.sources,
            "warnings": list(self.warnings),
        }

    @classmethod
    def from_json(cls, d: dict) -> "CostModel":
        m = cls(platform=d.get("platform", "unknown"))
        m.powmod_per_s = {k: Estimate.from_json(v)
                          for k, v in d.get("powmod_per_s", {}).items()}
        m.fixed_per_s = {k: Estimate.from_json(v)
                         for k, v in d.get("fixed_per_s", {}).items()}
        m.stream_per_ballot_s = {
            k: Estimate.from_json(v)
            for k, v in d.get("stream_per_ballot_s", {}).items()}
        if d.get("prod_verify_per_s_per_chip"):
            m.prod_verify_per_s_per_chip = Estimate.from_json(
                d["prod_verify_per_s_per_chip"])
        if d.get("rpc_per_ballot_s"):
            m.rpc_per_ballot_s = Estimate.from_json(d["rpc_per_ballot_s"])
        if d.get("serial_fraction"):
            m.serial_fraction = Estimate.from_json(d["serial_fraction"])
        if d.get("occupancy"):
            m.occupancy = Estimate.from_json(d["occupancy"])
        m.phase_profile = d.get("phase_profile", {})
        m.sources = d.get("sources", {})
        m.warnings = list(d.get("warnings", []))
        return m


# ---------------------------------------------------------------------------
# fitters
# ---------------------------------------------------------------------------

def fit_bignum(doc: dict, model: CostModel) -> None:
    """Per-backend modexp rooflines from a ``BENCH_BIGNUM.json`` doc.

    ``per_s`` rows are rows/s at the row's own ``exp_bits``; variable-
    base (``powmod``) rates are normalized to the full 256-bit ladder
    (ladder cost is linear in exponent bits), ``fixed`` rows are already
    measured at 256 bits.  Repeated rows of the same (backend, op,
    batch, exp_bits) config are uncertainty samples.
    """
    model.platform = doc.get("platform", model.platform)
    groups: dict = {}
    for r in doc.get("rows", []):
        op = r.get("op")
        if op not in ("powmod", "fixed") or not r.get("per_s"):
            continue
        key = (r.get("backend"), op, r.get("batch"), r.get("exp_bits"))
        groups.setdefault(key, []).append(float(r["per_s"]))
    best: dict = {}
    for (backend, op, _batch, exp_bits), samples in groups.items():
        est = Estimate.from_samples(samples)
        if op == "powmod":
            est = Estimate(est.mean * float(exp_bits or LADDER_BITS)
                           / LADDER_BITS, est.rel_band, est.n)
        prev = best.get((backend, op))
        if prev is None or est.mean > prev.mean:
            best[(backend, op)] = est
    for (backend, op), est in best.items():
        (model.powmod_per_s if op == "powmod"
         else model.fixed_per_s)[backend] = est


def fit_scale(rows: list, model: CostModel) -> None:
    """Streaming per-ballot host costs, the fabric worker-scaling fit,
    and the production-group verify anchor from ``SCALE.json``."""
    stream_samples: dict = {}
    for r in rows:
        phase = r.get("phase")
        if phase == "stream" and r.get("nballots"):
            n = float(r["nballots"])
            for name, key in (("encrypt", "encrypt_s"),
                              ("tally", "tally_s"),
                              ("verify", "verify_s")):
                if r.get(key):
                    stream_samples.setdefault(name, []).append(
                        float(r[key]) / n)
        elif phase == "prod" and r.get("verify_per_s_per_chip"):
            model.prod_verify_per_s_per_chip = Estimate(
                float(r["verify_per_s_per_chip"]))
        elif phase == "fabric" and r.get("curve"):
            _fit_fabric_curve(r["curve"], model)
    for name, samples in stream_samples.items():
        model.stream_per_ballot_s[name] = Estimate.from_samples(samples)


def _fit_fabric_curve(curve: list, model: CostModel,
                      holdout_last: bool = False) -> Optional[dict]:
    """Least-squares Amdahl fit of ``rate(w) = w·r1 / (1 + σ·(w−1))``
    over the fabric curve.  With ``holdout_last`` the final point is
    excluded from the fit and returned as a prediction row (the
    validation config)."""
    pts = [(int(p["workers"]), float(p["ballots_per_s"]))
           for p in curve if p.get("workers") and p.get("ballots_per_s")]
    pts.sort()
    if not pts or pts[0][0] != 1:
        model.warnings.append("fabric curve lacks a 1-worker point; "
                              "worker-scaling fit skipped")
        return None
    fit_pts = pts[:-1] if (holdout_last and len(pts) > 2) else pts
    r1 = fit_pts[0][1]
    # each point w>1 gives an exact σ_w = (w·r1/rate − 1)/(w−1);
    # the fit is their mean, the band their spread
    sigmas = [((w * r1 / rate) - 1.0) / (w - 1)
              for w, rate in fit_pts if w > 1 and rate > 0]
    if sigmas:
        model.serial_fraction = Estimate.from_samples(
            [max(s, 0.0) for s in sigmas])
    model.rpc_per_ballot_s = Estimate(1.0 / r1)
    if holdout_last and len(pts) > 2:
        w, measured = pts[-1]
        predicted = (w * r1) / (1.0 + model.serial_fraction.mean * (w - 1))
        return {"workers": w, "measured_ballots_per_s": measured,
                "predicted_ballots_per_s": round(predicted, 2),
                "err_pct": round(abs(predicted - measured)
                                 / measured * 100.0, 2)}
    return None


def fit_trace(analysis, model: CostModel) -> None:
    """Per-phase × per-category self-time shares from a trace forest
    (an ``obs/analyze.RunAnalysis``)."""
    profile: dict = {}
    for (phase, _proc, category), us in analysis.buckets.items():
        p = profile.setdefault(phase, {})
        p[category] = p.get(category, 0) + int(us)
    model.phase_profile = profile
    if analysis.warnings:
        model.warnings.extend(f"trace: {w}" for w in analysis.warnings[:5])


def fit_collector(snapshot: dict, model: CostModel) -> None:
    """Mean batch occupancy from the serving ``batch_occupancy``
    histogram(s) in a registry/collector metrics snapshot."""
    total, count = 0.0, 0
    for flat, h in snapshot.get("histograms", {}).items():
        if flat.split("{", 1)[0] == "batch_occupancy" and h.get("count"):
            total += float(h.get("sum", 0.0))
            count += int(h["count"])
    if count:
        model.occupancy = Estimate(min(total / count, 1.0),
                                   PRIOR_REL_BAND, count)


def fit(repo_root: Optional[str] = None,
        bignum_path: Optional[str] = None,
        scale_path: Optional[str] = None,
        trace_dir: Optional[str] = None,
        snapshot: Optional[dict] = None) -> CostModel:
    """Fit a ``CostModel`` from whatever artifacts exist; every missing
    input degrades to a warning plus that term's default, never a
    raise."""
    root = repo_root or REPO_ROOT
    model = CostModel()
    bignum_path = bignum_path or os.path.join(root, "BENCH_BIGNUM.json")
    scale_path = scale_path or os.path.join(root, "SCALE.json")
    try:
        with open(bignum_path) as f:
            fit_bignum(json.load(f), model)
        model.sources["bignum"] = bignum_path
    except (OSError, ValueError) as e:
        model.warnings.append(f"no bignum rooflines ({e})")
    try:
        with open(scale_path) as f:
            fit_scale(json.load(f), model)
        model.sources["scale"] = scale_path
    except (OSError, ValueError) as e:
        model.warnings.append(f"no scale curves ({e})")
    if trace_dir:
        try:
            from electionguard_tpu.obs import analyze
            fit_trace(analyze.analyze(trace_dir), model)
            model.sources["trace"] = trace_dir
        except Exception as e:  # noqa: BLE001 — fitting is best-effort
            model.warnings.append(f"trace fit failed ({e})")
    if snapshot:
        fit_collector(snapshot, model)
        model.sources["snapshot"] = "metrics snapshot"
    return model


# ---------------------------------------------------------------------------
# the analytic pipeline model
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Plan:
    """One what-if configuration.  ``workers=0`` means "enough fabric
    workers that serving never binds" (the headline chips question)."""

    ballots: int = 1_000_000
    workers: int = 0
    chips: int = 1
    mix_stages: int = 0
    backend: str = "cios"
    batch_verify: bool = True
    live_verify: bool = False

    def to_json(self) -> dict:
        return {"ballots": self.ballots, "workers": self.workers,
                "chips": self.chips, "mix_stages": self.mix_stages,
                "backend": self.backend,
                "batch_verify": self.batch_verify,
                "live_verify": self.live_verify}


@dataclass
class PhaseCost:
    name: str
    seconds: Estimate
    limiter: str = "device"

    def to_json(self) -> dict:
        return {"name": self.name, "seconds": self.seconds.to_json(),
                "limiter": self.limiter}


@dataclass
class Prediction:
    plan: Plan
    phases: list
    total: Estimate
    bottleneck: str
    knee_workers: Optional[int]
    warnings: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {"plan": self.plan.to_json(),
                "phases": [p.to_json() for p in self.phases],
                "total_s": self.total.to_json(),
                "bottleneck": self.bottleneck,
                "knee_workers": self.knee_workers,
                "warnings": list(self.warnings)}


def worker_efficiency(workers: int, sigma: float) -> float:
    """Amdahl effective-worker fraction: ``w_eff/w = 1/(1+σ·(w−1))``."""
    if workers <= 1:
        return 1.0
    return 1.0 / (1.0 + sigma * (workers - 1))


def predict(model: CostModel, plan: Plan) -> Prediction:
    """End-to-end wall-clock of ``plan`` under ``model``: serve-encrypt
    → K mix stages → compensated decrypt → verify (RLC batch or naive,
    live-verify residual)."""
    warnings: list[str] = []
    pow_est = model.powmod_per_s.get(plan.backend)
    if pow_est is None or pow_est.mean <= 0:
        raise ValueError(f"no powmod roofline for backend "
                         f"{plan.backend!r}; fit BENCH_BIGNUM.json first")
    fixed_est = model.fixed_per_s.get(plan.backend)
    if fixed_est is None:
        fixed_est = pow_est
        warnings.append(f"no fixed-base rate for {plan.backend}; "
                        f"using the variable-base ladder rate")
    occ = max(min(model.occupancy.mean, 1.0), 1e-3)
    chips = max(plan.chips, 1)

    def device_s(rows: float, rate: Estimate) -> Estimate:
        sec = rows / (rate.mean * chips * occ)
        band = math.hypot(rate.rel_band, model.occupancy.rel_band)
        return Estimate(sec, band, rate.n)

    phases: list[PhaseCost] = []

    # serve-encrypt: device fixed-base exponentiations vs the fabric
    # serving floor (admission + rpc + merge) — pipelined, so the wall
    # is whichever side binds
    enc_dev = device_s(plan.ballots * ROWS_PER_BALLOT["encrypt"],
                       fixed_est)
    enc = enc_dev
    limiter = "device"
    if plan.workers > 0 and model.rpc_per_ballot_s is not None:
        eff = worker_efficiency(plan.workers, model.serial_fraction.mean)
        serve_s = (plan.ballots * model.rpc_per_ballot_s.mean
                   / (plan.workers * eff))
        if serve_s > enc_dev.mean:
            enc = Estimate(serve_s,
                           math.hypot(model.rpc_per_ballot_s.rel_band,
                                      model.serial_fraction.rel_band),
                           model.rpc_per_ballot_s.n)
            limiter = "rpc"
    phases.append(PhaseCost("serve-encrypt", enc, limiter))

    if plan.mix_stages > 0:
        rows = (plan.ballots * ROWS_PER_BALLOT["mix_stage"]
                * plan.mix_stages)
        phases.append(PhaseCost(f"mix×{plan.mix_stages}",
                                device_s(rows, pow_est)))

    phases.append(PhaseCost(
        "decrypt", device_s(plan.ballots * ROWS_PER_BALLOT["decrypt"],
                            pow_est)))

    rows_key = "verify_batch" if plan.batch_verify else "verify"
    ver_rows = plan.ballots * ROWS_PER_BALLOT[rows_key]
    ver_name = "verify-batch" if plan.batch_verify else "verify"
    if plan.live_verify:
        ver_rows *= LIVE_RESIDUAL_FRACTION
        ver_name += "-residual"
    phases.append(PhaseCost(ver_name, device_s(ver_rows, pow_est)))

    total_mean = sum(p.seconds.mean for p in phases)
    # phase terms are independent fits: absolute sigmas add in
    # quadrature
    sigma = math.sqrt(sum((p.seconds.mean * p.seconds.rel_band) ** 2
                          for p in phases))
    total = Estimate(total_mean,
                     sigma / total_mean if total_mean else 0.0,
                     min(p.seconds.n for p in phases))
    bottleneck = max(phases, key=lambda p: p.seconds.mean).name
    sf = model.serial_fraction.mean
    knee = int(math.ceil(1.0 + 1.0 / sf)) if sf > 0 else None
    return Prediction(plan, phases, total, bottleneck, knee, warnings)


def chips_for_deadline(model: CostModel, ballots: int, deadline_s: float,
                       backend: str, **plan_kwargs) -> dict:
    """Smallest chip count whose predicted wall-clock meets the
    deadline, with optimistic/pessimistic bounds from the band."""
    def total_at(chips: int) -> Estimate:
        return predict(model, Plan(ballots=ballots, chips=chips,
                                   backend=backend,
                                   **plan_kwargs)).total

    def search(meets: Callable[[Estimate], bool]) -> Optional[int]:
        if not meets(total_at(1)):
            hi = 1
            while hi < 2 ** 40 and not meets(total_at(hi)):
                hi *= 2
            if hi >= 2 ** 40:
                return None
            lo = hi // 2
            while lo + 1 < hi:
                mid = (lo + hi) // 2
                if meets(total_at(mid)):
                    hi = mid
                else:
                    lo = mid
            return hi
        return 1

    chips = search(lambda t: t.mean <= deadline_s)
    chips_lo = search(lambda t: t.lo <= deadline_s)   # optimistic
    chips_hi = search(lambda t: t.hi <= deadline_s)   # pessimistic
    pred = (predict(model, Plan(ballots=ballots, chips=chips,
                                backend=backend, **plan_kwargs))
            if chips else None)
    return {"backend": backend, "ballots": ballots,
            "deadline_s": deadline_s, "chips": chips,
            "chips_lo": chips_lo, "chips_hi": chips_hi,
            "bottleneck": pred.bottleneck if pred else None,
            "total_s": pred.total.to_json() if pred else None}


# ---------------------------------------------------------------------------
# validation against measured configurations
# ---------------------------------------------------------------------------

def tolerance() -> float:
    from electionguard_tpu.utils import knobs
    return knobs.get_float("EGTPU_CAPACITY_TOL")


def validate_fabric(scale_path: Optional[str] = None,
                    tol: Optional[float] = None) -> dict:
    """Hold out the last point of the SCALE.json fabric curve, fit the
    worker-scaling law on the rest, predict the held-out throughput."""
    tol = tolerance() if tol is None else tol
    path = scale_path or os.path.join(REPO_ROOT, "SCALE.json")
    out = {"name": "scale-fabric-holdout", "source": path}
    try:
        with open(path) as f:
            rows = json.load(f)
    except (OSError, ValueError) as e:
        out.update(skipped=f"no SCALE.json ({e})")
        return out
    for r in rows:
        if r.get("phase") == "fabric" and len(r.get("curve") or []) >= 3:
            probe = CostModel()
            row = _fit_fabric_curve(r["curve"], probe, holdout_last=True)
            if row is None:
                continue
            out.update(row)
            out["pass"] = row["err_pct"] <= tol * 100.0
            return out
    out.update(skipped="no fabric curve with ≥3 points")
    return out


def measure_traced_run(nballots: int, tag: str, seed: int = 7) -> dict:
    """One tiny-group election end-to-end (encrypt → tally → verify)
    under the trace plane: every phase is a ``phase.*`` span, so the
    run's trace dir is a real flight-report trace.  Returns measured
    per-phase and total wall seconds."""
    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import tiny_group
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.obs import trace
    from electionguard_tpu.publish.election_record import (ElectionConfig,
                                                           ElectionRecord)
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from electionguard_tpu.verify.verifier import Verifier
    from electionguard_tpu.workflow.e2e import sample_manifest

    g = tiny_group()
    manifest = sample_manifest(1, 2)
    trustees = [KeyCeremonyTrustee(g, "guardian-0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "egplan"})
    ballots = list(RandomBallotProvider(manifest, nballots,
                                        seed=seed).ballots())
    phases: dict = {}
    t_run = clock.monotonic()
    with trace.span(f"plan.{tag}", {"n": nballots}):
        t0 = clock.monotonic()
        with trace.span("phase.encrypt", {"n": nballots}):
            encrypted, invalid = BatchEncryptor(init, g).encrypt_ballots(
                ballots, seed=g.int_to_q(97))
        phases["encrypt"] = clock.monotonic() - t0
        if invalid or len(encrypted) != nballots:
            raise RuntimeError(f"egplan measurement run rejected "
                               f"{len(invalid)} ballots")
        t0 = clock.monotonic()
        with trace.span("phase.tally"):
            tally_result = accumulate_ballots(init, encrypted)
        phases["tally"] = clock.monotonic() - t0
        record = ElectionRecord(election_init=init,
                                encrypted_ballots=encrypted,
                                tally_result=tally_result)
        t0 = clock.monotonic()
        with trace.span("phase.verify", {"n": nballots}):
            res = Verifier(record, g).verify()
        phases["verify"] = clock.monotonic() - t0
        if not res.ok:
            raise RuntimeError(f"egplan measurement run failed "
                               f"verification: {res.summary()}")
    return {"nballots": nballots, "phases": phases,
            "wall_s": clock.monotonic() - t_run}


def validate_e2e(runner: Callable[[int, str], dict] = measure_traced_run,
                 sizes: Optional[tuple] = None,
                 tol: Optional[float] = None) -> dict:
    """Fit per-phase linear costs (fixed + per-ballot) on two measured
    calibration elections and predict a third, held-out size between
    them, comparing against its measured end-to-end wall-clock.

    Warm passes run at every measured size first so each batch-bucket
    shape's kernels are compiled before timing, and every measurement
    is the per-phase MIN of three repetitions: scheduling jitter on a
    loaded host is strictly additive, so the min is the estimator of
    the actual cost (medians of sub-second runs still carry tens of
    percent of noise).  The calibration sizes bracket the validation
    size: device batches pad to power-of-two buckets, so per-ballot
    cost is a step function of n and only interpolation across the
    bracket is well-posed."""
    tol = tolerance() if tol is None else tol
    if sizes is None:
        from electionguard_tpu.utils import knobs
        sizes = tuple(int(s) for s in
                      knobs.get_str("EGTPU_CAPACITY_VALIDATE_N").split(","))
    n1, n2, n3 = sizes
    if n1 == n2:
        raise ValueError("calibration sizes must differ")

    def _best_run(n: int, tag: str, reps: int = 3) -> dict:
        runs = [runner(n, f"{tag}{i}") for i in range(reps)]
        phases = {name: min(r["phases"][name] for r in runs)
                  for name in runs[0]["phases"]}
        return {"nballots": n, "phases": phases}

    for n in sorted(set(sizes)):
        runner(n, "warm")
    m1 = _best_run(n1, "cal1-")
    m2 = _best_run(n2, "cal2-")
    fitted = {}
    for name in m1["phases"]:
        slope = (m2["phases"][name] - m1["phases"][name]) / (n2 - n1)
        slope = max(slope, 0.0)
        fixed = max(m1["phases"][name] - slope * n1, 0.0)
        fitted[name] = {"per_ballot_s": slope, "fixed_s": fixed}
    predicted = sum(f["fixed_s"] + f["per_ballot_s"] * n3
                    for f in fitted.values())
    m3 = _best_run(n3, "validate-")
    measured = sum(m3["phases"].values())
    err_pct = abs(predicted - measured) / measured * 100.0
    return {"name": "e2e-traced-election", "sizes": list(sizes),
            "fitted": fitted,
            "predicted_s": round(predicted, 4),
            "measured_s": round(measured, 4),
            "err_pct": round(err_pct, 2),
            "pass": err_pct <= tol * 100.0}


def validate_sim_election(tol: Optional[float] = None,
                          seed: int = 7) -> dict:
    """Predicted-vs-PLAYED-OUT: run the process-model virtual election
    (``sim/election``, chaos on — a mid-election worker SIGKILL/restart
    included) and gate its phase timeline against ``predict`` for the
    same plan.  Both sides share the fitted per-op rates, so the error
    measures the *composition* — shared-device queueing, micro-batch
    rounding, Amdahl'd worker drain, residual verification overlap —
    against the closed form."""
    tol = tolerance() if tol is None else tol
    out: dict = {"name": "sim-election"}
    try:
        from electionguard_tpu.sim import election
        model = fit()
        spec = election.ElectionSpec.from_knobs()
        rep = election.run_virtual_election(seed=seed, spec=spec,
                                            model=model, chaos=True)
        pred = predict(model, spec.plan())
    except Exception as e:  # noqa: BLE001 — gate degrades, never raises
        out["skipped"] = f"virtual election failed ({e})"
        return out
    sim_s = rep.modeled_total_s()
    err_pct = (abs(pred.total.mean - sim_s) / max(sim_s, 1e-9)) * 100.0
    out.update({
        "ballots": spec.ballots, "chaos": rep.chaos,
        "oracles_ok": rep.ok, "violations": list(rep.violations),
        "simulated_s": round(sim_s, 3),
        "predicted_s": round(pred.total.mean, 3),
        "err_pct": round(err_pct, 2),
        "phases": {k: round(v, 3)
                   for k, v in rep.phase_seconds().items()},
        "predicted_phases": {p.name: round(p.seconds.mean, 3)
                             for p in pred.phases},
        "trace_hash": rep.trace_hash,
        "events": rep.events,
        "wall_s": round(rep.wall_s, 3),
        "pass": rep.ok and err_pct <= tol * 100.0})
    return out


def validate(runner: Callable[[int, str], dict] = measure_traced_run,
             scale_path: Optional[str] = None,
             tol: Optional[float] = None,
             sim: bool = False) -> dict:
    """The full predicted-vs-actual gate: both measured configurations
    (the traced e2e election and the SCALE.json fabric point) must
    reproduce within the tolerance band.  With ``sim=True`` the
    played-out virtual election (``validate_sim_election``) joins the
    gate as a third config."""
    tol = tolerance() if tol is None else tol
    configs = [validate_fabric(scale_path, tol), validate_e2e(runner,
                                                              tol=tol)]
    if sim:
        configs.append(validate_sim_election(tol))
    checked = [c for c in configs if "err_pct" in c]
    max_err = max((c["err_pct"] for c in checked), default=None)
    return {"tolerance_pct": tol * 100.0, "configs": configs,
            "n_checked": len(checked),
            "max_err_pct": max_err,
            "pass": bool(checked) and all(c.get("pass") for c in checked)}


# ---------------------------------------------------------------------------
# flight-report integration: predicted vs actual phase shares
# ---------------------------------------------------------------------------

#: predicted phase name -> substrings matched against trace phase keys
_PHASE_MATCH = {
    "serve-encrypt": ("encrypt",),
    "mix": ("mix", "shuffle"),
    "decrypt": ("decrypt",),
    "verify": ("verify", "tally"),
}


def phase_comparison(analysis, capacity_path: Optional[str] = None
                     ) -> Optional[dict]:
    """Predicted vs actual wall-clock shares per pipeline phase: the
    tracked ``CAPACITY.json`` prediction against a run's trace buckets.
    Returns ``None`` when either side is missing — flight reports render
    the section best-effort."""
    path = capacity_path or os.path.join(REPO_ROOT, "CAPACITY.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    pred = (doc.get("predictions") or [{}])[0]
    pred_phases = pred.get("phases") or []
    pred_total = sum(p["seconds"]["mean"] for p in pred_phases) or 1.0
    actual: dict = {}
    for (phase, _proc, _cat), us in analysis.buckets.items():
        for name, needles in _PHASE_MATCH.items():
            if any(n in phase.lower() for n in needles):
                actual[name] = actual.get(name, 0) + int(us)
                break
    actual_total = sum(actual.values())
    if not actual_total or not pred_phases:
        return None
    rows = []
    for p in pred_phases:
        name = p["name"]
        key = next((k for k in _PHASE_MATCH if name.startswith(k)), name)
        pred_share = p["seconds"]["mean"] / pred_total
        act_share = actual.get(key, 0) / actual_total
        rows.append({"phase": name,
                     "predicted_share": round(pred_share, 3),
                     "actual_share": round(act_share, 3),
                     "delta_pp": round((act_share - pred_share) * 100, 1)})
    return {"source": path, "plan": pred.get("plan"),
            "validation": doc.get("validation"), "rows": rows}

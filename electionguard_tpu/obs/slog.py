"""Structured JSONL logging carrying the active trace context.

A ``logging.Handler`` that mirrors every log record as one JSON line —
``{"ts", "level", "logger", "msg", "pid", "proc", "trace_id",
"span_id"}`` — so library logs from all processes of a run can be joined
against the span timeline post-hoc (same ids, same files-per-process
layout as the span export).

Installed on the ROOT logger, so no call sites change: every module
keeps using stdlib ``logging`` and the JSONL mirror appears whenever
``EGTPU_OBS_LOG=<dir>`` is set (``obs.init_from_env``).  This — plus the
no-bare-print lint (tests/test_lint_print.py) — is the structured
replacement for ad-hoc ``print()`` telemetry in library code.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional

from electionguard_tpu.obs import trace

_lock = threading.Lock()
_handler: Optional["JsonlHandler"] = None
#: log-line tee: every structured record is also handed to these (the
#: telemetry client streams them to the obs collector)
_hooks: list = []


def add_hook(fn) -> None:
    """Tee every structured log record (a dict) to ``fn``; used by the
    collector client to stream logs live.  Hooks must never raise."""
    if fn not in _hooks:
        _hooks.append(fn)


def remove_hook(fn) -> None:
    if fn in _hooks:
        _hooks.remove(fn)


class JsonlHandler(logging.Handler):
    """Mirror log records as JSONL to ``path`` (None = hooks only: the
    collector-forwarding posture when no local mirror is wanted)."""

    def __init__(self, path: Optional[str]):
        super().__init__()
        self.path = path
        self._f = open(path, "a") if path else None

    def emit(self, record: logging.LogRecord) -> None:
        try:
            tid, sid = trace.current_ids()
            line = {"ts": round(record.created, 6),
                    "level": record.levelname,
                    "logger": record.name,
                    "msg": record.getMessage(),
                    "pid": os.getpid(),
                    "proc": trace.proc_name()}
            if tid:
                line["trace_id"] = tid
                line["span_id"] = sid
            if record.exc_info and record.exc_info[0] is not None:
                line["exc"] = record.exc_info[0].__name__
            if self._f is not None:
                self._f.write(json.dumps(line, separators=(",", ":"))
                              + "\n")
                self._f.flush()
            for hook in _hooks:
                hook(line)
        except Exception:  # noqa: BLE001 — logging must never raise
            self.handleError(record)

    def close(self) -> None:
        try:
            if self._f is not None:
                self._f.close()
        finally:
            super().close()


def install(dir_path: str) -> JsonlHandler:
    """Attach the JSONL mirror to the root logger (idempotent)."""
    global _handler
    with _lock:
        if _handler is not None:
            return _handler
        os.makedirs(dir_path, exist_ok=True)
        path = os.path.join(
            dir_path, f"log-{trace.proc_name()}-{os.getpid()}.jsonl")
        _handler = JsonlHandler(path)
    logging.getLogger().addHandler(_handler)
    return _handler


def ensure_forwarding() -> JsonlHandler:
    """Make sure SOME JsonlHandler is on the root logger so ``add_hook``
    consumers see log records even when no ``EGTPU_OBS_LOG`` mirror is
    configured (hooks-only handler, no file)."""
    global _handler
    with _lock:
        if _handler is not None:
            return _handler
        _handler = JsonlHandler(None)
    logging.getLogger().addHandler(_handler)
    return _handler


def install_from_env() -> Optional[JsonlHandler]:
    """``EGTPU_OBS_LOG=<dir>`` mirrors logs there; falls back to the
    trace dir so one env var lights up the whole obs surface."""
    d = os.environ.get("EGTPU_OBS_LOG")
    if not d and os.environ.get("EGTPU_OBS_TRACE"):
        d = os.environ["EGTPU_OBS_TRACE"]
    if not d:
        return None
    return install(d)

"""Device-time attribution via ``jax.monitoring``.

One process-wide listener counts every backend compilation into the
default registry (``jax_backend_compiles_total`` plus cumulative
``jax_backend_compile_seconds_total``) and — when tracing is on —
exports a retroactive ``device.compile`` span parented to whatever span
was active on the compiling thread, so a compile that lands inside a
``worker.batch`` or bench-phase span is attributed to that batch.

This is the live twin of the persistent-compile-cache-dir accounting
bench.py does: it sees *every* compile on every platform, not only the
ones above the persist threshold.
"""

from __future__ import annotations

import threading

from electionguard_tpu.obs import trace
from electionguard_tpu.obs.registry import REGISTRY

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_count = 0


def _on_event_duration(event: str, duration: float, **kw) -> None:
    global _count
    if event != _COMPILE_EVENT:
        return
    with _lock:
        _count += 1
    REGISTRY.counter("jax_backend_compiles_total").inc()
    REGISTRY.counter("jax_backend_compile_seconds_total").inc(duration)
    if trace.enabled():
        dur_us = int(duration * 1e6)
        trace.export_event("device.compile", trace._now_us() - dur_us,
                           dur_us)


def install() -> None:
    """Idempotently hook jax.monitoring so every backend compile in this
    process is counted (and traced, when tracing is on)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax.monitoring
    jax.monitoring.register_event_duration_secs_listener(_on_event_duration)


def compile_count() -> int:
    """Backend compiles observed in this process since install()."""
    with _lock:
        return _count

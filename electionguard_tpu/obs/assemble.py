"""Merge per-process span JSONL files into one Chrome-trace timeline.

Every traced process exports ``spans-<proc>-<pid>.jsonl`` into the
shared trace dir (obs.trace).  This module loads them all, validates the
cross-process structure (one trace id, resolvable parent links, every
span inside its process-root envelope) and emits Chrome-trace JSON —
``{"traceEvents": [...]}`` — which Perfetto (ui.perfetto.dev) and
``chrome://tracing`` open directly: one track per process, rpc
client/server pairs linked by parent ids across tracks.

``tools/assemble_trace.py`` is the CLI wrapper; ``workflow/e2e.py``
calls ``merge_dir`` after a traced run.
"""

from __future__ import annotations

import glob
import json
import os

#: root-envelope slack (us): a retroactive device.compile event can start
#: marginally before the exporting process's root span opened
_SLACK_US = 2_000_000


#: keys a record must carry to be a span at all; anything less is a
#: torn write (a process killed mid-line) and is skipped with a warning
_REQUIRED_KEYS = ("trace_id", "span_id", "name", "ts")


def load_spans(trace_dir: str,
               warnings: list[str] | None = None) -> list[dict]:
    """Load every span record under ``trace_dir``.

    Malformed lines — a truncated JSONL tail from a SIGKILL'd process, a
    torn concurrent write, a record missing its identity keys — are
    SKIPPED, not fatal: each one appends a message to ``warnings`` (when
    given), so a died run still degrades to a partial timeline instead
    of losing the whole report to its last broken byte."""
    spans: list[dict] = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "spans-*.jsonl"))):
        base = os.path.basename(path)
        with open(path, errors="replace") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    if warnings is not None:
                        warnings.append(
                            f"{base}:{lineno}: malformed span line "
                            f"skipped: {e}")
                    continue
                if not isinstance(rec, dict) or any(
                        k not in rec for k in _REQUIRED_KEYS):
                    if warnings is not None:
                        warnings.append(
                            f"{base}:{lineno}: span record missing "
                            f"identity keys skipped")
                    continue
                rec.setdefault("parent_id", "")
                rec.setdefault("pid", 0)
                rec.setdefault("proc", "?")
                spans.append(rec)
    return spans


def is_open(span: dict) -> bool:
    """An in-flight span: exported as an ``"open": true`` marker by a
    live process (collector stream) and not yet closed."""
    return bool(span.get("open")) or "dur" not in span


def dedupe(spans: list[dict]) -> list[dict]:
    """Collapse duplicate span ids, preferring the CLOSED record: a live
    collector stream sees a span first as a repeated open marker, then
    once as the closed export."""
    best: dict[str, dict] = {}
    for s in spans:
        cur = best.get(s["span_id"])
        if cur is None or (is_open(cur) and not is_open(s)):
            best[s["span_id"]] = s
    return list(best.values())


def validate(spans: list[dict]) -> dict:
    """Structural report over a merged span set.  A clean single-run
    trace has exactly one trace id, no orphan parents, and every CLOSED
    span inside its process's root envelope (``gaps`` empty).  In-flight
    spans — records without a ``dur`` (``"open": true``), streamed by a
    live collector before their processes finished — are reported under
    ``open_spans`` instead of tripping the envelope/orphan checks, so a
    mid-run (or died-run) assembly can still pass ``-strict``."""
    spans = dedupe(spans)
    ids = {s["span_id"] for s in spans}
    trace_ids = sorted({s["trace_id"] for s in spans})
    procs = sorted({(s["proc"], s["pid"]) for s in spans})
    orphans = [s["span_id"] for s in spans
               if s["parent_id"] and s["parent_id"] not in ids]
    open_spans = [s["span_id"] for s in spans if is_open(s)]
    roots = {s["pid"]: s for s in spans if s["name"] == "process"}
    gaps = []
    for s in spans:
        root = roots.get(s["pid"])
        if is_open(s):
            continue   # no envelope to check yet
        if root is None:
            gaps.append({"span": s["span_id"], "why": "no process root"})
        elif is_open(root):
            # live process: envelope end unknown; start must still hold
            if s["ts"] + _SLACK_US < root["ts"]:
                gaps.append({"span": s["span_id"], "name": s["name"],
                             "why": "before open process root"})
        elif s is not root and not (
                root["ts"] - _SLACK_US <= s["ts"]
                and s["ts"] + s["dur"]
                <= root["ts"] + root["dur"] + _SLACK_US):
            gaps.append({"span": s["span_id"], "name": s["name"],
                         "why": "outside process root envelope"})
    by_id = {s["span_id"]: s for s in spans}
    rpc_pairs = unpaired = 0
    for s in spans:
        if s["name"].startswith("rpc.server."):
            parent = by_id.get(s["parent_id"])
            if parent is not None and parent["name"] == \
                    "rpc.client." + s["name"][len("rpc.server."):]:
                rpc_pairs += 1
            else:
                unpaired += 1
    return {"n_spans": len(spans), "trace_ids": trace_ids,
            "processes": [f"{p}:{pid}" for p, pid in procs],
            "orphans": orphans, "gaps": gaps, "open_spans": open_spans,
            "rpc_pairs": rpc_pairs, "rpc_server_unpaired": unpaired}


def chrome_trace(spans: list[dict]) -> dict:
    """Chrome-trace JSON: per-process named tracks, one complete ("X")
    event per span, parent/trace ids preserved under ``args``.

    Cross-process parent links (an rpc.server span whose parent is the
    caller's rpc.client span, a subprocess root parented to a driver
    phase) additionally emit a flow pair — ``ph: "s"`` anchored inside
    the parent slice, ``ph: "f"`` (``bp: "e"``) on the child — so
    Perfetto renders RPC causality as arrows between tracks instead of
    disconnected slices."""
    spans = dedupe(spans)
    by_id = {s["span_id"]: s for s in spans}
    events: list[dict] = []
    named: set[int] = set()
    for s in sorted(spans, key=lambda s: s["ts"]):
        if s["pid"] not in named:
            named.add(s["pid"])
            events.append({"ph": "M", "name": "process_name",
                           "pid": s["pid"], "tid": 0,
                           "args": {"name": f"{s['proc']} ({s['pid']})"}})
        args = {"trace_id": s["trace_id"], "span_id": s["span_id"],
                "parent_id": s["parent_id"]}
        if is_open(s):
            args["open"] = True
        args.update(s.get("attrs") or {})
        events.append({"ph": "X", "name": s["name"], "cat": "egtpu",
                       "ts": s["ts"], "dur": max(s.get("dur", 0), 1),
                       "pid": s["pid"], "tid": s.get("tid", 0),
                       "args": args})
        parent = by_id.get(s["parent_id"])
        if parent is None or parent["pid"] == s["pid"]:
            continue
        # flow start must land INSIDE the parent slice to bind to it;
        # clamp the child's start into the parent's interval (the end
        # is unbounded for an open parent)
        p_end = parent["ts"] + max(parent.get("dur", 0), 1)
        anchor = max(parent["ts"], min(s["ts"], p_end - 1))
        flow = {"name": "egtpu-link", "cat": "egtpu",
                "id": s["span_id"]}
        events.append(dict(flow, ph="s", ts=anchor, pid=parent["pid"],
                           tid=parent.get("tid", 0)))
        events.append(dict(flow, ph="f", bp="e", ts=s["ts"],
                           pid=s["pid"], tid=s.get("tid", 0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_dir(trace_dir: str, out_path: str,
              extra_spans: list[dict] | None = None) -> dict:
    """Load + validate + write the merged Chrome trace; returns the
    validation report (with ``out`` and load ``warnings`` added).
    ``extra_spans`` lets a live collector merge its in-memory open-span
    markers into the files."""
    warnings: list[str] = []
    spans = load_spans(trace_dir, warnings) + list(extra_spans or [])
    report = validate(spans)
    with open(out_path, "w") as f:
        json.dump(chrome_trace(spans), f)
    report["out"] = out_path
    report["warnings"] = warnings
    return report

"""Distributed trace spans with gRPC metadata propagation.

One e2e run is one **trace**; every timed operation (a workflow phase, an
rpc leg, a device batch, a backend compile) is a **span** with a
trace_id/span_id/parent_id.  Spans export as one JSONL file per process
(``spans-<proc>-<pid>.jsonl`` under the trace dir) and
``tools/assemble_trace.py`` merges every process's file into a single
Chrome-trace/Perfetto timeline.

Propagation:

* across **threads/frames**: a ``contextvars`` stack — ``span()`` parents
  to the innermost active span (or the process root span);
* across **processes over gRPC**: the client interceptor stamps
  ``egtpu-trace-id``/``egtpu-span-id`` metadata on every outgoing rpc and
  the server wrapper adopts them, so the server-side span is a child of
  the caller's client-side span (hooked at the same
  ``rpc_util.make_channel``/``generic_service`` points as the fault
  harness — zero call-site changes);
* across **spawned subprocesses**: the workflow driver exports
  ``EGTPU_OBS_TRACE`` (dir), ``EGTPU_OBS_TRACE_ID`` and
  ``EGTPU_OBS_PARENT_SPAN`` so every child joins the driver's trace.

Tracing is **off by default** and free when off: ``span()`` returns a
module-level no-op singleton (no allocation), ``intercept_channel`` /
``wrap_server_method`` return their input untouched, so the disabled hot
path is exactly the pre-obs code path.  Enable with
``EGTPU_OBS_TRACE=<dir>`` (read by ``obs.init_from_env`` at CLI startup)
or programmatically with ``enable(dir)`` *before* channels/servers are
built.
"""

from __future__ import annotations

import atexit
import contextvars
import json
import os
import sys
import threading
from typing import Optional

import grpc

from electionguard_tpu.utils import clock

MD_TRACE_ID = "egtpu-trace-id"
MD_SPAN_ID = "egtpu-span-id"

_lock = threading.Lock()
_enabled = False
_dir: Optional[str] = None
_trace_id = ""
_proc = ""
_file = None
_root: Optional["Span"] = None
#: export tee: every exported span line is also handed to these (the
#: telemetry client streams them to the obs collector).  Hooks must be
#: fast and never raise — they run on the exporting thread.
_hooks: list = []
#: open-span tracking (off unless a consumer needs in-flight spans —
#: the collector client turns it on so a LIVE timeline can include the
#: process root and currently-running phases as "open" records)
_track_open = False
_open: dict = {}
#: (trace_id, span_id) of the innermost active span in this context
_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "egtpu_trace_ctx", default=None)


def _now_us() -> int:
    return int(clock.now() * 1e6)


def _new_id(nbytes: int = 8) -> str:
    return os.urandom(nbytes).hex()


def enabled() -> bool:
    return _enabled


def trace_id() -> str:
    return _trace_id


def proc_name() -> str:
    if _proc:
        return _proc
    return _default_proc()


def _default_proc() -> str:
    name = os.environ.get("EGTPU_OBS_PROC")
    if name:
        return name
    argv0 = os.path.basename(sys.argv[0]) if sys.argv and sys.argv[0] \
        else "python"
    return argv0[:-3] if argv0.endswith(".py") else argv0


def current_ids() -> tuple[str, str]:
    """(trace_id, span_id) of the active context — ("", "") when
    tracing is off and no rpc context was adopted."""
    ctx = _ctx.get()
    if ctx is not None:
        return ctx
    if _enabled:
        return _trace_id, _root.span_id if _root is not None else ""
    return "", ""


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def enable(dir_path: str, trace_id_hex: Optional[str] = None,
           proc: Optional[str] = None) -> None:
    """Start exporting spans to ``dir_path``.  Idempotent; the first call
    wins.  The process root span opens now and closes at interpreter
    exit, so every other span nests inside a per-process envelope."""
    global _enabled, _dir, _trace_id, _proc, _file, _root
    with _lock:
        if _enabled:
            return
        os.makedirs(dir_path, exist_ok=True)
        _dir = dir_path
        _trace_id = (trace_id_hex
                     or os.environ.get("EGTPU_OBS_TRACE_ID")
                     or _new_id(16))
        _proc = proc or _default_proc()
        _file = open(os.path.join(
            dir_path, f"spans-{_proc}-{os.getpid()}.jsonl"), "a")
        _enabled = True
    root = Span("process", {"argv": " ".join(sys.argv[:4])})
    root.parent_override = os.environ.get("EGTPU_OBS_PARENT_SPAN", "")
    _root = root
    root.__enter__()
    atexit.register(_shutdown)


def enable_from_env() -> bool:
    """Enable when ``EGTPU_OBS_TRACE=<dir>`` is set; returns enabled."""
    d = os.environ.get("EGTPU_OBS_TRACE")
    if d:
        enable(d)
    return _enabled


def shutdown() -> None:
    """Close the root span and the export file (idempotent).  Runs at
    interpreter exit; a driver that wants to MERGE its own spans before
    exiting (workflow/e2e.py) calls it explicitly first."""
    global _file, _root
    root = _root
    _root = None
    if root is not None:
        root.__exit__(None, None, None)
    with _lock:
        if _file is not None:
            try:
                _file.close()
            except OSError:
                pass
            _file = None


_shutdown = shutdown


def _reset_for_tests() -> None:
    """Return the module to the disabled state (tests only — production
    processes enable once and never disable)."""
    global _enabled, _dir, _trace_id, _proc, _file, _root
    shutdown()
    with _lock:
        _enabled = False
        _dir = None
        _trace_id = ""
        _proc = ""
    del _hooks[:]
    track_open_spans(False)


def _export(line: dict) -> None:
    with _lock:
        if _file is not None:
            _file.write(json.dumps(line, separators=(",", ":")) + "\n")
            _file.flush()
    for hook in _hooks:
        try:
            hook(line)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass


def add_export_hook(fn) -> None:
    """Tee every exported span record (a dict) to ``fn`` as well as the
    JSONL file; used by the collector client to stream spans live."""
    if fn not in _hooks:
        _hooks.append(fn)


def remove_export_hook(fn) -> None:
    if fn in _hooks:
        _hooks.remove(fn)


def track_open_spans(on: bool = True) -> None:
    """Keep a registry of currently-open spans so ``open_span_records``
    can describe in-flight work (the process root, a running phase) to a
    live consumer.  Off by default: the disabled path adds nothing to
    span enter/exit."""
    global _track_open
    _track_open = on
    if not on:
        _open.clear()


def open_span_records() -> list[dict]:
    """Snapshot of the currently-open spans as JSONL-shaped records with
    ``"open": true`` and no ``dur`` — the timeline assembler reports
    them as ``open_spans`` instead of failing on the missing envelope.
    Always includes the process root span (even when tracking is off),
    so a mid-run assembly can resolve every live process's parents."""
    out = []
    root = _root
    if root is not None and getattr(root, "span_id", ""):
        out.append(root._open_record())
    if _track_open:
        for s in list(_open.values()):
            if s is not root:
                try:
                    out.append(s._open_record())
                except AttributeError:
                    pass   # span mid-enter on another thread
    return out


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class Span:
    """One timed operation.  Context manager; on exit one JSONL line is
    exported.  ``set(k, v)`` attaches attributes mid-span."""

    __slots__ = ("name", "attrs", "trace_id", "span_id", "parent_id",
                 "parent_override", "t0", "_token", "_tid")

    def __init__(self, name: str, attrs: Optional[dict] = None):
        self.name = name
        self.attrs = attrs
        self.parent_override = None

    def __enter__(self) -> "Span":
        parent = _ctx.get()
        if parent is not None:
            self.trace_id, self.parent_id = parent
        else:
            self.trace_id = _trace_id
            root = _root
            self.parent_id = (root.span_id
                              if root is not None and root is not self
                              else "")
        if self.parent_override is not None:
            self.parent_id = self.parent_override
        self.span_id = _new_id()
        self._tid = threading.get_native_id()
        self._token = _ctx.set((self.trace_id, self.span_id))
        self.t0 = _now_us()
        if _track_open:
            _open[self.span_id] = self
        return self

    def _open_record(self) -> dict:
        """In-flight description of this span (no ``dur`` — still open)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "ts": self.t0, "open": True,
                "pid": os.getpid(), "tid": self._tid, "proc": _proc}

    def set(self, key: str, value) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def __exit__(self, et, ev, tb) -> bool:
        _ctx.reset(self._token)
        if _track_open:
            _open.pop(self.span_id, None)
        if et is not None:
            self.set("error", et.__name__)
        line = {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "ts": self.t0, "dur": _now_us() - self.t0,
                "pid": os.getpid(), "tid": self._tid, "proc": _proc}
        if self.attrs:
            line["attrs"] = self.attrs
        _export(line)
        return False


class _NoopSpan:
    """The disabled-path singleton: zero allocation per ``span()`` call."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = ""

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, et, ev, tb) -> bool:
        return False

    def set(self, key: str, value) -> None:
        pass


_NOOP = _NoopSpan()


def span(name: str, attrs: Optional[dict] = None):
    """A new child span of the active context — or the shared no-op when
    tracing is off.  Callers on true hot paths should guard the attrs
    dict construction behind ``trace.enabled()``."""
    if not _enabled:
        return _NOOP
    return Span(name, attrs)


def export_event(name: str, ts_us: int, dur_us: int,
                 attrs: Optional[dict] = None) -> None:
    """Export a retroactive span (e.g. a compile duration reported by a
    jax.monitoring listener after the fact), parented to the active
    context of the calling thread."""
    if not _enabled:
        return
    trace, parent = current_ids()
    line = {"trace_id": trace, "span_id": _new_id(),
            "parent_id": parent, "name": name, "ts": ts_us, "dur": dur_us,
            "pid": os.getpid(), "tid": threading.get_native_id(),
            "proc": _proc}
    if attrs:
        line["attrs"] = attrs
    _export(line)


# ---------------------------------------------------------------------------
# gRPC propagation
# ---------------------------------------------------------------------------

class _CallDetails(grpc.ClientCallDetails):
    __slots__ = ("method", "timeout", "metadata", "credentials",
                 "wait_for_ready", "compression")

    def __init__(self, base, metadata):
        self.method = base.method
        self.timeout = base.timeout
        self.metadata = metadata
        self.credentials = base.credentials
        self.wait_for_ready = getattr(base, "wait_for_ready", None)
        self.compression = getattr(base, "compression", None)


class TraceClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    """Opens a ``rpc.client.<method>`` span around every outgoing rpc and
    stamps its ids onto the call metadata for the server to adopt."""

    def intercept_unary_unary(self, continuation, client_call_details,
                              request):
        method = client_call_details.method.rsplit("/", 1)[-1]
        with Span(f"rpc.client.{method}") as s:
            md = list(client_call_details.metadata or ())
            md.append((MD_TRACE_ID, s.trace_id))
            md.append((MD_SPAN_ID, s.span_id))
            outcome = continuation(
                _CallDetails(client_call_details, md), request)
            try:
                code = outcome.code()
            except Exception:  # noqa: BLE001 — status is best-effort
                code = None
            if code is not None and code != grpc.StatusCode.OK:
                s.set("status", code.name)
            # an inner interceptor's RAISED error comes back as a raw
            # RpcError outcome (not a call) — re-raise so it reaches
            # the caller instead of dying on ``outcome.result()``
            if isinstance(outcome, grpc.RpcError) \
                    and not hasattr(outcome, "result"):
                raise outcome
            return outcome


def intercept_channel(channel: grpc.Channel) -> grpc.Channel:
    """Wrap ``channel`` with the trace interceptor (identity when
    tracing is off — the disabled path adds nothing)."""
    if not _enabled:
        return channel
    return grpc.intercept_channel(channel, TraceClientInterceptor())


def wrap_server_method(service: str, method: str, fn):
    """Wrap one ``fn(request, context)`` impl in a ``rpc.server.<method>``
    span that adopts the caller's trace context from the rpc metadata
    (identity when tracing is off)."""
    if not _enabled:
        return fn

    def traced(request, context):
        tid = sid = ""
        for k, v in (context.invocation_metadata() or ()):
            if k == MD_TRACE_ID:
                tid = v
            elif k == MD_SPAN_ID:
                sid = v
        token = _ctx.set((tid, sid)) if tid else None
        try:
            with Span(f"rpc.server.{method}", {"service": service}):
                return fn(request, context)
        finally:
            if token is not None:
                _ctx.reset(token)

    return traced

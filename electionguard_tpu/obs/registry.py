"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

One registry instance is one namespace of named (optionally labeled)
metrics.  The module-level ``REGISTRY`` is the process default — rpc
plumbing (client retries, server call counts) and the jax compile
listener write there; subsystems that need isolated counting (one
``ServiceMetrics`` per serving instance, tests) create their own
``MetricsRegistry`` and register it for exposition with ``expose()``.

Three read paths, all built on ``snapshot()`` (a plain JSON-able dict):

* ``prometheus_text()`` / ``prometheus_text_all()`` — Prometheus text
  exposition (served by ``obs.httpd``);
* ``to_proto()`` / ``merged_to_proto()`` — the ``metrics`` rpc's
  ``MetricsResponse`` (every gRPC server answers it, see
  ``rpc_util.generic_service``);
* ``MetricsRegistry.merge(snapshots)`` — cross-process aggregation:
  counters and histogram buckets sum, gauges sum (they are point-in-time
  per process; a merged gauge reads as the fleet total).

Everything is lock-protected and cheap enough for per-request updates.
"""

from __future__ import annotations

import bisect
import threading
import weakref
from typing import Callable, Optional, Sequence

#: shared default bucket edges (ms): log-ish spacing from sub-ms to minutes
LATENCY_MS_BOUNDS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                     500.0, 1000.0, 2500.0, 5000.0, 15000.0, 60000.0)


def flat_name(name: str, labels: Optional[dict] = None) -> str:
    """Prometheus-style series name: ``name{k="v",...}`` (keys sorted)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{escape_label_value(str(labels[k]))}"'
                     for k in sorted(labels))
    return f"{name}{{{inner}}}"


def escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline (exposition format 0.0.4)."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def unescape_label_value(v: str) -> str:
    """Inverse of :func:`escape_label_value` (for snapshot consumers
    that parse flat names back into label dicts)."""
    out = []
    i = 0
    while i < len(v):
        ch = v[i]
        if ch == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def election_labels(extra: Optional[dict] = None) -> dict:
    """The per-tenant label set election-scoped series carry: the
    AMBIENT election id (``obs.tenant`` contextvar, set per request by
    the router/service; the ``EGTPU_ELECTION`` knob — ``default`` out
    of the box — when no scope is active) as ``election=<id>``, plus
    any site-specific labels.  Resolve at WRITE time, not registration
    time: one process serving N tenants labels each increment with the
    requesting election, never a process-global."""
    from electionguard_tpu.obs import tenant
    labels = {"election": tenant.current_election()}
    if extra:
        labels.update(extra)
    return labels


class Counter:
    """Monotonic-by-convention numeric metric (float increments allowed —
    e.g. cumulative backoff seconds; negative ``inc`` is permitted for
    the rare admit-then-unadmit correction the serving plane does)."""

    __slots__ = ("name", "labels", "_v", "_lock")

    def __init__(self, name: str, labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels) if labels else None
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, by=1) -> None:
        with self._lock:
            self._v += by

    @property
    def value(self):
        with self._lock:
            return self._v


class Gauge:
    """Point-in-time value: either ``set()`` explicitly or backed by a
    zero-arg callback read at snapshot time."""

    __slots__ = ("name", "labels", "_v", "_fn", "_lock")

    def __init__(self, name: str, labels: Optional[dict] = None,
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.labels = dict(labels) if labels else None
        self._v = 0
        self._fn = fn
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self._v = v

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:  # noqa: BLE001 — a dead callback reads 0
                return 0
        with self._lock:
            return self._v


class Histogram:
    """Fixed-bound histogram: counts[i] observations ≤ bounds[i], last
    bucket is overflow.  Snapshot-able without stopping writers."""

    __slots__ = ("name", "labels", "bounds", "_counts", "_sum", "_n",
                 "_lock")

    def __init__(self, name: str, bounds: Sequence[float],
                 labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels) if labels else None
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._n += 1

    def snapshot(self) -> dict:
        with self._lock:
            return dict(name=self.name, bounds=list(self.bounds),
                        counts=list(self._counts), sum=self._sum,
                        count=self._n)

    def mean(self) -> float:
        with self._lock:
            return self._sum / self._n if self._n else 0.0

    def quantile(self, q: float) -> float:
        """Upper bucket-bound estimate of the q-quantile (q in [0,1])."""
        with self._lock:
            n, counts = self._n, list(self._counts)
        if n == 0:
            return 0.0
        target = q * n
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                return (self.bounds[i] if i < len(self.bounds)
                        else self.bounds[-1])
        return self.bounds[-1]


class MetricsRegistry:
    """Get-or-create namespace of metrics; the same (name, labels) always
    returns the same object, so call sites never cache by hand unless
    they are on a hot path."""

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    # ---- get-or-create ----------------------------------------------
    def counter(self, name: str, labels: Optional[dict] = None) -> Counter:
        key = flat_name(name, labels)
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter(name, labels)
            return c

    def gauge(self, name: str, labels: Optional[dict] = None,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        key = flat_name(name, labels)
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge(name, labels, fn=fn)
            elif fn is not None:
                g._fn = fn
            return g

    def histogram(self, name: str,
                  bounds: Sequence[float] = LATENCY_MS_BOUNDS,
                  labels: Optional[dict] = None) -> Histogram:
        key = flat_name(name, labels)
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram(name, bounds, labels)
            return h

    # ---- read paths --------------------------------------------------
    def snapshot(self) -> dict:
        """Plain JSON-able view: {"counters": {flat: v}, "gauges": ...,
        "histograms": {flat: {bounds, counts, sum, count}}}."""
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        return {
            "counters": {k: c.value for k, c in counters},
            "gauges": {k: g.value for k, g in gauges},
            "histograms": {k: h.snapshot() for k, h in hists},
        }

    @staticmethod
    def merge(snapshots: Sequence[dict]) -> dict:
        """Merge per-process ``snapshot()`` dicts: counters and gauges
        sum; histograms sum bucket-wise (first-seen bounds win — a
        mismatched-bounds series is summed on count/sum only)."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for snap in snapshots:
            for k, v in snap.get("counters", {}).items():
                out["counters"][k] = out["counters"].get(k, 0) + v
            for k, v in snap.get("gauges", {}).items():
                out["gauges"][k] = out["gauges"].get(k, 0) + v
            for k, h in snap.get("histograms", {}).items():
                acc = out["histograms"].get(k)
                if acc is None:
                    out["histograms"][k] = {
                        "name": h.get("name", k),
                        "bounds": list(h["bounds"]),
                        "counts": list(h["counts"]),
                        "sum": h["sum"], "count": h["count"]}
                else:
                    acc["sum"] += h["sum"]
                    acc["count"] += h["count"]
                    if acc["bounds"] == list(h["bounds"]):
                        acc["counts"] = [a + b for a, b in
                                         zip(acc["counts"], h["counts"])]
        return out

    def prometheus_text(self) -> str:
        return prometheus_text_of(self.snapshot())

    def to_proto(self):
        return proto_of(self.snapshot())


# ---------------------------------------------------------------------------
# exposition set: the process default + every registry expose()d later
# ---------------------------------------------------------------------------

REGISTRY = MetricsRegistry("default")
_exposed: "weakref.WeakSet[MetricsRegistry]" = weakref.WeakSet()


def expose(registry: MetricsRegistry) -> MetricsRegistry:
    """Include ``registry`` in this process's merged exposition (http
    endpoint, default ``metrics`` rpc).  Held weakly: a dropped
    subsystem disappears from the scrape instead of leaking."""
    _exposed.add(registry)
    return registry


def merged_snapshot() -> dict:
    snaps = [REGISTRY.snapshot()] + [r.snapshot() for r in list(_exposed)]
    return MetricsRegistry.merge(snaps)


def prometheus_text_all() -> str:
    return prometheus_text_of(merged_snapshot())


def merged_to_proto():
    return proto_of(merged_snapshot())


# ---------------------------------------------------------------------------
# formatters (off any snapshot, local or merged)
# ---------------------------------------------------------------------------

def _base_name(flat: str) -> str:
    return flat.split("{", 1)[0]


def _sanitize(name: str) -> str:
    return "".join(ch if (ch.isalnum() or ch in "_:") else "_"
                   for ch in name)


def prometheus_text_of(snap: dict, prefix: str = "egtpu_") -> str:
    """Prometheus text exposition format 0.0.4 of one snapshot."""
    lines: list[str] = []
    typed: set[str] = set()

    def emit(flat: str, value, kind: str) -> None:
        base = _sanitize(prefix + _base_name(flat))
        if base not in typed:
            typed.add(base)
            lines.append(f"# TYPE {base} {kind}")
        labels = flat[len(_base_name(flat)):]
        lines.append(f"{base}{labels} {value}")

    for k in sorted(snap.get("counters", {})):
        emit(k, snap["counters"][k], "counter")
    for k in sorted(snap.get("gauges", {})):
        emit(k, snap["gauges"][k], "gauge")
    for k in sorted(snap.get("histograms", {})):
        h = snap["histograms"][k]
        base = _sanitize(prefix + _base_name(k))
        if base not in typed:
            typed.add(base)
            lines.append(f"# TYPE {base} histogram")
        labels = k[len(_base_name(k)):]
        inner = labels[1:-1] if labels else ""
        cum = 0
        for bound, c in zip(h["bounds"], h["counts"]):
            cum += c
            le = ",".join(x for x in (inner, f'le="{bound}"') if x)
            lines.append(f"{base}_bucket{{{le}}} {cum}")
        le = ",".join(x for x in (inner, 'le="+Inf"') if x)
        lines.append(f"{base}_bucket{{{le}}} {h['count']}")
        lines.append(f"{base}_sum{labels} {h['sum']}")
        lines.append(f"{base}_count{labels} {h['count']}")
    return "\n".join(lines) + "\n"


def proto_of(snap: dict):
    """A ``MetricsResponse`` (counters map + histogram snapshots) of one
    snapshot; gauges ride in the counters map like the serving plane
    always did (the map is "counters AND point-in-time gauges")."""
    from electionguard_tpu.publish import pb
    counters = {k: int(v) for k, v in snap.get("counters", {}).items()}
    counters.update({k: int(v) for k, v in snap.get("gauges", {}).items()})
    resp = pb.msg("MetricsResponse")(counters=counters)
    for k in sorted(snap.get("histograms", {})):
        h = snap["histograms"][k]
        resp.histograms.add(name=k, bounds=h["bounds"], counts=h["counts"],
                            sum=h["sum"], count=h["count"])
    return resp

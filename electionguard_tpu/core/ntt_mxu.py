"""MXU Montgomery engine: 4096-bit modular multiplication as int8 matmuls.

This is the TPU-native answer to the reference's hot layer (JVM BigInteger
under ``ProductionElementModP`` — reference call sites:
src/main/java/electionguard/util/ConvertCommonProto.java:46,55 [ext]) for
TPU generations where the MXU dwarfs the VPU.  The VPU CIOS kernel
(electionguard_tpu.core.bignum_jax) remains the portable/differential twin;
both share the same (B, 256)-uint32 16-bit-limb interface and the same
Montgomery domain R = 2^4096, so PowRadix tables and all callers are
backend-agnostic.

Math design (all steps exact, no floating point)
------------------------------------------------
* Numbers are polynomials in base 256: 512 8-bit digits.  A 4096x4096-bit
  product is a length-1023 convolution whose coefficients are bounded by
  512*255^2 < 2^25 — they accumulate EXACTLY in int32.
* Convolution of two *varying* operands is bilinear, so it cannot be one
  matmul; we evaluate both operands with a number-theoretic transform
  (length-1024 NTT = dense matmul with a shared Vandermonde-of-roots
  matrix), multiply pointwise, and interpolate back.  Two NTT primes
  m1 = 12289, m2 = 13313 (both ≡ 1 mod 1024, product > 2^27 > max
  coefficient) give the true coefficients by CRT.
* Matmuls run on the MXU in int8: matrix entries are centered residues
  split into two balanced digit planes (lo ∈ [-128,127], hi = the
  carry plane, |hi| ≤ 26), inputs are digits-minus-128 ("e-form", one
  int8 plane) with the +128 offset folded into precomputed column-sum
  vectors.  Every partial matmul is ≤ 1024*128*128 = 2^24 — exact in
  int32 accumulation.
* Montgomery reduction needs T_low·p' mod R and m1·p — both have one
  FIXED operand (p' = -p^{-1} mod R, p), so they are plain Toeplitz
  matmuls, no NTT.  The unsigned-offset cross terms reduce to cumulative
  sums (VPU) and host-precomputed vectors.
* Mod-m reductions on the VPU use Barrett with constants exhaustively
  validated over the full input domain (see tests/test_ntt_mxu.py):
  (a=13,b=13) has max deficit 2 for x < 2^26; (a=14,b=13) max deficit 3
  for x < 2^28.  Pointwise products use 16-bit Montgomery reduction with
  the 2^-16 factor folded into the inverse-NTT matrix.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.core import table_cache

NL = 256          # 16-bit limbs per 4096-bit element
ND = 512          # 8-bit digits
NC = 1024         # convolution / NTT length
PRIMES = (12289, 13313)          # ≡ 1 (mod 1024); product 1.636e8 > 2^27
OMEGA = {12289: 10302, 13313: 10076}   # primitive 1024th roots of unity

U32 = jnp.uint32
I32 = jnp.int32


class NttCtx(NamedTuple):
    """Device constants for one modulus p (plus the shared CIOS context)."""

    mctx: bn.MontCtx
    V0: jax.Array        # (2, NC, NC) int8 forward-NTT lo digit plane
    V1: jax.Array        # (2, NC, NC) int8 forward-NTT hi digit plane
    iV0: jax.Array       # (2, NC, NC) int8 inverse-NTT lo plane (scaled)
    iV1: jax.Array       # (2, NC, NC) int8 inverse-NTT hi plane
    evoff0: jax.Array    # (2, 1, NC) int32  128·colsum(V0) + bias (mult of m)
    evoff1: jax.Array    # (2, 1, NC) int32  128·colsum(V1) + bias
    ivoff0: jax.Array    # (2, 1, NC) int32  128·colsum(iV0) + bias
    ivoff1: jax.Array    # (2, 1, NC) int32  128·colsum(iV1) + bias
    toep_m: jax.Array    # (ND, ND) int8   Toeplitz of p'e, low half
    f_m: jax.Array       # (ND,) int32     fixed offset terms for m1
    toep_p: jax.Array    # (ND, NC) int8   Toeplitz of pe, full product
    f_p: jax.Array       # (NC,) int32     fixed offset terms for m1·p
    p_pad: jax.Array     # (NL + 2,) uint32  p in 16-bit limbs, padded
    # static python ints (hashable; ctx is closed over, not traced)
    m: tuple             # (m1, m2)
    mprime: tuple        # -m^{-1} mod 2^16 per prime
    mu26: tuple          # floor(2^26/m) per prime   (barrett a=13,b=13)
    mu27: tuple          # floor(2^27/m) per prime   (barrett a=14,b=13)
    bias1: tuple         # eval stage-1 bias (multiple of m)
    bias0: tuple         # eval stage-0 bias
    biasc: tuple         # interp C-stage bias
    biasb: tuple         # interp B-stage bias
    biasa: tuple         # interp A-stage bias
    inv12s: int          # m1^{-1}·2^16 mod m2 (for CRT via mredc16)


# ---------------------------------------------------------------------------
# host-side construction
# ---------------------------------------------------------------------------

def _digit_planes(mat: np.ndarray, m: int):
    """Centered residues mod m -> two balanced int8 planes (lo, hi) with
    lo ∈ [-128,127], hi = (v+128)//256, v = lo + 256·hi."""
    v = mat % m
    v = np.where(v > m // 2, v - m, v).astype(np.int64)
    hi = (v + 128) >> 8
    lo = v - (hi << 8)
    assert lo.min() >= -128 and lo.max() <= 127
    assert abs(hi).max() <= 26, hi.max()
    return lo.astype(np.int8), hi.astype(np.int8)


def _int_to_digits(x: int, nd: int) -> np.ndarray:
    return np.frombuffer(x.to_bytes(nd, "little"), dtype=np.uint8).copy()


def _build_ntt_arrays(p: int) -> dict:
    """Host-side construction of every NttCtx array constant, as plain
    numpy (the expensive part of ``make_ntt_ctx`` — minutes of Python
    bigint/Vandermonde work on the production group — and therefore the
    part ``core.table_cache`` persists across processes).  The static
    ints ride along packed into the ``scalars`` vector so a cache hit
    skips the build entirely."""
    V0s, V1s, iV0s, iV1s = [], [], [], []
    ev0, ev1, iv0, iv1 = [], [], [], []
    mprime, mu26, mu27 = [], [], []
    b1, b0, bc, bb, ba = [], [], [], [], []
    for m in PRIMES:
        w = OMEGA[m]
        # powers of omega: o[k] = w^k mod m, k in [0, NC)
        o = np.ones(NC, dtype=np.int64)
        for k in range(1, NC):
            o[k] = o[k - 1] * w % m
        idx = np.outer(np.arange(NC), np.arange(NC)) % NC
        V = o[idx]                                   # V[i,j] = w^(ij)
        winv = pow(w, -1, m)
        oi = np.ones(NC, dtype=np.int64)
        for k in range(1, NC):
            oi[k] = oi[k - 1] * winv % m
        scale = pow(NC, -1, m) * (1 << 16) % m       # fold n^-1 and 2^16
        iV = oi[idx] * scale % m
        v0, v1 = _digit_planes(V, m)
        i0, i1 = _digit_planes(iV, m)
        V0s.append(v0); V1s.append(v1); iV0s.append(i0); iV1s.append(i1)

        def colsum_off(plane, extra_neg, bias_pow):
            off = 128 * plane.astype(np.int64).sum(axis=0)
            neg = -min(0, int(off.min())) + extra_neg
            bias = m * ((neg + m - 1) // m)
            assert bias + extra_neg < (1 << bias_pow)
            return off, bias

        # eval stage 1: X1 = e@V1 + off1 + bias1, |e@V1| <= NC*128*26 < 2^22
        off1, bias1 = colsum_off(v1, NC * 128 * 26, 24)
        # eval stage 0: X0 = e@V0 + off0 + (r1<<8) + bias0
        off0, bias0 = colsum_off(v0, NC * 128 * 128, 26)
        # interp C: t1@iV1, |.| <= NC*52*26 < 2^21
        biasC = m * ((NC * 52 * 26 + m - 1) // m)
        # interp B: t0e@iV1 + ivoff1 + t1@iV0 + (Cm<<8) + biasb
        ioff1, biasB = colsum_off(i1, NC * 128 * 26 + NC * 52 * 128
                                  + (m << 8), 25)
        # interp A: t0e@iV0 + ivoff0 + (Bm<<8) + biasa
        ioff0, biasA = colsum_off(i0, NC * 128 * 128 + (m << 8), 26)

        ev0.append(off0 + bias0); ev1.append(off1 + bias1)
        iv0.append(ioff0 + biasA); iv1.append(ioff1 + biasB)
        b1.append(bias1); b0.append(bias0)
        bc.append(biasC); bb.append(biasB); ba.append(biasA)
        mprime.append((-pow(m, -1, 1 << 16)) % (1 << 16))
        mu26.append((1 << 26) // m)
        mu27.append((1 << 27) // m)

    # Toeplitz constants for the Montgomery reduction (fixed operands)
    R = 1 << (16 * NL)
    pprime = (-pow(p, -1, R)) % R
    pd = _int_to_digits(pprime, ND).astype(np.int64)
    pe = pd - 128
    # toep_m[i, k] = p'e[k-i] for 0 <= k-i < ND (low-half product)
    i_idx = np.arange(ND)[:, None]
    k_idx = np.arange(ND)[None, :]
    d = k_idx - i_idx
    toep_m = np.where((d >= 0), pe[np.clip(d, 0, ND - 1)], 0).astype(np.int8)
    # f_m[k] = 128·prefixsum(p'e)[k] + 128^2·(k+1)
    f_m = 128 * np.cumsum(pe) + 16384 * (np.arange(ND) + 1)

    pdg = _int_to_digits(p, ND).astype(np.int64)
    ppe = pdg - 128
    k_idx = np.arange(NC)[None, :]
    d = k_idx - i_idx                                 # (ND, NC)
    toep_p = np.where((d >= 0) & (d < ND),
                      ppe[np.clip(d, 0, ND - 1)], 0).astype(np.int8)
    # f_p[k] = 128·(windowed prefix of pe) + 128^2·overlap(k)
    cs = np.concatenate([[0], np.cumsum(ppe)])        # cs[j] = sum pe[:j]
    k = np.arange(NC)
    lo_i = np.maximum(0, k - (ND - 1))
    hi_i = np.minimum(ND - 1, k)
    win = cs[np.clip(k - lo_i + 1, 0, ND)] - cs[np.clip(k - hi_i, 0, ND)]
    overlap = np.maximum(0, hi_i - lo_i + 1)
    f_p = 128 * win + 16384 * overlap

    p_pad = np.zeros(NL + 2, dtype=np.uint32)
    p_pad[:NL] = np.asarray(bn.int_to_limbs(p, NL))

    m1, m2 = PRIMES
    return {
        "V0": np.stack(V0s), "V1": np.stack(V1s),
        "iV0": np.stack(iV0s), "iV1": np.stack(iV1s),
        "evoff0": np.stack(ev0).astype(np.int32),
        "evoff1": np.stack(ev1).astype(np.int32),
        "ivoff0": np.stack(iv0).astype(np.int32),
        "ivoff1": np.stack(iv1).astype(np.int32),
        "toep_m": toep_m, "f_m": f_m.astype(np.int32),
        "toep_p": toep_p, "f_p": f_p.astype(np.int32),
        "p_pad": p_pad,
        "scalars": np.array(
            list(PRIMES) + mprime + mu26 + mu27 + b1 + b0 + bc + bb + ba
            + [pow(m1, -1, m2) * (1 << 16) % m2], dtype=np.int64),
    }


@functools.lru_cache(maxsize=None)
def make_ntt_ctx(p: int) -> NttCtx:
    mctx = bn.make_mont_ctx(p, NL)
    # keyed by the modulus digest + engine geometry only — the arrays
    # are pure functions of p, so every tenant (and every election key)
    # over one group shares this entry (table_cache contract)
    fp = table_cache.fingerprint(
        "nttctx", p=table_cache.int_digest(p), nl=NL, nd=ND, nc=NC,
        primes=list(PRIMES), omega=[OMEGA[m] for m in PRIMES])
    arrays = table_cache.load("nttctx", fp)
    if arrays is None:
        arrays = _build_ntt_arrays(p)
        table_cache.store("nttctx", fp, arrays)
    sc = arrays["scalars"]

    def pair(i: int) -> tuple:
        return (int(sc[i]), int(sc[i + 1]))

    return NttCtx(
        mctx=mctx,
        V0=jnp.asarray(arrays["V0"]), V1=jnp.asarray(arrays["V1"]),
        iV0=jnp.asarray(arrays["iV0"]), iV1=jnp.asarray(arrays["iV1"]),
        evoff0=jnp.asarray(arrays["evoff0"])[:, None, :],
        evoff1=jnp.asarray(arrays["evoff1"])[:, None, :],
        ivoff0=jnp.asarray(arrays["ivoff0"])[:, None, :],
        ivoff1=jnp.asarray(arrays["ivoff1"])[:, None, :],
        toep_m=jnp.asarray(arrays["toep_m"]),
        f_m=jnp.asarray(arrays["f_m"]),
        toep_p=jnp.asarray(arrays["toep_p"]),
        f_p=jnp.asarray(arrays["f_p"]),
        p_pad=jnp.asarray(arrays["p_pad"]),
        m=pair(0), mprime=pair(2), mu26=pair(4), mu27=pair(6),
        bias1=pair(8), bias0=pair(10), biasc=pair(12), biasb=pair(14),
        biasa=pair(16), inv12s=int(sc[18]),
    )


# ---------------------------------------------------------------------------
# device-side primitives
# ---------------------------------------------------------------------------

def _i8dot(a: jax.Array, w: jax.Array) -> jax.Array:
    """(B, K) int8 @ (K, N) int8 -> (B, N) int32, exact (MXU int8 path)."""
    return lax.dot_general(a, w, (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.int32)


def _barrett(x: jax.Array, m: int, mu: int, a: int, nsub: int) -> jax.Array:
    """x mod m for uint32 x; constants validated exhaustively (see module
    docstring).  q = ((x>>a)·mu)>>13, then nsub conditional subtracts."""
    q = ((x >> a) * U32(mu)) >> 13
    r = x - q * U32(m)
    for _ in range(nsub):
        r = jnp.where(r >= m, r - U32(m), r)
    return r


def _mredc16(x: jax.Array, m: int, mprime: int) -> jax.Array:
    """(x · 2^-16) mod m for uint32 x < 2^16·m: exact, in [0, m)."""
    u = (x * U32(mprime)) & U32(0xFFFF)
    t = (x + u * U32(m)) >> 16
    return jnp.where(t >= m, t - U32(m), t)


def _digits_to_limbs(d: jax.Array) -> jax.Array:
    """Nonneg redundant base-256 coeffs (..., L) u32 (< 2^25) -> canonical
    16-bit limbs (..., L/2).  Carries beyond limb L/2 are dropped (callers
    either prove them zero or want mod 2^(8L)).

    One ripple pass bounds digits by 255 + 2^17; the pair-combine then
    stays below 2^17.01 + 256·2^17.01 < 2^25.2, inside ``normalize``'s
    < 2^32 input domain — the carry/CRT glue between matmuls is the
    measured hot path, so every avoided (B, 1028) elementwise pass counts
    (three of the four ripple passes this replaces were redundant with
    normalize's own carry resolution)."""
    d = (d & U32(0xFF)) + bn._shift_up(d >> 8)   # < 255 + 2^17
    z = d[..., 0::2] + (d[..., 1::2] << 8)       # redundant base 2^16
    return bn.normalize(z)


def _limbs_to_e(x: jax.Array, pad_to: int | None = None) -> jax.Array:
    """(..., L) uint32 16-bit limbs -> (..., 2L [padded]) int8 e-form
    (digit - 128; zero digits pad as -128)."""
    d0 = (x & U32(0xFF)).astype(jnp.int32)
    d1 = ((x >> 8) & U32(0xFF)).astype(jnp.int32)
    e = jnp.stack([d0, d1], axis=-1).reshape(*x.shape[:-1], 2 * x.shape[-1])
    e = e - 128
    if pad_to is not None and pad_to > e.shape[-1]:
        pad = [(0, 0)] * (e.ndim - 1) + [(0, pad_to - e.shape[-1])]
        e = jnp.pad(e, pad, constant_values=-128)
    return e.astype(jnp.int8)


def _eval(ctx: NttCtx, e: jax.Array) -> list[jax.Array]:
    """Forward NTT of e-form digits (B, NC) -> per-prime (B, NC) uint32
    in [0, m)."""
    out = []
    for t in range(2):
        m = ctx.m[t]
        A1 = _i8dot(e, ctx.V1[t]) + ctx.evoff1[t]          # >= 0, < 2^24
        r1 = _barrett(A1.astype(U32), m, ctx.mu26[t], 13, 2)
        A0 = (_i8dot(e, ctx.V0[t]) + ctx.evoff0[t]).astype(U32) + (r1 << 8)
        out.append(_barrett(A0, m, ctx.mu27[t], 14, 3))    # < 2^27 domain
    return out


def _interp_crt(ctx: NttCtx, that: list[jax.Array]) -> jax.Array:
    """Pointwise-product values (per prime, [0,m)) -> exact convolution
    coefficients (B, NC) uint32 (< 2^25) via inverse NTT + CRT."""
    cs = []
    for t in range(2):
        m = ctx.m[t]
        th = that[t]
        t0e = ((th & U32(0xFF)).astype(jnp.int32) - 128).astype(jnp.int8)
        t1 = (th >> 8).astype(jnp.int8)                    # <= 51
        C = _i8dot(t1, ctx.iV1[t]) + ctx.biasc[t]
        Cm = _barrett(C.astype(U32), m, ctx.mu26[t], 13, 2)
        B_ = (_i8dot(t0e, ctx.iV1[t]) + _i8dot(t1, ctx.iV0[t])
              + ctx.ivoff1[t]).astype(U32) + (Cm << 8)
        Bm = _barrett(B_, m, ctx.mu26[t], 13, 2)
        A_ = (_i8dot(t0e, ctx.iV0[t]) + ctx.ivoff0[t]).astype(U32) + (Bm << 8)
        cs.append(_barrett(A_, m, ctx.mu27[t], 14, 3))
    c1, c2 = cs
    m1, m2 = ctx.m
    # CRT: y = c1 + m1·((c2 - c1)·m1^{-1} mod m2), via mredc16 with the
    # 2^16 factor folded into inv12s; d ≡ c2 - c1 (mod m2), nonneg.
    d = c2 + U32(2 * m2) - c1
    u = _mredc16(d * U32(ctx.inv12s), m2, ctx.mprime[1])
    return c1 + U32(m1) * u                                # exact, < 2^25


def _mont_reduce(ctx: NttCtx, y: jax.Array) -> jax.Array:
    """Exact conv coefficients of T = a·b (B, NC) -> (T·R^{-1} mod p) as
    canonical (B, NL) limbs.  R = 2^4096."""
    batch = y.shape[:-1]
    # normalize T to digits; T < p^2 so needs <= 1024 digits, keep 4 spare
    yp = jnp.pad(y, [(0, 0)] * (y.ndim - 1) + [(0, 4)])
    Tl = _digits_to_limbs(yp)                              # (B, 514) limbs
    eT = _limbs_to_e(Tl[..., :NL])                         # (B, ND) low half
    # m1 = T_low · p' mod 2^4096  (Toeplitz + offset terms, exact int32)
    csT = jnp.cumsum(eT.astype(jnp.int32), axis=-1)
    m1c = _i8dot(eT, ctx.toep_m) + ctx.f_m + (csT << 7)    # >= 0, < 2^25
    m1l = _digits_to_limbs(m1c.astype(U32))                # (B, NL) mod R
    em1 = _limbs_to_e(m1l)                                 # (B, ND)
    # m1 · p (full product): Toeplitz (ND, NC) + windowed-cumsum offsets
    cs1 = jnp.cumsum(em1.astype(jnp.int32), axis=-1)       # (B, ND)
    last = jnp.broadcast_to(cs1[..., -1:], batch + (ND,))
    wsum = (jnp.concatenate([cs1, last], axis=-1)
            - jnp.pad(cs1, [(0, 0)] * (cs1.ndim - 1) + [(ND, 0)])[..., :NC])
    m1pc = _i8dot(em1, ctx.toep_p) + ctx.f_p + (wsum << 7)  # >= 0, < 2^25
    # S = T + m1·p; low 512 digits vanish; U = S / 2^4096 < 2p
    # re-expand T limbs to digit stream cheaply: interleave 8-bit halves
    Td = jnp.stack([Tl & U32(0xFF), Tl >> 8], axis=-1)
    Td = Td.reshape(*batch, Tl.shape[-1] * 2)              # (B, 1028) digits
    S = Td.astype(jnp.int32).at[..., :NC].add(m1pc)
    Sl = _digits_to_limbs(S.astype(U32))                   # (B, 514)
    U = Sl[..., NL:NL + NL + 2]                            # (B, 258) = S/R
    U = bn._sub_if_ge(U, ctx.p_pad)
    return U[..., :NL]


# ---------------------------------------------------------------------------
# public ops (drop-in for bignum_jax.montmul / mont_pow / powmod)
# ---------------------------------------------------------------------------

def montmul(ctx: NttCtx, a: jax.Array, b: jax.Array) -> jax.Array:
    """Batched Montgomery product a·b·R^{-1} mod p on the MXU.
    a, b: (..., NL) canonical 16-bit limbs < p."""
    shape = a.shape
    a2 = a.reshape(-1, NL)
    b2 = jnp.broadcast_to(b, shape).reshape(-1, NL)
    ah = _eval(ctx, _limbs_to_e(a2, NC))
    bh = _eval(ctx, _limbs_to_e(b2, NC))
    that = [_mredc16(ah[t] * bh[t], ctx.m[t], ctx.mprime[t])
            for t in range(2)]
    return _mont_reduce(ctx, _interp_crt(ctx, that)).reshape(shape)


def montmul_shared(ctx: NttCtx, sel: jax.Array, base: jax.Array) -> jax.Array:
    """(B, k, NL) × (B, NL) Montgomery products sel[:, j]·base.

    The shared operand is forward-NTT'd ONCE and its evaluations
    broadcast across k — the bucket multiply of the Yao multi-exp ladder
    (bignum_jax.mont_multi_pow_shared) multiplies all k buckets by the
    same running base, so this saves a full forward NTT (4 MXU matmuls +
    the digit glue) on (B·(k-1)) rows per window."""
    B, k, n = sel.shape
    sh = _eval(ctx, _limbs_to_e(sel.reshape(B * k, n), NC))
    bh = _eval(ctx, _limbs_to_e(base, NC))
    that = [_mredc16(
        sh[t] * jnp.broadcast_to(bh[t][:, None, :],
                                 (B, k, NC)).reshape(B * k, NC),
        ctx.m[t], ctx.mprime[t]) for t in range(2)]
    return _mont_reduce(ctx, _interp_crt(ctx, that)).reshape(B, k, n)


def nttfwd(ctx: NttCtx, a: jax.Array) -> jax.Array:
    """(B, NL) canonical limbs -> (B, 2, NC) uint32 forward-NTT
    evaluations (one row per prime) — the precomputable half of a
    montmul, used to store PowRadix tables in the evaluated domain."""
    ah = _eval(ctx, _limbs_to_e(a, NC))
    return jnp.stack(ah, axis=1)


def montmul_hat(ctx: NttCtx, a: jax.Array, bh: jax.Array) -> jax.Array:
    """Montgomery product of a (B, NL) canonical limbs with a
    PRE-EVALUATED operand bh (B, 2, NC) (from ``nttfwd``).  Skips the
    second operand's forward NTT entirely — 4 of a montmul's 16 MXU
    matmuls plus its digit glue — which is what makes NTT-domain
    fixed-base tables pay: the table row's evaluation is computed once
    at table build, not once per ladder step."""
    ah = _eval(ctx, _limbs_to_e(a, NC))
    that = [_mredc16(ah[t] * bh[..., t, :], ctx.m[t], ctx.mprime[t])
            for t in range(2)]
    return _mont_reduce(ctx, _interp_crt(ctx, that))


def montsqr(ctx: NttCtx, a: jax.Array) -> jax.Array:
    """Batched Montgomery square (one forward NTT instead of two)."""
    shape = a.shape
    a2 = a.reshape(-1, NL)
    ah = _eval(ctx, _limbs_to_e(a2, NC))
    that = [_mredc16(ah[t] * ah[t], ctx.m[t], ctx.mprime[t])
            for t in range(2)]
    return _mont_reduce(ctx, _interp_crt(ctx, that)).reshape(shape)


def mont_pow(ctx: NttCtx, base_mont: jax.Array, exp: jax.Array,
             exp_bits: int) -> jax.Array:
    return bn.mont_pow(ctx.mctx, base_mont, exp, exp_bits,
                       montmul_fn=functools.partial(montmul, ctx),
                       montsqr_fn=functools.partial(montsqr, ctx))


def powmod(ctx: NttCtx, base: jax.Array, exp: jax.Array,
           exp_bits: int) -> jax.Array:
    return bn.powmod(ctx.mctx, base, exp, exp_bits,
                     montmul_fn=functools.partial(montmul, ctx),
                     montsqr_fn=functools.partial(montsqr, ctx))


def mulmod(ctx: NttCtx, a: jax.Array, b: jax.Array) -> jax.Array:
    """Plain modular product a·b mod p."""
    return montmul(ctx, montmul(ctx, a, b),
                   jnp.broadcast_to(ctx.mctx.r2_mod_p, a.shape))


def mont_prod_tree(ctx: NttCtx, x: jax.Array) -> jax.Array:
    return bn.mont_prod_tree(ctx.mctx, x,
                             montmul_fn=functools.partial(montmul, ctx))

"""Shared per-backend measurement for the bignum data plane.

One helper, two consumers: ``tools/bench_bignum.py`` (the standalone
CLI, which adds ``--backend``/``--json``) and bench.py's best-effort
``bignum`` phase (which lands the same rows in the benchmark artifact).
Rows carry both the *requested* and the *effective* backend so a
degraded fallback (pallas off-TPU without interpret mode, MXU engines
on a tiny group) is measured as whatever it degraded to and labeled
honestly rather than silently misattributed.

Reduced ``exp_bits`` keeps the interpret-mode pallas ladder tractable
on CPU (one montmul launch is ~2.5 s emulated; a full 256-bit ladder
would be ~12 minutes per call): the row records the width it actually
ran so throughputs are never compared across unequal ladders.
"""

from __future__ import annotations

import functools
import time
import warnings
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from electionguard_tpu.core import bignum_jax as bn

#: ops measurable per backend; "fixed" always runs the full-width
#: window ladder over the registered g table; "msm" times the Pippenger
#: multi-scalar accumulation end to end (host digit prep included)
DEFAULT_OPS = ("mulmod", "powmod", "fixed", "msm")


def timeit(fn, *args, reps: int = 3) -> float:
    """Warm (compile) once, then average ``reps`` timed calls."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / max(1, reps)


def backend_rows(group, backend: str, batch: int = 64,
                 ops: Sequence[str] = DEFAULT_OPS,
                 exp_bits: Optional[int] = None,
                 reps: int = 3) -> list[dict]:
    """Measure the requested ops on one backend; one row dict per op.

    Row fields: ``backend`` (requested), ``effective`` (post-fallback),
    ``op``, ``batch``, ``exp_bits`` (None for mulmod), ``platform``,
    ``sec_per_call``, ``per_s``.
    """
    from electionguard_tpu.core.group_jax import JaxGroupOps

    with warnings.catch_warnings():
        # fallback warnings are the point here — the row label carries
        # the same information without spamming the bench log
        warnings.simplefilter("ignore")
        gops = JaxGroupOps(group, backend=backend)
    bits = exp_bits or gops.exp_bits
    rng = np.random.default_rng(0)
    exps = [int.from_bytes(rng.bytes(32), "big") % group.q
            for _ in range(batch)]
    bases = [pow(group.g, e | 1, group.p) for e in exps[:min(batch, 64)]]
    bases = (bases * (batch // len(bases) + 1))[:batch]
    A = jnp.asarray(gops.to_limbs_p(bases))
    platform = jax.devices()[0].platform
    rows: list[dict] = []

    def row(op: str, sec: float, op_bits: Optional[int]) -> None:
        rows.append({"backend": backend, "effective": gops.backend,
                     "op": op, "batch": batch, "exp_bits": op_bits,
                     "platform": platform,
                     "sec_per_call": round(sec, 6),
                     "per_s": round(batch / sec, 2)})

    if "mulmod" in ops:
        row("mulmod", timeit(gops._mulmod_j, A, A, reps=reps), None)
    if "powmod" in ops:
        if bits == gops.exp_bits:
            E = jnp.asarray(gops.to_limbs_q(exps))
            row("powmod", timeit(gops._powmod_j, A, E, reps=reps), bits)
        else:
            # reduced ladder: same kernels, shorter square-and-multiply
            # chain; jitted here once since _powmod_j is fixed-width
            ne = max(1, (bits + 15) // 16)
            E = jnp.asarray(bn.ints_to_limbs(
                [e % (1 << bits) for e in exps], ne))
            kw = {}
            if gops._ms is not None:
                kw = {"montmul_fn": gops._mm, "montsqr_fn": gops._ms}
            pfn = jax.jit(functools.partial(
                bn.powmod, gops.ctx, exp_bits=bits, **kw))
            row("powmod", timeit(pfn, A, E, reps=reps), bits)
    if "fixed" in ops:
        E = jnp.asarray(gops.to_limbs_q(exps))
        row("fixed", timeit(gops._fixed_pow_j, gops.g_table, E,
                            reps=reps), gops.exp_bits)
    if "msm" in ops:
        # end-to-end (host window/digit prep + device buckets/combine):
        # that is the cost the RLC verify plane pays per batch
        An = np.asarray(gops.to_limbs_p(bases))
        es = ([e % (1 << bits) for e in exps]
              if bits != gops.exp_bits else exps)
        row("msm", timeit(lambda: gops.msm(An, es, exp_bits=bits),
                          reps=reps), bits)
    return rows

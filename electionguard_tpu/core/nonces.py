"""Deterministic nonce sequences derived from a seed.

Used by ballot encryption so an entire ballot's randomness derives from one
master nonce (enabling the reference workflow's ``fixedNonces`` batch mode —
reference: src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:140
``batchEncryption(..., fixedNonces=true, ...)``).
"""

from __future__ import annotations

from electionguard_tpu.core.group import ElementModQ
from electionguard_tpu.core.hash import hash_elems


class Nonces:
    """``Nonces(seed, h1, h2, ...)[i]`` is a deterministic Z_q sequence."""

    def __init__(self, seed: ElementModQ, *headers):
        self._group = seed.group
        self._seed = hash_elems(seed.group, seed, *headers) if headers else seed

    def __getitem__(self, i: int) -> ElementModQ:
        return hash_elems(self._group, self._seed, i)

    def take(self, n: int):
        return [self[i] for i in range(n)]

"""Batched big-integer modular arithmetic in JAX (the TPU data plane).

This is the framework's native-equivalent of the reference's hot layer: the
JVM ``BigInteger`` intrinsics underneath ``ProductionElementModP``
(reference: src/main/java/electionguard/util/ConvertCommonProto.java:46,55
[ext]) — rebuilt TPU-first instead of ported (SURVEY.md §2.10).

Design
------
* A big integer is a little-endian vector of 16-bit limbs held in ``uint32``
  lanes: shape ``(B, n)`` for a batch of B values, ``n = ceil(bits/16)``.
  16×16-bit products are exact in uint32; sums stay below 2**27 by keeping
  the accumulator *redundant* (limbs may exceed 16 bits) and deferring carry
  normalization — no data-dependent control flow in the hot loop, so XLA
  compiles one static program (SURVEY.md §7 hard part 1).
* Modular multiplication is Montgomery CIOS: a ``lax.scan`` over the 256
  multiplier limbs whose body is pure elementwise vector math over the
  batch — the batch axis rides the VPU lanes and shards over chips.
* Modular exponentiation is a fixed 4-bit-window ladder: ``lax.scan`` over
  64 exponent windows (256-bit exponents), each window = 4 Montgomery
  squarings + one table-gathered multiply.  ~335 montmuls per modexp.

All functions are shape-generic and jit/vmap/shard_map-compatible; they are
closed over per-group constants by ``JaxGroupOps``
(electionguard_tpu.core.group_jax).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# numpy scalar, NOT jnp: creating a device array at import time would
# initialise the XLA backend before jax.distributed.initialize can run
# (multi-host workers import this module before calling distributed_init).
MASK16 = np.uint32(0xFFFF)
U32 = jnp.uint32


class MontCtx(NamedTuple):
    """Static Montgomery context for a fixed odd modulus p.

    ``n`` limbs of 16 bits; R = 2**(16 n) > p; all host-precomputed.
    """

    p_limbs: jax.Array        # (n,) uint32, little-endian 16-bit limbs of p
    pinv16: jax.Array         # scalar uint32: -p^{-1} mod 2^16
    r_mod_p: jax.Array        # (n,) mont(1) = R mod p
    r2_mod_p: jax.Array       # (n,) R^2 mod p
    n: int                    # limb count (static)


# ---------------------------------------------------------------------------
# host-side codecs (numpy, python ints)
# ---------------------------------------------------------------------------

def int_to_limbs(x: int, n: int) -> np.ndarray:
    """Python int -> (n,) uint32 array of 16-bit little-endian limbs."""
    if x < 0 or x >= 1 << (16 * n):
        raise ValueError("int out of range for limb width")
    b = x.to_bytes(2 * n, "little")
    return np.frombuffer(b, dtype="<u2").astype(np.uint32)


def ints_to_limbs(xs, n: int) -> np.ndarray:
    """Iterable of ints -> (B, n) uint32.  One joined buffer + a single
    vectorized reinterpret instead of per-int numpy round trips — this
    codec sits on the host critical path of every batch dispatch."""
    try:
        # to_bytes raises OverflowError for negatives and for
        # x >= 2^(16n), so the width check rides the conversion
        buf = b"".join(x.to_bytes(2 * n, "little") for x in xs)
    except OverflowError:
        raise ValueError("int out of range for limb width") from None
    return (np.frombuffer(buf, dtype="<u2")
            .astype(np.uint32).reshape(-1, n))


def limbs_to_int(a: np.ndarray) -> int:
    a = np.asarray(a, dtype=np.uint32)
    return int.from_bytes(a.astype("<u2").tobytes(), "little")


def limbs_to_ints(a: np.ndarray) -> list[int]:
    a = np.asarray(a, dtype=np.uint32)
    flat = a.astype("<u2").tobytes()
    w = a.shape[-1] * 2
    return [int.from_bytes(flat[i * w:(i + 1) * w], "little")
            for i in range(a.shape[0])]


def make_mont_ctx(p: int, n: int | None = None) -> MontCtx:
    if p % 2 == 0:
        raise ValueError("Montgomery requires odd modulus")
    if n is None:
        n = (p.bit_length() + 15) // 16
    R = 1 << (16 * n)
    if R <= p:
        raise ValueError("R must exceed p")
    pinv16 = (-pow(p, -1, 1 << 16)) % (1 << 16)
    return MontCtx(
        p_limbs=jnp.asarray(int_to_limbs(p, n)),
        pinv16=jnp.uint32(pinv16),
        r_mod_p=jnp.asarray(int_to_limbs(R % p, n)),
        r2_mod_p=jnp.asarray(int_to_limbs(R * R % p, n)),
        n=n,
    )


# ---------------------------------------------------------------------------
# carry handling
# ---------------------------------------------------------------------------

def _shift_up(hi: jax.Array) -> jax.Array:
    """Move per-limb carries one limb towards the MSB (drop the top one —
    it must be zero by construction; moduli leave headroom)."""
    return jnp.pad(hi[..., :-1], [(0, 0)] * (hi.ndim - 1) + [(1, 0)])


def normalize(t: jax.Array) -> jax.Array:
    """Carry-propagate a redundant limb vector (..., m) to canonical 16-bit
    limbs.  Values < 2**32 in.  Exact and data-independent: two ripple
    passes bound every limb by 2**16, then a log-depth carry-lookahead
    (Kogge-Stone over the limb axis) resolves arbitrarily long 0xFFFF
    ripple chains — no ``while_loop``, no cross-batch predicate reduction,
    safe for adversarial inputs."""
    # pass 1: limbs < 2**32 -> <= 2**17 - 2
    t = (t & MASK16) + _shift_up(t >> 16)
    # pass 2: limbs <= 2**17 - 2 -> <= 2**16
    t = (t & MASK16) + _shift_up(t >> 16)
    # carry-lookahead: generate g_i = (limb == 2**16), propagate
    # p_i = (limb == 0xFFFF); carry into i+1 = g_i | (p_i & c_i).
    g = (t >> 16).astype(jnp.uint32)          # 0/1
    p = (t == MASK16)

    def combine(left, right):
        gl, pl = left
        gr, pr = right
        return gr | (pr.astype(jnp.uint32) & gl), pl & pr

    G, _ = lax.associative_scan(combine, (g, p), axis=-1)
    c = _shift_up(G)                          # exclusive prefix: carry into i
    return (t + c) & MASK16


def _sub_p(t: jax.Array, p_limbs: jax.Array):
    """Two's-complement computation of t - p over canonical limbs.

    Returns ``(wrapped, ge)``: ``wrapped = (t + 2^(16n) - p) mod 2^(16n)``
    (which equals t - p whenever t >= p) and ``ge`` (..., 1) bool, the carry
    out of the add, true iff t >= p.
    """
    n = p_limbs.shape[-1]
    comp = (MASK16 - p_limbs)  # (n,), 16-bit complement of p
    s = t + comp
    s = s.at[..., 0].add(U32(1))  # +1 completes two's complement of p
    # propagate carries over a widened vector to capture the top carry
    s = jnp.concatenate(
        [s, jnp.zeros(s.shape[:-1] + (1,), dtype=jnp.uint32)], axis=-1)
    s = normalize(s)
    return s[..., :n], s[..., n:n + 1] > 0


def _sub_if_ge(t: jax.Array, p_limbs: jax.Array) -> jax.Array:
    """Given canonical t (..., n) with t < 2p, return t mod p."""
    wrapped, ge = _sub_p(t, p_limbs)
    return jnp.where(ge, wrapped, t)


def is_lt(t: jax.Array, p_limbs: jax.Array) -> jax.Array:
    """Batched canonical-limb comparison t < p -> (...,) bool."""
    _, ge = _sub_p(t, p_limbs)
    return ~ge[..., 0]


def add_mod(a: jax.Array, b: jax.Array, p_limbs: jax.Array) -> jax.Array:
    """(a + b) mod p for canonical a, b < p.  Sum < 2p fits n+1 limbs."""
    s = a + b  # limbs < 2^17, redundant
    s = jnp.concatenate(
        [s, jnp.zeros(s.shape[:-1] + (1,), jnp.uint32)], axis=-1)
    s = normalize(s)
    n = p_limbs.shape[-1]
    low, top = s[..., :n], s[..., n:n + 1]
    wrapped, _ = _sub_p(low, p_limbs)
    low = jnp.where(top > 0, wrapped, low)
    return _sub_if_ge(low, p_limbs)


def sub_mod(a: jax.Array, b: jax.Array, p_limbs: jax.Array) -> jax.Array:
    """(a - b) mod p for canonical a, b < p, via a + (p - b)."""
    p_minus_b, _ = _sub_p(jnp.broadcast_to(p_limbs, b.shape), b)  # p - b
    # b == 0 makes p - b == p (not canonical); map it back to 0
    b_zero = jnp.all(b == 0, axis=-1, keepdims=True)
    p_minus_b = jnp.where(b_zero, jnp.zeros_like(p_minus_b), p_minus_b)
    return add_mod(a, p_minus_b, p_limbs)


# ---------------------------------------------------------------------------
# Montgomery CIOS multiply
# ---------------------------------------------------------------------------

def montmul(ctx: MontCtx, a: jax.Array, b: jax.Array) -> jax.Array:
    """Batched Montgomery product a·b·R^{-1} mod p.

    a, b: (..., n) canonical 16-bit limbs, values < p.  Returns canonical
    limbs < p.  The scan body is carry-free: the (..., n+1) accumulator is
    redundant; per-limb growth is < 4·2^16 per step over n steps, bounded by
    2^27 « 2^32.
    """
    n = ctx.n
    batch_shape = a.shape[:-1]
    aT = jnp.moveaxis(a, -1, 0)  # (n, ...) iterate multiplier limbs

    def step(t, a_i):
        # t: (..., n+1) redundant accumulator
        prod = a_i[..., None] * b                      # (..., n) exact u32
        t = t.at[..., :n].add(prod & MASK16)
        t = t.at[..., 1:n + 1].add(prod >> 16)
        m = ((t[..., 0] & MASK16) * ctx.pinv16) & MASK16
        q = m[..., None] * ctx.p_limbs                 # (..., n)
        t = t.at[..., :n].add(q & MASK16)
        t = t.at[..., 1:n + 1].add(q >> 16)
        carry = t[..., 0] >> 16                        # low 16 bits now zero
        t = jnp.concatenate(
            [t[..., 1:], jnp.zeros(batch_shape + (1,), jnp.uint32)], axis=-1)
        t = t.at[..., 0].add(carry)
        return t, None

    t0 = jnp.zeros(batch_shape + (n + 1,), dtype=jnp.uint32)
    t, _ = lax.scan(step, t0, aT)
    t = normalize(t)
    # t < 2p over n+1 limbs; since t < 2p < 2^(16n) + p the top limb is 0 or
    # 1, and 1 implies exactly one extra p beyond the n-limb window.
    t_low = t[..., :n]
    top = t[..., n:n + 1]
    wrapped, _ = _sub_p(t_low, ctx.p_limbs)  # t_low - p mod 2^(16n)
    t_low = jnp.where(top > 0, wrapped, t_low)
    return _sub_if_ge(t_low, ctx.p_limbs)


def to_mont(ctx: MontCtx, a: jax.Array) -> jax.Array:
    return montmul(ctx, a, jnp.broadcast_to(ctx.r2_mod_p, a.shape))


def from_mont_via(mul, a: jax.Array) -> jax.Array:
    """Montgomery-domain exit a·R^{-1} mod p through any backend's
    Montgomery multiplier ``mul``."""
    one = jnp.zeros_like(a).at[..., 0].set(U32(1))
    return mul(a, one)


def from_mont(ctx: MontCtx, a: jax.Array) -> jax.Array:
    return from_mont_via(functools.partial(montmul, ctx), a)


def mulmod(ctx: MontCtx, a: jax.Array, b: jax.Array) -> jax.Array:
    """Plain modular product a·b mod p (canonical in, canonical out)."""
    return montmul(ctx, montmul(ctx, a, b),
                   jnp.broadcast_to(ctx.r2_mod_p, a.shape))


# ---------------------------------------------------------------------------
# Montgomery-domain exponentiation
# ---------------------------------------------------------------------------

def mont_pow(ctx: MontCtx, base_mont: jax.Array, exp: jax.Array,
             exp_bits: int, montmul_fn=None, montsqr_fn=None) -> jax.Array:
    """Batched modexp in the Montgomery domain.

    base_mont: (..., n) Montgomery-domain bases.
    exp:       (..., ne) 16-bit limbs of exponents (little-endian),
               ne = ceil(exp_bits/16).
    Returns Montgomery-domain base^exp.

    Fixed 4-bit windows, MSB-first scan: acc = acc^16 · table[window].
    ``montmul_fn`` / ``montsqr_fn`` plug in an alternative Montgomery
    multiplier over the same limb format (the MXU NTT engine of
    electionguard_tpu.core.ntt_mxu); default is the VPU CIOS kernel.
    """
    mul = montmul_fn if montmul_fn is not None else \
        functools.partial(montmul, ctx)
    sqr = montsqr_fn if montsqr_fn is not None else (lambda a: mul(a, a))
    nwin = (exp_bits + 3) // 4

    # table[d] = base^d in Montgomery domain, d = 0..15: (16, ..., n)
    one_mont = jnp.broadcast_to(ctx.r_mod_p, base_mont.shape)

    def build_row(carry, _):
        nxt = mul(carry, base_mont)
        return nxt, carry

    _, table = lax.scan(build_row, one_mont, None, length=16)
    # table: (16, ..., n) with table[d] = base^d (mont)

    # window digits, MSB first: digit w = bits [4w, 4w+4) of exp
    win_idx = jnp.arange(nwin - 1, -1, -1)  # MSB-first window numbers

    def step(acc, w):
        # acc^16
        for _ in range(4):
            acc = sqr(acc)
        limb = exp[..., w // 4]            # (...,) uint32 16-bit limb
        digit = (limb >> ((w % 4) * 4)) & U32(0xF)
        # gather table[digit] per batch element
        sel = jnp.take_along_axis(
            table, digit[None, ..., None].astype(jnp.int32),
            axis=0)[0]                     # (..., n)
        acc = mul(acc, sel)
        return acc, None

    acc0 = jnp.broadcast_to(ctx.r_mod_p, base_mont.shape)  # mont(1)
    acc, _ = lax.scan(step, acc0, win_idx)
    return acc


def powmod(ctx: MontCtx, base: jax.Array, exp: jax.Array,
           exp_bits: int, montmul_fn=None, montsqr_fn=None) -> jax.Array:
    """Canonical-domain batched base^exp mod p."""
    mul = montmul_fn if montmul_fn is not None else \
        functools.partial(montmul, ctx)
    r2 = jnp.broadcast_to(ctx.r2_mod_p, base.shape)
    acc = mont_pow(ctx, mul(base, r2), exp, exp_bits,
                   montmul_fn=montmul_fn, montsqr_fn=montsqr_fn)
    return from_mont_via(mul, acc)


def mont_multi_pow_shared(ctx: MontCtx, base_mont: jax.Array,
                          exps: jax.Array, exp_bits: int,
                          montmul_fn=None, montsqr_fn=None,
                          montmul_shared_fn=None) -> jax.Array:
    """k exponents on ONE shared base, Montgomery domain, batched.

    base_mont: (B, n) Montgomery-domain bases; exps: (B, k, ne) 16-bit
    exponent limbs (little-endian).  Returns (B, k, n) = base^exps (mont).

    Right-to-left 4-bit bucket method (Yao): the ladder squares the BASE,
    not the accumulator, so the 4·nwin base-squarings are paid once and
    SHARED across the k exponents; each exponent adds one bucket multiply
    per window plus a 30-multiply combine.  Cost for 256-bit exponents:
    256 + 94k Montgomery multiplies vs k·336 for independent ladders —
    the workhorse for the verifier, where each ciphertext element carries
    exponents {q, c0, c1} (subgroup membership + both disjunctive-proof
    branches; reference recomputes these per-element on 11 CPU threads,
    src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:180).
    """
    mul = montmul_fn if montmul_fn is not None else \
        functools.partial(montmul, ctx)
    sqr = montsqr_fn if montsqr_fn is not None else (lambda a: mul(a, a))
    B, k, ne = exps.shape
    n = base_mont.shape[-1]
    nwin = (exp_bits + 3) // 4

    def mul_bk(a, b):  # (B, k, n) pairs through the 2-D multiplier
        return mul(a.reshape(B * k, n), b.reshape(B * k, n)).reshape(
            B, k, n)

    if montmul_shared_fn is None:  # generic: broadcast the shared base
        def montmul_shared_fn(sel, base):
            return mul_bk(sel, jnp.broadcast_to(base[:, None, :],
                                                (B, k, n)))

    # window digits, LSB-first: (nwin, B, k)
    widx = jnp.arange(nwin)
    limb = exps[..., widx // 4]                    # (B, k, nwin)
    digits = (limb >> ((widx % 4) * 4).astype(jnp.uint32)) & U32(0xF)
    digits = jnp.moveaxis(digits, -1, 0).astype(jnp.int32)

    buckets0 = jnp.broadcast_to(ctx.r_mod_p, (B, k, 16, n))

    def step(carry, d):
        base_cur, buckets = carry                  # (B,n), (B,k,16,n)
        sel = jnp.take_along_axis(
            buckets, d[..., None, None], axis=2)[..., 0, :]  # (B,k,n)
        prod = montmul_shared_fn(sel, base_cur)
        onehot = jnp.arange(16)[None, None, :] == d[..., None]  # (B,k,16)
        buckets = jnp.where(onehot[..., None], prod[:, :, None, :], buckets)
        for _ in range(4):
            base_cur = sqr(base_cur)
        return (base_cur, buckets), None

    (_, buckets), _ = lax.scan(step, (base_mont, buckets0), digits)

    # total = prod_d bucket[d]^d via suffix products: acc_d = prod_{j>=d}
    # bucket[j]; total = prod acc_d.  Digit-0 bucket is excluded (its
    # accumulated products carry exponent weight 0).
    acc = buckets[:, :, 15, :]
    total = acc
    for d in range(14, 0, -1):
        acc = mul_bk(acc, buckets[:, :, d, :])
        total = mul_bk(total, acc)
    return total


def multi_powmod_shared(ctx: MontCtx, base: jax.Array, exps: jax.Array,
                        exp_bits: int, montmul_fn=None,
                        montsqr_fn=None, montmul_shared_fn=None) -> jax.Array:
    """Canonical-domain base^exps for k exponents per shared base:
    base (B, n), exps (B, k, ne) -> (B, k, n)."""
    mul = montmul_fn if montmul_fn is not None else \
        functools.partial(montmul, ctx)
    base_mont = mul(base, jnp.broadcast_to(ctx.r2_mod_p, base.shape))
    acc = mont_multi_pow_shared(ctx, base_mont, exps, exp_bits,
                                montmul_fn=montmul_fn,
                                montsqr_fn=montsqr_fn,
                                montmul_shared_fn=montmul_shared_fn)
    return from_mont_via(
        lambda a, b: mul(a.reshape(-1, base.shape[-1]),
                         b.reshape(-1, base.shape[-1])).reshape(a.shape),
        acc)


def mont_prod_tree(ctx: MontCtx, x: jax.Array, montmul_fn=None) -> jax.Array:
    """Log-depth Montgomery product over axis 0: (M, ..., n) mont-domain
    values -> (..., n) mont-domain product.  Odd levels pad with mont(1);
    exact shape program per static M."""
    mul = montmul_fn if montmul_fn is not None else \
        functools.partial(montmul, ctx)
    m = x.shape[0]
    while m > 1:
        if m % 2 == 1:
            pad = jnp.broadcast_to(ctx.r_mod_p, (1,) + x.shape[1:])
            x = jnp.concatenate([x, pad], axis=0)
            m += 1
        x = mul(x[0::2], x[1::2])
        m //= 2
    return x[0]

"""Batch group operations on TPU: the data plane behind the workflow hot
loops (encryption, tally accumulation, proof verification — SURVEY.md §3 🔥).

``JaxGroupOps`` closes the generic limb kernels of
``electionguard_tpu.core.bignum_jax`` over one group's constants and adds:

* codecs between Python-int elements and limb arrays,
* jitted elementwise batch ops (``powmod``, ``mulmod``, ``g_pow``),
* PowRadix-style fixed-base exponentiation tables (the TPU answer to the
  reference's ``PowRadixOption.LOW_MEMORY_USE`` —
  reference: src/main/java/electionguard/util/KUtils.java:11): 8-bit windows,
  32 gathers + 31 Montgomery multiplies per 256-bit fixed-base exponent
  instead of ~335 for the generic ladder,
* a log-depth Montgomery product-reduce for homomorphic tally accumulation
  (the reference's per-ballot ``∏ ciphertexts mod p`` loop —
  reference call site: src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:151).

Everything is jit-compiled once per (batch-shape, op); the batch axis is the
sharding axis for multi-chip meshes (see electionguard_tpu.parallel).
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.core import ntt_mxu
from electionguard_tpu.core import table_cache
from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.utils import knobs


def _dispatch_tile() -> int:
    """Row cap per device dispatch (EGTPU_TILE, default 4096): batches
    larger than this run as a loop of cap-shaped tiles, bounding the set
    of compiled batch shapes for any workload size."""
    return max(16, int(os.environ.get("EGTPU_TILE", "4096")))


def dispatch_bucket(n: int, cap: int) -> int:
    """Rows per dispatch for a batch of ``n`` ≤ ``cap``: power-of-two
    buckets up to cap/8, then straight to the cap.  The compiled shape
    set per op is therefore tiny — {16 … cap/8, cap} — and every LARGE
    dispatch in a workload hits the one cap shape, which a benchmark (or
    first production run) can prewarm with a single dummy dispatch per
    op instead of paying a multi-minute XLA compile per batch size
    mid-run."""
    from electionguard_tpu.utils import batch_bucket
    nb = batch_bucket(n)
    return nb if nb <= cap // 8 else cap


def pad_rows(arr, nb: int, fill_one: bool = False):
    """Pad (B, ...) rows up to nb; pad rows are 0, or 1 (first limb) for
    ops whose neutral element is 1."""
    b = arr.shape[0]
    if nb == b:
        return arr
    pad = jnp.zeros((nb - b,) + arr.shape[1:], dtype=arr.dtype)
    if fill_one:
        pad = pad.at[:, 0].set(jnp.asarray(1, dtype=arr.dtype))
    return jnp.concatenate([arr, pad], axis=0)


def pad_rows_np(arr: np.ndarray, nb: int,
                fill_one: bool = False) -> np.ndarray:
    """``pad_rows`` on the host: identical row semantics, numpy ops."""
    b = arr.shape[0]
    if nb == b:
        return arr
    pad = np.zeros((nb - b,) + arr.shape[1:], dtype=arr.dtype)
    if fill_one:
        pad[:, 0] = 1
    return np.concatenate([arr, pad], axis=0)


def _host_pad() -> bool:
    """Host-side padding fast path (EGTPU_DISPATCH_HOST_PAD, default
    on): when every input is already a host array, bucket-pad in numpy
    (microseconds) and let the jitted program's own argument transfer
    move the rows, instead of dispatching zeros/scatter/concatenate as
    eager device ops before every call.  On small batches that eager
    glue costs ~5x the jitted dispatch itself and was the seeds/s
    ceiling of the sim sweeps (tools/sim_matrix reports the
    before/after)."""
    return knobs.get_str("EGTPU_DISPATCH_HOST_PAD") != "0"


def run_tiled(jfn, arrays, fills, cap: int | None = None):
    """THE dispatch policy, shared by every batch plane (group ops,
    exponent ops, device SHA-256): dispatch ``jfn(*arrays)`` over
    row-tiles — batches ≤ cap pad to their ``dispatch_bucket`` shape,
    larger batches loop over cap-sized tiles (last tile padded to the
    cap) — so any workload size compiles the same bounded set of
    programs.  ``fills[i]`` selects 1-rows (True) or 0-rows (False) as
    the i-th array's padding."""
    cap = cap or _dispatch_tile()
    host = _host_pad() and all(isinstance(a, np.ndarray) for a in arrays)
    if not host:
        arrays = [jnp.asarray(a) for a in arrays]
    pad = pad_rows_np if host else pad_rows
    n = arrays[0].shape[0]

    def one(tiles, nb):
        m = tiles[0].shape[0]
        out = jfn(*[pad(a, nb, f) for a, f in zip(tiles, fills)])
        return out if m == nb else out[:m]

    if n <= cap:
        return one(arrays, dispatch_bucket(n, cap))
    return jnp.concatenate(
        [one([a[lo:lo + cap] for a in arrays], cap)
         for lo in range(0, n, cap)], axis=0)


def run_tiled_multi(jfn, arrays, fills, cap: int | None = None):
    """``run_tiled`` for programs returning a TUPLE of per-row arrays
    (fused pipelines that keep many products of one dispatch).  Same
    bounded-shape bucketing; each output is sliced back to the tile's
    true row count and concatenated across tiles."""
    cap = cap or _dispatch_tile()
    host = _host_pad() and all(isinstance(a, np.ndarray) for a in arrays)
    if not host:
        arrays = [jnp.asarray(a) for a in arrays]
    pad = pad_rows_np if host else pad_rows
    n = arrays[0].shape[0]

    def one(tiles, nb):
        m = tiles[0].shape[0]
        out = jfn(*[pad(a, nb, f) for a, f in zip(tiles, fills)])
        return list(out) if m == nb else [o[:m] for o in out]

    if n <= cap:
        return one(arrays, dispatch_bucket(n, cap))
    parts = [one([a[lo:lo + cap] for a in arrays], cap)
             for lo in range(0, n, cap)]
    return [jnp.concatenate(ps, axis=0) for ps in zip(*parts)]


def _default_backend() -> str:
    """Fused Pallas kernels on TPU, VPU CIOS elsewhere; override with
    EGTPU_BIGNUM=pallas|ntt|cios."""
    env = os.environ.get("EGTPU_BIGNUM", "auto").strip().lower()
    if env in ("pallas", "ntt", "cios"):
        return env
    if env not in ("", "auto"):
        raise ValueError(f"EGTPU_BIGNUM={env!r} not recognized; "
                         "expected 'pallas', 'ntt', 'cios', or 'auto'")
    return "pallas" if jax.default_backend() == "tpu" else "cios"


class JaxGroupOps:
    """Batch plane for one ``GroupContext``.  Thread-compatible, stateless
    after construction (all tables are device constants).

    ``backend`` selects the Montgomery multiplier: "cios" (VPU lax.scan
    kernel, bignum_jax), "ntt" (MXU int8-matmul engine, ntt_mxu), or
    "pallas" (the fused-kernel build of the same NTT math,
    core.pallas.engine); all share the R = 2^4096 Montgomery domain and
    limb format.  The fallback chain pallas→ntt→cios degrades with a
    warning instead of raising: pallas needs a TPU (or the
    EGTPU_PALLAS_INTERPRET opt-in for bit-exact-but-slow CPU testing)
    and, like ntt, the 4096-bit production limb count."""

    def __init__(self, group: GroupContext, backend: str | None = None):
        self.group = group
        p = group.p
        self.n = (p.bit_length() + 15) // 16          # p limbs (256 prod)
        self.ne = (group.q.bit_length() + 15) // 16   # exponent limbs (16)
        self.exp_bits = group.q.bit_length()
        self.ctx = bn.make_mont_ctx(p, self.n)
        self.backend = backend or _default_backend()
        if self.backend not in ("pallas", "ntt", "cios"):
            raise ValueError(f"unknown bignum backend {self.backend!r}; "
                             "expected 'pallas', 'ntt', or 'cios'")
        if self.backend in ("pallas", "ntt") and self.n != ntt_mxu.NL:
            # the MXU engines are built for the 4096-bit production group
            warnings.warn(f"{self.backend} backend requires "
                          f"{ntt_mxu.NL}-limb groups; falling back to "
                          f"cios for {self.n}-limb group")
            self.backend = "cios"
        if (self.backend == "pallas" and jax.default_backend() != "tpu"
                and not knobs.get_flag("EGTPU_PALLAS_INTERPRET")):
            warnings.warn("pallas backend requires a TPU (set "
                          "EGTPU_PALLAS_INTERPRET=1 to run its kernels "
                          "in interpret mode); falling back to ntt")
            self.backend = "ntt"
        if self.backend == "pallas":
            try:
                from electionguard_tpu.core.pallas import (
                    engine as pallas_eng)
            except ImportError as e:  # jax without pallas support
                warnings.warn(f"pallas backend unavailable ({e}); "
                              "falling back to ntt")
                self.backend = "ntt"
            else:
                pctx = pallas_eng.make_pallas_ctx(p)
                self._nctx = pctx.nctx
                self._mm = functools.partial(pallas_eng.montmul, pctx)
                self._ms = functools.partial(pallas_eng.montsqr, pctx)
                self._mm_shared = functools.partial(
                    pallas_eng.montmul_shared, pctx)
                self._mm_hat = functools.partial(pallas_eng.montmul_hat,
                                                 pctx)
                self._nttfwd = functools.partial(pallas_eng.nttfwd, pctx)
        if self.backend == "ntt":
            nctx = ntt_mxu.make_ntt_ctx(p)
            self._nctx = nctx
            self._mm = functools.partial(ntt_mxu.montmul, nctx)
            self._ms = functools.partial(ntt_mxu.montsqr, nctx)
            # bucket multiplies share their base operand's forward NTT
            self._mm_shared = functools.partial(ntt_mxu.montmul_shared,
                                                nctx)
            # fixed-base ladders multiply by pre-evaluated table rows
            self._mm_hat = functools.partial(ntt_mxu.montmul_hat, nctx)
            self._nttfwd = functools.partial(ntt_mxu.nttfwd, nctx)
        elif self.backend == "cios":
            self._nctx = None
            self._mm = functools.partial(bn.montmul, self.ctx)
            self._ms = None
            self._mm_shared = None
            self._mm_hat = None
            self._nttfwd = None
        R = 1 << (16 * self.n)
        self._R = R

        # fixed-base tables for g and (lazily) other bases: 8-bit windows
        self.nwin8 = (self.exp_bits + 7) // 8
        self._fixed_tables: dict[int, jax.Array] = {}
        self._fixed_tables_hat: dict[int, jax.Array] = {}
        self.g_table = self.fixed_table(group.g)  # registered: base g
        # cache hits for later fixed_table(g.g) callers

        # jitted entry points
        self._powmod_j = jax.jit(self._powmod_impl)
        self._multi_powmod_j = jax.jit(self._multi_powmod_impl)
        self._mulmod_j = jax.jit(self._mulmod_impl)
        self._fixed_pow_j = jax.jit(self._fixed_pow_impl)
        self._fixed_multi_pow_j = jax.jit(self._fixed_multi_pow_impl)
        self._prod_reduce_j = jax.jit(self._prod_reduce_impl)
        self._verify_residue_j = jax.jit(self._verify_residue_impl)
        self._to_mont_j = jax.jit(self._to_mont_impl)
        self._msm_window_j = jax.jit(self._msm_window_impl)
        self._msm_combine_j = jax.jit(self._msm_combine_impl,
                                      static_argnums=(1,))
        self._cofactor_j = None  # built lazily by cofactor_pow

    # ------------------------------------------------------------------
    # codecs
    # ------------------------------------------------------------------
    def to_limbs_p(self, xs: Iterable[int]) -> np.ndarray:
        return bn.ints_to_limbs(xs, self.n)

    def to_limbs_q(self, xs: Iterable[int]) -> np.ndarray:
        return bn.ints_to_limbs(xs, self.ne)

    def from_limbs(self, arr) -> list[int]:
        return bn.limbs_to_ints(np.asarray(arr))

    # ------------------------------------------------------------------
    # fixed-base tables (PowRadix)
    # ------------------------------------------------------------------
    def _table_fingerprint(self, kind: str, base: int) -> str:
        # keyed by GROUP digest + base digest + geometry, nothing else:
        # no election id, manifest, or tenant component — concurrent
        # elections over one group share entries (table_cache contract)
        return table_cache.fingerprint(
            kind, group=table_cache.group_digest(self.group),
            base=table_cache.int_digest(base % self.group.p),
            nwin8=self.nwin8, n=self.n)

    def _make_fixed_table(self, base: int) -> jax.Array:
        """table[w, d] = mont(base^(d * 2^(8w))), shape (nwin8, 256, n).

        Host-built with Python ints (one-time, ~8k modmuls of 4096-bit
        values — the dominant setup cost per base), stored on device in
        the Montgomery domain and persisted via core.table_cache when
        EGTPU_TABLE_CACHE is set.
        """
        fp = self._table_fingerprint("powradix", base)
        cached = table_cache.load("powradix", fp)
        if cached is not None:
            return jnp.asarray(cached["table"])
        p, R = self.group.p, self._R
        rows = np.empty((self.nwin8, 256, self.n), dtype=np.uint32)
        step = base % p  # base^(2^(8w)) for current w
        for w in range(self.nwin8):
            acc = 1
            for d in range(256):
                rows[w, d] = bn.int_to_limbs(acc * R % p, self.n)
                acc = acc * step % p
            step = acc  # after 256 iters acc = step^256 = base^(2^(8(w+1)))
        table_cache.store("powradix", fp, {"table": rows})
        return jnp.asarray(rows)

    _TABLE_CACHE_MAX = 16  # 8 MiB each; FIFO like the hat cache

    def fixed_table(self, base: int) -> jax.Array:
        t = self._fixed_tables.get(base)
        if t is None:
            t = self._make_fixed_table(base)
            while len(self._fixed_tables) >= self._TABLE_CACHE_MAX:
                self._fixed_tables.pop(next(iter(self._fixed_tables)))
            self._fixed_tables[base] = t
        return t

    _HAT_CACHE_MAX = 4  # g, g^-1, K + one spare; ~64 MiB of HBM each

    def fixed_table_hat(self, base: int):
        """NTT-evaluated twin of ``fixed_table``: (nwin8, 256, 2, NC)
        uint32 forward evaluations of every table row (ntt/pallas
        backends only; None otherwise).  8x the plain table's memory —
        lets the fixed-base ladder skip the table operand's forward NTT
        in every window (montmul_hat).  Cache is FIFO-bounded: a
        long-lived process serving many elections (many keys K) must not
        accrete 64 MiB of HBM per key; evicted tables rebuild in one
        device pass.  Evaluations are backend-independent (pallas is
        bit-identical to ntt), so the on-disk entry is shared."""
        if self._nttfwd is None:
            return None
        t = self._fixed_tables_hat.get(base)
        if t is None:
            fp = self._table_fingerprint("powradix_hat", base)
            cached = table_cache.load("powradix_hat", fp)
            if cached is not None:
                t = jnp.asarray(cached["table"])
            else:
                plain = self.fixed_table(base)
                hat = self._nttfwd(plain.reshape(-1, self.n))
                t = hat.reshape(self.nwin8, 256, 2, ntt_mxu.NC)
                table_cache.store("powradix_hat", fp,
                                  {"table": np.asarray(t)})
            while len(self._fixed_tables_hat) >= self._HAT_CACHE_MAX:
                self._fixed_tables_hat.pop(
                    next(iter(self._fixed_tables_hat)))
            self._fixed_tables_hat[base] = t
        return t

    def _fixed_pow_impl(self, table: jax.Array, exp: jax.Array) -> jax.Array:
        """Canonical base^exp for a fixed-base table; exp (B, ne) limbs."""
        acc = None
        for w in range(self.nwin8):
            limb = exp[..., w // 2]
            digit = ((limb >> ((w % 2) * 8)) & jnp.uint32(0xFF)).astype(jnp.int32)
            sel = table[w][digit]          # (B, n) gather over 256 rows
            acc = sel if acc is None else self._mm(acc, sel)
        return bn.from_mont_via(self._mm, acc)

    def _fixed_multi_pow_impl(self, tables: jax.Array,
                              exps: jax.Array) -> jax.Array:
        """∏_j tables[j]^{exps[:, j]} for k host-known bases in ONE fused
        program: tables (k, nwin8, 256, n) stacked fixed-base tables,
        exps (B, k, ne) -> (B, n) canonical.  k·nwin8 gathers plus
        k·nwin8 - 1 Montgomery multiplies — a k-base PowRadix ladder, vs
        ~k·335 multiplies for k variable-base ladders plus the combining
        mulmods.  The mixnet's bridging commitments ĉ_i = g^{R_i} h^{U_i}
        and their sigma commitments are exactly this shape."""
        k = tables.shape[0]
        acc = None
        for j in range(k):
            for w in range(self.nwin8):
                limb = exps[:, j, w // 2]
                digit = ((limb >> ((w % 2) * 8))
                         & jnp.uint32(0xFF)).astype(jnp.int32)
                sel = tables[j, w][digit]      # (B, n) gather
                acc = sel if acc is None else self._mm(acc, sel)
        return bn.from_mont_via(self._mm, acc)

    # ------------------------------------------------------------------
    # op implementations
    # ------------------------------------------------------------------
    def _mulmod_impl(self, a: jax.Array, b: jax.Array) -> jax.Array:
        return self._mm(self._mm(a, b),
                        jnp.broadcast_to(self.ctx.r2_mod_p, a.shape))

    def _powmod_impl(self, base: jax.Array, exp: jax.Array) -> jax.Array:
        return bn.powmod(self.ctx, base, exp, self.exp_bits,
                         montmul_fn=self._mm, montsqr_fn=self._ms)

    def _multi_powmod_impl(self, base: jax.Array,
                           exps: jax.Array) -> jax.Array:
        return bn.multi_powmod_shared(self.ctx, base, exps, self.exp_bits,
                                      montmul_fn=self._mm,
                                      montsqr_fn=self._ms,
                                      montmul_shared_fn=self._mm_shared)

    def _prod_reduce_impl(self, x: jax.Array) -> jax.Array:
        """Product over axis 0 of (M, B, n) canonical values -> (B, n),
        via the log-depth Montgomery tree (bignum_jax.mont_prod_tree)."""
        r2 = jnp.broadcast_to(self.ctx.r2_mod_p, x.shape)
        acc = bn.mont_prod_tree(self.ctx, self._mm(x, r2),
                                montmul_fn=self._mm)
        return bn.from_mont_via(self._mm, acc)

    def _to_mont_impl(self, x: jax.Array) -> jax.Array:
        return self._mm(x, jnp.broadcast_to(self.ctx.r2_mod_p, x.shape))

    def _msm_window_impl(self, bases_m: jax.Array,
                         idx: jax.Array) -> jax.Array:
        """One Pippenger window's bucket products: gather every base row
        assigned to each of the D digit buckets (idx (D, G) int32 into
        the Montgomery-domain ``bases_m`` (Nb+1, n), whose last row is
        mont(1) — the shared pad target), then product-reduce each
        bucket's G rows with the log-depth Montgomery tree -> (D, n)."""
        sel = bases_m[idx]                          # (D, G, n)
        return bn.mont_prod_tree(self.ctx, sel.swapaxes(0, 1),
                                 montmul_fn=self._mm)

    def _msm_combine_impl(self, buckets: jax.Array, w: int) -> jax.Array:
        """Fold (nwin, D, n) Montgomery bucket products into the final
        MSM value.  Per window, the digit-weighted sum ∏_d bucket[d]^d
        comes from the standard running-suffix-product scan (2 montmuls
        per bucket, all windows batched down the row axis); the windows
        then fold MSB-first with w squarings per step.  Returns (1, n)
        canonical."""
        nwin, D, _ = buckets.shape
        S0 = buckets[:, D - 1]
        xs = jnp.flip(buckets[:, 1:D - 1], axis=1).transpose(1, 0, 2)

        def step(carry, x):
            S, acc = carry
            S = self._mm(S, x)
            return (S, self._mm(acc, S)), None

        (_, acc), _ = jax.lax.scan(step, (S0, S0), xs)
        sq = self._ms or (lambda x: self._mm(x, x))
        out = acc[nwin - 1:nwin]
        if nwin > 1:
            # MSB-first fold, also a scan: the compiled graph stays O(w)
            # regardless of window count (wide RLC exponents reach ~48
            # windows; unrolling their squarings made compiles minutes)
            def fold(carry, x):
                for _ in range(w):
                    carry = sq(carry)
                return self._mm(carry, x), None

            out, _ = jax.lax.scan(
                fold, out, jnp.flip(acc[:nwin - 1], axis=0)[:, None, :])
        return bn.from_mont_via(self._mm, out)

    def _verify_residue_impl(self, x: jax.Array, q_exp: jax.Array) -> jax.Array:
        """Subgroup membership: 0 < x < p and x^q == 1, batched.

        The range check matches the scalar plane's
        ``ElementModP.is_valid_residue`` so non-canonical limb encodings
        (e.g. x = p + 1) are rejected, not silently reduced."""
        in_range = bn.is_lt(x, self.ctx.p_limbs) & jnp.any(x != 0, axis=-1)
        y = bn.powmod(self.ctx, x, q_exp, self.group.q.bit_length(),
                      montmul_fn=self._mm, montsqr_fn=self._ms)
        one = jnp.zeros_like(y).at[..., 0].set(jnp.uint32(1))
        return in_range & jnp.all(y == one, axis=-1)

    # ------------------------------------------------------------------
    # public array API (jnp/np arrays of limbs in and out)
    #
    # Every op dispatches through the shared ``run_tiled`` policy: padded
    # power-of-two buckets capped at a fixed tile size, so the whole
    # workflow compiles a BOUNDED set of shapes no matter how large the
    # workload — compile time is the practical cost of the big NTT
    # programs, and an arbitrary-size election must not pay a fresh
    # multi-minute compile per batch size (EGTPU_TILE overrides the cap).
    # ------------------------------------------------------------------
    @property
    def tile(self) -> int:
        return _dispatch_tile()

    def powmod(self, base, exp):
        """Elementwise batch base^exp mod p; base (B,n), exp (B,ne)."""
        return run_tiled(self._powmod_j, [base, exp],
                         [True, False])   # 1^0 = 1 padding

    def multi_powmod(self, base, exps):
        """k powers of each shared base in one pass: base (B,n), exps
        (B,k,ne) -> (B,k,n).  The 256 base squarings amortize over the k
        exponents (bignum_jax.mont_multi_pow_shared); the verifier's
        {x^q, x^c0, x^c1} triple costs ~0.56x three independent ladders."""
        return run_tiled(self._multi_powmod_j, [base, exps],
                         [True, False])   # 1^0 = 1 padding

    def mulmod(self, a, b_arr):
        return run_tiled(self._mulmod_j, [a, b_arr], [True, True])

    def g_pow(self, exp):
        """g^exp via the PowRadix table; exp (B, ne)."""
        return run_tiled(
            lambda e: self._fixed_pow_j(self.g_table, e),
            [exp], [False])               # g^0 = 1 padding

    def base_pow(self, base: int, exp):
        """base^exp for a host-known base (K, g^{-1}, ...) via cached table."""
        table = self.fixed_table(base)
        return run_tiled(
            lambda e: self._fixed_pow_j(table, e), [exp], [False])

    def fixed_multi_pow(self, bases: Sequence[int], exps):
        """∏_j bases[j]^{exps[:, j]} for k host-known bases via cached
        tables, one fused ladder per dispatch: exps (B, k, ne) -> (B, n).
        The shared/fixed-base multi-exp behind the mixnet's permutation
        proof commitments (tools/bench_bignum.py 'fixedmulti' compares it
        against k variable-base ladders)."""
        tables = jnp.stack([self.fixed_table(b) for b in bases])
        return run_tiled(
            lambda e: self._fixed_multi_pow_j(tables, e), [exps], [False])

    def cofactor_pow(self, x):
        """x^((p-1)/q) batched: project arbitrary nonzero residues into
        the order-q subgroup (independent-generator derivation for the
        mixnet's Pedersen bases; hash-to-group, dlog-free)."""
        if self._cofactor_j is None:
            r = (self.group.p - 1) // self.group.q
            bits = r.bit_length()
            r_l = jnp.asarray(bn.int_to_limbs(r, (bits + 15) // 16))

            def impl(xt):
                e = jnp.broadcast_to(r_l, xt.shape[:-1] + r_l.shape)
                return bn.powmod(self.ctx, xt, e, bits,
                                 montmul_fn=self._mm, montsqr_fn=self._ms)
            self._cofactor_j = jax.jit(impl)
        return run_tiled(self._cofactor_j, [x], [True])  # 1^r = 1 padding

    def prod_reduce(self, x):
        """Product over axis 0: (M, B, n) -> (B, n).  Both the reduced M
        axis (which varies with ballot count) and the B axis are bucketed
        with neutral 1-rows (same bounded shape set as _run_tiled)."""
        x = jnp.asarray(x)
        m, b = x.shape[0], x.shape[1]
        cap = self.tile
        if m > cap:   # reduce cap-sized slabs, then combine the partials
            parts = [self.prod_reduce(x[lo:lo + cap])
                     for lo in range(0, m, cap)]
            return self.prod_reduce(jnp.stack(parts))
        if b > cap:   # tile the passive axis
            return jnp.concatenate(
                [self.prod_reduce(x[:, lo:lo + cap])
                 for lo in range(0, b, cap)], axis=0)
        nm, nb = dispatch_bucket(m, cap), dispatch_bucket(b, cap)
        if nm != m or nb != b:
            one = jnp.zeros((1, 1, x.shape[2]), dtype=x.dtype)
            one = one.at[..., 0].set(jnp.asarray(1, dtype=x.dtype))
            if nb != b:
                x = jnp.concatenate(
                    [x, jnp.broadcast_to(one, (m, nb - b, x.shape[2]))],
                    axis=1)
            if nm != m:
                x = jnp.concatenate(
                    [x, jnp.broadcast_to(one, (nm - m, nb, x.shape[2]))],
                    axis=0)
        return self._prod_reduce_j(x)[:b]

    def msm(self, bases, exps: Sequence[int],
            exp_bits: int | None = None) -> np.ndarray:
        """Multi-scalar accumulation ∏_i bases[i]^{exps[i]} mod p via
        Pippenger bucketing: bases (N, n) canonical limb rows, exps N
        host-known non-negative Python ints of ANY width (the RLC
        verifier mixes 128-bit randomizers with ~384-bit exact combined
        exponents; zero digits cost nothing).  Returns the (n,) canonical
        limb row of the product.

        Each w-bit window (w = EGTPU_MSM_WINDOW ∈ {4, 8, 16}, divisors
        of the 16-bit limb) gathers its rows into 2^w digit buckets and
        product-reduces them with the log-depth Montgomery tree, so the
        cost is ~nwin·N tree multiplies plus 2·(2^w)·nwin scan multiplies
        — at N = 4096, w = 8, 128-bit exponents that is ~8x fewer
        montmul-rows than N independent square-and-multiply ladders.
        Batches beyond the dispatch tile split into cap-row sub-MSMs
        whose partial products combine through ``prod_reduce``, keeping
        the gather working set and the compiled shape set bounded."""
        bases = jnp.asarray(bases)
        exps = [int(e) for e in exps]
        n_rows = bases.shape[0]
        if n_rows != len(exps):
            raise ValueError(f"msm: {n_rows} bases vs {len(exps)} exps")
        if any(e < 0 for e in exps):
            raise ValueError("msm exponents must be non-negative")
        out = np.zeros((self.n,), dtype=np.uint32)
        out[0] = 1
        if n_rows == 0:
            return out
        mx = max(e.bit_length() for e in exps)
        exp_bits = max(exp_bits or 0, mx, 1)
        cap = self.tile
        if n_rows > cap:
            parts = [self.msm(bases[lo:lo + cap], exps[lo:lo + cap],
                              exp_bits)
                     for lo in range(0, n_rows, cap)]
            stacked = np.stack(parts)[:, None, :]      # (chunks, 1, n)
            return np.asarray(self.prod_reduce(stacked))[0]
        w = knobs.get_int("EGTPU_MSM_WINDOW")
        if w not in (4, 8, 16):
            raise ValueError(f"EGTPU_MSM_WINDOW={w} must be 4, 8 or 16")
        nwin = (exp_bits + w - 1) // w
        D = 1 << w
        per = 16 // w                      # digits per 16-bit limb
        el = bn.ints_to_limbs(exps, (exp_bits + 15) // 16)
        nb = dispatch_bucket(n_rows, cap)
        bases_m = self._to_mont_j(pad_rows(bases, nb, fill_one=True))
        one_m = jnp.broadcast_to(self.ctx.r_mod_p, (1, self.n))
        bases_m = jnp.concatenate([bases_m, one_m], axis=0)
        mask = np.uint32(D - 1)
        one_rows = None
        buckets = []
        for win in range(nwin):
            dig = ((el[:, win // per] >> np.uint32((win % per) * w))
                   & mask).astype(np.int64)
            nz = np.nonzero(dig)[0]
            if len(nz) == 0:               # all-zero digit column
                if one_rows is None:
                    one_rows = jnp.broadcast_to(self.ctx.r_mod_p,
                                                (D, self.n))
                buckets.append(one_rows)
                continue
            order = np.argsort(dig[nz], kind="stable")
            si = nz[order].astype(np.int32)
            sd = dig[nz][order]
            maxg = int(np.bincount(sd, minlength=D).max())
            g_pad = dispatch_bucket(maxg, cap)
            starts = np.searchsorted(sd, np.arange(D))
            idx = np.full((D, g_pad), nb, dtype=np.int32)
            idx[sd, np.arange(len(sd)) - starts[sd]] = si
            buckets.append(self._msm_window_j(bases_m, jnp.asarray(idx)))
        res = self._msm_combine_j(jnp.stack(buckets), w)
        return np.asarray(res)[0]

    def msm_ints(self, bases: Sequence[int], exps: Sequence[int],
                 exp_bits: int | None = None) -> int:
        """Int-facing ``msm``: ∏_i bases[i]^{exps[i]} mod p."""
        out = self.msm(self.to_limbs_p(bases), exps, exp_bits)
        return self.from_limbs(out[None, :])[0]

    def is_valid_residue(self, x):
        """Batched subgroup membership x^q == 1 (and 0 < x < p)."""
        q_l = jnp.asarray(bn.int_to_limbs(self.group.q, self.ne))

        def fn(xt):                               # 1 is a valid residue
            q_exp = jnp.broadcast_to(q_l, xt.shape[:-1] + (self.ne,))
            return self._verify_residue_j(xt, q_exp)
        return run_tiled(fn, [x], [True])

    # ------------------------------------------------------------------
    # int-facing convenience (tests, small control-plane batches)
    # ------------------------------------------------------------------
    def powmod_ints(self, bases: Sequence[int], exps: Sequence[int]) -> list[int]:
        return self.from_limbs(
            self.powmod(self.to_limbs_p(bases), self.to_limbs_q(exps)))

    def mulmod_ints(self, a: Sequence[int], b: Sequence[int]) -> list[int]:
        return self.from_limbs(
            self.mulmod(self.to_limbs_p(a), self.to_limbs_p(b)))

    def g_pow_ints(self, exps: Sequence[int]) -> list[int]:
        return self.from_limbs(self.g_pow(self.to_limbs_q(exps)))

    def prod_ints(self, xs: Sequence[Sequence[int]]) -> list[int]:
        arr = np.stack([self.to_limbs_p(row) for row in xs])  # (M, B, n)
        return self.from_limbs(self.prod_reduce(arr))


class JaxExponentOps:
    """Batched Z_q (exponent field) arithmetic: the 256-bit side plane used
    by proof generation/verification pipelines (response and challenge
    algebra: v = u - c·s mod q, nonce products, Lagrange weights)."""

    def __init__(self, group: GroupContext):
        self.group = group
        self.ne = (group.q.bit_length() + 15) // 16
        self.ctx = bn.make_mont_ctx(group.q, self.ne)
        self._mul_j = jax.jit(functools.partial(bn.mulmod, self.ctx))
        self._add_j = jax.jit(
            functools.partial(bn.add_mod, p_limbs=self.ctx.p_limbs))
        self._sub_j = jax.jit(
            functools.partial(bn.sub_mod, p_limbs=self.ctx.p_limbs))

    def to_limbs(self, xs: Iterable[int]) -> np.ndarray:
        return bn.ints_to_limbs(xs, self.ne)

    def from_limbs(self, arr) -> list[int]:
        return bn.limbs_to_ints(np.asarray(arr))

    def mul(self, a, b):
        return run_tiled(self._mul_j, [a, b], [False, False])

    def add(self, a, b):
        return run_tiled(self._add_j, [a, b], [False, False])

    def sub(self, a, b):
        return run_tiled(self._sub_j, [a, b], [False, False])

    def a_minus_bc(self, a, b, c):
        """a - b·c mod q, the response equation of every proof."""
        return run_tiled(
            lambda x, y, z: self._sub_j(x, self._mul_j(y, z)),
            [a, b, c], [False, False, False])


def limbs_to_bytes_be(arr: np.ndarray) -> np.ndarray:
    """(B, n) uint32 16-bit little-endian limbs -> (B, 2n) uint8 big-endian
    byte images (the wire/hash encoding of common.proto:6-16)."""
    arr = np.asarray(arr, dtype=np.uint32)
    le16 = arr.astype("<u2")[..., ::-1]          # big-endian limb order
    return le16.astype(">u2").view(np.uint8).reshape(arr.shape[0], -1)


def bytes_be_to_limbs(b: np.ndarray) -> np.ndarray:
    """(B, 2n) uint8 big-endian bytes -> (B, n) uint32 limbs."""
    b = np.ascontiguousarray(b, dtype=np.uint8)
    be16 = b.view(">u2").reshape(b.shape[0], -1)
    return be16[..., ::-1].astype(np.uint32)


@functools.lru_cache(maxsize=None)
def jax_ops(group: GroupContext) -> JaxGroupOps:
    """Process-wide cached batch plane per group."""
    return JaxGroupOps(group)


@functools.lru_cache(maxsize=None)
def jax_exp_ops(group: GroupContext) -> JaxExponentOps:
    """Process-wide cached exponent plane per group."""
    return JaxExponentOps(group)

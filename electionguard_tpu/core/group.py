"""Group arithmetic for the ElectionGuard production group.

This is the native replacement for the reference's [ext] crypto core
(``GroupContext``, ``ElementModP``, ``ElementModQ`` — constructed via
``productionGroup(PowRadixOption.LOW_MEMORY_USE, ProductionMode.Mode4096)``,
reference: src/main/java/electionguard/util/KUtils.java:10-13, wrapped at the
codec boundary in src/main/java/electionguard/util/ConvertCommonProto.java:42-57).

Two planes:

* **Scalar plane (this module):** Python-int backed ``ElementModP`` /
  ``ElementModQ`` and a ``GroupContext`` with the mod-p / mod-q operations the
  protocol control paths need (key ceremony, share encryption, coordinator
  combine).  CPython's ``pow`` is the CPU baseline the TPU plane is
  differential-tested against.
* **Batch plane (electionguard_tpu.core.group_jax):** the same operations
  batch-first over limb arrays, vmapped/sharded on TPU.  The hot loops of the
  workflow (encryption, tally accumulation, proof verification — SURVEY.md §3
  🔥 marks) run there.

Wire encodings are big-endian unsigned: ElementModP = 512 bytes, ElementModQ
= 32 bytes (reference: src/main/proto/common.proto:6-16,
ConvertCommonProto.java:46,55).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from functools import lru_cache
from typing import Iterable


@dataclass(frozen=True)
class GroupSpec:
    """The numeric constants defining a multiplicative subgroup.

    ``p`` prime, ``q`` prime, ``p - 1 == q * r``, ``g`` of order ``q``.
    ``p_bytes``/``q_bytes`` fix the wire widths (512/32 for production).
    """

    p: int
    q: int
    r: int
    g: int
    p_bytes: int
    q_bytes: int
    name: str = "production"


class ElementModQ:
    """An element of Z_q (256-bit exponent field).  Immutable."""

    __slots__ = ("value", "group")

    def __init__(self, value: int, group: "GroupContext"):
        if not (0 <= value < group.q):
            raise ValueError(f"ElementModQ out of range: {value:#x}")
        object.__setattr__(self, "value", value)
        object.__setattr__(self, "group", group)

    def __setattr__(self, *a):  # immutability
        raise AttributeError("ElementModQ is immutable")

    def to_bytes(self) -> bytes:
        return self.value.to_bytes(self.group.spec.q_bytes, "big")

    def is_zero(self) -> bool:
        return self.value == 0

    def __eq__(self, other):
        return (isinstance(other, ElementModQ) and self.value == other.value
                and self.group.spec is other.group.spec)

    def __hash__(self):
        return hash(("Q", self.group.spec.name, self.value))

    def __repr__(self):
        return f"ElementModQ({self.value:#x})"


class ElementModP:
    """An element of Z_p^* (4096-bit).  Immutable."""

    __slots__ = ("value", "group")

    def __init__(self, value: int, group: "GroupContext"):
        if not (0 <= value < group.p):
            raise ValueError("ElementModP out of range")
        object.__setattr__(self, "value", value)
        object.__setattr__(self, "group", group)

    def __setattr__(self, *a):
        raise AttributeError("ElementModP is immutable")

    def to_bytes(self) -> bytes:
        return self.value.to_bytes(self.group.spec.p_bytes, "big")

    def is_valid_residue(self) -> bool:
        """True iff the element is in the order-q subgroup (spec check)."""
        g = self.group
        return 0 < self.value < g.p and pow(self.value, g.q, g.p) == 1

    def __eq__(self, other):
        return (isinstance(other, ElementModP) and self.value == other.value
                and self.group.spec is other.group.spec)

    def __hash__(self):
        return hash(("P", self.group.spec.name, self.value))

    def __repr__(self):
        v = self.value
        return f"ElementModP({v:#x})" if v < 1 << 64 else f"ElementModP({v >> (v.bit_length() - 32):#x}...)"


class GroupContext:
    """Scalar-plane group operations (CPU, Python int).

    API surface mirrors the capability set the reference imports from the
    Kotlin library's ``GroupContext`` (SURVEY.md §2.9 crypto core row).
    """

    def __init__(self, spec: GroupSpec):
        self.spec = spec
        self.p = spec.p
        self.q = spec.q
        self.r = spec.r
        self.g = spec.g
        self._g_elem = ElementModP(spec.g, self)
        self.ZERO_MOD_Q = ElementModQ(0, self)
        self.ONE_MOD_Q = ElementModQ(1, self)
        self.TWO_MOD_Q = ElementModQ(2 % spec.q, self)
        self.ONE_MOD_P = ElementModP(1, self)
        self.G_MOD_P = self._g_elem
        # g^-1 mod p, used by exponential-ElGamal decryption
        self.GINV_MOD_P = ElementModP(pow(spec.g, -1, spec.p), self)

    # ---- constructors -------------------------------------------------
    def int_to_q(self, i: int) -> ElementModQ:
        return ElementModQ(i % self.q, self)

    def int_to_p(self, i: int) -> ElementModP:
        return ElementModP(i % self.p, self)

    def bytes_to_q(self, b: bytes) -> ElementModQ:
        """Big-endian decode; must already be < q (strict, wire contract)."""
        return ElementModQ(int.from_bytes(b, "big"), self)

    def bytes_to_p(self, b: bytes) -> ElementModP:
        return ElementModP(int.from_bytes(b, "big"), self)

    def fingerprint(self) -> bytes:
        """32-byte SHA-256 of the (p, q, g) wire-width byte images — the
        registration-time group-constants check (reference defined but
        never populated the analogous field: decrypting_rpc.proto:20)."""
        import hashlib
        h = hashlib.sha256()
        h.update(self.p.to_bytes(self.spec.p_bytes, "big"))
        h.update(self.q.to_bytes(self.spec.q_bytes, "big"))
        h.update(self.g.to_bytes(self.spec.p_bytes, "big"))
        return h.digest()

    def rand_q(self, minimum: int = 2) -> ElementModQ:
        """Uniform random element of [minimum, q) via rejection sampling.

        Default floor of 2 matches the constraint on ElGamal secret keys;
        pass ``minimum=0`` for unconstrained nonces.
        """
        while True:
            v = secrets.randbits(self.q.bit_length())
            if minimum <= v < self.q:
                return ElementModQ(v, self)

    # ---- mod q --------------------------------------------------------
    def add_q(self, *xs: ElementModQ) -> ElementModQ:
        s = 0
        for x in xs:
            s += x.value
        return ElementModQ(s % self.q, self)

    def sub_q(self, a: ElementModQ, b: ElementModQ) -> ElementModQ:
        return ElementModQ((a.value - b.value) % self.q, self)

    def mult_q(self, *xs: ElementModQ) -> ElementModQ:
        s = 1
        for x in xs:
            s = s * x.value % self.q
        return ElementModQ(s, self)

    def neg_q(self, a: ElementModQ) -> ElementModQ:
        return ElementModQ((-a.value) % self.q, self)

    def inv_q(self, a: ElementModQ) -> ElementModQ:
        if a.value == 0:
            raise ZeroDivisionError("inverse of 0 mod q")
        return ElementModQ(pow(a.value, -1, self.q), self)

    def a_plus_bc_q(self, a: ElementModQ, b: ElementModQ, c: ElementModQ) -> ElementModQ:
        return ElementModQ((a.value + b.value * c.value) % self.q, self)

    # ---- mod p --------------------------------------------------------
    def mult_p(self, *xs: ElementModP) -> ElementModP:
        s = 1
        for x in xs:
            s = s * x.value % self.p
        return ElementModP(s, self)

    def inv_p(self, a: ElementModP) -> ElementModP:
        return ElementModP(pow(a.value, -1, self.p), self)

    def div_p(self, a: ElementModP, b: ElementModP) -> ElementModP:
        return self.mult_p(a, self.inv_p(b))

    def pow_p(self, base: ElementModP, e: ElementModQ) -> ElementModP:
        return ElementModP(pow(base.value, e.value, self.p), self)

    def g_pow_p(self, e: ElementModQ) -> ElementModP:
        return ElementModP(pow(self.g, e.value, self.p), self)

    def prod_pow_p(self, pairs: Iterable[tuple[ElementModP, ElementModQ]]) -> ElementModP:
        """∏ base_i^{e_i} mod p (multi-exponentiation, naive scalar form)."""
        s = 1
        for base, e in pairs:
            s = s * pow(base.value, e.value, self.p) % self.p
        return ElementModP(s, self)

    def is_valid_residue(self, a: ElementModP) -> bool:
        return a.is_valid_residue()


# ---------------------------------------------------------------------------
# group factories
# ---------------------------------------------------------------------------

@lru_cache(maxsize=None)
def production_group() -> GroupContext:
    """The 4096-bit production group — single construction point, mirroring
    the reference's ``KUtils.productionGroup()``
    (reference: src/main/java/electionguard/util/KUtils.java:10-13)."""
    from electionguard_tpu.core import constants as C

    return GroupContext(GroupSpec(
        p=C.P, q=C.Q, r=C.R, g=C.G,
        p_bytes=C.P_BYTES, q_bytes=C.Q_BYTES, name="production-4096",
    ))


@lru_cache(maxsize=None)
def tiny_group() -> GroupContext:
    """A tiny group (64-bit p, 32-bit q) with the same structure, for fast
    differential tests of every code path (the reference's test strategy has
    no crypto unit tests at all — SURVEY.md §4; we supply the missing
    pyramid)."""
    # p = q*r + 1, q prime 32-bit, p prime 64-bit, g = 2^r mod p order q.
    q = 4294967291  # 2^32 - 5, prime
    r = 4294967298  # even, p = q*r+1 prime (verified below at import)
    p = q * r + 1
    g = pow(2, r, p)
    assert pow(g, q, p) == 1 and g != 1
    return GroupContext(GroupSpec(p=p, q=q, r=r, g=g, p_bytes=9, q_bytes=5,
                                  name="test-64"))

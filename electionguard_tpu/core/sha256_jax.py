"""Batched SHA-256 on the device: Fiat–Shamir challenges without leaving TPU.

The reference computes every proof challenge on the JVM one element at a
time [ext]; our batch planes produce the commitment byte images ON DEVICE,
so hashing them host-side would round-trip megabytes per batch and burn
~0.4 ms of Python per selection (the measured host ceiling, ~1.7k
ballots/s).  SHA-256 is pure uint32 arithmetic — exact on TPU — so the
challenge computation runs as one jitted program over the whole batch:
message assembly, 64-round compression via ``lax.scan``, and the final
reduction into Z_q.

Exactly reproduces ``electionguard_tpu.core.hash.hash_elems`` for the
fixed-layout call sites (tag || len || payload concatenation); differential
tests pin byte-for-byte equality against hashlib.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from electionguard_tpu.core import bignum_jax as bn

U32 = jnp.uint32

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

_H0 = np.array([0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19],
               dtype=np.uint32)


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def _compress_block(state, wk):
    """One SHA-256 block: state (B, 8) u32, wk (B, 16) u32 message words."""
    w16 = [wk[:, t] for t in range(16)]

    def extend(carry, _):
        # carry: tuple of last 16 w values, rotating window
        w = list(carry)
        s0 = _rotr(w[1], 7) ^ _rotr(w[1], 18) ^ (w[1] >> 3)
        s1 = _rotr(w[14], 17) ^ _rotr(w[14], 19) ^ (w[14] >> 10)
        nxt = w[0] + s0 + w[9] + s1
        return tuple(w[1:] + [nxt]), nxt

    _, w_ext = lax.scan(extend, tuple(w16), None, length=48)
    # full schedule (64, B)
    w_all = jnp.concatenate([jnp.stack(w16), w_ext], axis=0)
    k_all = jnp.asarray(_K)

    def round_fn(carry, wt_kt):
        a, b, c, d, e, f, g, h = carry
        wt, kt = wt_kt
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    init = tuple(state[:, i] for i in range(8))
    out, _ = lax.scan(round_fn, init, (w_all, k_all))
    return state + jnp.stack(out, axis=1)


def sha256_rows(msgs: jax.Array) -> jax.Array:
    """SHA-256 of each row: (B, L) uint8 -> (B, 32) uint8.  L is static."""
    B, L = msgs.shape
    total = ((L + 9 + 63) // 64) * 64
    padding = np.zeros(total - L, dtype=np.uint8)
    padding[0] = 0x80
    padding[-8:] = np.frombuffer((8 * L).to_bytes(8, "big"), np.uint8)
    m = jnp.concatenate(
        [msgs, jnp.broadcast_to(jnp.asarray(padding), (B, total - L))],
        axis=1)
    w = ((m[:, 0::4].astype(U32) << 24) | (m[:, 1::4].astype(U32) << 16)
         | (m[:, 2::4].astype(U32) << 8) | m[:, 3::4].astype(U32))
    blocks = w.reshape(B, total // 64, 16).swapaxes(0, 1)  # (nb, B, 16)

    def per_block(state, wk):
        return _compress_block(state, wk), None

    state0 = jnp.broadcast_to(jnp.asarray(_H0), (B, 8))
    state, _ = lax.scan(per_block, state0, blocks)
    out = jnp.stack([(state >> 24) & 0xFF, (state >> 16) & 0xFF,
                     (state >> 8) & 0xFF, state & 0xFF],
                    axis=2).astype(jnp.uint8)        # (B, 8, 4) BE bytes
    return out.reshape(B, 32)


_sha256_rows_j = jax.jit(sha256_rows)


def sha256_rows_np(msgs: np.ndarray) -> np.ndarray:
    """Host convenience: (B, L) uint8 -> (B, 32) uint8 digests, jitted
    and dispatched through the shared bounded-shape tiling policy."""
    from electionguard_tpu.core.group_jax import run_tiled
    return np.asarray(run_tiled(_sha256_rows_j, [msgs], [False]))


def _digest_mod_q(digest: jax.Array, q_limbs: jax.Array) -> jax.Array:
    """(B, 32) uint8 big-endian digests -> (B, 16) limbs of digest mod q
    (single conditional subtract; valid because 2^256 < 2q)."""
    b = digest.astype(U32)
    limbs_be = (b[:, 0::2] << 8) | b[:, 1::2]        # (B, 16) BE 16-bit
    limbs = limbs_be[:, ::-1]                        # little-endian order
    return bn._sub_if_ge(limbs, q_limbs)


def digest_to_q_limbs(group, digest: jax.Array) -> jax.Array:
    """(B, 32) uint8 big-endian digests -> (B, 16) uint32 16-bit limbs of
    (digest mod q); production group only (see ``supports``)."""
    if not supports(group):
        raise ValueError("digest_to_q_limbs requires the production group")
    return _digest_mod_q(digest, jnp.asarray(bn.int_to_limbs(group.q, 16)))


_TAG_P_HDR = b"\x01" + (512).to_bytes(4, "big")


@jax.jit
def _hash_rows_mod_q(msgs: jax.Array, q_limbs: jax.Array) -> jax.Array:
    """(B, L) uint8 messages + (16,) q limbs -> (B, 16) challenge limbs."""
    return _digest_mod_q(sha256_rows(msgs), q_limbs)


def supports(group) -> bool:
    """Whether the device challenge path applies: the production group's
    256-bit q (single-subtract mod-q reduction) AND 4096-bit p (the fixed
    512-byte element frame in ``_TAG_P_HDR``)."""
    return (group.q.bit_length() == 256 and (1 << 256) < 2 * group.q
            and group.p.bit_length() == 4096)


def batch_challenge_p(group, prefix: bytes, elem_bytes: list) -> np.ndarray:
    """Fiat–Shamir challenge over fixed-layout messages, batched on device.

    ``prefix``: host bytes — the encoded leading items (e.g. enc(Q̄)), same
    for every row.  ``elem_bytes``: list of (B, 512) uint8 arrays, each the
    big-endian byte image of a batch of ElementModP; every element is
    framed exactly as ``hash._encode`` frames an ElementModP.  Returns
    (B, 16) uint32 limbs of the challenge mod q — byte-identical to
    ``hash_elems(group, *items)``.

    Requires the production group's 256-bit q (2^256 < 2q); callers fall
    back to host hashing for other groups.
    """
    if not supports(group):
        raise ValueError("batch_challenge_p requires the production group "
                         "(256-bit q, 4096-bit p)")
    from electionguard_tpu.core.group_jax import run_tiled

    arrs = [np.asarray(e, dtype=np.uint8) for e in elem_bytes]
    q_limbs = jnp.asarray(bn.int_to_limbs(group.q, 16))
    prefix_row = jnp.asarray(np.frombuffer(prefix, np.uint8))
    hdr_row = jnp.asarray(np.frombuffer(_TAG_P_HDR, np.uint8))

    def jfn(*padded):
        nb = padded[0].shape[0]
        parts = [jnp.broadcast_to(prefix_row, (nb, len(prefix)))]
        for a in padded:
            parts.append(jnp.broadcast_to(hdr_row, (nb, 5)))
            parts.append(a)
        return _hash_rows_mod_q(jnp.concatenate(parts, axis=1), q_limbs)

    return run_tiled(jfn, arrs, [False] * len(arrs))

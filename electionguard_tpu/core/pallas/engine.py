"""Fused Pallas kernels + launch plumbing for the MXU Montgomery engine.

``ntt_mxu`` runs a montmul as ~16 MXU matmuls stitched together by long
XLA elementwise chains (digit/carry resolution, Barrett, CRT, Toeplitz
offset glue) — and on the production group that glue, not the matmuls,
is the measured bottleneck: every (B, 1024) intermediate round-trips
through HBM as its own fused-elementwise op.  This module re-expresses
the same math as TWO Pallas kernels per montmul so all intermediates
live in VMEM for the whole stage:

* ``eval`` kernel — canonical limbs -> balanced digit planes -> forward
  NTT -> Barrett, per prime.  The input block is (bb, 256) uint32 limbs;
  the low/high bytes ARE the even/odd base-256 digits, so the kernel
  builds the two int8 e-form planes in registers and contracts them
  against the de-interleaved Vandermonde rows: four (bb, 256) @
  (256, 1024) MXU dots instead of ``ntt_mxu``'s four (bb, 1024) @
  (1024, 1024) dots.  The dropped rows are the constant padding half of
  the digit vector (e = -128 there); their contribution,
  ``-128 * colsum(V[512:])``, is folded into the eval offset vectors
  host-side (`PallasCtx`), so the kernel computes the *same exact
  integers* with half the MACs.
* ``combine`` kernel — per-prime pointwise 16-bit Montgomery products,
  inverse NTT + CRT (six MXU dots + Barretts), then the full Montgomery
  reduction (two Toeplitz dots + carry/cumsum offset glue + final
  conditional subtract) in ONE launch: canonical product limbs out,
  nothing between the pointwise multiply and the final result ever
  leaves VMEM.

Bound analysis is inherited UNCHANGED from the ``ntt_mxu`` module
header: every intermediate here is the identical integer the unfused
engine computes, so its int32/uint32 proofs (int8 partial dots < 2^24
exact in int32; Barrett domains < 2^26 / < 2^28; conv coefficients
< 2^25; Toeplitz rows >= 0 and < 2^25) apply verbatim.  The only
re-derived pieces are Mosaic-friendly rewrites with the same results:
``bignum_jax.normalize``'s carry-lookahead becomes an explicit
Kogge-Stone shift/mask ladder (no ``lax.associative_scan``), and the
offset prefix-sums become log-depth pad/add ladders (no ``jnp.cumsum``)
— `|csT| <= 512*128 = 2^16` and `|cs1| <= 2^16` keep them exact in
int32.

VMEM working set per block (bb = EGTPU_PALLAS_BLOCK rows): the eval
kernel holds the (bb, 256) limb block, two int8 digit planes, and one
(bb, 1024) int32 accumulator per dot (~bb * 20 KiB) against 1 MiB of
resident int8 Vandermonde planes; the combine kernel peaks at the
(bb, 1028) digit stream plus two (bb, 1024) int32 accumulators
(~bb * 24 KiB) against ~4.7 MiB of resident inverse-NTT/Toeplitz
constants — bb = 128 fits comfortably in 16 MiB VMEM cores.

Off-TPU every launch runs under ``pallas_call(..., interpret=True)``,
which executes the kernel body with stock jax ops — bit-identical to
``bignum_jax``/``ntt_mxu`` and exercised differentially by tier-1
(tests/test_pallas.py) on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.core import ntt_mxu
from electionguard_tpu.core.ntt_mxu import NC, ND, NL
from electionguard_tpu.utils import knobs

U32 = jnp.uint32
I32 = jnp.int32


# ---------------------------------------------------------------------------
# in-kernel math (VPU element ops; values stay in VMEM/registers)
# ---------------------------------------------------------------------------

def _dot_i8(a: jax.Array, w: jax.Array) -> jax.Array:
    """(B, K) int8 @ (K, N) int8 -> (B, N) int32, exact (MXU int8 path)."""
    return lax.dot_general(a, w, (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.int32)


def _barrett(x: jax.Array, m: int, mu: int, a: int, nsub: int) -> jax.Array:
    """x mod m for uint32 x; same exhaustively-validated constants as
    ``ntt_mxu._barrett`` (q = ((x>>a)*mu)>>13, nsub conditional subs)."""
    q = ((x >> a) * U32(mu)) >> 13
    r = x - q * U32(m)
    for _ in range(nsub):
        r = jnp.where(r >= m, r - U32(m), r)
    return r


def _mredc16(x: jax.Array, m: int, mprime: int) -> jax.Array:
    """(x · 2^-16) mod m for uint32 x < 2^16·m: exact, in [0, m)."""
    u = (x * U32(mprime)) & U32(0xFFFF)
    t = (x + u * U32(m)) >> 16
    return jnp.where(t >= m, t - U32(m), t)


def _shup(x: jax.Array, d: int = 1, fill=None) -> jax.Array:
    """Shift limbs ``d`` towards the MSB, dropping the top ``d`` (zero by
    construction in every call site — moduli leave headroom)."""
    pad = [(0, 0)] * (x.ndim - 1) + [(d, 0)]
    if fill is None:
        return jnp.pad(x[..., :-d], pad)
    return jnp.pad(x[..., :-d], pad, constant_values=fill)


def _normalize(t: jax.Array) -> jax.Array:
    """Carry-propagate a redundant limb vector to canonical 16-bit limbs;
    values < 2^32 in.  Same algorithm as ``bignum_jax.normalize`` (two
    ripple passes then carry-lookahead over generate/propagate flags),
    with the lookahead unrolled as an explicit Kogge-Stone doubling
    ladder — shift/mask/and ops Mosaic lowers natively, in place of
    ``lax.associative_scan``.  Step d combines each prefix with the
    prefix d limbs below it (identity (g=0, p=1) shifts in), which is
    exactly the associative scan of (gr | pr&gl, pl & pr)."""
    m16 = U32(0xFFFF)
    t = (t & m16) + _shup(t >> 16)        # limbs < 2^32 -> <= 2^17 - 2
    t = (t & m16) + _shup(t >> 16)        # -> <= 2^16
    g = (t >> 16).astype(U32)             # generate: limb == 2^16
    p = t == m16                          # propagate: limb == 0xFFFF
    d = 1
    while d < t.shape[-1]:
        g = g | (p.astype(U32) & _shup(g, d))
        p = p & _shup(p, d, fill=True)
        d <<= 1
    return (t + _shup(g)) & m16           # exclusive prefix = carry-in


def _prefix_sum(x: jax.Array) -> jax.Array:
    """Inclusive prefix sum over the last axis as a log-depth pad/add
    ladder (integer adds — bit-identical to ``jnp.cumsum``).  Callers
    keep |sums| <= 2^16, exact in int32."""
    d = 1
    while d < x.shape[-1]:
        x = x + _shup(x, d)
        d <<= 1
    return x


def _digits_to_limbs(d: jax.Array) -> jax.Array:
    """Nonneg redundant base-256 coeffs (..., L) u32 (< 2^25) -> canonical
    16-bit limbs (..., L/2); carries beyond limb L/2 are provably zero at
    every call site (see ``ntt_mxu._digits_to_limbs``)."""
    d = (d & U32(0xFF)) + _shup(d >> 8)          # < 255 + 2^17
    pairs = d.reshape(d.shape[:-1] + (d.shape[-1] // 2, 2))
    return _normalize(pairs[..., 0] + (pairs[..., 1] << 8))


def _limbs_to_e(x: jax.Array) -> jax.Array:
    """(..., L) uint32 16-bit limbs -> (..., 2L) int8 e-form (digit-128)."""
    d0 = (x & U32(0xFF)).astype(I32)
    d1 = ((x >> 8) & U32(0xFF)).astype(I32)
    e = jnp.stack([d0, d1], axis=-1).reshape(x.shape[:-1]
                                             + (2 * x.shape[-1],))
    return (e - 128).astype(jnp.int8)


def _sub_if_ge(t: jax.Array, pp: jax.Array) -> jax.Array:
    """t mod p for canonical t (..., n) < 2p; pp is p as (1, n) limbs.
    Two's-complement add of -p (``bignum_jax._sub_p``) with the +1 and
    the carry-capture limb built by concatenation instead of ``.at``."""
    n = pp.shape[-1]
    s = t + (U32(0xFFFF) - pp)
    s = jnp.concatenate([s[..., :1] + U32(1), s[..., 1:],
                         jnp.zeros_like(s[..., :1])], axis=-1)
    s = _normalize(s)
    return jnp.where(s[..., n:n + 1] > 0, s[..., :n], t)


def _mont_reduce_vals(y, toep_m, f_m, toep_p, f_p, pp):
    """Exact conv coefficients of T = a·b (bb, NC) int/uint32 -> canonical
    (bb, NL) limbs of T·R^{-1} mod p.  Line-for-line ``ntt_mxu.
    _mont_reduce`` on VMEM-resident values; offsets f_m/f_p/pp arrive as
    (1, ·) rows so every op is a 2D broadcast."""
    yp = jnp.pad(y, [(0, 0)] * (y.ndim - 1) + [(0, 4)])
    Tl = _digits_to_limbs(yp)                             # (bb, 514)
    eT = _limbs_to_e(Tl[..., :NL])                        # (bb, 512) low half
    csT = _prefix_sum(eT.astype(I32))                     # |.| <= 2^16
    m1c = _dot_i8(eT, toep_m) + f_m + (csT << 7)          # >= 0, < 2^25
    m1l = _digits_to_limbs(m1c.astype(U32))               # (bb, 256) mod R
    em1 = _limbs_to_e(m1l)                                # (bb, 512)
    cs1 = _prefix_sum(em1.astype(I32))
    last = jnp.broadcast_to(cs1[..., -1:], cs1.shape[:-1] + (ND,))
    wsum = (jnp.concatenate([cs1, last], axis=-1)
            - jnp.pad(cs1, [(0, 0)] * (cs1.ndim - 1) + [(ND, 0)]))
    m1pc = _dot_i8(em1, toep_p) + f_p + (wsum << 7)       # >= 0, < 2^25
    Td = jnp.stack([Tl & U32(0xFF), Tl >> 8], axis=-1)
    Td = Td.reshape(Tl.shape[:-1] + (Tl.shape[-1] * 2,))  # (bb, 1028)
    S = Td.astype(I32) + jnp.pad(m1pc, [(0, 0)] * (y.ndim - 1) + [(0, 4)])
    Sl = _digits_to_limbs(S.astype(U32))                  # (bb, 514)
    U = Sl[..., NL:NL + NL + 2]                           # (bb, 258) = S/R
    return _sub_if_ge(U, pp)[..., :NL]


# ---------------------------------------------------------------------------
# kernel factories (statics baked in as python ints; refs in VMEM)
# ---------------------------------------------------------------------------

def make_eval_kernel(m: tuple, mu26: tuple, mu27: tuple):
    """Fused limbs -> e-form planes -> forward NTT -> Barrett kernel.

    Block shapes: x (bb, NL) uint32 canonical limbs; vlo/vhi the
    de-interleaved (2, 2, ND/2, NC) int8 Vandermonde planes
    ([prime, input-digit-parity, row, col]); off0/off1 the (2, 1, NC)
    int32 folded eval offsets; outputs one (bb, NC) uint32 evaluation
    block per prime, in [0, m_t)."""

    def eval_kernel(x_ref, vlo_ref, vhi_ref, off0_ref, off1_ref,
                    o0_ref, o1_ref):
        x = x_ref[...]
        d0 = ((x & U32(0xFF)).astype(I32) - 128).astype(jnp.int8)
        d1 = ((x >> 8).astype(I32) - 128).astype(jnp.int8)
        for t, o_ref in enumerate((o0_ref, o1_ref)):
            a1 = (_dot_i8(d0, vhi_ref[t, 0]) + _dot_i8(d1, vhi_ref[t, 1])
                  + off1_ref[t])                          # >= 0, < 2^24
            r1 = _barrett(a1.astype(U32), m[t], mu26[t], 13, 2)
            a0 = (_dot_i8(d0, vlo_ref[t, 0]) + _dot_i8(d1, vlo_ref[t, 1])
                  + off0_ref[t]).astype(U32) + (r1 << 8)
            o_ref[...] = _barrett(a0, m[t], mu27[t], 14, 3)  # < 2^27 dom

    return eval_kernel


def make_combine_kernel(m: tuple, mprime: tuple, mu26: tuple, mu27: tuple,
                        biasc: tuple, inv12s: int):
    """Fused pointwise-product -> inverse NTT -> CRT -> Montgomery
    reduction kernel: per-prime evaluation blocks of both operands in,
    canonical product limbs out, one launch."""

    def combine_kernel(a0_ref, a1_ref, b0_ref, b1_ref, iv0_ref, iv1_ref,
                       ivo0_ref, ivo1_ref, tm_ref, fm_ref, tp_ref,
                       fp_ref, pp_ref, o_ref):
        cs = []
        for t, (a_ref, b_ref) in enumerate(((a0_ref, b0_ref),
                                            (a1_ref, b1_ref))):
            th = _mredc16(a_ref[...] * b_ref[...], m[t], mprime[t])
            t0e = ((th & U32(0xFF)).astype(I32) - 128).astype(jnp.int8)
            t1 = (th >> 8).astype(jnp.int8)               # <= 51
            c = _dot_i8(t1, iv1_ref[t]) + biasc[t]
            cm = _barrett(c.astype(U32), m[t], mu26[t], 13, 2)
            b_ = (_dot_i8(t0e, iv1_ref[t]) + _dot_i8(t1, iv0_ref[t])
                  + ivo1_ref[t]).astype(U32) + (cm << 8)
            bm = _barrett(b_, m[t], mu26[t], 13, 2)
            a_ = (_dot_i8(t0e, iv0_ref[t])
                  + ivo0_ref[t]).astype(U32) + (bm << 8)
            cs.append(_barrett(a_, m[t], mu27[t], 14, 3))
        c1, c2 = cs
        # CRT: y = c1 + m1·((c2 - c1)·m1^{-1} mod m2) via mredc16 with
        # the 2^16 factor folded into inv12s; exact conv coeffs < 2^25.
        d = c2 + U32(2 * m[1]) - c1
        u = _mredc16(d * U32(inv12s), m[1], mprime[1])
        y = c1 + U32(m[0]) * u
        o_ref[...] = _mont_reduce_vals(y, tm_ref[...], fm_ref[...],
                                       tp_ref[...], fp_ref[...],
                                       pp_ref[...])

    return combine_kernel


# ---------------------------------------------------------------------------
# context + launch plumbing
# ---------------------------------------------------------------------------

def _pow2ceil(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class PallasCtx:
    """Device constants + kernel closures for one modulus p.

    Wraps the host-built ``NttCtx`` (same Barrett/bias constants, same
    bound analysis) and derives the eval kernel's de-interleaved
    operands: ``vlo[t, par]`` holds rows ``par::2`` of the first ND rows
    of ``V0[t]`` (matching the low/high byte planes of the input limbs)
    and the eval offsets absorb the constant -128 padding rows' column
    sums, so the kernel's two-plane contraction equals the unfused
    ``e_full @ V + evoff`` integer-for-integer."""

    def __init__(self, p: int):
        nctx = ntt_mxu.make_ntt_ctx(p)
        self.nctx = nctx
        self.block = max(8, knobs.get_int("EGTPU_PALLAS_BLOCK"))
        # off-TPU the kernels always run in interpret mode (stock jax
        # ops, bit-identical); backend *selection* policy lives in
        # group_jax, not here.
        self.interpret = jax.default_backend() != "tpu"

        V0 = np.asarray(nctx.V0)
        V1 = np.asarray(nctx.V1)
        self.vlo = jnp.asarray(np.stack(
            [V0[:, 0:ND:2, :], V0[:, 1:ND:2, :]], axis=1))
        self.vhi = jnp.asarray(np.stack(
            [V1[:, 0:ND:2, :], V1[:, 1:ND:2, :]], axis=1))

        def fold(off, plane):
            # e = -128 on the padded rows [ND:]; fold their contribution
            # out of the offset so the kernel can skip those rows.
            tail = 128 * plane[:, ND:, :].astype(np.int64).sum(axis=1)
            out = np.asarray(off).astype(np.int64) - tail[:, None, :]
            assert out.min() > -(1 << 31) and out.max() < (1 << 31)
            return jnp.asarray(out.astype(np.int32))

        self.evoff0 = fold(nctx.evoff0, V0)
        self.evoff1 = fold(nctx.evoff1, V1)
        # combine-kernel constants; vectors as (1, ·) rows for 2D layout
        self.iv0, self.iv1 = nctx.iV0, nctx.iV1
        self.ivoff0, self.ivoff1 = nctx.ivoff0, nctx.ivoff1
        self.toep_m = nctx.toep_m
        self.f_m = nctx.f_m.reshape(1, ND)
        self.toep_p = nctx.toep_p
        self.f_p = nctx.f_p.reshape(1, NC)
        self.p_pad = nctx.p_pad.reshape(1, NL + 2)
        self._eval_kernel = make_eval_kernel(nctx.m, nctx.mu26, nctx.mu27)
        self._combine_kernel = make_combine_kernel(
            nctx.m, nctx.mprime, nctx.mu26, nctx.mu27, nctx.biasc,
            nctx.inv12s)
        # per-launch-site jitted dispatchers (see _launch); mutate
        # ``block`` only before the first op on a ctx — traced programs
        # bake the grid plan per input shape
        self._jits: dict = {}

    @property
    def mctx(self):
        return self.nctx.mctx


@functools.lru_cache(maxsize=None)
def make_pallas_ctx(p: int) -> PallasCtx:
    return PallasCtx(p)


def _row0(i):
    return (i, 0)


def _pin(nd, i):
    return (0,) * nd


def _const_specs(arrays):
    """Whole-array BlockSpecs pinned to block (0, ..): the NTT/Toeplitz
    constants are grid-invariant and stay resident in VMEM."""
    return [pl.BlockSpec(a.shape, functools.partial(_pin, a.ndim))
            for a in arrays]


def _block_plan(ctx: PallasCtx, b: int) -> tuple[int, int]:
    """Rows per grid step and padded row count: small batches run as one
    pow2-padded block, large ones as a 1-D grid of EGTPU_PALLAS_BLOCK
    row tiles (zero rows are valid inputs at every stage)."""
    bb = min(ctx.block, max(8, _pow2ceil(b)))
    return bb, -(-b // bb) * bb


def _launch(ctx: PallasCtx, name: str, fn):
    """One jitted dispatcher per (ctx, launch site).  Callers already
    under jit (group_jax's op programs) inline it as a nested jit;
    outside-jit callers — PowRadix hat-table builds, interpret-mode
    tests — compile the launch once per input shape instead of
    re-tracing the whole pallas_call (in interpret mode, the whole
    kernel emulation) on every call."""
    try:
        return ctx._jits[name]
    except KeyError:
        return ctx._jits.setdefault(name, jax.jit(fn))


def _eval2(ctx: PallasCtx, x: jax.Array):
    return _launch(ctx, "eval2", functools.partial(_eval2_impl, ctx))(x)


def _combine(ctx: PallasCtx, a0, a1, b0, b1) -> jax.Array:
    return _launch(ctx, "combine",
                   functools.partial(_combine_impl, ctx))(a0, a1, b0, b1)


def _eval2_impl(ctx: PallasCtx, x: jax.Array):
    """(B, NL) canonical limbs -> per-prime forward evaluations, two
    (B, NC) uint32 arrays in [0, m_t)."""
    b = x.shape[0]
    bb, bp = _block_plan(ctx, b)
    if bp != b:
        x = jnp.pad(x, [(0, bp - b), (0, 0)])
    consts = (ctx.vlo, ctx.vhi, ctx.evoff0, ctx.evoff1)
    h0, h1 = pl.pallas_call(
        ctx._eval_kernel,
        grid=(bp // bb,),
        in_specs=[pl.BlockSpec((bb, NL), _row0)] + _const_specs(consts),
        out_specs=(pl.BlockSpec((bb, NC), _row0),
                   pl.BlockSpec((bb, NC), _row0)),
        out_shape=(jax.ShapeDtypeStruct((bp, NC), jnp.uint32),
                   jax.ShapeDtypeStruct((bp, NC), jnp.uint32)),
        interpret=ctx.interpret,
    )(x, *consts)
    return h0[:b], h1[:b]


def _combine_impl(ctx: PallasCtx, a0, a1, b0, b1) -> jax.Array:
    """Per-prime evaluations of both operands (each (B, NC)) ->
    canonical (B, NL) limbs of a·b·R^{-1} mod p."""
    b = a0.shape[0]
    bb, bp = _block_plan(ctx, b)
    if bp != b:
        pads = [(0, bp - b), (0, 0)]
        a0, a1, b0, b1 = (jnp.pad(v, pads) for v in (a0, a1, b0, b1))
    consts = (ctx.iv0, ctx.iv1, ctx.ivoff0, ctx.ivoff1, ctx.toep_m,
              ctx.f_m, ctx.toep_p, ctx.f_p, ctx.p_pad)
    out = pl.pallas_call(
        ctx._combine_kernel,
        grid=(bp // bb,),
        in_specs=([pl.BlockSpec((bb, NC), _row0)] * 4
                  + _const_specs(consts)),
        out_specs=pl.BlockSpec((bb, NL), _row0),
        out_shape=jax.ShapeDtypeStruct((bp, NL), jnp.uint32),
        interpret=ctx.interpret,
    )(a0, a1, b0, b1, *consts)
    return out[:b]


# ---------------------------------------------------------------------------
# public ops (drop-in for ntt_mxu / bignum_jax signatures)
# ---------------------------------------------------------------------------

def montmul(ctx: PallasCtx, a: jax.Array, b: jax.Array) -> jax.Array:
    """Batched Montgomery product a·b·R^{-1} mod p: one eval launch over
    the concatenated operands, one combine launch."""
    shape = a.shape
    a2 = a.reshape(-1, NL)
    b2 = jnp.broadcast_to(b, shape).reshape(-1, NL)
    k = a2.shape[0]
    h0, h1 = _eval2(ctx, jnp.concatenate([a2, b2], axis=0))
    return _combine(ctx, h0[:k], h1[:k], h0[k:], h1[k:]).reshape(shape)


def montsqr(ctx: PallasCtx, a: jax.Array) -> jax.Array:
    """Batched Montgomery square (one eval launch instead of two)."""
    shape = a.shape
    h0, h1 = _eval2(ctx, a.reshape(-1, NL))
    return _combine(ctx, h0, h1, h0, h1).reshape(shape)


def montmul_shared(ctx: PallasCtx, sel: jax.Array,
                   base: jax.Array) -> jax.Array:
    """(B, k, NL) × (B, NL) products sel[:, j]·base: the shared operand
    is evaluated ONCE (in the same launch as the buckets) and its
    evaluations broadcast across k — same saving as
    ``ntt_mxu.montmul_shared`` for the Yao bucket multiply."""
    B, k, n = sel.shape
    h0, h1 = _eval2(ctx, jnp.concatenate([sel.reshape(B * k, n), base],
                                         axis=0))
    s0, s1 = h0[:B * k], h1[:B * k]
    bx0 = jnp.broadcast_to(h0[B * k:][:, None, :],
                           (B, k, NC)).reshape(B * k, NC)
    bx1 = jnp.broadcast_to(h1[B * k:][:, None, :],
                           (B, k, NC)).reshape(B * k, NC)
    return _combine(ctx, s0, s1, bx0, bx1).reshape(B, k, n)


def nttfwd(ctx: PallasCtx, a: jax.Array) -> jax.Array:
    """(B, NL) limbs -> (B, 2, NC) forward evaluations (PowRadix tables
    store this layout; see ``ntt_mxu.nttfwd``)."""
    h0, h1 = _eval2(ctx, a)
    return jnp.stack([h0, h1], axis=1)


def montmul_hat(ctx: PallasCtx, a: jax.Array, bh: jax.Array) -> jax.Array:
    """Montgomery product of canonical a (B, NL) with a pre-evaluated
    operand bh (B, 2, NC) — the fixed-base ladder's table-row multiply,
    skipping the table operand's forward NTT."""
    a0, a1 = _eval2(ctx, a)
    return _combine(ctx, a0, a1, bh[..., 0, :], bh[..., 1, :])


def mont_pow(ctx: PallasCtx, base_mont: jax.Array, exp: jax.Array,
             exp_bits: int) -> jax.Array:
    return bn.mont_pow(ctx.mctx, base_mont, exp, exp_bits,
                       montmul_fn=functools.partial(montmul, ctx),
                       montsqr_fn=functools.partial(montsqr, ctx))


def powmod(ctx: PallasCtx, base: jax.Array, exp: jax.Array,
           exp_bits: int) -> jax.Array:
    return bn.powmod(ctx.mctx, base, exp, exp_bits,
                     montmul_fn=functools.partial(montmul, ctx),
                     montsqr_fn=functools.partial(montsqr, ctx))


def mulmod(ctx: PallasCtx, a: jax.Array, b: jax.Array) -> jax.Array:
    """Plain modular product a·b mod p."""
    return montmul(ctx, montmul(ctx, a, b),
                   jnp.broadcast_to(ctx.mctx.r2_mod_p, a.shape))


def mont_prod_tree(ctx: PallasCtx, x: jax.Array) -> jax.Array:
    return bn.mont_prod_tree(ctx.mctx, x,
                             montmul_fn=functools.partial(montmul, ctx))

"""Fused Pallas/Mosaic kernel set for the NTT Montgomery engine.

The third ``EGTPU_BIGNUM`` backend ("pallas"): the same 4096-bit MXU
NTT montmul math as ``core.ntt_mxu``, with the inter-matmul glue (digit
carries, Barrett, CRT, Toeplitz offsets) fused into two hand-written
kernels so coefficients stay in VMEM between stages instead of
round-tripping through HBM as separate XLA ops.  Off-TPU the kernels
run under ``pallas_call(..., interpret=True)`` and are bit-identical to
``bignum_jax`` / ``ntt_mxu`` — tier-1 exercises them differentially on
the CPU backend (tests/test_pallas.py).
"""

from electionguard_tpu.core.pallas.engine import (  # noqa: F401
    PallasCtx, make_pallas_ctx, mont_pow, mont_prod_tree, montmul,
    montmul_hat, montmul_shared, montsqr, mulmod, nttfwd, powmod)

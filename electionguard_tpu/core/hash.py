"""Fiat–Shamir hashing to Z_q (spec-1.03-shaped).

The reference carries 32-byte ``UInt256`` hash values on the wire
(reference: src/main/proto/common.proto:44-48) and delegates the hash
construction to the Kotlin library [ext].  We define a canonical, injective
encoding — every item is serialized as ``tag(1B) || len(4B BE) || payload``
and the concatenation is SHA-256'd — rather than the spec-1.0 "|"-joined
hex-string form, which is not injective across types.  Challenges are the
digest reduced mod q.  Hashing runs host-side (CPU); only group math runs on
TPU — the digest/limb seam is the contract (SURVEY.md §7 hard part 2).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
from typing import Iterable, Union

from electionguard_tpu.core.group import ElementModP, ElementModQ, GroupContext

Hashable = Union[
    "ElementModP", "ElementModQ", int, str, bytes, None, Iterable
]

_TAG_NONE = b"\x00"
_TAG_P = b"\x01"
_TAG_Q = b"\x02"
_TAG_INT = b"\x03"
_TAG_STR = b"\x04"
_TAG_BYTES = b"\x05"
_TAG_SEQ = b"\x06"


def _encode(item: Hashable) -> bytes:
    if item is None:
        return _TAG_NONE + (0).to_bytes(4, "big")
    if isinstance(item, ElementModP):
        b = item.to_bytes()
        return _TAG_P + len(b).to_bytes(4, "big") + b
    if isinstance(item, ElementModQ):
        b = item.to_bytes()
        return _TAG_Q + len(b).to_bytes(4, "big") + b
    if isinstance(item, bool):
        raise TypeError("refusing to hash bool")
    if isinstance(item, int):
        if item < 0:
            raise ValueError("refusing to hash negative int")
        b = item.to_bytes(max(1, (item.bit_length() + 7) // 8), "big")
        return _TAG_INT + len(b).to_bytes(4, "big") + b
    if isinstance(item, str):
        b = item.encode("utf-8")
        return _TAG_STR + len(b).to_bytes(4, "big") + b
    if isinstance(item, (bytes, bytearray)):
        b = bytes(item)
        return _TAG_BYTES + len(b).to_bytes(4, "big") + b
    if hasattr(item, "__iter__"):
        inner = b"".join(_encode(x) for x in item)
        d = hashlib.sha256(inner).digest()
        return _TAG_SEQ + len(d).to_bytes(4, "big") + d
    raise TypeError(f"unhashable item type {type(item)}")


def hash_digest(*items: Hashable) -> bytes:
    """SHA-256 digest (32 bytes) of the canonical encoding of ``items``."""
    h = hashlib.sha256()
    for item in items:
        h.update(_encode(item))
    return h.digest()


def hash_elems(group: GroupContext, *items: Hashable) -> ElementModQ:
    """Fiat–Shamir challenge: digest reduced into Z_q."""
    return group.int_to_q(int.from_bytes(hash_digest(*items), "big"))


def hmac_digest(key: bytes, *items: Hashable) -> bytes:
    """HMAC-SHA256 over the canonical encoding (MAC for hashed ElGamal,
    spec 1.03 eq 17 — reference: src/main/proto/keyceremony_trustee_rpc.proto:38-41)."""
    h = hmac_mod.new(key, digestmod=hashlib.sha256)
    for item in items:
        h.update(_encode(item))
    return h.digest()


def kdf(key: bytes, label: str, context: bytes, nbytes: int) -> bytes:
    """NIST SP 800-108 counter-mode KDF with HMAC-SHA256 PRF (the KDF shape
    spec 1.03 uses for HashedElGamalCiphertext key streams)."""
    out = b""
    counter = 1
    while len(out) < nbytes:
        out += hmac_mod.new(
            key,
            counter.to_bytes(4, "big") + label.encode() + b"\x00" + context
            + (nbytes * 8).to_bytes(4, "big"),
            hashlib.sha256,
        ).digest()
        counter += 1
    return out[:nbytes]

"""Discrete log of g^t for small t (tally decode).

The coordinator-side decryption combine ends with ``M = B / ∏ Mᵢ^wᵢ`` being
``g^t`` for a small tally count ``t`` (SURVEY.md §3.2 "discrete log of g^t
(small-exponent)" [ext]).  Baby-step/giant-step so 1M-ballot tallies decode in
~2·√t group ops instead of t.
"""

from __future__ import annotations

from typing import Optional

from electionguard_tpu.core.group import ElementModP, GroupContext


class DLog:
    def __init__(self, group: GroupContext, base: Optional[ElementModP] = None,
                 max_exponent: int = 100_000_000):
        self.group = group
        self.base = base if base is not None else group.G_MOD_P
        self.max_exponent = max_exponent
        self._m = 1 << ((max_exponent.bit_length() + 1) // 2)  # ~sqrt
        self._baby: dict[int, int] = {}
        self._giant_step: Optional[int] = None

    def _ensure_tables(self):
        if self._baby:
            return
        g, p = self.base.value, self.group.p
        acc = 1
        for j in range(self._m):
            self._baby[acc] = j
            acc = acc * g % p
        # giant step multiplier: base^(-m) mod p
        self._giant_step = pow(pow(g, self._m, p), -1, p)

    def dlog(self, e: ElementModP) -> Optional[int]:
        """Return t with base^t == e, or None if t > max_exponent."""
        self._ensure_tables()
        p = self.group.p
        gamma = e.value
        for i in range(self._m + 1):
            j = self._baby.get(gamma)
            if j is not None:
                t = i * self._m + j
                return t if t <= self.max_exponent else None
            gamma = gamma * self._giant_step % p
        return None


_default_dlogs: dict[int, DLog] = {}


def default_dlog(group: GroupContext) -> DLog:
    """Process-wide cached g-base DLog per group (table built once)."""
    key = id(group.spec)
    if key not in _default_dlogs:
        _default_dlogs[key] = DLog(group)
    return _default_dlogs[key]

"""Persistent on-disk cache for host-precomputed setup tables.

The production group pays ~3 minutes of host setup per process
(BENCH_r05: setup_s 187.9) rebuilding arrays that are pure functions of
the group: the NTT engine's Vandermonde/Toeplitz constants
(``ntt_mxu._build_ntt_arrays``) and the PowRadix fixed-base tables
(~8k modmuls of 4096-bit Python ints per base, plus their NTT-evaluated
twins).  This module persists those arrays under a directory named by
the ``EGTPU_TABLE_CACHE`` knob so every process after the first starts
warm.

Contract:

* **keyed by fingerprint** — sha256 over a canonical JSON blob naming
  the table kind, a format ``VERSION``, and every input the build
  depends on (group modulus digest, base digest, window/limb geometry).
  Any mismatch — including a stale format version — is a miss, never a
  wrong answer.
* **torn-write safe** — entries are written to a ``mkstemp`` temp file
  in the same directory and ``os.replace``'d into place (atomic on
  POSIX); the full fingerprint is embedded IN the payload and re-checked
  on load, so a partial or corrupt file (unreadable npz, truncated
  array set, foreign fingerprint) degrades to a rebuild.
* **always optional** — unset/empty knob disables everything; any I/O
  error on load or store logs a warning and falls back to recompute.

``stats()`` exposes hit/miss/write counters so bench.py can report
whether a run was warm or cold.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import tempfile
from typing import Optional

import numpy as np

from electionguard_tpu.utils import knobs

log = logging.getLogger(__name__)

VERSION = 2


def group_digest(group) -> str:
    """Stable digest of a GroupContext's defining constants (p, q, g).

    Setup-table fingerprints key on THIS — never on an election id,
    manifest hash, or any key-ceremony output — so N concurrent tenants
    running elections over the same group share every powradix/nttctx
    cache entry byte-for-byte.  That sharing is the multi-tenant cache
    contract: the N-tenant drill asserts cross-tenant ``hits`` > 0."""
    blob = b"".join(
        x.to_bytes(max(1, (x.bit_length() + 7) // 8), "little")
        for x in (group.p, group.q, group.g))
    return hashlib.sha256(blob).hexdigest()

_stats = {"hits": 0, "misses": 0, "writes": 0, "errors": 0}


def stats() -> dict:
    """Copy of the process-lifetime cache counters."""
    return dict(_stats)


def reset_stats() -> None:
    for k in _stats:
        _stats[k] = 0


def cache_dir() -> Optional[str]:
    """The configured cache directory, or None when caching is off."""
    return knobs.get_str("EGTPU_TABLE_CACHE") or None


def int_digest(x: int) -> str:
    """Stable digest of an arbitrarily large nonnegative int (group
    moduli, table bases) — keeps fingerprints short and canonical."""
    nbytes = max(1, (x.bit_length() + 7) // 8)
    return hashlib.sha256(x.to_bytes(nbytes, "little")).hexdigest()


def fingerprint(kind: str, **fields) -> str:
    """sha256 over the canonical JSON of (VERSION, kind, fields)."""
    blob = json.dumps({"version": VERSION, "kind": kind, **fields},
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def _entry_path(d: str, kind: str, fp: str) -> str:
    return os.path.join(d, f"{kind}-{fp[:32]}.npz")


def load(kind: str, fp: str) -> Optional[dict]:
    """The cached array dict for (kind, fingerprint), or None on any
    miss — absent, torn, corrupt, or fingerprint-mismatched entries all
    land here and the caller rebuilds."""
    d = cache_dir()
    if d is None:
        return None
    path = _entry_path(d, kind, fp)
    try:
        with np.load(path) as z:
            if z["__fingerprint__"].tobytes().decode() != fp:
                _stats["misses"] += 1
                return None
            arrays = {k: np.asarray(z[k]) for k in z.files
                      if k != "__fingerprint__"}
    except FileNotFoundError:
        _stats["misses"] += 1
        return None
    except Exception as e:  # torn write, bad zip, missing key, ...
        _stats["errors"] += 1
        _stats["misses"] += 1
        log.warning("table cache: unreadable entry %s (%s); rebuilding",
                    path, e)
        return None
    _stats["hits"] += 1
    return arrays


def store(kind: str, fp: str, arrays: dict) -> None:
    """Atomically persist ``arrays`` (str -> numpy) under (kind, fp).
    Best-effort: failures warn and leave the cache unchanged."""
    d = cache_dir()
    if d is None:
        return
    path = _entry_path(d, kind, fp)
    tmp = None
    try:
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=f".{kind}-",
                                   suffix=".tmp")
        # uncompressed: hat tables are 64 MiB and load time matters more
        # than disk; savez needs a real file object for the zip footer
        buf = io.BytesIO()
        np.savez(buf,
                 __fingerprint__=np.frombuffer(fp.encode(),
                                               dtype=np.uint8),
                 **{k: np.asarray(v) for k, v in arrays.items()})
        with os.fdopen(fd, "wb") as f:
            f.write(buf.getvalue())
        os.replace(tmp, path)
        tmp = None
        _stats["writes"] += 1
    except Exception as e:
        _stats["errors"] += 1
        log.warning("table cache: failed to store %s (%s)", path, e)
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass

"""Plaintext ballots + the random ballot provider.

Native replacement for the reference's [ext] ``PlaintextBallot`` and
``RandomBallotProvider`` (call site: RunRemoteWorkflowTest.java:133-137 —
``new RandomBallotProvider(manifest, nballots).ballots()``).
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass
from typing import Iterator

from electionguard_tpu.ballot.manifest import Manifest


@dataclass(frozen=True)
class PlaintextBallotSelection:
    selection_id: str
    vote: int


@dataclass(frozen=True)
class PlaintextBallotContest:
    contest_id: str
    selections: tuple[PlaintextBallotSelection, ...]


@dataclass(frozen=True)
class PlaintextBallot:
    ballot_id: str
    ballot_style_id: str
    contests: tuple[PlaintextBallotContest, ...]

    def to_json(self) -> str:
        return json.dumps({
            "ballot_id": self.ballot_id,
            "ballot_style_id": self.ballot_style_id,
            "contests": [
                {"contest_id": c.contest_id,
                 "selections": [
                     {"selection_id": s.selection_id, "vote": s.vote}
                     for s in c.selections]}
                for c in self.contests],
        }, indent=2, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "PlaintextBallot":
        d = json.loads(s)
        return PlaintextBallot(
            ballot_id=d["ballot_id"],
            ballot_style_id=d["ballot_style_id"],
            contests=tuple(
                PlaintextBallotContest(
                    contest_id=c["contest_id"],
                    selections=tuple(
                        PlaintextBallotSelection(s["selection_id"], s["vote"])
                        for s in c["selections"]))
                for c in d["contests"]),
        )


class RandomBallotProvider:
    """Deterministic (seeded) fake-ballot generator for tests/benchmarks."""

    def __init__(self, manifest: Manifest, nballots: int, seed: int = 0):
        self.manifest = manifest
        self.nballots = nballots
        self.rng = random.Random(seed)

    def ballots(self) -> Iterator[PlaintextBallot]:
        styles = self.manifest.ballot_styles
        for i in range(self.nballots):
            style = styles[self.rng.randrange(len(styles))]
            contests = []
            for c in self.manifest.contests_for_style(style.object_id):
                k = self.rng.randint(0, c.votes_allowed)
                chosen = set(self.rng.sample(range(len(c.selections)), k))
                contests.append(PlaintextBallotContest(
                    contest_id=c.object_id,
                    selections=tuple(
                        PlaintextBallotSelection(
                            s.object_id, 1 if j in chosen else 0)
                        for j, s in enumerate(c.selections))))
            yield PlaintextBallot(
                ballot_id=f"ballot-{i:07d}",
                ballot_style_id=style.object_id,
                contests=tuple(contests))

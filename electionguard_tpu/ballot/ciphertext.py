"""Encrypted ballots.

Native replacement for the reference's [ext] ``EncryptedBallot`` data model
(imported at RunRemoteDecryptor.java:9-21).  Selections carry the ElGamal
ciphertext plus its disjunctive (0-or-1) range proof; contests carry the
constant proof for the vote limit; the ballot carries a chained confirmation
code.  Serialization lives in ``electionguard_tpu.publish``.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum

from electionguard_tpu.core.hash import hash_digest
from electionguard_tpu.crypto.chaum_pedersen import (
    ConstantChaumPedersenProof, DisjunctiveChaumPedersenProof)
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext


class BallotState(Enum):
    CAST = "CAST"
    SPOILED = "SPOILED"
    UNKNOWN = "UNKNOWN"


@dataclass(frozen=True)
class EncryptedSelection:
    selection_id: str
    sequence_order: int
    ciphertext: ElGamalCiphertext
    proof: DisjunctiveChaumPedersenProof
    # placeholder selections pad every contest so the selection sum always
    # equals the vote limit; excluded from reported tallies
    is_placeholder: bool = False

    def crypto_hash(self) -> bytes:
        # is_placeholder is hashed: the flag decides tally membership, so it
        # must be bound to the ballot's confirmation code
        return hash_digest("enc-selection", self.selection_id,
                           self.sequence_order, int(self.is_placeholder),
                           self.ciphertext.pad, self.ciphertext.data)


@dataclass(frozen=True)
class EncryptedContest:
    contest_id: str
    sequence_order: int
    selections: tuple[EncryptedSelection, ...]
    proof: ConstantChaumPedersenProof

    def crypto_hash(self) -> bytes:
        return hash_digest("enc-contest", self.contest_id,
                           self.sequence_order,
                           [s.crypto_hash() for s in self.selections])

    def accumulation(self) -> ElGamalCiphertext:
        """Homomorphic sum of the contest's selections (limit-proof target)."""
        acc = self.selections[0].ciphertext
        for s in self.selections[1:]:
            acc = acc.mult(s.ciphertext)
        return acc


@dataclass(frozen=True)
class EncryptedBallot:
    ballot_id: str
    ballot_style_id: str
    manifest_hash: bytes
    code_seed: bytes        # previous ballot's code (chaining)
    code: bytes             # this ballot's confirmation code
    timestamp: int
    contests: tuple[EncryptedContest, ...]
    state: BallotState = BallotState.UNKNOWN

    def crypto_hash(self) -> bytes:
        return hash_digest("enc-ballot", self.ballot_id,
                           self.manifest_hash,
                           [c.crypto_hash() for c in self.contests])

    @staticmethod
    def make_code(code_seed: bytes, timestamp: int,
                  crypto_hash: bytes) -> bytes:
        """Chained confirmation code H(seed, timestamp, ballot-hash)."""
        return hash_digest("ballot-code", code_seed, timestamp, crypto_hash)

    def is_valid_code(self) -> bool:
        return self.code == self.make_code(self.code_seed, self.timestamp,
                                           self.crypto_hash())

"""Encrypted and plaintext tallies.

Native replacement for the reference's [ext] ``EncryptedTally`` /
``PlaintextTally`` (imported at RunRemoteDecryptor.java:9-21; the encrypted
tally is what the decryption coordinator loads and the trustees partially
decrypt — SURVEY.md §3.2).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from electionguard_tpu.core.group import ElementModP
from electionguard_tpu.crypto.chaum_pedersen import GenericChaumPedersenProof
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext


@dataclass(frozen=True)
class EncryptedTallySelection:
    selection_id: str
    sequence_order: int
    ciphertext: ElGamalCiphertext


@dataclass(frozen=True)
class EncryptedTallyContest:
    contest_id: str
    sequence_order: int
    selections: tuple[EncryptedTallySelection, ...]


@dataclass(frozen=True)
class EncryptedTally:
    tally_id: str
    contests: tuple[EncryptedTallyContest, ...]
    cast_ballot_count: int = 0


@dataclass(frozen=True)
class PartialDecryption:
    """One guardian's (possibly compensated) share for one selection."""

    guardian_id: str
    share: ElementModP                      # Mᵢ or combined Mᵢ from shares
    proof: Optional[GenericChaumPedersenProof] = None
    recovered_parts: Optional[dict] = None  # ℓ -> CompensatedShare when missing


@dataclass(frozen=True)
class PlaintextTallySelection:
    selection_id: str
    tally: int                              # decoded vote count t
    value: ElementModP                      # g^t
    message: ElGamalCiphertext              # the encrypted accumulation
    shares: tuple[PartialDecryption, ...]


@dataclass(frozen=True)
class PlaintextTallyContest:
    contest_id: str
    selections: tuple[PlaintextTallySelection, ...]


@dataclass(frozen=True)
class PlaintextTally:
    tally_id: str
    contests: tuple[PlaintextTallyContest, ...]

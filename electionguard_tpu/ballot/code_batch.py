"""Batched confirmation-code recomputation.

``EncryptedBallot.is_valid_code()`` recomputes the nested
selection→contest→ballot hash tree one ``hash_digest`` call at a time —
~130 µs of Python framing per ballot, which caps the whole verifier (and
encryptor) at a few thousand ballots/s of HOST time no matter how fast
the chip is (the reference's analogue is per-ballot JVM hashing inside
``Verifier``, RunRemoteWorkflowTest.java:180).  This module rebuilds the
exact same byte rows in bulk — constant framing prefixes cached per
(id, sequence) key, element bytes appended once — and hashes each
width-group of rows in a single device SHA-256 dispatch (small groups
fall back to hashlib; the construction is pure SHA-256, so it works for
every group, not just production).

Byte-exactness with ``core.hash.hash_digest`` is pinned by tests that
compare against the per-ballot path on heterogeneous ballots.
"""

from __future__ import annotations

import functools
import hashlib
import os
from typing import Sequence

import numpy as np

from electionguard_tpu.core.hash import (_TAG_BYTES, _TAG_P, _TAG_SEQ,
                                         _encode)

#: rows per width-group before offloading to the device SHA plane
#: (EGTPU_SHA_DEVICE_MIN).  hashlib runs ~2 µs/row — the speedup of this
#: module comes from the cached framing prefixes, so the device only
#: wins for very large groups, and staying on hashlib below the
#: threshold keeps ordinary chunks off the (compile-heavy, sometimes
#: flaky) device path entirely.
_DEVICE_MIN_ROWS = int(os.environ.get("EGTPU_SHA_DEVICE_MIN", "65536"))

_DIGEST_FRAME_HDR = _TAG_BYTES + (32).to_bytes(4, "big")  # _encode(bytes32)
_SEQ_HDR = _TAG_SEQ + (32).to_bytes(4, "big")             # _encode([...])


def _sha_rows(rows: Sequence[bytes]) -> np.ndarray:
    """(N, 32) uint8 SHA-256 digests of variable-width byte rows; rows
    are grouped by width, each group hashed in one device dispatch."""
    out = np.empty((len(rows), 32), np.uint8)
    by_width: dict[int, list[int]] = {}
    for i, r in enumerate(rows):
        by_width.setdefault(len(r), []).append(i)
    for width, idxs in by_width.items():
        if len(idxs) < _DEVICE_MIN_ROWS:
            for i in idxs:
                out[i] = np.frombuffer(
                    hashlib.sha256(rows[i]).digest(), np.uint8)
            continue
        from electionguard_tpu.core.sha256_jax import sha256_rows_np
        mat = np.frombuffer(b"".join(rows[i] for i in idxs),
                            np.uint8).reshape(len(idxs), width)
        out[np.asarray(idxs)] = sha256_rows_np(mat)
    return out


@functools.lru_cache(maxsize=65536)
def _sel_prefix(selection_id: str, seq: int, placeholder: bool) -> bytes:
    return (_encode("enc-selection") + _encode(selection_id)
            + _encode(seq) + _encode(int(placeholder)))


@functools.lru_cache(maxsize=65536)
def _contest_prefix(contest_id: str, seq: int) -> bytes:
    return _encode("enc-contest") + _encode(contest_id) + _encode(seq)


@functools.lru_cache(maxsize=None)
def _elem_hdr(nbytes: int) -> bytes:
    return _TAG_P + nbytes.to_bytes(4, "big")           # _encode(ElementModP)


def batch_crypto_hashes(ballots: Sequence) -> np.ndarray:
    """(B, 32) uint8 — ``b.crypto_hash()`` for every ballot, batched.

    Level by level (selections → contest digest-lists → contests →
    ballot digest-lists → ballots), each level one `_sha_rows` call.
    """
    sel_rows: list[bytes] = []
    contest_meta: list[tuple] = []   # (prefix, sel_start, sel_count)
    ballot_meta: list[tuple] = []    # (prefix, contest_start, count)
    for b in ballots:
        b_start = len(contest_meta)
        for c in b.contests:
            start = len(sel_rows)
            for s in c.selections:
                pad = s.ciphertext.pad.to_bytes()
                data = s.ciphertext.data.to_bytes()
                hdr = _elem_hdr(len(pad))
                sel_rows.append(
                    _sel_prefix(s.selection_id, s.sequence_order,
                                s.is_placeholder)
                    + hdr + pad + hdr + data)
            contest_meta.append((
                _contest_prefix(c.contest_id, c.sequence_order),
                start, len(c.selections)))
        ballot_meta.append((
            _encode("enc-ballot") + _encode(b.ballot_id)
            + _encode(b.manifest_hash),
            b_start, len(b.contests)))

    sel_digests = _sha_rows(sel_rows)

    # per contest: digest of the selection-digest list, then the contest row
    inner_rows = [
        b"".join(_DIGEST_FRAME_HDR + sel_digests[i].tobytes()
                 for i in range(start, start + count))
        for _, start, count in contest_meta]
    inner_digests = _sha_rows(inner_rows)
    contest_rows = [
        prefix + _SEQ_HDR + inner_digests[ci].tobytes()
        for ci, (prefix, _, _) in enumerate(contest_meta)]
    contest_digests = _sha_rows(contest_rows)

    # per ballot: digest of the contest-digest list, then the ballot row
    binner_rows = [
        b"".join(_DIGEST_FRAME_HDR + contest_digests[i].tobytes()
                 for i in range(start, start + count))
        for _, start, count in ballot_meta]
    binner_digests = _sha_rows(binner_rows)
    ballot_rows = [
        prefix + _SEQ_HDR + binner_digests[bi].tobytes()
        for bi, (prefix, _, _) in enumerate(ballot_meta)]
    return _sha_rows(ballot_rows)


def batch_codes(ballots: Sequence) -> np.ndarray:
    """(B, 32) uint8 — each ballot's confirmation code RECOMPUTED from
    its stored (code_seed, timestamp) and batched crypto hash; comparing
    against ``b.code`` replicates ``is_valid_code()`` in bulk."""
    hashes = batch_crypto_hashes(ballots)
    rows = [
        _encode("ballot-code") + _encode(b.code_seed)
        + _encode(b.timestamp) + _encode(hashes[i].tobytes())
        for i, b in enumerate(ballots)]
    return _sha_rows(rows)

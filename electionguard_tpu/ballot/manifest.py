"""Election manifest data model + input validation.

Native replacement for the reference's [ext] ``Manifest`` and
``ManifestInputValidation`` (call sites: RunRemoteKeyCeremony.java:106-112,
RunRemoteDecryptor.java:114-127 — both validate the manifest fail-fast before
starting a ceremony/decryption and abort on any error).

The model covers what the election workflow consumes: geopolitical units,
parties, candidates, contests with selections, and ballot styles.  JSON
(de)serialization lives here; the election-record directory layout lives in
``electionguard_tpu.publish``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from electionguard_tpu.core.hash import hash_digest


@dataclass(frozen=True)
class SelectionDescription:
    object_id: str
    sequence_order: int
    candidate_id: str

    def crypto_hash(self) -> bytes:
        return hash_digest("selection", self.object_id, self.sequence_order,
                           self.candidate_id)


@dataclass(frozen=True)
class ContestDescription:
    object_id: str
    sequence_order: int
    geopolitical_unit_id: str
    vote_variation: str          # "one_of_m" | "n_of_m"
    votes_allowed: int
    name: str
    selections: tuple[SelectionDescription, ...]

    def crypto_hash(self) -> bytes:
        return hash_digest("contest", self.object_id, self.sequence_order,
                           self.geopolitical_unit_id, self.vote_variation,
                           self.votes_allowed, self.name,
                           [s.crypto_hash() for s in self.selections])


@dataclass(frozen=True)
class BallotStyle:
    object_id: str
    geopolitical_unit_ids: tuple[str, ...]


@dataclass(frozen=True)
class Candidate:
    object_id: str
    name: str
    party_id: str = ""


@dataclass(frozen=True)
class GeopoliticalUnit:
    object_id: str
    name: str
    type: str = "district"


@dataclass(frozen=True)
class Party:
    object_id: str
    name: str


@dataclass(frozen=True)
class Manifest:
    election_scope_id: str
    spec_version: str
    start_date: str
    end_date: str
    geopolitical_units: tuple[GeopoliticalUnit, ...]
    parties: tuple[Party, ...]
    candidates: tuple[Candidate, ...]
    contests: tuple[ContestDescription, ...]
    ballot_styles: tuple[BallotStyle, ...]

    def crypto_hash(self) -> bytes:
        return hash_digest(
            "manifest", self.election_scope_id, self.spec_version,
            self.start_date, self.end_date,
            [c.crypto_hash() for c in self.contests],
            [b.object_id for b in self.ballot_styles])

    # ------------------------------------------------------------------
    def contests_for_style(self, style_id: str) -> list[ContestDescription]:
        style = next(b for b in self.ballot_styles if b.object_id == style_id)
        gids = set(style.geopolitical_unit_ids)
        return [c for c in self.contests if c.geopolitical_unit_id in gids]

    # ------------------------------------------------------------------
    def to_json(self) -> str:
        def enc(o):
            if hasattr(o, "__dataclass_fields__"):
                return {k: getattr(o, k) for k in o.__dataclass_fields__}
            if isinstance(o, tuple):
                return list(o)
            raise TypeError(type(o))
        return json.dumps(self, default=enc, indent=2, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "Manifest":
        d = json.loads(s)
        return Manifest(
            election_scope_id=d["election_scope_id"],
            spec_version=d["spec_version"],
            start_date=d["start_date"],
            end_date=d["end_date"],
            geopolitical_units=tuple(
                GeopoliticalUnit(**g) for g in d["geopolitical_units"]),
            parties=tuple(Party(**p) for p in d["parties"]),
            candidates=tuple(Candidate(**c) for c in d["candidates"]),
            contests=tuple(
                ContestDescription(
                    **{**c, "selections": tuple(
                        SelectionDescription(**s) for s in c["selections"])})
                for c in d["contests"]),
            ballot_styles=tuple(
                BallotStyle(object_id=b["object_id"],
                            geopolitical_unit_ids=tuple(
                                b["geopolitical_unit_ids"]))
                for b in d["ballot_styles"]),
        )


@dataclass
class ValidationMessages:
    """Mirrors the reference's ValidationMessages consumption pattern:
    ``hasErrors`` gates startup (RunRemoteKeyCeremony.java:107-112)."""

    errors: list[str] = field(default_factory=list)
    warnings: list[str] = field(default_factory=list)

    def has_errors(self) -> bool:
        return bool(self.errors)

    def __str__(self):
        return "\n".join(["ERROR: " + e for e in self.errors]
                         + ["WARN: " + w for w in self.warnings])


def validate_manifest(manifest: Manifest) -> ValidationMessages:
    """Structural validation before any ceremony starts."""
    msgs = ValidationMessages()
    err = msgs.errors.append

    def check_unique(ids, kind):
        seen = set()
        for i in ids:
            if i in seen:
                err(f"duplicate {kind} id: {i}")
            seen.add(i)
        return seen

    gids = check_unique([g.object_id for g in manifest.geopolitical_units],
                        "geopolitical unit")
    check_unique([p.object_id for p in manifest.parties], "party")
    cand_ids = check_unique([c.object_id for c in manifest.candidates],
                            "candidate")
    check_unique([c.object_id for c in manifest.contests], "contest")
    check_unique([b.object_id for b in manifest.ballot_styles], "ballot style")

    if not manifest.contests:
        err("manifest has no contests")
    if not manifest.ballot_styles:
        err("manifest has no ballot styles")

    party_ids = {p.object_id for p in manifest.parties}
    for cand in manifest.candidates:
        if cand.party_id and cand.party_id not in party_ids:
            err(f"candidate {cand.object_id} references unknown party "
                f"{cand.party_id}")

    for c in manifest.contests:
        if c.geopolitical_unit_id not in gids:
            err(f"contest {c.object_id} references unknown geopolitical "
                f"unit {c.geopolitical_unit_id}")
        if not c.selections:
            err(f"contest {c.object_id} has no selections")
        if c.votes_allowed < 1:
            err(f"contest {c.object_id} votes_allowed must be >= 1")
        if c.votes_allowed > len(c.selections):
            err(f"contest {c.object_id} votes_allowed exceeds selection count")
        if c.vote_variation not in ("one_of_m", "n_of_m"):
            err(f"contest {c.object_id} unknown vote variation "
                f"{c.vote_variation}")
        check_unique([s.object_id for s in c.selections],
                     f"selection in {c.object_id}")
        seqs = [s.sequence_order for s in c.selections]
        if len(set(seqs)) != len(seqs):
            err(f"contest {c.object_id} has duplicate selection "
                f"sequence orders")
        for s in c.selections:
            if s.candidate_id not in cand_ids:
                err(f"selection {s.object_id} references unknown candidate "
                    f"{s.candidate_id}")

    for b in manifest.ballot_styles:
        for gid in b.geopolitical_unit_ids:
            if gid not in gids:
                err(f"ballot style {b.object_id} references unknown "
                    f"geopolitical unit {gid}")

    return msgs

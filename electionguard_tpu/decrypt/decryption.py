"""Coordinator-side decryption: share gathering, Lagrange combine, decode.

Native replacement for the reference's [ext] ``Decryption`` —
``Decryption(group, electionInit, trustees, missingGuardians)`` with
``.decrypt(tally)`` / ``.decryptBallot(ballot)`` / ``.getAvailableGuardians()``
(call site: src/main/java/electionguard/decrypt/RunRemoteDecryptor.java:261-273).

For every selection (A, B):
  * each available guardian i contributes Mᵢ = A^{a_i0} (direct),
  * each missing guardian m is reconstructed from quorum backups:
    M_m = Π_ℓ (A^{P_m(ℓ)})^{w_ℓ} with Lagrange coefficients
    w_ℓ = Π_{j≠ℓ} x_j/(x_j − x_ℓ) mod q — the cryptographic fault tolerance
    of SURVEY.md §5.3,
  * B / Π M = g^t, and t is decoded with the small-exponent dlog table
    (SURVEY.md §3.2 🔥).

All trustee calls are batched over the whole tally (one round trip per
trustee per protocol leg, matching the reference's batch rpcs); every proof
is verified on arrival — a bad trustee is detected here, not in the final
verifier.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from electionguard_tpu.ballot.ciphertext import BallotState, EncryptedBallot
from electionguard_tpu.ballot.tally import (EncryptedTally, PartialDecryption,
                                            PlaintextTally,
                                            PlaintextTallyContest,
                                            PlaintextTallySelection)
from electionguard_tpu.core.dlog import DLog
from electionguard_tpu.core.group import (ElementModP, ElementModQ,
                                          GroupContext)
from electionguard_tpu.core.group_jax import jax_ops
from electionguard_tpu.crypto import validate
from electionguard_tpu.crypto.cp_batch import batch_cp_verify
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
from electionguard_tpu.decrypt.interface import DecryptingTrusteeIF
from electionguard_tpu.keyceremony.interface import Result
from electionguard_tpu.keyceremony.trustee import commitment_product
from electionguard_tpu.publish.election_record import (DecryptingGuardian,
                                                       ElectionInitialized)
from electionguard_tpu.utils import devicetime


def lagrange_coefficient(group: GroupContext, xs: Sequence[int],
                         x_l: int) -> ElementModQ:
    """w_ℓ = Π_{j≠ℓ} x_j / (x_j − x_ℓ) mod q."""
    num, den = 1, 1
    for x_j in xs:
        if x_j == x_l:
            continue
        num = num * x_j % group.q
        den = den * (x_j - x_l) % group.q
    return group.mult_q(group.int_to_q(num),
                        group.inv_q(group.int_to_q(den)))


class DecryptionError(Exception):
    pass


class TrusteeFailure(Exception):
    """An available trustee failed mid-decryption (rpc exhausted its
    retries, in-band error, malformed batch).  Internal signal: the
    degradation loop catches it and demotes the trustee to the missing
    set when quorum still holds."""

    def __init__(self, trustee_id: str, reason: str):
        super().__init__(f"{trustee_id}: {reason}")
        self.trustee_id = trustee_id
        self.reason = reason


class Decryption:
    def __init__(self, group: GroupContext, election_init: ElectionInitialized,
                 trustees: Sequence[DecryptingTrusteeIF],
                 missing_guardian_ids: Sequence[str],
                 dlog: Optional[DLog] = None):
        self.group = group
        self.init = election_init
        self.trustees = list(trustees)
        self.missing = list(missing_guardian_ids)
        self.dlog = dlog if dlog is not None else DLog(group)

        n = election_init.config.n_guardians
        quorum = election_init.config.quorum
        if len(self.trustees) < quorum:
            raise DecryptionError(
                f"navailable {len(self.trustees)} < quorum {quorum}")
        if len(self.trustees) + len(self.missing) != n:
            raise DecryptionError("available + missing != nguardians")
        known = {g.guardian_id for g in election_init.guardians}
        for t in self.trustees:
            if t.id not in known:
                raise DecryptionError(f"unknown trustee {t.id}")
            rec = election_init.guardian(t.id)
            if rec.x_coordinate != t.x_coordinate:
                raise DecryptionError(f"trustee {t.id} x mismatch")
            if rec.coefficient_commitments[0] != t.election_public_key:
                raise DecryptionError(f"trustee {t.id} public key mismatch")
        for m in self.missing:
            if m not in known:
                raise DecryptionError(f"unknown missing guardian {m}")

        xs = [t.x_coordinate for t in self.trustees]
        self.lagrange = {
            t.id: lagrange_coefficient(group, xs, t.x_coordinate)
            for t in self.trustees}

    # ------------------------------------------------------------------
    def get_available_guardians(self) -> list[DecryptingGuardian]:
        return [DecryptingGuardian(t.id, t.x_coordinate, self.lagrange[t.id])
                for t in self.trustees]

    # ------------------------------------------------------------------
    def _demote(self, trustee_id: str, reason: str) -> None:
        """Move a failed available trustee to the missing set and
        recompute the Lagrange basis — the cryptographic fault tolerance
        of SURVEY.md §5.3 applied DYNAMICALLY: the threshold scheme never
        needed the failed trustee's cooperation, only quorum-many
        survivors holding its backup shares."""
        quorum = self.init.config.quorum
        remaining = [t for t in self.trustees if t.id != trustee_id]
        if len(remaining) < quorum:
            raise DecryptionError(
                f"trustee {trustee_id} failed mid-decryption ({reason}) "
                f"and the remaining {len(remaining)} guardians no longer "
                f"meet quorum {quorum}")
        import logging
        logging.getLogger("egtpu.decrypt").warning(
            "demoting trustee %s to missing (%s); recomputing with %d "
            "available + %d missing", trustee_id, reason, len(remaining),
            len(self.missing) + 1)
        self.trustees = remaining
        self.missing.append(trustee_id)
        xs = [t.x_coordinate for t in remaining]
        self.lagrange = {
            t.id: lagrange_coefficient(self.group, xs, t.x_coordinate)
            for t in remaining}

    def _decrypt_batch(
            self, texts: list[ElGamalCiphertext]
    ) -> list[tuple[int, ElementModP, tuple[PartialDecryption, ...]]]:
        """``_decrypt_batch_once`` with graceful degradation: a trustee
        that fails mid-batch (dead peer after bounded retries, in-band
        error, malformed response) is demoted to the missing set and the
        batch recomputed with compensated shares — as long as the
        survivors still meet quorum.  Shares already gathered from the
        failed attempt are discarded; the recompute is a fresh protocol
        round, so the published shares are always one consistent set."""
        from electionguard_tpu.obs import trace
        attrs = ({"n_texts": len(texts), "n_trustees": len(self.trustees),
                  "n_missing": len(self.missing)}
                 if trace.enabled() else None)
        with trace.span("decrypt.batch", attrs) as sp:
            while True:
                try:
                    return self._decrypt_batch_once(texts)
                except TrusteeFailure as e:
                    sp.set("demoted", e.trustee_id)
                    self._demote(e.trustee_id, e.reason)

    def _decrypt_batch_once(
            self, texts: list[ElGamalCiphertext]
    ) -> list[tuple[int, ElementModP, tuple[PartialDecryption, ...]]]:
        """Decrypt a batch of ciphertexts; returns (t, g^t, shares) each.

        Every modexp runs on the device plane in a handful of dispatches:
        all on-arrival CP proof checks through ``batch_cp_verify``, the
        Lagrange recombination powers through one ``powmod`` dispatch, and
        the share-product inverses through one ``powmod`` with exponent
        q-1 (valid because every Mᵢ that survived its proof check lies in
        the q-order subgroup; a host-side ``inv·M == 1`` guard catches any
        violation).  No per-selection host ``pow``.
        """
        g = self.group
        qbar = self.init.extended_base_hash
        ops = jax_ops(g)
        n = len(texts)
        pads = [ct.pad.value for ct in texts]

        cp_x, cp_g2, cp_y, cp_c, cp_v = [], [], [], [], []
        cp_err: list[str] = []

        # direct shares: one batched call per available trustee
        direct: dict[str, list] = {}
        for t in self.trustees:
            res = t.direct_decrypt(texts, qbar)
            if isinstance(res, Result):
                raise TrusteeFailure(t.id, f"directDecrypt: {res.error}")
            if len(res) != n:
                raise TrusteeFailure(t.id, "returned wrong batch size")
            # ingestion gate at share receipt (covers in-process
            # trustees too; remote proxies additionally pre-screen the
            # wire) — a defective share demotes the trustee with the
            # gate's named class instead of corrupting the combine
            try:
                validate.gate_elements(
                    g, [(f"{t.id} share[{j}]", d.partial_decryption.value)
                        for j, d in enumerate(res)],
                    "decrypt")
            except validate.GateError as e:
                raise TrusteeFailure(t.id, str(e))
            k0 = self.init.guardian(t.id).coefficient_commitments[0].value
            for pad, d in zip(pads, res):
                cp_x.append(k0)
                cp_g2.append(pad)
                cp_y.append(d.partial_decryption.value)
                cp_c.append(d.proof.challenge.value)
                cp_v.append(d.proof.response.value)
                cp_err.append(f"direct decryption proof of {t.id} invalid")
            direct[t.id] = res

        # compensated shares: per missing guardian, per available trustee
        compensated: dict[str, dict[str, list]] = {}
        for m in self.missing:
            m_rec = self.init.guardian(m)
            per_trustee = {}
            for t in self.trustees:
                res = t.compensated_decrypt(m, texts, qbar)
                if isinstance(res, Result):
                    raise TrusteeFailure(
                        t.id, f"compensatedDecrypt({m}): {res.error}")
                if len(res) != n:
                    raise TrusteeFailure(
                        t.id, f"returned wrong batch size for {m}")
                try:
                    validate.gate_elements(
                        g, [(f"{t.id} comp[{j}].{nm} for {m}", v)
                            for j, c in enumerate(res)
                            for nm, v in (
                                ("share", c.partial_decryption.value),
                                ("recovery",
                                 c.recovered_public_key_share.value))],
                        "decrypt")
                except validate.GateError as e:
                    raise TrusteeFailure(t.id, str(e))
                expected_recovery = commitment_product(
                    g, m_rec.coefficient_commitments, t.x_coordinate)
                for pad, c in zip(pads, res):
                    if c.recovered_public_key_share != expected_recovery:
                        raise TrusteeFailure(
                            t.id, f"recovery key for {m} mismatches "
                                  f"public commitments")
                    cp_x.append(c.recovered_public_key_share.value)
                    cp_g2.append(pad)
                    cp_y.append(c.partial_decryption.value)
                    cp_c.append(c.proof.challenge.value)
                    cp_v.append(c.proof.response.value)
                    cp_err.append(
                        f"compensated proof of {t.id} for {m} invalid")
                per_trustee[t.id] = res
            compensated[m] = per_trustee

        ok = batch_cp_verify(g, cp_x, cp_g2, cp_y, cp_c, cp_v, qbar)
        bad = np.nonzero(~ok)[0]
        if bad.size:
            raise DecryptionError(cp_err[int(bad[0])])

        # Lagrange recombination M_m = Π_ℓ parts^{w_ℓ}: ONE powmod dispatch
        # over every (missing × trustee × text) row, then host products
        recovered: dict[str, list[int]] = {}
        if self.missing:
            rows, exps = [], []
            for m in self.missing:
                for t in self.trustees:
                    w = self.lagrange[t.id].value
                    for c in compensated[m][t.id]:
                        rows.append(c.partial_decryption.value)
                        exps.append(w)
            pows = ops.powmod_ints(rows, exps)
            i = 0
            for m in self.missing:
                acc = [1] * n
                for t in self.trustees:
                    for k in range(n):
                        acc[k] = acc[k] * pows[i] % g.p
                        i += 1
                recovered[m] = acc

        m_totals = []
        for idx in range(n):
            mt = 1
            for t in self.trustees:
                mt = mt * direct[t.id][idx].partial_decryption.value % g.p
            for m in self.missing:
                mt = mt * recovered[m][idx] % g.p
            m_totals.append(mt)

        # value = B · (Π Mᵢ)^{-1}; subgroup inverse = ^(q-1), one dispatch
        inv = ops.powmod_ints(m_totals, [g.q - 1] * n)
        out = []
        for idx, ct in enumerate(texts):
            if inv[idx] * m_totals[idx] % g.p != 1:
                raise DecryptionError(
                    "share product is not in the q-order subgroup")
            shares: list[PartialDecryption] = []
            for t in self.trustees:
                d = direct[t.id][idx]
                shares.append(PartialDecryption(
                    t.id, d.partial_decryption, d.proof))
            for m in self.missing:
                parts = {t.id: compensated[m][t.id][idx]
                         for t in self.trustees}
                shares.append(PartialDecryption(
                    m, g.int_to_p(recovered[m][idx]), None, parts))
            value = g.int_to_p(ct.data.value * inv[idx] % g.p)  # g^t
            t_val = self.dlog.dlog(value)
            if t_val is None:
                raise DecryptionError("tally exceeds dlog table")
            out.append((t_val, value, tuple(shares)))
        return out

    # ------------------------------------------------------------------
    def _decrypt_groups(
            self, groups: Sequence[tuple[str, Sequence]]
    ) -> list[PlaintextTally]:
        """Shared assembly: decrypt every selection of every group (one
        ``_decrypt_batch`` — one rpc leg per trustee per protocol for the
        whole lot) and rebuild one PlaintextTally per group.  Keys index
        by GROUP POSITION, not id, so duplicated ballot ids in a tampered
        record decrypt independently instead of silently sharing one
        result."""
        devicetime.charge("decrypt", len(groups))
        texts, keys = [], []
        for gi, (_, contests) in enumerate(groups):
            for c in contests:
                for s in c.selections:
                    texts.append(s.ciphertext)
                    keys.append((gi, c.contest_id, s.selection_id))
        by_key = dict(zip(keys, self._decrypt_batch(texts)))
        out = []
        for gi, (tally_id, contests) in enumerate(groups):
            out.append(PlaintextTally(tally_id, tuple(
                PlaintextTallyContest(
                    contest_id=c.contest_id,
                    selections=tuple(
                        PlaintextTallySelection(
                            selection_id=s.selection_id,
                            tally=by_key[(gi, c.contest_id,
                                          s.selection_id)][0],
                            value=by_key[(gi, c.contest_id,
                                          s.selection_id)][1],
                            message=s.ciphertext,
                            shares=by_key[(gi, c.contest_id,
                                           s.selection_id)][2])
                        for s in c.selections))
                for c in contests)))
        return out

    def decrypt(self, tally: EncryptedTally) -> PlaintextTally:
        return self._decrypt_groups(
            [(tally.tally_id, tally.contests)])[0]

    def decrypt_ballot(self, ballot: EncryptedBallot) -> PlaintextTally:
        """Decrypt one (spoiled) ballot as a single-ballot tally
        (reference: RunRemoteDecryptor.java:264-269)."""
        return self.decrypt_ballots([ballot])[0]

    def decrypt_ballots(
            self, ballots: Sequence[EncryptedBallot]
    ) -> list[PlaintextTally]:
        """Decrypt a batch of (spoiled) ballots with ONE ``_decrypt_batch``
        across every selection of every ballot — one rpc leg per trustee
        per protocol for the whole chunk, where the reference shape is one
        round trip per trustee per ballot
        (RunRemoteDecryptor.java:264-269).  Callers stream large spoiled
        sets chunk-by-chunk to keep memory O(chunk)."""
        return self._decrypt_groups(
            [(b.ballot_id, b.contests) for b in ballots])


def stream_spoiled_tallies(ballots, decryption: Decryption,
                           chunk_size: int = 512):
    """Lazily decrypt the SPOILED ballots of a (possibly huge) ballot
    stream: collect chunk_size spoiled ballots, decrypt them with one
    batched rpc leg per trustee per protocol, yield their tallies, drop
    the chunk — O(chunks) round trips, O(chunk) memory (the reference
    decrypts one rpc per trustee per ballot,
    RunRemoteDecryptor.java:264-269)."""
    chunk: list[EncryptedBallot] = []
    for b in ballots:
        if b.state != BallotState.SPOILED:
            continue
        chunk.append(b)
        if len(chunk) >= chunk_size:
            yield from decryption.decrypt_ballots(chunk)
            chunk = []
    if chunk:
        yield from decryption.decrypt_ballots(chunk)

"""The decrypting trustee: holds a guardian's decryption secrets.

Native replacement for the reference's [ext] ``DecryptingTrustee`` —
deserialized from the key ceremony's saved state and served over gRPC
(reference: src/main/java/electionguard/decrypt/RunRemoteDecryptingTrustee.java:24,90
``readTrustee(group, trusteeFile)``).

Holds: the guardian's own secret ``a_{i0}``, the received backup shares
``P_i(ℓ)`` for every other guardian i (enabling compensated decryption for
missing guardians), and everyone's public commitments (for recovery keys).
Secrets never leave; only shares Mᵢ = A^s and proofs do (SURVEY.md §7 hard
part 5).

The reference hands its trustee the whole rpc batch and loops per
ciphertext on the JVM (RunRemoteDecryptingTrustee.java:189-193 🔥); here the
guardian-side hot loop runs on the device batch plane: shares A^s and proof
commitments (g^u, A^u) in two powmod dispatches, Fiat–Shamir challenges in
one device SHA-256 dispatch, responses in one Z_q dispatch — no per-text
host ``pow`` on the production group.
"""

from __future__ import annotations

import json
from typing import Sequence, Union

import numpy as np

from electionguard_tpu.core.group import (ElementModP, ElementModQ,
                                          GroupContext)
from electionguard_tpu.core import sha256_jax
from electionguard_tpu.core.group_jax import (jax_exp_ops, jax_ops,
                                              limbs_to_bytes_be)
from electionguard_tpu.core.hash import _encode
from electionguard_tpu.crypto.chaum_pedersen import (
    GenericChaumPedersenProof, make_generic_cp_proof)
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
from electionguard_tpu.decrypt.interface import (
    CompensatedDecryptionAndProof, DecryptingTrusteeIF,
    DirectDecryptionAndProof)
from electionguard_tpu.keyceremony.interface import Result
from electionguard_tpu.keyceremony.trustee import commitment_product


def _batch_shares_and_proofs(
        g: GroupContext, texts: Sequence[ElGamalCiphertext],
        s: ElementModQ, x: ElementModP, qbar: ElementModQ,
) -> list[tuple[ElementModP, GenericChaumPedersenProof]]:
    """Batched (Mᵢ = A^s, CP proof) for every ciphertext.

    Device plan (production group): one ``powmod`` dispatch computes both
    the shares A^s and the proof commitments A^u, one fixed-base dispatch
    computes g^u, one device SHA-256 dispatch derives every challenge
    c = H(Q̄, g, A, x, y, a, b), and one Z_q dispatch closes the responses
    v = u − c·s.  ``x = g^s`` is the public counterpart of ``s`` (the
    guardian's election public key for direct decryption, the recovery key
    for compensated) — supplied by the caller, never recomputed from the
    secret per text.  Non-production groups fall back to the host loop.
    """
    n = len(texts)
    if n == 0:
        return []
    if not sha256_jax.supports(g):
        out = []
        for ct in texts:
            share = g.pow_p(ct.pad, s)
            proof = make_generic_cp_proof(
                g, s, g.G_MOD_P, ct.pad, g.rand_q(), qbar)
            out.append((share, proof))
        return out

    ops = jax_ops(g)
    ee = jax_exp_ops(g)
    pads = [ct.pad.value for ct in texts]
    A_l = ops.to_limbs_p(pads)
    s_l = ops.to_limbs_q([s.value] * n)
    u_ints = [g.rand_q().value for _ in range(n)]
    u_l = ops.to_limbs_q(u_ints)

    # shares y = A^s and commitments b = A^u: ONE variable-base dispatch
    pows = np.asarray(ops.powmod(np.concatenate([A_l, A_l]),
                                 np.concatenate([s_l, u_l])))
    y_l, b_l = pows[:n], pows[n:]
    a_l = np.asarray(ops.g_pow(u_l))

    # device Fiat–Shamir: c = H(Q̄, g, A, x, y, a, b); fixed items (Q̄, g)
    # fold into the host prefix, the fixed x broadcasts as a row
    x_b = np.broadcast_to(
        np.frombuffer(x.to_bytes(), np.uint8), (n, g.spec.p_bytes))
    prefix = _encode(qbar) + _encode(g.G_MOD_P)
    c_l = np.asarray(sha256_jax.batch_challenge_p(
        g, prefix,
        [limbs_to_bytes_be(A_l), x_b, limbs_to_bytes_be(y_l),
         limbs_to_bytes_be(a_l), limbs_to_bytes_be(b_l)]))

    v_l = np.asarray(ee.a_minus_bc(u_l, c_l, s_l))
    y_i = ops.from_limbs(y_l)
    c_i = ee.from_limbs(c_l)
    v_i = ee.from_limbs(v_l)
    return [(ElementModP(y_i[k], g),
             GenericChaumPedersenProof(g.int_to_q(c_i[k]),
                                       g.int_to_q(v_i[k])))
            for k in range(n)]


class DecryptingTrustee(DecryptingTrusteeIF):
    def __init__(self, group: GroupContext, guardian_id: str,
                 x_coordinate: int, secret_key: ElementModQ,
                 received_shares: dict[str, ElementModQ],
                 public_commitments: dict[str, list[ElementModP]],
                 own_commitments: list[ElementModP]):
        self.group = group
        self._id = guardian_id
        self._x = x_coordinate
        self._secret = secret_key
        self._received_shares = dict(received_shares)
        self._public_commitments = dict(public_commitments)
        self._own_commitments = list(own_commitments)

    # ------------------------------------------------------------------
    @property
    def id(self) -> str:
        return self._id

    @property
    def x_coordinate(self) -> int:
        return self._x

    @property
    def election_public_key(self) -> ElementModP:
        return self._own_commitments[0]

    # ------------------------------------------------------------------
    def direct_decrypt(
            self, texts: Sequence[ElGamalCiphertext],
            extended_base_hash: ElementModQ,
    ) -> Union[list[DirectDecryptionAndProof], Result]:
        """Mᵢ = A^{a_i0} + CP proof, for every ciphertext in the batch
        (the trustee-side hot loop — SURVEY.md §3.2 🔥), in a handful of
        device dispatches (reference per-text analogue:
        RunRemoteDecryptingTrustee.java:189-193)."""
        pairs = _batch_shares_and_proofs(
            self.group, texts, self._secret, self.election_public_key,
            extended_base_hash)
        return [DirectDecryptionAndProof(share, proof)
                for share, proof in pairs]

    def compensated_decrypt(
            self, missing_guardian_id: str,
            texts: Sequence[ElGamalCiphertext],
            extended_base_hash: ElementModQ,
    ) -> Union[list[CompensatedDecryptionAndProof], Result]:
        """Mᵢ,ℓ = A^{P_i(ℓ)} for a missing guardian i, plus the recovery key
        g^{P_i(ℓ)} recomputed from i's public commitments."""
        g = self.group
        backup = self._received_shares.get(missing_guardian_id)
        if backup is None:
            return Result.Err(
                f"{self._id} holds no backup for {missing_guardian_id}")
        commitments = self._public_commitments.get(missing_guardian_id)
        if commitments is None:
            return Result.Err(
                f"{self._id} has no commitments for {missing_guardian_id}")
        recovery = commitment_product(g, tuple(commitments), self._x)
        if g.g_pow_p(backup) != recovery:
            return Result.Err(
                f"backup for {missing_guardian_id} fails commitment check")
        pairs = _batch_shares_and_proofs(
            g, texts, backup, recovery, extended_base_hash)
        return [CompensatedDecryptionAndProof(share, proof, recovery)
                for share, proof in pairs]

    # ------------------------------------------------------------------
    # persistence (the trustee-file checkpoint of SURVEY.md §5.4)
    # ------------------------------------------------------------------
    @staticmethod
    def from_state(group: GroupContext, state: dict) -> "DecryptingTrustee":
        return DecryptingTrustee(
            group=group,
            guardian_id=state["guardian_id"],
            x_coordinate=state["x_coordinate"],
            secret_key=group.int_to_q(state["secret_key"]),
            received_shares={
                gid: group.int_to_q(v)
                for gid, v in state["received_shares"].items()},
            public_commitments={
                gid: [ElementModP(v, group) for v in ks]
                for gid, ks in state["public_commitments"].items()},
            own_commitments=[ElementModP(v, group)
                             for v in state["own_commitments"]],
        )


def read_trustee(group: GroupContext, path: str) -> DecryptingTrustee:
    """Mirror of the reference's [ext] ``readTrustee(group, file)``
    (RunRemoteDecryptingTrustee.java:90)."""
    with open(path) as f:
        return DecryptingTrustee.from_state(group, json.load(f))

"""The decrypting trustee: holds a guardian's decryption secrets.

Native replacement for the reference's [ext] ``DecryptingTrustee`` —
deserialized from the key ceremony's saved state and served over gRPC
(reference: src/main/java/electionguard/decrypt/RunRemoteDecryptingTrustee.java:24,90
``readTrustee(group, trusteeFile)``).

Holds: the guardian's own secret ``a_{i0}``, the received backup shares
``P_i(ℓ)`` for every other guardian i (enabling compensated decryption for
missing guardians), and everyone's public commitments (for recovery keys).
Secrets never leave; only shares Mᵢ = A^s and proofs do (SURVEY.md §7 hard
part 5).
"""

from __future__ import annotations

import json
from typing import Sequence, Union

from electionguard_tpu.core.group import (ElementModP, ElementModQ,
                                          GroupContext)
from electionguard_tpu.crypto.chaum_pedersen import (
    GenericChaumPedersenProof, make_generic_cp_proof)
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
from electionguard_tpu.decrypt.interface import (
    CompensatedDecryptionAndProof, DecryptingTrusteeIF,
    DirectDecryptionAndProof)
from electionguard_tpu.keyceremony.interface import Result
from electionguard_tpu.keyceremony.trustee import commitment_product


class DecryptingTrustee(DecryptingTrusteeIF):
    def __init__(self, group: GroupContext, guardian_id: str,
                 x_coordinate: int, secret_key: ElementModQ,
                 received_shares: dict[str, ElementModQ],
                 public_commitments: dict[str, list[ElementModP]],
                 own_commitments: list[ElementModP]):
        self.group = group
        self._id = guardian_id
        self._x = x_coordinate
        self._secret = secret_key
        self._received_shares = dict(received_shares)
        self._public_commitments = dict(public_commitments)
        self._own_commitments = list(own_commitments)

    # ------------------------------------------------------------------
    @property
    def id(self) -> str:
        return self._id

    @property
    def x_coordinate(self) -> int:
        return self._x

    @property
    def election_public_key(self) -> ElementModP:
        return self._own_commitments[0]

    # ------------------------------------------------------------------
    def direct_decrypt(
            self, texts: Sequence[ElGamalCiphertext],
            extended_base_hash: ElementModQ,
    ) -> Union[list[DirectDecryptionAndProof], Result]:
        """Mᵢ = A^{a_i0} + CP proof, for every ciphertext in the batch
        (the trustee-side hot loop — SURVEY.md §3.2 🔥)."""
        g = self.group
        out = []
        for ct in texts:
            share = g.pow_p(ct.pad, self._secret)
            proof = make_generic_cp_proof(
                g, self._secret, g.G_MOD_P, ct.pad, g.rand_q(),
                extended_base_hash)
            out.append(DirectDecryptionAndProof(share, proof))
        return out

    def compensated_decrypt(
            self, missing_guardian_id: str,
            texts: Sequence[ElGamalCiphertext],
            extended_base_hash: ElementModQ,
    ) -> Union[list[CompensatedDecryptionAndProof], Result]:
        """Mᵢ,ℓ = A^{P_i(ℓ)} for a missing guardian i, plus the recovery key
        g^{P_i(ℓ)} recomputed from i's public commitments."""
        g = self.group
        backup = self._received_shares.get(missing_guardian_id)
        if backup is None:
            return Result.Err(
                f"{self._id} holds no backup for {missing_guardian_id}")
        commitments = self._public_commitments.get(missing_guardian_id)
        if commitments is None:
            return Result.Err(
                f"{self._id} has no commitments for {missing_guardian_id}")
        recovery = commitment_product(g, tuple(commitments), self._x)
        if g.g_pow_p(backup) != recovery:
            return Result.Err(
                f"backup for {missing_guardian_id} fails commitment check")
        out = []
        for ct in texts:
            share = g.pow_p(ct.pad, backup)
            proof = make_generic_cp_proof(
                g, backup, g.G_MOD_P, ct.pad, g.rand_q(),
                extended_base_hash)
            out.append(CompensatedDecryptionAndProof(share, proof, recovery))
        return out

    # ------------------------------------------------------------------
    # persistence (the trustee-file checkpoint of SURVEY.md §5.4)
    # ------------------------------------------------------------------
    @staticmethod
    def from_state(group: GroupContext, state: dict) -> "DecryptingTrustee":
        return DecryptingTrustee(
            group=group,
            guardian_id=state["guardian_id"],
            x_coordinate=state["x_coordinate"],
            secret_key=group.int_to_q(state["secret_key"]),
            received_shares={
                gid: group.int_to_q(v)
                for gid, v in state["received_shares"].items()},
            public_commitments={
                gid: [ElementModP(v, group) for v in ks]
                for gid, ks in state["public_commitments"].items()},
            own_commitments=[ElementModP(v, group)
                             for v in state["own_commitments"]],
        )


def read_trustee(group: GroupContext, path: str) -> DecryptingTrustee:
    """Mirror of the reference's [ext] ``readTrustee(group, file)``
    (RunRemoteDecryptingTrustee.java:90)."""
    with open(path) as f:
        return DecryptingTrustee.from_state(group, json.load(f))

"""Decryption-side trustee interface + result types.

Mirrors the reference's [ext] ``DecryptingTrusteeIF`` surface
(``id, xCoordinate, electionPublicKey, directDecrypt, compensatedDecrypt`` —
reference: src/main/java/electionguard/decrypt/RemoteDecryptingTrusteeProxy.java:33-115)
so the coordinator's combine logic is location-transparent: in-process
trustees, gRPC proxies, and the TPU batch backend all implement it.

Requests are *batched*: one call covers a whole tally's selections, exactly
the reference's batch-rpc shape (repeated ElGamalCiphertext —
src/main/proto/decrypting_trustee_rpc.proto:17,33).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol, Sequence, Union

from electionguard_tpu.core.group import ElementModP, ElementModQ
from electionguard_tpu.crypto.chaum_pedersen import GenericChaumPedersenProof
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
from electionguard_tpu.keyceremony.interface import Result


@dataclass(frozen=True)
class DirectDecryptionAndProof:
    """Mᵢ = A^{sᵢ} plus the Chaum-Pedersen proof of correct decryption
    (reference [ext] DirectDecryptionAndProof,
    RunRemoteDecryptingTrustee.java:210-215)."""

    partial_decryption: ElementModP
    proof: GenericChaumPedersenProof


@dataclass(frozen=True)
class CompensatedDecryptionAndProof:
    """Mᵢ,ℓ = A^{P_i(ℓ)} plus proof plus the recovered public key share
    g^{P_i(ℓ)} (reference [ext] CompensatedDecryptionAndProof,
    RunRemoteDecryptingTrustee.java:249-255)."""

    partial_decryption: ElementModP
    proof: GenericChaumPedersenProof
    recovered_public_key_share: ElementModP


class DecryptingTrusteeIF(Protocol):
    @property
    def id(self) -> str: ...

    @property
    def x_coordinate(self) -> int: ...

    @property
    def election_public_key(self) -> ElementModP: ...

    def direct_decrypt(
            self, texts: Sequence[ElGamalCiphertext],
            extended_base_hash: ElementModQ,
    ) -> Union[list[DirectDecryptionAndProof], Result]: ...

    def compensated_decrypt(
            self, missing_guardian_id: str,
            texts: Sequence[ElGamalCiphertext],
            extended_base_hash: ElementModQ,
    ) -> Union[list[CompensatedDecryptionAndProof], Result]: ...

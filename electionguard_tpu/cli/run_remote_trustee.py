"""Key ceremony guardian binary.

Mirror of the reference's ``RunRemoteTrustee``
(src/main/java/electionguard/keyceremony/RunRemoteTrustee.java:33-361):
binds a free port, registers with the coordinator (which assigns the
x-coordinate and quorum), serves the trustee rpcs, and blocks until the
coordinator calls finish.

Flags mirror the reference (:37-52): -name -port -serverPort -out.
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (add_group_flag, resolve_group,
                                          setup_logging)
from electionguard_tpu.remote.keyceremony_remote import KeyCeremonyTrusteeServer


def main(argv=None) -> int:
    log = setup_logging("RunRemoteTrustee")
    ap = argparse.ArgumentParser("RunRemoteTrustee")
    ap.add_argument("-name", required=True, help="guardian id")
    ap.add_argument("-port", type=int, default=0,
                    help="listen port (0 = random free port)")
    ap.add_argument("-serverPort", dest="server_port", type=int,
                    default=17111, help="coordinator port")
    ap.add_argument("-serverHost", dest="server_host", default="localhost")
    ap.add_argument("-out", dest="output", default=None,
                    help="default dir for saveState")
    ap.add_argument("-resumeFile", dest="resume_file", default=None,
                    help="mid-ceremony checkpoint file: written after "
                         "every mutating rpc; a relaunch pointed at an "
                         "existing file resumes the ceremony in place "
                         "(same port, same registration). Holds the "
                         "secret polynomial — protect like the trustee "
                         "state file")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    server = KeyCeremonyTrusteeServer(
        group, args.name,
        f"{args.server_host}:{args.server_port}",
        out_dir=args.output, port=args.port,
        resume_file=args.resume_file)
    log.info("trustee %s serving on %s (x=%d, quorum=%d)", args.name,
             server.url, server.x_coordinate, server.quorum)
    ok = server.wait_until_finished()
    log.info("trustee %s finished: all_ok=%s", args.name, ok)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

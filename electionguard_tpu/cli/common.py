"""Shared CLI plumbing: flags, logging, manifest loading.

The reference uses JCommander @Parameter flags per binary (SURVEY.md §5.6);
we mirror the flag names (-in, -out, -nguardians, ...) with argparse.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time

from electionguard_tpu.ballot.manifest import Manifest, validate_manifest
from electionguard_tpu.core.group import GroupContext, production_group, tiny_group
from electionguard_tpu.utils import enable_compile_cache

enable_compile_cache()


def setup_logging(name: str) -> logging.Logger:
    logging.basicConfig(
        level=os.environ.get("EGTPU_LOG", "INFO"),
        format="%(asctime)s [%(levelname)s] %(name)s: %(message)s",
        stream=sys.stdout)
    log = logging.getLogger(name)
    # one hook lights up the whole observability surface in every binary:
    # EGTPU_OBS_TRACE (spans), EGTPU_OBS_HTTP (Prometheus endpoint),
    # EGTPU_OBS_LOG (structured JSONL mirror) — all off by default
    from electionguard_tpu import obs
    info = obs.init_from_env()
    if info:
        log.info("observability: %s", " ".join(
            f"{k}={v}" for k, v in sorted(info.items())))
    return log


def add_group_flag(ap: argparse.ArgumentParser):
    ap.add_argument("-group", choices=["production", "tiny"],
                    default="production",
                    help="group context (tiny = fast 64-bit test group)")


def resolve_group(args) -> GroupContext:
    return tiny_group() if args.group == "tiny" else production_group()


def load_manifest(input_dir: str) -> Manifest:
    path = os.path.join(input_dir, "manifest.json")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no manifest.json in {input_dir}")
    with open(path) as f:
        manifest = Manifest.from_json(f.read())
    msgs = validate_manifest(manifest)
    if msgs.has_errors():
        # fail fast before any ceremony starts, like the reference
        # (RunRemoteKeyCeremony.java:107-112)
        raise ValueError(f"manifest validation failed:\n{msgs}")
    return manifest


class Stopwatch:
    """Per-phase wall-clock timing, mirroring the reference's Guava
    Stopwatch prints (SURVEY.md §5.1)."""

    def __init__(self):
        self.t0 = time.time()

    def elapsed(self) -> float:
        return time.time() - self.t0

    def took(self, what: str, n: int = 0) -> str:
        dt = self.elapsed()
        per = f" ({dt / n:.3f}s each)" if n else ""
        return f"{what} took {dt:.2f}s{per}"

"""Fabric router binary: the fleet's one front door.

Serves the ``BallotEncryptionService`` surface (clients point here
unchanged) and ``FabricRegistrationService`` for the workers' reverse
dial.  Requests fan out to the least-loaded live worker; membership is
driven by the background health poll (eviction after
``EGTPU_FABRIC_EVICT_AFTER`` consecutive misses, readmission on the next
success).  No record is written here — each worker publishes its own
shard record; ``tools/merge_record.py`` (or ``workflow/e2e.py
-fabricWorkers``) folds them into the one verifiable merged record.

Run:  python -m electionguard_tpu.cli.run_router -port 17710 \
          -minWorkers 2 -group tiny
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)


def main(argv=None) -> int:
    log = setup_logging("RunRouter")
    ap = argparse.ArgumentParser("RunRouter")
    ap.add_argument("-port", type=int, default=17710,
                    help="front-door + registration gRPC port "
                         "(0 = pick a free one)")
    ap.add_argument("-minWorkers", dest="min_workers", type=int, default=0,
                    help="block startup until this many workers are LIVE "
                         "(registered and health-checked); 0 = serve "
                         "immediately")
    ap.add_argument("-registrationTimeout", dest="reg_timeout",
                    type=float, default=300.0,
                    help="-minWorkers wait bound, seconds")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    from electionguard_tpu.fabric.router import EncryptionRouter
    sw = Stopwatch()
    router = EncryptionRouter(group, port=args.port)
    log.info("router front door on port %d (startup took %.2fs)",
             router.port, sw.elapsed())
    if args.min_workers:
        if not router.wait_for_workers(args.min_workers,
                                       timeout=args.reg_timeout, live=True):
            log.error("only %d of %d workers live within %.0fs: %s",
                      sum(1 for s in router.snapshot() if s["live"]),
                      args.min_workers, args.reg_timeout, router.snapshot())
            router.shutdown()
            return 1
        log.info("%d workers live; routing", args.min_workers)

    stop = threading.Event()

    def _on_signal(signum, frame):
        log.info("signal %d: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    stop.wait()
    for s in router.snapshot():
        log.info("shard %d (%s): forwarded=%d requeued=%d live=%s",
                 s["shard_id"], s["worker_id"], s["forwarded"],
                 s["requeued"], s["live"])
    router.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Batch encryption binary (workflow phase 2).

Mirror of the reference's [ext] ``batchEncryption(group, inDir, outDir,
ballotsDir, invalidDir, fixedNonces, nthreads, createdBy, check)``
(call site: RunRemoteWorkflowTest.java:140) — the 11-thread CPU pool is
replaced by the TPU batch pipeline.
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.publish.publisher import Consumer, Publisher
from electionguard_tpu.utils import maybe_profile


def main(argv=None) -> int:
    log = setup_logging("RunBatchEncryption")
    ap = argparse.ArgumentParser("RunBatchEncryption")
    ap.add_argument("-in", dest="input", required=True,
                    help="record dir with election_initialized.pb")
    ap.add_argument("-ballots", dest="ballots", required=True,
                    help="dir of plaintext ballot JSON files")
    ap.add_argument("-out", dest="output", required=True)
    ap.add_argument("-invalidDir", dest="invalid_dir", default=None)
    ap.add_argument("-fixedNonces", dest="fixed_nonces", action="store_true",
                    help="derive nonces deterministically from a fixed seed")
    ap.add_argument("-batchSize", dest="batch_size", type=int, default=8192)
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    consumer = Consumer(args.input, group)
    init = consumer.read_election_initialized()
    publisher = Publisher(args.output)

    import glob
    import os

    from electionguard_tpu.ballot.plaintext import PlaintextBallot
    ballots = []
    for path in sorted(glob.glob(os.path.join(args.ballots, "*.json"))):
        with open(path) as f:
            ballots.append(PlaintextBallot.from_json(f.read()))
    if not ballots:
        log.error("no plaintext ballots found under %s", args.ballots)
        return 2

    sw = Stopwatch()
    enc = BatchEncryptor(init, group)
    seed = group.int_to_q(42) if args.fixed_nonces else group.rand_q()
    # chunk the ballot stream so device/host memory stays bounded; the
    # confirmation-code chain continues across chunks via code_seed
    encrypted, invalid = [], []
    code_seed = None
    with maybe_profile("encrypt"):
        for lo in range(0, len(ballots), args.batch_size):
            chunk = ballots[lo:lo + args.batch_size]
            enc_chunk, inv_chunk = enc.encrypt_ballots(
                chunk, seed=seed, code_seed=code_seed)
            encrypted.extend(enc_chunk)
            invalid.extend(inv_chunk)
            if enc_chunk:
                code_seed = enc_chunk[-1].code
    n = publisher.write_encrypted_ballots(encrypted)
    if invalid:
        inv_pub = Publisher(args.invalid_dir) if args.invalid_dir else publisher
        for b, reason in invalid:
            log.warning("invalid ballot %s: %s", b.ballot_id, reason)
            inv_pub.write_plaintext_ballot("invalid_ballots", b)
    log.info("%s; %d encrypted, %d invalid",
             sw.took("encryption", max(n, 1)), n, len(invalid))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Batch encryption binary (workflow phase 2).

Mirror of the reference's [ext] ``batchEncryption(group, inDir, outDir,
ballotsDir, invalidDir, fixedNonces, nthreads, createdBy, check)``
(call site: RunRemoteWorkflowTest.java:140) — the 11-thread CPU pool is
replaced by the TPU batch pipeline.
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.publish.publisher import Consumer, Publisher
from electionguard_tpu.utils import maybe_profile


def main(argv=None) -> int:
    log = setup_logging("RunBatchEncryption")
    ap = argparse.ArgumentParser("RunBatchEncryption")
    ap.add_argument("-in", dest="input", required=True,
                    help="record dir with election_initialized.pb")
    ap.add_argument("-ballots", dest="ballots", required=True,
                    help="dir of plaintext ballot JSON files")
    ap.add_argument("-out", dest="output", required=True)
    ap.add_argument("-invalidDir", dest="invalid_dir", default=None)
    ap.add_argument("-fixedNonces", dest="fixed_nonces", action="store_true",
                    help="derive nonces deterministically from a fixed seed")
    ap.add_argument("-batchSize", dest="batch_size", type=int, default=8192)
    ap.add_argument("-spoilEvery", dest="spoil_every", type=int, default=0,
                    help="mark every Nth ballot SPOILED instead of CAST "
                         "(0 = none); spoiled ballots are excluded from the "
                         "tally and decrypted individually when the "
                         "decryptor runs with -decryptSpoiled")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    consumer = Consumer(args.input, group)
    init = consumer.read_election_initialized()
    publisher = Publisher(args.output)

    import glob
    import os

    from electionguard_tpu.ballot.plaintext import PlaintextBallot
    paths = sorted(glob.glob(os.path.join(args.ballots, "*.json")))
    if not paths:
        log.error("no plaintext ballots found under %s", args.ballots)
        return 2

    sw = Stopwatch()
    enc = BatchEncryptor(init, group)
    seed = group.int_to_q(42) if args.fixed_nonces else group.rand_q()
    # fully streaming: plaintext ballots are loaded, encrypted, written,
    # and dropped one chunk at a time — host memory stays O(batchSize).
    # The confirmation-code chain continues across chunks via code_seed;
    # nonces are keyed by ballot identity, so chunking is nonce-safe.
    n_invalid = n_spoiled = 0
    code_seed = None
    inv_pub = Publisher(args.invalid_dir) if args.invalid_dir else publisher
    with maybe_profile("encrypt"), \
            publisher.open_encrypted_ballots() as stream:
        for lo in range(0, len(paths), args.batch_size):
            chunk = []
            for path in paths[lo:lo + args.batch_size]:
                with open(path) as f:
                    chunk.append(PlaintextBallot.from_json(f.read()))
            spoiled_ids = ({b.ballot_id for i, b in enumerate(chunk)
                            if (lo + i + 1) % args.spoil_every == 0}
                           if args.spoil_every > 0 else set())
            enc_chunk, inv_chunk = enc.encrypt_ballots(
                chunk, seed=seed, code_seed=code_seed,
                ballot_index_base=lo, spoiled_ids=spoiled_ids)
            for b in enc_chunk:
                stream.write(b)
                n_spoiled += b.ballot_id in spoiled_ids
            for b, reason in inv_chunk:
                log.warning("invalid ballot %s: %s", b.ballot_id, reason)
                inv_pub.write_plaintext_ballot("invalid_ballots", b)
                n_invalid += 1
            if enc_chunk:
                code_seed = enc_chunk[-1].code
        n = stream.n
    if args.spoil_every:
        log.info("spoiled %d of %d ballots", n_spoiled, n)
    log.info("%s; %d encrypted, %d invalid",
             sw.took("encryption", max(n, 1)), n, n_invalid)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Mixnet binary: run K sequential re-encryption mix stages over the
record's cast ballots (between tally accumulation and decryption in the
workflow — the ballot-anonymization stage of the egk-mixnet ecosystem).

Each stage shuffles + re-encrypts all cast ballots' ciphertext rows and
publishes the output rows plus a Terelius–Wikström proof of shuffle as
``mix_stage_NNN.pb`` in the record dir; ``run_verifier`` then checks the
whole cascade (V15 family) as part of record verification.

Run:  python -m electionguard_tpu.cli.run_mixnet -in record -out record \
          -stages 2 -group tiny
"""

from __future__ import annotations

import argparse
import sys
import time

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.mixnet.shuffle import Shuffler
from electionguard_tpu.mixnet.stage import rows_from_ballots, run_stage
from electionguard_tpu.publish.publisher import Consumer, Publisher
from electionguard_tpu.utils import maybe_profile


def main(argv=None) -> int:
    log = setup_logging("RunMixnet")
    ap = argparse.ArgumentParser("RunMixnet")
    ap.add_argument("-in", dest="input", required=True,
                    help="record dir with encrypted_ballots.pb")
    ap.add_argument("-out", dest="output", required=True)
    ap.add_argument("-stages", type=int, default=2,
                    help="number of sequential mix stages")
    ap.add_argument("-seed", default=None,
                    help="pin the mix randomness (tests/reproducible "
                         "runs); omit for fresh secret randomness")
    add_group_flag(ap)
    args = ap.parse_args(argv)
    if args.stages < 1:
        log.error("-stages must be >= 1")
        return 1

    group = resolve_group(args)
    consumer = Consumer(args.input, group)
    init = consumer.read_election_initialized()
    publisher = Publisher(args.output)

    sw = Stopwatch()
    pads, datas = rows_from_ballots(consumer.iterate_encrypted_ballots())
    if not pads:
        log.error("no cast ballots in %s — nothing to mix", args.input)
        return 1
    n, w = len(pads), len(pads[0])
    log.info("mixing %d cast ballots x %d ciphertexts through %d stages",
             n, w, args.stages)

    shuffler = Shuffler(group, init.joint_public_key.value)
    qbar = init.extended_base_hash
    with maybe_profile("mixnet"):
        for k in range(args.stages):
            t0 = time.time()
            seed = (f"{args.seed}-stage-{k}".encode()
                    if args.seed is not None else None)
            stage = run_stage(group, init.joint_public_key.value, qbar,
                              k, pads, datas, seed=seed, shuffler=shuffler)
            path = publisher.write_mix_stage(group, stage)
            dt = time.time() - t0
            log.info("stage %d: shuffled+proved %d rows in %.2fs "
                     "(%.1f rows/s) -> %s", k, n, dt, n / max(dt, 1e-9),
                     path)
            pads, datas = stage.pads, stage.datas

    log.info("%s; %d stages over %d ballots published",
             sw.took("mixnet", max(n * args.stages, 1)), args.stages, n)
    return 0


if __name__ == "__main__":
    sys.exit(main())

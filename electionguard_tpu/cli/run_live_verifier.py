"""Live verifier binary: audit the election record WHILE it is written.

Tails the record dir's framed ballot stream + admission journal
(verify/live), verifying chunk-at-a-time and serving the commitment
ledger on a BulletinBoardService port mid-election.  When the terminal
artifacts land (``decryption_result.pb``) and the stream goes quiet,
it drains the residual tail, runs the record-level checks, writes a
machine-readable audit artifact (``-audit``), and exits 0 green /
1 red — the same verdict contract as ``run_verifier``, reached while
the election was still running.

SIGKILL-safe: the checkpoint in the record dir makes a relaunched
instance resume at the last committed chunk with an identical final
verdict and commitment root (tests/test_live_verify.py pins this).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.utils import knobs


def main(argv=None) -> int:
    log = setup_logging("RunLiveVerifier")
    ap = argparse.ArgumentParser("RunLiveVerifier")
    ap.add_argument("-in", dest="input", required=True,
                    help="election record dir (may still be growing)")
    ap.add_argument("-port", type=int, default=0,
                    help="BulletinBoardService port (0 = ephemeral)")
    ap.add_argument("-chunk", type=int,
                    default=knobs.get_int("EGTPU_LIVE_CHUNK"),
                    help="ballot frames per verified/committed chunk")
    ap.add_argument("-poll", type=float,
                    default=knobs.get_float("EGTPU_LIVE_POLL_S"),
                    help="tail poll period, seconds")
    ap.add_argument("-audit", default=None,
                    help="write the final audit JSON here "
                         "(default <record>/live_audit.json)")
    ap.add_argument("-timeout", type=float, default=0,
                    help="give up after this many seconds of tailing "
                         "(0 = wait forever for the terminal artifacts)")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    from electionguard_tpu.verify.live import BulletinBoard, LiveVerifier

    # the record dir must hold election_initialized.pb before we can
    # fold anything — wait for the producing workflow's phase 1
    init_path = os.path.join(args.input, "election_initialized.pb")
    t0 = time.monotonic()
    while not os.path.exists(init_path):
        if args.timeout and time.monotonic() - t0 > args.timeout:
            log.error("timed out waiting for %s", init_path)
            return 1
        time.sleep(args.poll)

    live = LiveVerifier(args.input, group, chunk=args.chunk)
    board = BulletinBoard(live, port=args.port)
    log.info("bulletin board on port %d (chunk=%d poll=%.2fs, resumed "
             "at frame %d)", board.port, args.chunk, args.poll,
             live.verified_frames)
    print(f"bulletin board port: {board.port}", flush=True)

    decr_path = os.path.join(args.input, "decryption_result.pb")
    sw = Stopwatch()
    residual_frames = None
    quiet = 0
    try:
        while True:
            with board._lock:
                n = live.poll()
            if n:
                s = live.audit_state()
                log.info("committed %d chunk(s): %d/%d frames verified, "
                         "lag %d", n, s["frames_verified"],
                         s["frames_published"], s["audit_lag_frames"])
            # terminal condition: decryption landed and two quiet polls
            # (the producer fsyncs frames before the terminal artifact,
            # so "quiet after decryption" means the stream is closed)
            if os.path.exists(decr_path):
                if residual_frames is None:
                    # the audit-lag figure the e2e acceptance gates on:
                    # how much work was LEFT when the election ended
                    live.poll()
                    residual_frames = (live.frames_published()
                                       - live.verified_frames)
                quiet = quiet + 1 if n == 0 else 0
                if quiet >= 2:
                    break
            elif args.timeout and time.monotonic() - t0 > args.timeout:
                log.error("timed out tailing %s (no decryption result "
                          "after %.0fs)", args.input, args.timeout)
                return 1
            time.sleep(args.poll)

        total = max(live.frames_published(), 1)
        drain_sw = Stopwatch()
        with board._lock:
            res = live.finalize()
        residual_s = drain_sw.elapsed()
    finally:
        board.shutdown()

    audit = dict(live.audit_state())
    audit.update({
        "root": live.ledger.root().hex(),
        "chain_head": live.ledger.head.hex(),
        "n_chunks": len(live.ledger.chunks),
        "residual_frames_at_close": residual_frames or 0,
        "residual_fraction": (residual_frames or 0) / total,
        "residual_verify_s": residual_s,
    })
    audit_path = args.audit or os.path.join(args.input,
                                            "live_audit.json")
    with open(audit_path, "w") as f:
        json.dump(audit, f, indent=2)
    print(res.summary())
    log.info("%s; ok=%s root=%s residual=%.1f%% (%d frames, %.2fs "
             "drain)", sw.took("live verification",
                               max(live.verified_frames, 1)),
             res.ok, live.ledger.root().hex()[:16],
             100.0 * audit["residual_fraction"],
             audit["residual_frames_at_close"], residual_s)
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())

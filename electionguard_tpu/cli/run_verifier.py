"""Election-record verifier binary (workflow phase 5).

Mirror of the reference's [ext] ``Verifier(record, nthreads).verify()``
(call site: RunRemoteWorkflowTest.java:179-182) — the final ground truth of
the workflow.
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.publish.publisher import Consumer
from electionguard_tpu.verify.verifier import Verifier
from electionguard_tpu.utils import maybe_profile


def main(argv=None) -> int:
    log = setup_logging("RunVerifier")
    ap = argparse.ArgumentParser("RunVerifier")
    ap.add_argument("-in", dest="input", required=True,
                    help="election record dir")
    ap.add_argument("-chunkSize", dest="chunk_size", type=int, default=4096,
                    help="ballots resident/dispatched at once (streaming)")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    n_seen = 0
    try:
        consumer = Consumer(args.input, group)
        record = ElectionRecord(consumer.read_election_initialized())
        if consumer.has_tally_result():
            record.tally_result = consumer.read_tally_result()
        if consumer.has_decryption_result():
            record.decryption_result = consumer.read_decryption_result()
        record.spoiled_ballot_tallies = list(
            consumer.iterate_spoiled_ballot_tallies())

        def counting_ballots():
            nonlocal n_seen
            for b in consumer.iterate_encrypted_ballots():
                n_seen += 1
                yield b

        # lazy ballot stream: O(chunk) host residency at any record size
        record.encrypted_ballots = counting_ballots()
    except Exception as e:  # corrupt/truncated record is a verification FAIL
        log.error("record unreadable (corrupt or truncated): %s", e)
        return 1

    sw = Stopwatch()
    try:
        with maybe_profile("verify"):
            res = Verifier(record, group,
                           chunk_size=args.chunk_size).verify()
    except Exception as e:  # truncated ballot stream surfaces mid-iteration
        log.error("record unreadable (corrupt or truncated): %s", e)
        return 1
    print(res.summary())
    log.info("%s; ok=%s",
             sw.took("verification", max(n_seen, 1)), res.ok)
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Election-record verifier binary (workflow phase 5).

Mirror of the reference's [ext] ``Verifier(record, nthreads).verify()``
(call site: RunRemoteWorkflowTest.java:179-182) — the final ground truth of
the workflow.
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.publish.publisher import (Consumer,
                                                 election_record_from_consumer)
from electionguard_tpu.verify.verifier import Verifier
from electionguard_tpu.utils import maybe_profile


def main(argv=None) -> int:
    log = setup_logging("RunVerifier")
    ap = argparse.ArgumentParser("RunVerifier")
    ap.add_argument("-in", dest="input", required=True,
                    help="election record dir")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    try:
        record = election_record_from_consumer(Consumer(args.input, group))
    except Exception as e:  # corrupt/truncated record is a verification FAIL
        log.error("record unreadable (corrupt or truncated): %s", e)
        return 1

    sw = Stopwatch()
    with maybe_profile("verify"):
        res = Verifier(record, group).verify()
    print(res.summary())
    log.info("%s; ok=%s",
             sw.took("verification", max(len(record.encrypted_ballots), 1)),
             res.ok)
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Election-record verifier binary (workflow phase 5).

Mirror of the reference's [ext] ``Verifier(record, nthreads).verify()``
(call site: RunRemoteWorkflowTest.java:179-182) — the final ground truth of
the workflow.  ``-feeders N`` replaces the reference's 11-thread pool
with N feeder PROCESSES over disjoint file-offset slices of the framed
ballot stream (README §Scaling model): each feeder streams + verifies
its slice (V4/V5/V6 and the V7/V13 bookkeeping), the parent merges the
partial aggregates (the tally product tree is associative) and runs the
record-level checks once.  V6 chain continuity across a slice boundary
needs only the boundary ballot's 32-byte code, which the parent hands
to the next feeder.
"""

from __future__ import annotations

import argparse
import os
import sys

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.publish.publisher import Consumer
from electionguard_tpu.verify.verifier import Verifier
from electionguard_tpu.utils import maybe_profile


def _feeder_worker(wargs):
    """One feeder process: verify a contiguous ballot-stream slice.
    Top-level (picklable) for multiprocessing spawn; returns the
    (VerificationResult, _BallotAggregates) partial pair.

    Feeders run their device math on the HOST platform (CPU) by default:
    N spawned processes must not contend for one accelerator.  The
    platform is pinned in the environment the spawn Pool's children
    INHERIT (see _verify_with_feeders) — setting it here would come too
    late, because the import chain (and on some machines a site hook)
    pulls jax in before this body runs.  On a machine with per-process
    device assignment configured externally (e.g. one chip per feeder
    via TPU_VISIBLE_DEVICES), set EGTPU_FEEDER_PLATFORM to override."""
    (record_dir, group_name, offset, count, prev_code, chunk_size) = wargs
    import argparse as _ap
    ns = _ap.Namespace(group=group_name)
    group = resolve_group(ns)
    consumer = Consumer(record_dir, group)
    record = ElectionRecord(consumer.read_election_initialized())
    # shard manifests flip the V6 bookkeeping into segment mode — every
    # feeder must agree on which mode the record is in
    record.shard_manifests = consumer.read_shard_manifests()
    v = Verifier(record, group, chunk_size=chunk_size)
    from electionguard_tpu.verify.verifier import (VerificationResult,
                                                   _BallotAggregates)
    res, agg = VerificationResult(), _BallotAggregates()
    v.verify_ballots_partial(
        consumer.iterate_encrypted_ballots_slice(offset, count),
        res, agg, prev_code=prev_code)
    return res, agg


def _verify_with_feeders(args, group, consumer, record, log,
                         mix_input_fn=None):
    """Fan the ballot stream out over ``args.feeders`` processes."""
    import multiprocessing as mp

    shards = consumer.ballot_shards(args.feeders)
    if not shards:  # empty/absent ballot stream: nothing to fan out
        v = Verifier(record, group, chunk_size=args.chunk_size,
                     mix_input_fn=mix_input_fn)
        from electionguard_tpu.verify.verifier import (VerificationResult,
                                                       _BallotAggregates)
        return v.finalize(VerificationResult(), _BallotAggregates()), 0
    # boundary codes: the parent decodes ONE ballot per interior boundary
    prev_codes = [None]
    for _, _, last_off in shards[:-1]:
        last = next(consumer.iterate_encrypted_ballots_slice(last_off, 1))
        prev_codes.append(last.code)
    n_ballots = sum(cnt for _, cnt, _ in shards)
    wargs = [(args.input, args.group, off, cnt, prev_codes[i],
              args.chunk_size)
             for i, (off, cnt, _) in enumerate(shards)]
    # pin the feeder platform (and scrub tunnel env for the CPU default)
    # in the PARENT env before the spawn Pool exists, so children inherit
    # it at interpreter startup — an assignment inside the worker body is
    # too late, jax is already imported there (ADVICE r5)
    from electionguard_tpu.utils.platform import pinned_child_platform
    ctx = mp.get_context("spawn")
    with pinned_child_platform(
            os.environ.get("EGTPU_FEEDER_PLATFORM", "cpu")):
        with ctx.Pool(processes=len(wargs)) as pool:
            parts = pool.map(_feeder_worker, wargs)
    res, agg = Verifier.merge_partials(parts)
    log.info("merged %d feeder partials (%d ballots)", len(parts),
             n_ballots)
    return Verifier(record, group, chunk_size=args.chunk_size,
                    mix_input_fn=mix_input_fn).finalize(res, agg), \
        n_ballots


def main(argv=None) -> int:
    log = setup_logging("RunVerifier")
    ap = argparse.ArgumentParser("RunVerifier")
    ap.add_argument("-in", dest="input", required=True,
                    help="election record dir")
    ap.add_argument("-chunkSize", dest="chunk_size", type=int, default=4096,
                    help="ballots resident/dispatched at once (streaming)")
    ap.add_argument("-feeders", type=int, default=1,
                    help="verify the ballot stream with N feeder "
                         "processes over disjoint file-offset slices "
                         "(the reference's 11-thread pool, as processes)")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    n_seen = 0
    try:
        consumer = Consumer(args.input, group)
        record = ElectionRecord(consumer.read_election_initialized())
        if consumer.has_tally_result():
            record.tally_result = consumer.read_tally_result()
        if consumer.has_decryption_result():
            record.decryption_result = consumer.read_decryption_result()
        record.spoiled_ballot_tallies = list(
            consumer.iterate_spoiled_ballot_tallies())
        record.shard_manifests = consumer.read_shard_manifests()
        if record.shard_manifests:
            log.info("record carries %d shard manifests (merged fleet "
                     "record)", len(record.shard_manifests))
        if consumer.has_mix_stages():
            # mix stages are O(cast ballots) resident by design — the
            # cascade's working set IS the row matrix
            record.mix_stages = consumer.read_mix_stages()
            log.info("record carries %d mix stages",
                     len(record.mix_stages))

        def counting_ballots():
            nonlocal n_seen
            for b in consumer.iterate_encrypted_ballots():
                n_seen += 1
                yield b

        # lazy ballot stream: O(chunk) host residency at any record size
        record.encrypted_ballots = counting_ballots()
    except Exception as e:  # corrupt/truncated record is a verification FAIL
        log.error("record unreadable (corrupt or truncated): %s", e)
        return 1

    def mix_input_fn():
        # second streaming pass: the mix plane needs the cast ballots'
        # ciphertext rows resident (same O(N) as one published stage)
        from electionguard_tpu.mixnet.verify_mix import rows_from_ballots
        return rows_from_ballots(consumer.iterate_encrypted_ballots())

    sw = Stopwatch()
    try:
        with maybe_profile("verify"):
            if args.feeders > 1:
                res, n_seen = _verify_with_feeders(args, group, consumer,
                                                   record, log,
                                                   mix_input_fn)
            else:
                res = Verifier(record, group, chunk_size=args.chunk_size,
                               mix_input_fn=mix_input_fn).verify()
    except Exception as e:  # truncated ballot stream surfaces mid-iteration
        log.error("record unreadable (corrupt or truncated): %s", e)
        return 1
    print(res.summary())
    log.info("%s; ok=%s",
             sw.took("verification", max(n_seen, 1)), res.ok)
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Decryption guardian binary.

Mirror of the reference's ``RunRemoteDecryptingTrustee``
(src/main/java/electionguard/decrypt/RunRemoteDecryptingTrustee.java:28-279):
loads the serialized trustee from its ceremony state file, registers with
the coordinator (bringing its own identity: id, url, x, public key), serves
batch direct/compensated decryption, and exits when the coordinator calls
finish.

Flags mirror the reference (:32-44): -trusteeFile -port -serverPort.
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (add_group_flag, resolve_group,
                                          setup_logging)
from electionguard_tpu.decrypt.trustee import read_trustee
from electionguard_tpu.remote.decrypting_remote import DecryptingTrusteeServer


def main(argv=None) -> int:
    log = setup_logging("RunRemoteDecryptingTrustee")
    ap = argparse.ArgumentParser("RunRemoteDecryptingTrustee")
    ap.add_argument("-trusteeFile", dest="trustee_file", required=True)
    ap.add_argument("-port", type=int, default=0)
    ap.add_argument("-serverPort", dest="server_port", type=int,
                    default=17711)
    ap.add_argument("-serverHost", dest="server_host", default="localhost")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    trustee = read_trustee(group, args.trustee_file)
    server = DecryptingTrusteeServer(
        group, trustee, f"{args.server_host}:{args.server_port}",
        port=args.port)
    log.info("decrypting trustee %s serving on %s", trustee.id, server.url)
    ok = server.wait_until_finished()
    log.info("decrypting trustee %s finished: all_ok=%s", trustee.id, ok)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

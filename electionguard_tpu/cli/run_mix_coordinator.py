"""Federated mix-coordinator binary.

Waits for ``-servers`` mix-server registrations (start at least
``-stages``; extras are hot spares), then drives the cascade over the
record's cast ballots, verifying every stage before publishing it
(mixfed/coordinator.py).  The published artifact is the standard
``mix_stage_NNN.pb`` set, verifiable by ``run_verifier`` exactly like a
single-process ``run_mixnet`` record.

Run:  python -m electionguard_tpu.cli.run_mix_coordinator -in record \
          -out record -stages 3 -servers 3 -port 17141 -group tiny
"""

from __future__ import annotations

import argparse
import sys
import time

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.mixfed.coordinator import MixCoordinator, MixFedError
from electionguard_tpu.mixnet.stage import rows_from_ballots
from electionguard_tpu.publish.publisher import Consumer
from electionguard_tpu.utils import maybe_profile


def main(argv=None) -> int:
    log = setup_logging("RunMixCoordinator")
    ap = argparse.ArgumentParser("RunMixCoordinator")
    ap.add_argument("-in", dest="input", required=True,
                    help="record dir with encrypted_ballots.pb")
    ap.add_argument("-out", dest="output", required=True)
    ap.add_argument("-stages", type=int, default=2,
                    help="number of sequential mix stages")
    ap.add_argument("-servers", dest="servers", type=int, default=0,
                    help="mix-server registrations to wait for "
                         "(default: -stages; start more for hot spares)")
    ap.add_argument("-port", type=int, default=17141,
                    help="registration service port")
    ap.add_argument("-registrationTimeout", dest="reg_timeout",
                    type=float, default=300.0)
    ap.add_argument("-checkpointFile", dest="checkpoint_file", default=None,
                    help="journal of the last verified stage; a relaunch "
                         "pointed at the same file (and -out) resumes at "
                         "the first unpublished stage")
    add_group_flag(ap)
    args = ap.parse_args(argv)
    if args.stages < 1:
        log.error("-stages must be >= 1")
        return 1
    n_servers = args.servers or args.stages

    group = resolve_group(args)
    consumer = Consumer(args.input, group)
    init = consumer.read_election_initialized()

    sw = Stopwatch()
    pads, datas = rows_from_ballots(consumer.iterate_encrypted_ballots())
    if not pads:
        log.error("no cast ballots in %s — nothing to mix", args.input)
        return 1
    log.info("federated mix: %d cast ballots x %d ciphertexts through "
             "%d stages over %d server(s)", len(pads), len(pads[0]),
             args.stages, n_servers)

    coord = MixCoordinator(group, args.output, port=args.port,
                           checkpoint_file=args.checkpoint_file)
    try:
        if not coord.wait_for_servers(n_servers, timeout=args.reg_timeout):
            log.error("only %d of %d mix servers registered within %.0fs",
                      coord.ready(), n_servers, args.reg_timeout)
            return 1
        t0 = time.time()
        with maybe_profile("mixfed"):
            published = coord.run_mix(init.joint_public_key.value,
                                      init.extended_base_hash,
                                      args.stages, pads, datas)
        dt = time.time() - t0
        log.info("%d mix stages took %.2fs (%.2f stages/s)",
                 published, dt, published / max(dt, 1e-9))
    except MixFedError as e:
        log.error("federated mix FAILED: %s", e)
        coord.shutdown(all_ok=False)
        return 1
    coord.shutdown(all_ok=True)
    log.info("%s; %d stages published", sw.took(
        "mixfed", max(len(pads) * args.stages, 1)), args.stages)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Obs collector binary: the run's telemetry sink and fleet SLO engine.

Starts the ``ObsCollectorService`` gRPC server (obs/collector.py) plus
the fleet-merged Prometheus ``/metrics`` endpoint, then blocks until a
``finish`` rpc (the workflow driver sends one at the end of the run) or
SIGTERM.  Every other process of the run points at it with
``EGTPU_OBS_COLLECTOR=<host:port>``.

Run:  python -m electionguard_tpu.cli.run_obs_collector -port 17171 \
          -metricsPort 9090 -out /tmp/run-obs
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from electionguard_tpu.cli.common import setup_logging
from electionguard_tpu.obs import collector as collector_mod
from electionguard_tpu.obs import slo


def main(argv=None) -> int:
    log = setup_logging("RunObsCollector")
    ap = argparse.ArgumentParser("RunObsCollector")
    ap.add_argument("-port", type=int, default=17171,
                    help="collector rpc port (0 = random free port)")
    ap.add_argument("-metricsPort", dest="metrics_port", type=int,
                    default=0,
                    help="fleet /metrics http port (0 = ephemeral; "
                         "-1 = disabled)")
    ap.add_argument("-out", default=".",
                    help="output dir: received spans/logs under recv/, "
                         "live timeline at trace_live.json")
    ap.add_argument("-slo", default="",
                    help="SLO config: inline JSON or @file, deep-merged "
                         "over obs.slo.DEFAULT_SLO (also EGTPU_OBS_SLO)")
    args = ap.parse_args(argv)

    config = slo.load_config(args.slo or None)
    http_port = None if args.metrics_port < 0 else args.metrics_port
    collector, server, bound, http_bound = collector_mod.serve(
        args.port, args.out, slo_config=config, http_port=http_port)
    log.info("obs collector serving on :%d; fleet scrape on :%s; "
             "out dir %s", bound, http_bound, args.out)

    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    while not done.is_set() and not collector._stop.is_set():
        done.wait(0.25)
    collector.stop()
    server.stop(grace=2.0).wait()
    report = collector.live_report
    log.info("obs collector done: %d spans from %d processes, "
             "%d slo evals, timeline %s",
             report.get("n_spans", 0), len(report.get("processes", [])),
             collector.engine.evals, collector.live_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())

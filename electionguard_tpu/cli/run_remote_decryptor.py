"""Decryption coordinator binary.

Mirror of the reference's ``RunRemoteDecryptor``
(src/main/java/electionguard/decrypt/RunRemoteDecryptor.java:55-373): loads
the encrypted tally + election init from the record, waits for
``navailable`` registrations (quorum ≤ navailable ≤ nguardians), computes
the missing-guardian list, decrypts the tally (and optionally spoiled
ballots), and publishes ``DecryptionResult``.

Flags mirror the reference (:58-77): -in -out -navailable -port
-decryptSpoiled.
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.decrypt.decryption import (Decryption,
                                                  DecryptionError,
                                                  stream_spoiled_tallies)
from electionguard_tpu.publish.election_record import DecryptionResult
from electionguard_tpu.publish.publisher import Consumer, Publisher
from electionguard_tpu.remote.decrypting_remote import DecryptionCoordinator


def main(argv=None) -> int:
    log = setup_logging("RunRemoteDecryptor")
    ap = argparse.ArgumentParser("RunRemoteDecryptor")
    ap.add_argument("-in", dest="input", required=True,
                    help="election record dir (with tally_result.pb)")
    ap.add_argument("-out", dest="output", required=True)
    ap.add_argument("-navailable", type=int, required=True)
    ap.add_argument("-port", type=int, default=17711)
    ap.add_argument("-decryptSpoiled", dest="decrypt_spoiled",
                    action="store_true")
    ap.add_argument("-chunkSize", dest="chunk_size", type=int, default=512,
                    help="spoiled ballots decrypted per trustee round trip")
    ap.add_argument("-timeout", type=float, default=300.0)
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    consumer = Consumer(args.input, group)
    tally_result = consumer.read_tally_result()
    init = tally_result.election_init
    publisher = Publisher(args.output)

    n, quorum = init.config.n_guardians, init.config.quorum
    if not (quorum <= args.navailable <= n):
        log.error("require quorum (%d) <= navailable (%d) <= nguardians (%d)",
                  quorum, args.navailable, n)
        return 2

    sw = Stopwatch()
    coord = DecryptionCoordinator(group, args.navailable, args.port)
    log.info("waiting for %d decrypting trustees on port %d ...",
             args.navailable, coord.port)
    all_ok = False
    try:
        if not coord.wait_for_registrations(args.timeout):
            log.error("timed out with %d/%d registrations",
                      coord.ready(), args.navailable)
            return 2
        coord.mark_started()
        proxies = coord.registered()
        registered = {p.id for p in proxies}
        missing = [g.guardian_id for g in init.guardians
                   if g.guardian_id not in registered]
        log.info("registered=%s missing=%s", sorted(registered), missing)

        decryption = Decryption(group, init, proxies, missing)
        decrypted = decryption.decrypt(tally_result.encrypted_tally)
        result = DecryptionResult(
            tally_result, decrypted,
            tuple(decryption.get_available_guardians()),
            {"created_by": "RunRemoteDecryptor"})
        publisher.write_decryption_result(result)

        if args.decrypt_spoiled:
            n_sp = publisher.write_spoiled_ballot_tallies(
                stream_spoiled_tallies(
                    consumer.iterate_encrypted_ballots(), decryption,
                    args.chunk_size))
            log.info("decrypted %d spoiled ballots", n_sp)

        log.info("published DecryptionResult to %s (%s)",
                 args.output, sw.took("decryption"))
        all_ok = True
        return 0
    except DecryptionError as e:
        log.error("decryption failed: %s", e)
        return 3
    finally:
        coord.shutdown(all_ok)


if __name__ == "__main__":
    sys.exit(main())

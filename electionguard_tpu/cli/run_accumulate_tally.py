"""Tally accumulation binary (workflow phase 3).

Mirror of the reference's [ext] ``runAccumulateBallots(group, inDir, outDir,
name, createdBy)`` (call site: RunRemoteWorkflowTest.java:151).
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.publish.publisher import Consumer, Publisher
from electionguard_tpu.tally.accumulate import accumulate_ballots
from electionguard_tpu.utils import maybe_profile


def main(argv=None) -> int:
    log = setup_logging("RunAccumulateTally")
    ap = argparse.ArgumentParser("RunAccumulateTally")
    ap.add_argument("-in", dest="input", required=True,
                    help="record dir with encrypted_ballots.pb")
    ap.add_argument("-out", dest="output", required=True)
    ap.add_argument("-name", default="tally")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    consumer = Consumer(args.input, group)
    init = consumer.read_election_initialized()
    publisher = Publisher(args.output)

    sw = Stopwatch()
    with maybe_profile("accumulate"):
        # lazy iterator: million-ballot records stream with O(chunk) memory
        result = accumulate_ballots(init,
                                    consumer.iterate_encrypted_ballots(),
                                    args.name,
                                    {"created_by": "RunAccumulateTally"})
    publisher.write_tally_result(result)
    n_cast = result.encrypted_tally.cast_ballot_count
    log.info("%s; %d cast ballots accumulated",
             sw.took("accumulation", max(n_cast, 1)), n_cast)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Online ballot-encryption service binary (the serving-plane analogue of
``run_batch_encryption.py``'s offline phase 2).

Reads ``election_initialized.pb`` from ``-in``, then serves
``BallotEncryptionService`` (serve/service.py) until SIGTERM/SIGINT:
plaintext ballots arrive over gRPC, the dynamic batcher aggregates them
into bucket shapes, the device-owner worker encrypts, and every
submitted ballot is appended to the growing record under ``-out``.

Graceful drain on SIGTERM: stop admitting (new requests get UNAVAILABLE,
queue-full requests were already getting RESOURCE_EXHAUSTED), flush
every admitted request through the device, close the framed ballot
stream so the partial record under ``-out`` is a valid, verifiable
election record, log the final metrics, exit 0.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          resolve_group, setup_logging)
from electionguard_tpu.publish.publisher import Consumer
from electionguard_tpu.utils import maybe_profile


def main(argv=None) -> int:
    log = setup_logging("RunEncryptionService")
    ap = argparse.ArgumentParser("RunEncryptionService")
    ap.add_argument("-in", dest="input", required=True,
                    help="record dir with election_initialized.pb")
    ap.add_argument("-out", dest="output", required=True,
                    help="record dir the growing ballot stream is "
                         "published to")
    ap.add_argument("-port", type=int, default=17711,
                    help="gRPC port (0 = pick a free one)")
    ap.add_argument("-maxBatch", dest="max_batch", type=int, default=64,
                    help="flush when this many requests are pending")
    ap.add_argument("-maxWaitMs", dest="max_wait_ms", type=float,
                    default=25.0,
                    help="flush when the oldest pending request is this "
                         "old")
    ap.add_argument("-maxQueue", dest="max_queue", type=int, default=256,
                    help="admission queue bound; beyond it requests are "
                         "rejected with RESOURCE_EXHAUSTED")
    ap.add_argument("-fixedNonces", dest="fixed_nonces",
                    action="store_true",
                    help="derive nonces deterministically from a fixed "
                         "seed (tests only)")
    ap.add_argument("-timestamp", type=int, default=None,
                    help="pin the ballot timestamp (tests/differential "
                         "runs; default: stamp each batch with "
                         "encryption time)")
    ap.add_argument("-noPrewarm", dest="no_prewarm", action="store_true",
                    help="skip the per-bucket compile prewarm at startup")
    ap.add_argument("-metricsPort", dest="metrics_port", type=int,
                    default=None,
                    help="serve Prometheus text metrics on this HTTP "
                         "port (0 = ephemeral; also via EGTPU_OBS_HTTP)")
    ap.add_argument("-router", default=None,
                    help="fabric mode: reverse-dial this router "
                         "(host:port), own one shard of the fleet's "
                         "code chain under a signed manifest")
    ap.add_argument("-workerId", dest="worker_id", default=None,
                    help="fabric: stable worker identity; a relaunch "
                         "with the same id reclaims its shard "
                         "(default: basename of -out)")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    init = Consumer(args.input, group).read_election_initialized()

    from electionguard_tpu.serve.service import EncryptionService
    seed = group.int_to_q(42) if args.fixed_nonces else None
    # fabric mode: register BEFORE the service exists — the shard id
    # decides the chain seed and the requeued-ids list decides which
    # journal entries recovery must tombstone instead of replay
    shard_kw = {}
    if args.router:
        from electionguard_tpu.fabric import manifest as fab_manifest
        from electionguard_tpu.fabric.router import register_worker
        from electionguard_tpu.remote import rpc_util
        worker_id = args.worker_id or \
            os.path.basename(os.path.normpath(args.output))
        keypair = fab_manifest.ManifestKeypair.generate(group)
        port = args.port or rpc_util.find_free_port()
        kval = keypair.public.value
        shard_id, requeued = register_worker(
            args.router, group, worker_id, port,
            manifest_public_key=kval.to_bytes(
                (kval.bit_length() + 7) // 8 or 1, "big"))
        log.info("registered with router %s as shard %d (%d requeued "
                 "ids to skip)", args.router, shard_id, len(requeued))
        args.port = port
        shard_kw = dict(
            shard_id=shard_id, worker_id=worker_id,
            chain_seed=fab_manifest.shard_chain_seed(init.manifest_hash,
                                                     shard_id),
            skip_ballot_ids=requeued, manifest_keypair=keypair)
    # chaos hook for the SIGKILL recovery test: wedge the device-owner
    # worker after N encrypted ballots so admitted-but-unpublished
    # ballots pile up deterministically in the (journaled) queue
    hold_after = None
    if os.environ.get("EGTPU_CHAOS_HOLD_AFTER_BALLOTS"):
        hold_after = int(os.environ["EGTPU_CHAOS_HOLD_AFTER_BALLOTS"])
        log.warning("CHAOS: worker will wedge after %d ballots",
                    hold_after)
    # install the drain handlers BEFORE the (slow: prewarm compiles)
    # service construction: a SIGTERM that lands mid-startup must still
    # end in a graceful drain — the signed shard manifest is only
    # written on drain, and a fabric relaunch can be terminated moments
    # after it starts (chaos drill: SIGKILL -> restart -> fleet drain)
    stop = threading.Event()

    def _on_signal(signum, frame):
        log.info("signal %d: draining", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    sw = Stopwatch()
    with maybe_profile("serve"):
        service = EncryptionService(
            init, group, port=args.port, out_dir=args.output,
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            max_queue=args.max_queue, seed=seed,
            timestamp=args.timestamp,
            prewarm=not args.no_prewarm, hold_after=hold_after,
            metrics_http_port=args.metrics_port, **shard_kw)
        log.info("serving on port %d (startup took %.2fs)", service.port,
                 sw.elapsed())
        if service.metrics_http_port is not None:
            log.info("prometheus metrics on http://127.0.0.1:%d/metrics",
                     service.metrics_http_port)
        stop.wait()
        service.drain()
    n = service.metrics.get("ballots_encrypted")
    log.info("%s; record published to %s",
             sw.took("serving", max(n, 1)), args.output)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Federated mix-server binary: one process, one shuffle stage.

Binds a free port, reverse-registers with the mix coordinator, then
serves the stage rpcs (mixfed/server.py) and blocks until the
coordinator calls finish.  ``-shards N`` spreads the shuffle and proof
dispatches over an in-process device mesh.

Run:  python -m electionguard_tpu.cli.run_mix_server -name mix1 \
          -serverPort 17141 -group tiny
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (add_group_flag, resolve_group,
                                          setup_logging)
from electionguard_tpu.mixfed.server import MixServerServer


def main(argv=None) -> int:
    log = setup_logging("RunMixServer")
    ap = argparse.ArgumentParser("RunMixServer")
    ap.add_argument("-name", required=True, help="mix server id")
    ap.add_argument("-port", type=int, default=0,
                    help="listen port (0 = random free port)")
    ap.add_argument("-serverPort", dest="server_port", type=int,
                    default=17141, help="coordinator port")
    ap.add_argument("-serverHost", dest="server_host", default="localhost")
    ap.add_argument("-shards", type=int, default=0,
                    help="shard the shuffle/proof over N local devices "
                         "(0 = single device; also EGTPU_MIX_SHARDS)")
    ap.add_argument("-wp", type=int, default=1,
                    help="within-element mesh axis for -shards")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    server = MixServerServer(
        group, f"{args.server_host}:{args.server_port}", args.name,
        port=args.port, shards=args.shards or None, wp=args.wp)
    log.info("mix server %s serving on %s", args.name, server.url)
    ok = server.wait_until_finished()
    log.info("mix server %s finished: all_ok=%s", args.name, ok)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Key ceremony coordinator binary.

Mirror of the reference's ``RunRemoteKeyCeremony``
(src/main/java/electionguard/keyceremony/RunRemoteKeyCeremony.java:49-313):
loads + validates the manifest, starts the registration server, waits for
``nguardians`` trustees, runs the exchange, orders remote saveState, and
publishes ``ElectionInitialized``.

Flags mirror the reference (:52-71): -in -out -nguardians -quorum -port.
"""

from __future__ import annotations

import argparse
import sys

from electionguard_tpu.cli.common import (Stopwatch, add_group_flag,
                                          load_manifest, resolve_group,
                                          setup_logging)
from electionguard_tpu.keyceremony.interface import Result
from electionguard_tpu.publish.election_record import ElectionConfig
from electionguard_tpu.publish.publisher import Publisher
from electionguard_tpu.remote.keyceremony_remote import KeyCeremonyCoordinator


def main(argv=None) -> int:
    log = setup_logging("RunRemoteKeyCeremony")
    ap = argparse.ArgumentParser("RunRemoteKeyCeremony")
    ap.add_argument("-in", dest="input", required=True,
                    help="directory containing manifest.json")
    ap.add_argument("-out", dest="output", required=True,
                    help="election record output directory")
    ap.add_argument("-nguardians", type=int, required=True)
    ap.add_argument("-quorum", type=int, required=True)
    ap.add_argument("-port", type=int, default=17111)
    ap.add_argument("-trusteeDir", dest="trustee_dir", default=None,
                    help="where trustees save private state "
                         "(default <out>/private/trustees)")
    ap.add_argument("-timeout", type=float, default=300.0,
                    help="registration wait timeout seconds")
    add_group_flag(ap)
    args = ap.parse_args(argv)

    group = resolve_group(args)
    manifest = load_manifest(args.input)
    config = ElectionConfig(manifest, args.nguardians, args.quorum)
    publisher = Publisher(args.output)  # fail-fast before serving
    trustee_dir = args.trustee_dir or f"{args.output}/private/trustees"

    sw = Stopwatch()
    coord = KeyCeremonyCoordinator(group, args.nguardians, args.quorum,
                                   args.port)
    log.info("waiting for %d guardians on port %d ...",
             args.nguardians, coord.port)
    all_ok = False
    try:
        if not coord.wait_for_registrations(args.timeout):
            log.error("timed out with %d/%d registrations",
                      coord.ready(), args.nguardians)
            return 2
        log.info("all %d guardians registered (%s)", args.nguardians,
                 sw.took("registration"))
        results = coord.run_key_ceremony(trustee_dir)
        if isinstance(results, Result):
            log.error("key ceremony failed: %s", results.error)
            return 3
        init = results.make_election_initialized(
            config, {"created_by": "RunRemoteKeyCeremony"})
        publisher.write_election_initialized(init)
        log.info("published ElectionInitialized to %s (%s)",
                 args.output, sw.took("key ceremony"))
        all_ok = True
        return 0
    finally:
        coord.shutdown(all_ok)


if __name__ == "__main__":
    sys.exit(main())

"""The key ceremony trustee: secret polynomial, commitments, share exchange.

Native replacement for the reference's [ext] ``KeyCeremonyTrustee`` —
constructed ``(group, id, xCoordinate, quorum)``
(reference: src/main/java/electionguard/keyceremony/RunRemoteTrustee.java:184)
and driven through the six trustee operations by the ceremony exchange.

A guardian i holds a random degree-(k-1) polynomial
``P_i(x) = Σ_j a_ij x^j mod q`` with public commitments ``K_ij = g^{a_ij}``
and Schnorr proofs for each.  Its share for guardian ℓ is ``P_i(ℓ)``,
encrypted to ℓ's election public key with hashed ElGamal (spec 1.03 eq 17
shape — reference: src/main/proto/keyceremony_trustee_rpc.proto:34-43) and
verified against the commitments: ``g^{P_i(ℓ)} == Π_j K_ij^{ℓ^j}``.

Guardian secrets never leave this object except (a) encrypted shares and
(b) the plaintext coordinate under an explicit challenge — preserving the
reference's process-level trust boundary (SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Union

from electionguard_tpu.core.group import (ElementModP, ElementModQ,
                                          GroupContext)
from electionguard_tpu.crypto.hashed_elgamal import hashed_elgamal_encrypt
from electionguard_tpu.crypto.schnorr import make_schnorr_proof
from electionguard_tpu.keyceremony.interface import (KeyCeremonyTrusteeIF,
                                                     KeyShareChallengeResponse,
                                                     PublicKeys, Result,
                                                     SecretKeyShare)


def compute_polynomial(group: GroupContext, coefficients: list[ElementModQ],
                       x: int) -> ElementModQ:
    """P(x) = Σ a_j x^j mod q (Horner)."""
    acc = 0
    for a in reversed(coefficients):
        acc = (acc * x + a.value) % group.q
    return group.int_to_q(acc)


def commitment_product(group: GroupContext,
                       commitments: tuple[ElementModP, ...],
                       x: int) -> ElementModP:
    """g^{P(x)} from public commitments: Π_j K_j^{x^j} mod p."""
    acc = 1
    xj = 1
    for k in commitments:
        acc = acc * pow(k.value, xj, group.p) % group.p
        xj = xj * x % group.q
    return ElementModP(acc, group)


class KeyCeremonyTrustee(KeyCeremonyTrusteeIF):
    def __init__(self, group: GroupContext, guardian_id: str,
                 x_coordinate: int, quorum: int,
                 coefficients: Optional[list[ElementModQ]] = None):
        if x_coordinate < 1:
            raise ValueError("x coordinate must be >= 1")
        if quorum < 1:
            raise ValueError("quorum must be >= 1")
        self.group = group
        self._id = guardian_id
        self._x = x_coordinate
        self.quorum = quorum
        # secret polynomial coefficients a_0 .. a_{k-1}
        self._coefficients = (coefficients if coefficients is not None
                              else [group.rand_q() for _ in range(quorum)])
        if len(self._coefficients) != quorum:
            raise ValueError("coefficient count must equal quorum")
        self._commitments = tuple(
            group.g_pow_p(a) for a in self._coefficients)
        self._proofs = tuple(
            make_schnorr_proof(group, a, k, group.rand_q())
            for a, k in zip(self._coefficients, self._commitments))
        # state accumulated during the ceremony
        self.other_public_keys: dict[str, PublicKeys] = {}
        self.received_shares: dict[str, ElementModQ] = {}  # P_i(self.x) by i
        self._revealed_to: set[str] = set()  # challenge-reveal audit trail

    # ------------------------------------------------------------------
    @property
    def id(self) -> str:
        return self._id

    @property
    def x_coordinate(self) -> int:
        return self._x

    @property
    def coefficient_commitments(self) -> tuple[ElementModP, ...]:
        return self._commitments

    @property
    def election_public_key(self) -> ElementModP:
        return self._commitments[0]

    # ------------------------------------------------------------------
    def send_public_keys(self) -> Union[PublicKeys, Result]:
        return PublicKeys(self._id, self._x, self._commitments, self._proofs)

    def receive_public_keys(self, keys: PublicKeys) -> Result:
        if keys.guardian_id == self._id:
            return Result.Err("guardian cannot receive its own keys")
        res = keys.validate()
        if not res.ok:
            return res
        if len(keys.coefficient_commitments) != self.quorum:
            return Result.Err(
                f"expected {self.quorum} commitments, "
                f"got {len(keys.coefficient_commitments)}")
        self.other_public_keys[keys.guardian_id] = keys
        return Result.Ok()

    def send_secret_key_share(self, other_id: str) -> Union[SecretKeyShare, Result]:
        keys = self.other_public_keys.get(other_id)
        if keys is None:
            return Result.Err(f"no public keys for {other_id}")
        coordinate = compute_polynomial(self.group, self._coefficients,
                                        keys.x_coordinate)
        ctx = f"{self._id}->{other_id}".encode()
        enc = hashed_elgamal_encrypt(
            self.group, coordinate.to_bytes(), self.group.rand_q(),
            keys.election_public_key, ctx)
        return SecretKeyShare(self._id, other_id, keys.x_coordinate, enc)

    def receive_secret_key_share(self, share: SecretKeyShare) -> Result:
        if share.designated_guardian_id != self._id:
            return Result.Err("share not addressed to this guardian")
        gen = self.other_public_keys.get(share.generating_guardian_id)
        if gen is None:
            return Result.Err(
                f"no public keys for {share.generating_guardian_id}")
        ctx = f"{share.generating_guardian_id}->{self._id}".encode()
        data = share.encrypted_coordinate.decrypt(self._coefficients[0], ctx)
        if data is None:
            return Result.Err("share decryption failed (bad MAC)")
        coordinate = self.group.bytes_to_q(data)
        # verify against commitments: g^{P_i(ℓ)} == Π_j K_ij^{ℓ^j}
        expected = commitment_product(self.group,
                                      gen.coefficient_commitments, self._x)
        if self.group.g_pow_p(coordinate) != expected:
            return Result.Err(
                f"share from {share.generating_guardian_id} fails "
                f"commitment check")
        self.received_shares[share.generating_guardian_id] = coordinate
        return Result.Ok()

    def challenge_share(self, challenger_id: str) -> Union[KeyShareChallengeResponse, Result]:
        """Reveal P_self(challenger) in the clear (challenge path the
        reference left unwired — keyceremony_trustee_rpc.proto:52-62).

        Each reveal publishes one point of the secret polynomial (the point
        the challenger legitimately owns anyway), but quorum-many distinct
        reveals would reconstruct the secret — so a trustee answers at most
        ONE challenge per ceremony; a ceremony with more disputes must abort
        and re-key with a fresh polynomial.
        """
        keys = self.other_public_keys.get(challenger_id)
        if keys is None:
            return Result.Err(f"no public keys for {challenger_id}")
        if self._revealed_to and challenger_id not in self._revealed_to:
            return Result.Err(
                "refusing second challenge reveal: restart the ceremony "
                "with a fresh polynomial")
        self._revealed_to.add(challenger_id)
        coordinate = compute_polynomial(self.group, self._coefficients,
                                        keys.x_coordinate)
        return KeyShareChallengeResponse(self._id, challenger_id, coordinate)

    def receive_challenged_share(self, response: KeyShareChallengeResponse) -> Result:
        """Accept a plaintext coordinate revealed under challenge, after
        verifying it against the generator's public commitments."""
        if response.designated_guardian_id != self._id:
            return Result.Err("challenged share not addressed to this guardian")
        gen = self.other_public_keys.get(response.generating_guardian_id)
        if gen is None:
            return Result.Err(
                f"no public keys for {response.generating_guardian_id}")
        expected = commitment_product(self.group,
                                      gen.coefficient_commitments, self._x)
        if self.group.g_pow_p(response.coordinate) != expected:
            return Result.Err("challenged coordinate fails commitment check")
        self.received_shares[response.generating_guardian_id] = \
            response.coordinate
        return Result.Ok()

    # ------------------------------------------------------------------
    # post-ceremony: the trustee's decryption state
    # ------------------------------------------------------------------
    def secret_key_share_sum(self) -> ElementModQ:
        """s_ℓ = P_ℓ(ℓ) + Σ_{i≠ℓ} P_i(ℓ) mod q (full share of the joint key
        evaluated at this x — used for share-based decryption paths)."""
        own = compute_polynomial(self.group, self._coefficients, self._x)
        return self.group.add_q(own, *self.received_shares.values())

    def decrypting_trustee_state(self) -> dict:
        """Private state persisted by saveState and reloaded by the
        decrypting trustee binary (reference: RunRemoteTrustee.java:329
        publisher.writeTrustee -> RunRemoteDecryptingTrustee.java:90
        readTrustee)."""
        return {
            "guardian_id": self._id,
            "x_coordinate": self._x,
            "quorum": self.quorum,
            "secret_key": self._coefficients[0].value,
            "received_shares": {
                gid: q.value for gid, q in self.received_shares.items()},
            "public_commitments": {
                gid: [k.value for k in pk.coefficient_commitments]
                for gid, pk in self.other_public_keys.items()},
            "own_commitments": [k.value for k in self._commitments],
        }

    def save_state(self, out_dir: str) -> Result:
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, f"trustee-{self._id}.json")
            with open(path, "w") as f:
                json.dump(self.decrypting_trustee_state(), f)
            return Result.Ok()
        except OSError as e:
            return Result.Err(f"save_state failed: {e}")

    # ------------------------------------------------------------------
    # mid-ceremony checkpoint (crash/restart resume — same sensitivity as
    # the decrypting-trustee file: it holds the secret polynomial)
    # ------------------------------------------------------------------
    def ceremony_state(self) -> dict:
        """The FULL mid-ceremony state: secret coefficients, own proofs,
        every received public-key set, received shares, reveal audit.
        ``from_ceremony_state`` restores a trustee that continues the
        ceremony exactly where this one stopped."""
        return {
            "guardian_id": self._id,
            "x_coordinate": self._x,
            "quorum": self.quorum,
            "coefficients": [a.value for a in self._coefficients],
            "proofs": [[p.challenge.value, p.response.value]
                       for p in self._proofs],
            "other_public_keys": {
                gid: {"x_coordinate": pk.x_coordinate,
                      "commitments": [k.value
                                      for k in pk.coefficient_commitments],
                      "proofs": [[p.challenge.value, p.response.value]
                                 for p in pk.coefficient_proofs]}
                for gid, pk in self.other_public_keys.items()},
            "received_shares": {
                gid: q.value for gid, q in self.received_shares.items()},
            "revealed_to": sorted(self._revealed_to),
        }

    @staticmethod
    def from_ceremony_state(group: GroupContext,
                            state: dict) -> "KeyCeremonyTrustee":
        from electionguard_tpu.crypto.schnorr import SchnorrProof

        def proofs_for(commitments, rows):
            return tuple(
                SchnorrProof(k, group.int_to_q(c), group.int_to_q(v))
                for k, (c, v) in zip(commitments, rows))

        t = KeyCeremonyTrustee(
            group, state["guardian_id"], state["x_coordinate"],
            state["quorum"],
            coefficients=[group.int_to_q(v)
                          for v in state["coefficients"]])
        # restore the ORIGINAL proofs: a resumed trustee re-answers a
        # retried sendPublicKeys with the bytes the first answer carried
        t._proofs = proofs_for(t._commitments, state["proofs"])
        for gid, pk in state["other_public_keys"].items():
            commitments = tuple(ElementModP(v, group)
                                for v in pk["commitments"])
            t.other_public_keys[gid] = PublicKeys(
                gid, pk["x_coordinate"], commitments,
                proofs_for(commitments, pk["proofs"]))
        t.received_shares = {
            gid: group.int_to_q(v)
            for gid, v in state["received_shares"].items()}
        t._revealed_to = set(state["revealed_to"])
        return t

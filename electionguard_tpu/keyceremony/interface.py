"""Key ceremony data types + the location-transparent trustee interface.

The reference's key design move is that remote proxies implement the *same
interface* as in-process trustees, so the ceremony algorithm cannot tell
local from remote (``RemoteTrusteeProxy implements KeyCeremonyTrusteeIF`` —
reference: src/main/java/electionguard/keyceremony/RemoteTrusteeProxy.java:28,
interface surface :34-153).  We keep that move: ``KeyCeremonyTrusteeIF`` is
implemented by ``KeyCeremonyTrustee`` (in-process) and by the gRPC proxy in
``electionguard_tpu.remote``.

Errors are values (``Result``) rather than exceptions, mirroring the
reference's in-band error strings (src/main/proto/common_rpc.proto:10-12).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol, Union

from electionguard_tpu.core.group import ElementModP, ElementModQ
from electionguard_tpu.crypto.hashed_elgamal import HashedElGamalCiphertext
from electionguard_tpu.crypto.schnorr import SchnorrProof


@dataclass(frozen=True)
class Result:
    """Ok/Err result carried in-band (common_rpc.proto ErrorResponse).

    ``transport`` distinguishes a TRANSPORT-LEVEL failure (rpc died after
    its bounded retries — the peer's answer is unknown) from an in-band
    rejection (the peer answered "no").  Failure handling differs: a
    share-verification rejection legitimately triggers the public
    challenge path, a dead peer must not — revealing a polynomial
    coordinate because the network hiccuped would leak secret-sharing
    state on every crash."""

    ok: bool
    error: str = ""
    transport: bool = False

    @staticmethod
    def Ok() -> "Result":
        return Result(True)

    @staticmethod
    def Err(msg: str) -> "Result":
        return Result(False, msg)

    @staticmethod
    def TransportErr(msg: str) -> "Result":
        return Result(False, msg, transport=True)


@dataclass(frozen=True)
class PublicKeys:
    """A guardian's public commitments (PublicKeySet on the wire —
    reference: src/main/proto/keyceremony_trustee_rpc.proto:22-28)."""

    guardian_id: str
    x_coordinate: int
    coefficient_commitments: tuple[ElementModP, ...]  # K_ij = g^{a_ij}
    coefficient_proofs: tuple[SchnorrProof, ...]

    @property
    def election_public_key(self) -> ElementModP:
        return self.coefficient_commitments[0]

    def validate(self) -> Result:
        if not self.coefficient_commitments:
            return Result.Err("no coefficient commitments")
        if len(self.coefficient_commitments) != len(self.coefficient_proofs):
            return Result.Err("commitment/proof count mismatch")
        # subgroup membership runs through the one ingestion gate
        # (crypto/validate) — named classes, batched screen
        from electionguard_tpu.crypto import validate as vgate
        try:
            vgate.gate_elements(
                self.coefficient_commitments[0].group,
                [(f"{self.guardian_id} commitment[{j}]", k.value)
                 for j, k in enumerate(self.coefficient_commitments)],
                "keyceremony")
        except vgate.GateError as e:
            return Result.Err(str(e))
        for j, (k, pr) in enumerate(zip(self.coefficient_commitments,
                                        self.coefficient_proofs)):
            if pr.public_key != k:
                return Result.Err(f"proof {j} is not for commitment {j}")
            if not pr.is_valid():
                return Result.Err(f"Schnorr proof {j} invalid for "
                                  f"{self.guardian_id}")
        return Result.Ok()


@dataclass(frozen=True)
class SecretKeyShare:
    """Encrypted share Eℓ(Pᵢ(ℓ)) (PartialKeyBackup on the wire —
    reference: src/main/proto/keyceremony_trustee_rpc.proto:34-43)."""

    generating_guardian_id: str
    designated_guardian_id: str
    designated_guardian_x: int
    encrypted_coordinate: HashedElGamalCiphertext


@dataclass(frozen=True)
class KeyShareChallengeResponse:
    """Plaintext Pᵢ(ℓ) revealed under challenge.

    The reference *defines* the challenge messages but never wires them to
    an rpc (keyceremony_trustee_rpc.proto:52-62, SURVEY.md §2 row 13); we
    wire the full path.
    """

    generating_guardian_id: str
    designated_guardian_id: str
    coordinate: ElementModQ


class KeyCeremonyTrusteeIF(Protocol):
    """The surface ``keyCeremonyExchange`` drives (reference:
    RemoteTrusteeProxy.java:34-153)."""

    @property
    def id(self) -> str: ...

    @property
    def x_coordinate(self) -> int: ...

    def send_public_keys(self) -> Union[PublicKeys, Result]: ...

    def receive_public_keys(self, keys: PublicKeys) -> Result: ...

    def send_secret_key_share(self, other_id: str) -> Union[SecretKeyShare, Result]: ...

    def receive_secret_key_share(self, share: SecretKeyShare) -> Result: ...

    def challenge_share(self, challenger_id: str) -> Union[KeyShareChallengeResponse, Result]: ...

    def receive_challenged_share(self, response: KeyShareChallengeResponse) -> Result: ...

    def save_state(self, out_dir: str) -> Result: ...

"""The key ceremony exchange: round-robin over all trustee pairs.

Native replacement for the reference's [ext] ``keyCeremonyExchange`` +
``KeyCeremonyResults`` (call site:
src/main/java/electionguard/keyceremony/RunRemoteKeyCeremony.java:206,224-228).
Drives any mix of in-process trustees and remote proxies through the
``KeyCeremonyTrusteeIF`` surface — O(n²) pairwise exchange, exactly the
traffic pattern of SURVEY.md §3.1.

Beyond the reference, a failed share verification triggers the challenge
path (plaintext coordinate revealed and publicly checked against the
commitments) instead of aborting outright — the reference defines these
messages but never wires them (keyceremony_trustee_rpc.proto:52-62).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Union

from electionguard_tpu.core.group import ElementModP, GroupContext
from electionguard_tpu.core.hash import hash_elems
from electionguard_tpu.keyceremony.interface import (KeyCeremonyTrusteeIF,
    PublicKeys,
    Result,
    SecretKeyShare)
from electionguard_tpu.keyceremony.trustee import commitment_product
from electionguard_tpu.publish.election_record import (ElectionConfig,
                                                       ElectionInitialized,
                                                       GuardianRecord)
from electionguard_tpu.utils import clock, errors

# A transport-dead step is re-attempted at the PROTOCOL level before the
# ceremony is abandoned: one rpc's bounded retries span well under a
# second of backoff, while a crashed-and-restarting guardian is gone for
# seconds — compound faults (found by the deterministic simulator, seeds
# 77/347) exhaust the rpc budget and used to abort the whole ceremony.
# Safe because every exchange step is idempotent: sends are pure
# recomputes, receives overwrite by sender id behind a WAL checkpoint,
# and a challenge replays the same audited reveal.
TRANSPORT_RETRY_ROUNDS = 3
TRANSPORT_RETRY_PAUSE_S = 2.0


def _transport_dead(outcome) -> bool:
    return (isinstance(outcome, Result) and not outcome.ok
            and outcome.transport)


def _step(fn):
    """Run one exchange step, re-attempting transport deaths after a
    pause long enough for a peer to restart."""
    outcome = fn()
    for _ in range(TRANSPORT_RETRY_ROUNDS - 1):
        if not _transport_dead(outcome):
            break
        clock.sleep(TRANSPORT_RETRY_PAUSE_S)
        outcome = fn()
    return outcome


@dataclass
class KeyCeremonyResults:
    public_keys: dict[str, PublicKeys]

    @property
    def joint_public_key(self) -> ElementModP:
        """K = Π K_i0 mod p."""
        keys = list(self.public_keys.values())
        group = keys[0].election_public_key.group
        return group.mult_p(*(k.election_public_key for k in keys))

    def make_election_initialized(
            self, config: ElectionConfig,
            metadata: Optional[dict[str, str]] = None) -> ElectionInitialized:
        """Mirror of KeyCeremonyResults.makeElectionInitialized(config, meta)
        (reference: RunRemoteKeyCeremony.java:224-228)."""
        group = self.joint_public_key.group
        manifest_hash = config.manifest.crypto_hash()
        crypto_base_hash = hash_elems(
            group, group.p, group.q, group.g, config.n_guardians,
            config.quorum, manifest_hash)
        extended_base_hash = hash_elems(
            group, crypto_base_hash, self.joint_public_key)
        guardians = tuple(
            GuardianRecord(
                guardian_id=pk.guardian_id,
                x_coordinate=pk.x_coordinate,
                coefficient_commitments=pk.coefficient_commitments,
                coefficient_proofs=pk.coefficient_proofs)
            for pk in sorted(self.public_keys.values(),
                             key=lambda p: p.x_coordinate))
        return ElectionInitialized(
            config=config,
            joint_public_key=self.joint_public_key,
            manifest_hash=manifest_hash,
            crypto_base_hash=crypto_base_hash,
            extended_base_hash=extended_base_hash,
            guardians=guardians,
            metadata=dict(metadata or {}),
        )


def key_ceremony_exchange(
        trustees: Sequence[KeyCeremonyTrusteeIF],
        group: GroupContext) -> Union[KeyCeremonyResults, Result]:
    """Run the full pairwise ceremony; returns results or an Err Result."""
    from electionguard_tpu.obs import trace
    attrs = {"n_trustees": len(trustees)} if trace.enabled() else None
    with trace.span("keyceremony.exchange", attrs):
        return _key_ceremony_exchange(trustees, group)


def _key_ceremony_exchange(
        trustees: Sequence[KeyCeremonyTrusteeIF],
        group: GroupContext) -> Union[KeyCeremonyResults, Result]:
    from electionguard_tpu.obs import set_phase
    if len({t.id for t in trustees}) != len(trustees):
        return Result.Err("duplicate trustee ids")
    if len({t.x_coordinate for t in trustees}) != len(trustees):
        return Result.Err("duplicate x coordinates")

    # round 1: collect all public key sets
    set_phase("keyceremony-round1")
    all_keys: dict[str, PublicKeys] = {}
    for t in trustees:
        keys = _step(t.send_public_keys)
        if isinstance(keys, Result):
            return Result.Err(errors.named(
                "kc.exchange_failed",
                f"{t.id} sendPublicKeys: {keys.error}"))
        # identity binding: a (possibly remote) trustee must answer with the
        # identity it registered under, or it could impersonate another
        # guardian and corrupt everyone's commitment bookkeeping
        if keys.guardian_id != t.id or keys.x_coordinate != t.x_coordinate:
            msg = (f"trustee {t.id} (x={t.x_coordinate}) answered with "
                   f"identity {keys.guardian_id} (x={keys.x_coordinate})")
            errors.reject("kc.equivocation", msg)
            return Result.Err(errors.named("kc.equivocation", msg))
        val = keys.validate()
        if not val.ok:
            msg = f"{t.id} public keys invalid: {val.error}"
            errors.reject("kc.bad_proof", msg)
            return Result.Err(errors.named("kc.bad_proof", msg))
        all_keys[t.id] = keys

    # round 2: distribute all key sets to all other trustees
    set_phase("keyceremony-round2")
    for t in trustees:
        for other_id, keys in all_keys.items():
            if other_id == t.id:
                continue
            res = _step(lambda: t.receive_public_keys(keys))
            if not res.ok:
                msg = f"{t.id} rejected keys of {other_id}: {res.error}"
                errors.reject("kc.peer_reject", msg)
                return Result.Err(errors.named("kc.peer_reject", msg))

    # round 3: pairwise encrypted share exchange, with challenge fallback
    set_phase("keyceremony-round3")
    for sender in trustees:
        for receiver in trustees:
            if sender.id == receiver.id:
                continue
            share = _step(lambda: sender.send_secret_key_share(receiver.id))
            if isinstance(share, Result):
                return Result.Err(errors.named(
                    "kc.exchange_failed",
                    f"{sender.id} sendSecretKeyShare({receiver.id}): "
                    f"{share.error}"))
            res = _step(lambda: receiver.receive_secret_key_share(share))
            if not res.ok and res.transport:
                # transport death, not a rejection: the receiver never
                # answered (its bounded retries are exhausted).  Abort —
                # revealing a coordinate under challenge because the
                # network died would leak secret-sharing state on every
                # crash; only an explicit in-band rejection may trigger
                # the reveal below.
                return Result.Err(errors.named(
                    "rpc.unreachable",
                    f"{receiver.id} unreachable receiving "
                    f"{sender.id}'s share: {res.error}"))
            if not res.ok:
                # in-band rejection of the encrypted share (bad MAC /
                # polynomial check): a contained detection — the
                # challenge path below decides whether the ceremony
                # survives it
                errors.reject("kc.bad_share",
                              f"{receiver.id} rejected {sender.id}'s "
                              f"share: {res.error}")
                # challenge path: sender must reveal the coordinate; everyone
                # can check it against the public commitments.
                challenge = _step(
                    lambda: sender.challenge_share(receiver.id))
                if isinstance(challenge, Result):
                    msg = (f"{sender.id} failed challenge for "
                           f"{receiver.id}: {challenge.error} "
                           f"(original: {res.error})")
                    errors.reject("kc.challenge_refused", msg)
                    return Result.Err(errors.named(
                        "kc.challenge_refused", msg))
                expected = commitment_product(
                    group, all_keys[sender.id].coefficient_commitments,
                    receiver.x_coordinate)
                if group.g_pow_p(challenge.coordinate) != expected:
                    msg = (f"challenge verification failed: {sender.id}'s "
                           f"share for {receiver.id} does not match its "
                           f"commitments (original: {res.error})")
                    errors.reject("kc.challenge_failed", msg)
                    return Result.Err(errors.named(
                        "kc.challenge_failed", msg))
                # coordinate is publicly verified; receiver ingests it
                accept = _step(
                    lambda: receiver.receive_challenged_share(challenge))
                if not accept.ok:
                    msg = (f"{receiver.id} rejects {sender.id}'s "
                           f"challenged share: {accept.error}")
                    errors.reject("kc.challenge_failed", msg)
                    return Result.Err(errors.named(
                        "kc.challenge_failed", msg))

    return KeyCeremonyResults(all_keys)

"""Exponential ElGamal over the production group.

Native replacement for the reference's [ext] ``ElGamalCiphertext`` et al.
(wire contract: pad/data pair of ElementModP — reference:
src/main/proto/common.proto:18-22, codec ConvertCommonProto.java:60-68).

Encryption of a small vote ``v`` with nonce ``R`` under joint key ``K``:
``(α, β) = (g^R, g^v · K^R) mod p``.  Homomorphic accumulation is the
componentwise product — the tally hot loop the TPU plane product-reduces
(SURVEY.md §3.4 phase 3 🔥).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional

from electionguard_tpu.core.dlog import DLog, default_dlog
from electionguard_tpu.core.group import ElementModP, ElementModQ, GroupContext


@dataclass(frozen=True)
class ElGamalKeypair:
    secret_key: ElementModQ
    public_key: ElementModP

    @staticmethod
    def from_secret(s: ElementModQ) -> "ElGamalKeypair":
        if s.value < 2:
            raise ValueError("secret key must be >= 2")
        return ElGamalKeypair(s, s.group.g_pow_p(s))

    @staticmethod
    def generate(group: GroupContext) -> "ElGamalKeypair":
        return ElGamalKeypair.from_secret(group.rand_q())


@dataclass(frozen=True)
class ElGamalCiphertext:
    pad: ElementModP   # α = g^R
    data: ElementModP  # β = g^v · K^R

    def mult(self, other: "ElGamalCiphertext") -> "ElGamalCiphertext":
        """Homomorphic add of plaintexts = componentwise product."""
        g = self.pad.group
        return ElGamalCiphertext(g.mult_p(self.pad, other.pad),
                                 g.mult_p(self.data, other.data))

    def partial_decrypt(self, secret: ElementModQ) -> ElementModP:
        """Mᵢ = α^sᵢ — the trustee-side share (SURVEY.md §3.2 🔥)."""
        return self.pad.group.pow_p(self.pad, secret)

    def decrypt(self, secret: ElementModQ, dlog: Optional[DLog] = None) -> int:
        g = self.pad.group
        m = g.div_p(self.data, self.partial_decrypt(secret))  # g^v
        d = dlog if dlog is not None else default_dlog(g)
        v = d.dlog(m)
        if v is None:
            raise ValueError("plaintext exceeds dlog table")
        return v

    def decrypt_with_shares(self, shares: Iterable[ElementModP],
                            dlog: Optional[DLog] = None) -> int:
        """Combine full partial decryptions: v = dlog(β / ∏ Mᵢ)."""
        g = self.pad.group
        m = g.div_p(self.data, g.mult_p(*shares))
        d = dlog if dlog is not None else default_dlog(g)
        v = d.dlog(m)
        if v is None:
            raise ValueError("plaintext exceeds dlog table")
        return v

    def crypto_hash(self):
        from electionguard_tpu.core.hash import hash_digest
        return hash_digest(self.pad, self.data)


def elgamal_encrypt(group: GroupContext, v: int, nonce: ElementModQ,
                    public_key: ElementModP) -> ElGamalCiphertext:
    if v < 0:
        raise ValueError("vote must be non-negative")
    if nonce.is_zero():
        raise ValueError("nonce must be nonzero")
    pad = group.g_pow_p(nonce)
    data = group.mult_p(group.g_pow_p(group.int_to_q(v)),
                        group.pow_p(public_key, nonce))
    return ElGamalCiphertext(pad, data)


def elgamal_accumulate(cts: Iterable[ElGamalCiphertext]) -> ElGamalCiphertext:
    cts = list(cts)
    if not cts:
        raise ValueError("nothing to accumulate")
    acc = cts[0]
    for ct in cts[1:]:
        acc = acc.mult(ct)
    return acc

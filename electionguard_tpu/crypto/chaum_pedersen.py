"""Chaum–Pedersen proofs: generic, disjunctive (0-or-1), and constant.

Native replacement for the reference's [ext] ``GenericChaumPedersenProof``.
Wire contract: proofs carry (challenge, response) only — the commitment
fields are ``reserved`` in the reference proto (reference:
src/main/proto/common.proto:24-28), so verification *recomputes* commitments
from (c, v) and re-derives the Fiat–Shamir challenge.

Generic proof of a shared discrete log ``s`` with ``x = g1^s, y = g2^s``:
  commitments ``a = g1^u, b = g2^u``; ``c = H(context, g1, g2, x, y, a, b)``;
  response ``v = u - c·s``.
Verify: ``a' = g1^v x^c``, ``b' = g2^v y^c``, accept iff c matches the hash.

The disjunctive (range {0,1}) proof guards every encrypted selection and the
constant proof guards every contest's vote limit — together they are the
dominant verification workload the TPU plane batches (SURVEY.md §3.4 phase 5
🔥, BASELINE.md config 3).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from electionguard_tpu.core.group import ElementModP, ElementModQ, GroupContext
from electionguard_tpu.core.hash import hash_elems
from electionguard_tpu.core.nonces import Nonces
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext


@dataclass(frozen=True)
class GenericChaumPedersenProof:
    """Compact (challenge, response) proof that log_{g1} x == log_{g2} y."""

    challenge: ElementModQ
    response: ElementModQ

    def is_valid(self, g1: ElementModP, x: ElementModP,
                 g2: ElementModP, y: ElementModP,
                 context: ElementModQ) -> bool:
        g = self.challenge.group
        a = g.mult_p(g.pow_p(g1, self.response), g.pow_p(x, self.challenge))
        b = g.mult_p(g.pow_p(g2, self.response), g.pow_p(y, self.challenge))
        return self.challenge == hash_elems(g, context, g1, g2, x, y, a, b)


def make_generic_cp_proof(group: GroupContext, s: ElementModQ,
                          g1: ElementModP, g2: ElementModP,
                          nonce: ElementModQ,
                          context: ElementModQ) -> GenericChaumPedersenProof:
    x = group.pow_p(g1, s)
    y = group.pow_p(g2, s)
    a = group.pow_p(g1, nonce)
    b = group.pow_p(g2, nonce)
    c = hash_elems(group, context, g1, g2, x, y, a, b)
    v = group.sub_q(nonce, group.mult_q(c, s))
    return GenericChaumPedersenProof(c, v)


@dataclass(frozen=True)
class DisjunctiveChaumPedersenProof:
    """Proof that an ElGamal ciphertext encrypts 0 or 1.

    Stored compact: (c0, v0, c1, v1); overall challenge c = c0 + c1 must
    equal H(context, α, β, a0, b0, a1, b1) with recomputed commitments:
      a0 = g^v0 α^c0        b0 = K^v0 β^c0
      a1 = g^v1 α^c1        b1 = K^v1 (β/g)^c1
    """

    proof_zero_challenge: ElementModQ
    proof_zero_response: ElementModQ
    proof_one_challenge: ElementModQ
    proof_one_response: ElementModQ
    # Untrusted verification hints: the prover's commitment values
    # (a0, b0, a1, b1) as plain ints.  Never serialized (the publish
    # plane writes the four named fields above), excluded from
    # equality/repr; the RLC batch verifier hash-checks them per row
    # before use and falls back to the naive path when absent.
    commitment_hints: Optional[tuple] = field(
        default=None, compare=False, repr=False)

    def is_valid(self, ct: ElGamalCiphertext, public_key: ElementModP,
                 context: ElementModQ) -> bool:
        g = self.proof_zero_challenge.group
        c0, v0 = self.proof_zero_challenge, self.proof_zero_response
        c1, v1 = self.proof_one_challenge, self.proof_one_response
        alpha, beta = ct.pad, ct.data
        a0 = g.mult_p(g.g_pow_p(v0), g.pow_p(alpha, c0))
        b0 = g.mult_p(g.pow_p(public_key, v0), g.pow_p(beta, c0))
        a1 = g.mult_p(g.g_pow_p(v1), g.pow_p(alpha, c1))
        beta_over_g = g.mult_p(beta, g.GINV_MOD_P)
        b1 = g.mult_p(g.pow_p(public_key, v1), g.pow_p(beta_over_g, c1))
        c = hash_elems(g, context, alpha, beta, a0, b0, a1, b1)
        return g.add_q(c0, c1) == c


def make_disjunctive_cp_proof(
        group: GroupContext, ct: ElGamalCiphertext, nonce: ElementModQ,
        public_key: ElementModP, context: ElementModQ, vote: int,
        seed: ElementModQ) -> DisjunctiveChaumPedersenProof:
    """Prove ct = (g^R, g^vote · K^R) encrypts vote ∈ {0, 1}.

    The false branch is simulated with (c_f, v_f) drawn from ``seed``; the
    real branch commits with u and closes with v = u - c_real·R.
    """
    if vote not in (0, 1):
        raise ValueError("disjunctive proof requires vote in {0,1}")
    g = group
    alpha, beta = ct.pad, ct.data
    nonces = Nonces(seed, "disjoint-cp")
    u, c_fake, v_fake = nonces[0], nonces[1], nonces[2]
    beta_over_g = g.mult_p(beta, g.GINV_MOD_P)

    if vote == 0:
        # real zero-branch commitments
        a0, b0 = g.g_pow_p(u), g.pow_p(public_key, u)
        # simulated one-branch: a1 = g^v1 α^c1, b1 = K^v1 (β/g)^c1
        a1 = g.mult_p(g.g_pow_p(v_fake), g.pow_p(alpha, c_fake))
        b1 = g.mult_p(g.pow_p(public_key, v_fake), g.pow_p(beta_over_g, c_fake))
        c = hash_elems(g, context, alpha, beta, a0, b0, a1, b1)
        c0 = g.sub_q(c, c_fake)
        v0 = g.sub_q(u, g.mult_q(c0, nonce))
        return DisjunctiveChaumPedersenProof(
            c0, v0, c_fake, v_fake,
            commitment_hints=(a0.value, b0.value, a1.value, b1.value))
    else:
        # simulated zero-branch
        a0 = g.mult_p(g.g_pow_p(v_fake), g.pow_p(alpha, c_fake))
        b0 = g.mult_p(g.pow_p(public_key, v_fake), g.pow_p(beta, c_fake))
        # real one-branch on (α, β/g)
        a1, b1 = g.g_pow_p(u), g.pow_p(public_key, u)
        c = hash_elems(g, context, alpha, beta, a0, b0, a1, b1)
        c1 = g.sub_q(c, c_fake)
        v1 = g.sub_q(u, g.mult_q(c1, nonce))
        return DisjunctiveChaumPedersenProof(
            c_fake, v_fake, c1, v1,
            commitment_hints=(a0.value, b0.value, a1.value, b1.value))


@dataclass(frozen=True)
class ConstantChaumPedersenProof:
    """Proof that a ciphertext encrypts a known constant L (contest limit).

    Proves (α, β/g^L) is an encryption of zero under K with the aggregate
    nonce: a = g^v α^c, b = K^v (β/g^L)^c, c = H(context, L, α, β, a, b).
    """

    challenge: ElementModQ
    response: ElementModQ
    constant: int
    # Untrusted (a, b) commitment hints, same contract as the
    # disjunctive proof's: unserialized, hash-checked before batch use.
    commitment_hints: Optional[tuple] = field(
        default=None, compare=False, repr=False)

    def is_valid(self, ct: ElGamalCiphertext, public_key: ElementModP,
                 context: ElementModQ) -> bool:
        g = self.challenge.group
        if not isinstance(self.constant, int) or not (0 <= self.constant < g.q):
            return False  # malformed wire value must reject, not raise
        c, v = self.challenge, self.response
        alpha, beta = ct.pad, ct.data
        beta_shift = g.mult_p(
            beta, g.inv_p(g.g_pow_p(g.int_to_q(self.constant))))
        a = g.mult_p(g.g_pow_p(v), g.pow_p(alpha, c))
        b = g.mult_p(g.pow_p(public_key, v), g.pow_p(beta_shift, c))
        return c == hash_elems(g, context, self.constant, alpha, beta, a, b)


def make_constant_cp_proof(
        group: GroupContext, ct: ElGamalCiphertext, aggregate_nonce: ElementModQ,
        public_key: ElementModP, context: ElementModQ, constant: int,
        seed: ElementModQ) -> ConstantChaumPedersenProof:
    g = group
    alpha, beta = ct.pad, ct.data
    u = Nonces(seed, "constant-cp")[0]
    a, b = g.g_pow_p(u), g.pow_p(public_key, u)
    c = hash_elems(g, context, constant, alpha, beta, a, b)
    v = g.sub_q(u, g.mult_q(c, aggregate_nonce))
    return ConstantChaumPedersenProof(
        c, v, constant, commitment_hints=(a.value, b.value))

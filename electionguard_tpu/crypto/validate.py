"""The universal ingestion validation gate.

Every group element that crosses a trust boundary — a trustee's public
key commitments at the key ceremony, ciphertext rows pushed into the
mixnet, partial-decryption shares, a fabric worker's manifest key, a
live-verifier chunk — must be screened HERE before it participates in
any arithmetic.  The Moscow internet-voting break (arxiv 1908.09170)
worked entirely on parameters nobody validated; ROADMAP names this the
open soundness item.  This module turns the scattered ad-hoc checks
(`is_valid_residue` loops, bare width checks, nothing at all) into one
code path with NAMED rejection classes the sim's soundness oracle can
assert on:

* ``validate.range``          — x = 0 or x ≥ p (non-canonical wire value)
* ``validate.identity``       — x = 1 where the protocol forbids it
* ``validate.small_order``    — x = p−1 (the order-2 element of Z_p^*)
* ``validate.nonsubgroup``    — x^q ≠ 1 (outside the order-q subgroup)
* ``validate.response_range`` — proof response/challenge ≥ q
* ``validate.group_mismatch`` — peer's group-constants fingerprint differs

Cost: the subgroup screen is the PR 14 RLC (`verify/rlc.membership_rlc`)
— ONE q-exponentiation per ≤``CHUNK``-element batch instead of one per
element (2^-127 soundness per batch).  The RLC's one structural blind
spot — an even number of order-2-twisted elements cancels under the
all-odd randomizers — is closed by a deterministic per-element Jacobi
symbol check (O(log^2 p) int ops, no modexp): the order-q subgroup lies
inside the quadratic residues, so (x|p) = −1 is a certain non-member
verdict, and with p ≡ 3 mod 4 every order-2 twist flips it.  On a red
batch the gate bisects, re-running the screen on halves, to NAME the
offending elements; attribution cost is O(log n) extra batch checks and
only ever paid under attack.

Modes (``EGTPU_VALIDATE``):

* ``on`` (default) — range/identity/small-order per element (cheap int
  compares), RLC-batched subgroup screen.
* ``strict``       — exact per-element ``pow(x, q, p)`` instead of the
  RLC screen (audit posture; no probabilistic component).
* ``off``          — the gate is a no-op (perf experiments only; the
  terminal verifier still re-checks everything).

Observability: every gate call opens a ``validate.gate`` span tagged
with its boundary label and bumps ``validate_elements_total`` /
``validate_batches_total``; every rejection bumps
``validate_rejects_total`` and fans out through ``utils.errors.reject``
so the sim's detection log sees it even when the rejection is contained
in-band.
"""

from __future__ import annotations

from typing import Optional, Sequence

from electionguard_tpu import obs
from electionguard_tpu.core.group import ElementModP, GroupContext
from electionguard_tpu.utils import errors, knobs

#: elements per RLC screening batch; keeps the accumulator MSM bounded
#: and the bisection depth ≤ ~10
CHUNK = 512


class GateError(ValueError):
    """An ingestion-gate rejection.  ``str(e)`` carries the named class
    token (``[validate.*]``) so callers that stringify the error keep it
    machine-matchable; ``cls``/``boundary`` are available structurally."""

    def __init__(self, cls: str, boundary: str, detail: str):
        self.cls = cls
        self.boundary = boundary
        super().__init__(errors.named(cls, f"{boundary}: {detail}"))


def mode() -> str:
    """The configured gate mode: ``on`` | ``strict`` | ``off``."""
    m = knobs.get_str("EGTPU_VALIDATE")
    return m if m in ("on", "strict", "off") else "on"


def _reject(cls: str, boundary: str, detail: str) -> GateError:
    obs.REGISTRY.counter("validate_rejects_total").inc()
    errors.reject(cls, f"{boundary}: {detail}")
    return GateError(cls, boundary, detail)


# ---------------------------------------------------------------------------
# subgroup screening: RLC batch + bisection attribution
# ---------------------------------------------------------------------------

def _jacobi(a: int, n: int) -> int:
    """Jacobi symbol (a|n) for odd n > 0 — binary algorithm, O(log^2)
    integer ops, no modular exponentiation."""
    a %= n
    result = 1
    while a:
        while a % 2 == 0:
            a //= 2
            if n % 8 in (3, 5):
                result = -result
        a, n = n, a
        if a % 4 == 3 and n % 4 == 3:
            result = -result
        a %= n
    return result if n == 1 else 0


def _screen(group: GroupContext, values: Sequence[int], ops) -> bool:
    """One batched subgroup check over canonical-range values.  Uses the
    device MSM path when the caller supplies ``ops`` (JaxGroupOps),
    else the host RLC (same math, Python ints)."""
    obs.REGISTRY.counter("validate_batches_total").inc()
    if ops is not None:
        from electionguard_tpu.verify import rlc
        return rlc.membership_rlc(ops, list(values))
    from electionguard_tpu.verify.rlc import sample_randomizers
    p, q = group.p, group.q
    acc = 1
    for x, r in zip(values, sample_randomizers(len(values))):
        acc = acc * pow(x, r, p) % p
    return pow(acc, q, p) == 1


def _bisect_offenders(group: GroupContext, names: Sequence[str],
                      values: Sequence[int], ops) -> list[str]:
    """Names of the non-members inside a red batch.  Recursive halving:
    a green half is vouched for wholesale; a red singleton is judged by
    the exact residue test (the RLC on one element IS exact up to the
    odd-randomizer argument, but the pow is cheaper than sampling)."""
    if len(values) == 1:
        exact = pow(values[0], group.q, group.p) == 1
        return [] if exact else [names[0]]
    mid = len(values) // 2
    out: list[str] = []
    for lo, hi in ((0, mid), (mid, len(values))):
        if not _screen(group, values[lo:hi], ops):
            out.extend(_bisect_offenders(group, names[lo:hi],
                                         values[lo:hi], ops))
    return out


# ---------------------------------------------------------------------------
# the gate proper
# ---------------------------------------------------------------------------

def gate_elements(group: GroupContext, items: Sequence[tuple[str, int]],
                  boundary: str, *, allow_identity: bool = False,
                  ops=None) -> None:
    """Screen named raw integers as order-q subgroup members.

    ``items`` is ``(name, value)`` pairs — the name is what the
    rejection message and the bisection report carry, so callers pass
    something a human can act on ("guardian-1 commitment[3]").  Raises
    :class:`GateError` on the first failed check class; order is
    range → identity → small-order → subgroup so the cheapest check
    names the defect when several apply.
    """
    m = mode()
    if m == "off" or not items:
        return
    with obs.span("validate.gate", {"boundary": boundary,
                                    "n": len(items)}):
        p, q = group.p, group.q
        obs.REGISTRY.counter("validate_elements_total").inc(len(items))
        for name, v in items:
            if not 0 < v < p:
                raise _reject("validate.range", boundary,
                              f"{name} out of canonical range "
                              f"(0 < x < p): {_short(v)}")
            if v == 1 and not allow_identity:
                raise _reject("validate.identity", boundary,
                              f"{name} is the identity element")
            if v == p - 1:
                raise _reject("validate.small_order", boundary,
                              f"{name} is the order-2 element p-1")
            # quadratic character: the order-q subgroup (q odd) lies
            # inside the QRs, so (v|p) = -1 is a deterministic
            # non-member verdict.  This closes the RLC's one parity
            # blind spot — an EVEN number of order-2-twisted elements
            # (x = -v for subgroup v) cancels under the all-odd
            # randomizers, but each twist flips the Jacobi symbol
            # individually (p ≡ 3 mod 4 for both groups, so -1 is a
            # non-residue).  Cost: O(log^2 p) int ops, no modexp.
            if _jacobi(v, p) != 1:
                raise _reject("validate.nonsubgroup", boundary,
                              f"{name} has quadratic character -1 "
                              f"(outside the order-q subgroup)")
        values = [v for _, v in items]
        if m == "strict":
            for name, v in items:
                if pow(v, q, p) != 1:
                    raise _reject("validate.nonsubgroup", boundary,
                                  f"{name} outside the order-q subgroup")
            return
        names = [n for n, _ in items]
        for lo in range(0, len(values), CHUNK):
            chunk_v = values[lo:lo + CHUNK]
            if _screen(group, chunk_v, ops):
                continue
            bad = _bisect_offenders(group, names[lo:lo + CHUNK],
                                    chunk_v, ops)
            raise _reject("validate.nonsubgroup", boundary,
                          "outside the order-q subgroup: "
                          + ", ".join(bad or ["<batch>"]))


def gate_wire_p(group: GroupContext, items: Sequence[tuple[str, bytes]],
                boundary: str, *, allow_identity: bool = False,
                ops=None) -> list[ElementModP]:
    """Screen big-endian wire bytes BEFORE ElementModP construction (a
    non-canonical wire value must die here with ``validate.range``, not
    as an anonymous ValueError inside the importer) and return the
    constructed elements in order."""
    ints = [(name, int.from_bytes(b, "big")) for name, b in items]
    gate_elements(group, ints, boundary, allow_identity=allow_identity,
                  ops=ops)
    # with the gate off this reverts to the importer's own posture:
    # a non-canonical value raises ElementModP's anonymous ValueError
    return [ElementModP(v, group) for _, v in ints]


def gate_wire_q(group: GroupContext, items: Sequence[tuple[str, bytes]],
                boundary: str) -> None:
    """Range-check proof fields (responses, challenges) that live in
    Z_q: the wire value must satisfy 0 ≤ v < q (v = 0 is legal — a
    Schnorr response can be zero)."""
    if mode() == "off" or not items:
        return
    q = group.q
    for name, b in items:
        v = int.from_bytes(b, "big")
        if v >= q:
            raise _reject("validate.response_range", boundary,
                          f"{name} out of range (v < q): {_short(v)}")


def gate_fingerprint(group: GroupContext, fingerprint: bytes,
                     boundary: str) -> str:
    """Compare a peer's group-constants fingerprint against ours.
    Returns "" on match (or empty fingerprint / gate off), else the
    named error string — registration handlers embed it in their
    response instead of raising, so the peer learns why."""
    if mode() == "off" or not fingerprint:
        return ""
    ours = group.fingerprint()
    if fingerprint == ours:
        return ""
    obs.REGISTRY.counter("validate_rejects_total").inc()
    detail = (f"{boundary}: group constants mismatch — peer fingerprint "
              f"{fingerprint.hex()[:16]} != ours {ours.hex()[:16]}")
    errors.reject("validate.group_mismatch", detail)
    return errors.named("validate.group_mismatch", detail)


def _short(v: int) -> str:
    h = f"{v:x}"
    return f"0x{h}" if len(h) <= 16 else f"0x{h[:12]}..({v.bit_length()}b)"

"""Hashed ElGamal: KDF-stream encryption of byte strings to a public key.

Native replacement for the reference's [ext] ``HashedElGamalCiphertext`` —
wire form (c0, c1, c2, numBytes) (reference: src/main/proto/common.proto:30-35).
Used in the key ceremony to encrypt the share Pᵢ(ℓ) to guardian ℓ's key
("spec 1.03 eq 17" — reference: src/main/proto/keyceremony_trustee_rpc.proto:38-43).

Scheme: session key k = H(K^ε, g^ε); keystream = KDF(k); c0 = g^ε;
c1 = data ⊕ keystream; c2 = HMAC(mac_key, c0 || c1).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from electionguard_tpu.core.group import ElementModP, ElementModQ, GroupContext
from electionguard_tpu.core.hash import hash_digest, hmac_digest, kdf


@dataclass(frozen=True)
class HashedElGamalCiphertext:
    c0: ElementModP   # g^ε
    c1: bytes         # data ⊕ KDF keystream
    c2: bytes         # HMAC tag (32 bytes)
    num_bytes: int

    def decrypt(self, secret: ElementModQ,
                context: bytes = b"") -> Optional[bytes]:
        """Returns plaintext, or None if the MAC check fails."""
        g = secret.group
        if self.num_bytes != len(self.c1):
            return None
        shared = g.pow_p(self.c0, secret)  # K^ε = (g^ε)^s
        session_key = hash_digest(shared, self.c0)
        mac_key = kdf(session_key, "mac", context, 32)
        tag = hmac_digest(mac_key, self.c0, self.c1, self.num_bytes)
        if tag != self.c2:
            return None
        stream = kdf(session_key, "data", context, self.num_bytes)
        return bytes(a ^ b for a, b in zip(self.c1, stream))


def hashed_elgamal_encrypt(group: GroupContext, data: bytes,
                           nonce: ElementModQ, public_key: ElementModP,
                           context: bytes = b"") -> HashedElGamalCiphertext:
    c0 = group.g_pow_p(nonce)
    shared = group.pow_p(public_key, nonce)
    session_key = hash_digest(shared, c0)
    stream = kdf(session_key, "data", context, len(data))
    c1 = bytes(a ^ b for a, b in zip(data, stream))
    mac_key = kdf(session_key, "mac", context, 32)
    c2 = hmac_digest(mac_key, c0, c1, len(data))
    return HashedElGamalCiphertext(c0, c1, c2, len(data))

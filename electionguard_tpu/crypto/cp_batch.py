"""Batched generic Chaum–Pedersen verification on the device plane.

The decryption-side checks — verifier V8/V9/V13 share proofs and the
coordinator's on-arrival proof validation (reference combine loop:
src/main/java/electionguard/decrypt/RunRemoteDecryptor.java:261-273) — are
per-(selection × share) generic CP verifications: 4 modexps each.  Looping
``GenericChaumPedersenProof.is_valid`` host-side re-creates the reference's
CPU-bound per-element loop; this module verifies the whole batch in a
handful of device dispatches, exactly like the verifier's V4/V5 paths.

Every call site has ``g1 = g`` (the group generator), so that base rides
the fixed-base PowRadix table.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from electionguard_tpu.core import sha256_jax
from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.core.group_jax import (jax_exp_ops, jax_ops,
                                              limbs_to_bytes_be)
from electionguard_tpu.core.hash import _encode, hash_elems


def batch_cp_verify(group: GroupContext,
                    xs: Sequence[int], g2s: Sequence[int],
                    ys: Sequence[int],
                    cs: Sequence[int], vs: Sequence[int],
                    context) -> np.ndarray:
    """Verify B generic CP proofs with ``g1 = g`` in a few dispatches.

    Row i claims ``log_g xs[i] == log_{g2s[i]} ys[i]`` with (challenge,
    response) = (cs[i], vs[i]); ``context`` is the Fiat–Shamir context
    element (extended base hash).  Returns a (B,) bool mask, semantically
    identical to ``GenericChaumPedersenProof.is_valid``
    (crypto/chaum_pedersen.py:38): recompute ``a = g^v x^c``,
    ``b = g2^v y^c`` and re-derive the challenge.
    """
    B = len(xs)
    if B == 0:
        return np.zeros(0, dtype=bool)
    eo, ee = jax_ops(group), jax_exp_ops(group)
    x_l = eo.to_limbs_p(xs)
    g2_l = eo.to_limbs_p(g2s)
    y_l = eo.to_limbs_p(ys)
    c_l = ee.to_limbs(cs)
    v_l = ee.to_limbs(vs)

    # x^c, g2^v, y^c in ONE variable-base dispatch; g^v via the fixed table
    var = np.asarray(eo.powmod(
        np.concatenate([x_l, g2_l, y_l]),
        np.concatenate([c_l, v_l, c_l])))
    gp = np.asarray(eo.g_pow(v_l))
    a = np.asarray(eo.mulmod(gp, var[:B]))
    b = np.asarray(eo.mulmod(var[B:2 * B], var[2 * B:]))

    if sha256_jax.supports(group):
        # c' = H(context, g, g2, x, y, a, b) hashed + reduced mod q on-device
        prefix = _encode(context) + _encode(group.G_MOD_P)
        c_limbs = np.asarray(sha256_jax.batch_challenge_p(
            group, prefix,
            [limbs_to_bytes_be(g2_l), limbs_to_bytes_be(x_l),
             limbs_to_bytes_be(y_l), limbs_to_bytes_be(a),
             limbs_to_bytes_be(b)]))
        return (np.asarray(c_l) == c_limbs).all(axis=1)

    # host-hash fallback (non-production groups, e.g. the tiny test group);
    # commitments still come from the device — no host pow anywhere
    from electionguard_tpu.core import bignum_jax as bn
    a_i = bn.limbs_to_ints(a)
    b_i = bn.limbs_to_ints(b)
    ok = np.zeros(B, dtype=bool)
    for i in range(B):
        c = hash_elems(group, context, group.G_MOD_P,
                       group.int_to_p(g2s[i]), group.int_to_p(xs[i]),
                       group.int_to_p(ys[i]),
                       group.int_to_p(a_i[i]), group.int_to_p(b_i[i]))
        ok[i] = c.value == cs[i]
    return ok

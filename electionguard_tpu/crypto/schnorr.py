"""Schnorr proofs of knowledge of a secret key.

Native replacement for the reference's [ext] ``SchnorrProof`` — wire form is
(challenge, response) only (reference: src/main/proto/common.proto:37-42);
the commitment is recomputed at verification, so verify checks the
Fiat–Shamir equation rather than comparing commitments.

Prove knowledge of ``s`` with ``K = g^s``:
  commitment ``h = g^u``; challenge ``c = H(K, h)``; response ``v = u - c·s``.
Verify: ``h' = g^v · K^c``, accept iff ``c == H(K, h')``.

Every guardian polynomial coefficient carries one of these (key ceremony
PublicKeySet — reference: src/main/proto/keyceremony_trustee_rpc.proto:22-28);
verification of all commitments from all guardians is a batch job
(SURVEY.md §3.1 🔥 "verifies Schnorr proofs").
"""

from __future__ import annotations

from dataclasses import dataclass

from electionguard_tpu.core.group import ElementModP, ElementModQ, GroupContext
from electionguard_tpu.core.hash import hash_elems


@dataclass(frozen=True)
class SchnorrProof:
    public_key: ElementModP
    challenge: ElementModQ
    response: ElementModQ

    def is_valid(self) -> bool:
        g = self.public_key.group
        commitment = g.mult_p(g.g_pow_p(self.response),
                              g.pow_p(self.public_key, self.challenge))
        return self.challenge == hash_elems(g, self.public_key, commitment)


def make_schnorr_proof(group: GroupContext, secret: ElementModQ,
                       public_key: ElementModP,
                       nonce: ElementModQ) -> SchnorrProof:
    h = group.g_pow_p(nonce)
    c = hash_elems(group, public_key, h)
    v = group.sub_q(nonce, group.mult_q(c, secret))
    return SchnorrProof(public_key, c, v)

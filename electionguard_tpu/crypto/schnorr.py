"""Schnorr proofs of knowledge of a secret key.

Native replacement for the reference's [ext] ``SchnorrProof`` — wire form is
(challenge, response) only (reference: src/main/proto/common.proto:37-42);
the commitment is recomputed at verification, so verify checks the
Fiat–Shamir equation rather than comparing commitments.

Prove knowledge of ``s`` with ``K = g^s``:
  commitment ``h = g^u``; challenge ``c = H(K, h)``; response ``v = u - c·s``.
Verify: ``h' = g^v · K^c``, accept iff ``c == H(K, h')``.

Every guardian polynomial coefficient carries one of these (key ceremony
PublicKeySet — reference: src/main/proto/keyceremony_trustee_rpc.proto:22-28);
verification of all commitments from all guardians is a batch job
(SURVEY.md §3.1 🔥 "verifies Schnorr proofs").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from electionguard_tpu.core.group import ElementModP, ElementModQ, GroupContext
from electionguard_tpu.core.hash import hash_elems


@dataclass(frozen=True)
class SchnorrProof:
    public_key: ElementModP
    challenge: ElementModQ
    response: ElementModQ
    # Untrusted commitment hint h = g^u (plain int): unserialized,
    # excluded from equality/repr; the RLC batch verifier hash-checks
    # it per proof before use (see batch_schnorr_verify).
    commitment_hint: Optional[int] = field(
        default=None, compare=False, repr=False)

    def is_valid(self) -> bool:
        g = self.public_key.group
        commitment = g.mult_p(g.g_pow_p(self.response),
                              g.pow_p(self.public_key, self.challenge))
        return self.challenge == hash_elems(g, self.public_key, commitment)


def make_schnorr_proof(group: GroupContext, secret: ElementModQ,
                       public_key: ElementModP,
                       nonce: ElementModQ) -> SchnorrProof:
    h = group.g_pow_p(nonce)
    c = hash_elems(group, public_key, h)
    v = group.sub_q(nonce, group.mult_q(c, secret))
    return SchnorrProof(public_key, c, v, commitment_hint=h.value)


def batch_schnorr_verify(group: GroupContext, proofs,
                         check_subgroup: bool = False):
    """Verify B Schnorr proofs in a few device dispatches.

    ``proofs``: sequence of SchnorrProof.  Returns a (B,) bool mask,
    semantically identical to per-proof ``is_valid``: the key carries
    exponents {c, q} through ONE shared-base multi-exp (K^c for the
    commitment recompute; K^q for the subgroup check when
    ``check_subgroup`` — then the return is a pair of masks
    ``(proof_ok, subgroup_ok)``), plus one fixed-base pass (g^v), one
    product, and one batched Fiat–Shamir (device SHA on the production
    group, host hash_elems otherwise).  The reference verifies these one
    at a time inside each trustee [ext] (SURVEY.md §3.1 🔥); the
    verifier's V2 runs the whole ceremony's proofs as one batch.

    Under ``EGTPU_VERIFY_BATCH`` (and when every proof carries its
    ``commitment_hint``) the commitment recompute is replaced by a hash
    binding of each hint plus ONE random-linear-combination check over
    the whole batch (two MSMs — verify/rlc.py); an RLC reject bisects
    recursively with fresh randomizers so each failing proof is still
    named individually, with per-proof ``is_valid`` as the leaf oracle.
    Hash-red rows (hint absent from the equation, e.g. stale after
    tampering) also drop to ``is_valid``, so the returned masks are
    semantically identical to the naive path in every case.
    """
    import numpy as np

    from electionguard_tpu.core import bignum_jax as bn
    from electionguard_tpu.core import sha256_jax
    from electionguard_tpu.core.group_jax import (jax_exp_ops, jax_ops,
                                                  limbs_to_bytes_be)
    from electionguard_tpu.utils import knobs

    B = len(proofs)
    if B == 0:
        empty = np.zeros(0, dtype=bool)
        return (empty, empty) if check_subgroup else empty
    eo, ee = jax_ops(group), jax_exp_ops(group)
    k_l = np.asarray(eo.to_limbs_p([p.public_key.value for p in proofs]))
    c_l = np.asarray(ee.to_limbs([p.challenge.value for p in proofs]))
    v_l = np.asarray(ee.to_limbs([p.response.value for p in proofs]))
    # the 0 < K < p range mask is part of the per-proof semantics, so it
    # is computed UNCONDITIONALLY and ANDed into the returned proof mask
    # — with check_subgroup=False it was previously skipped entirely
    # (ADVICE r5): an out-of-range key could pass
    in_range = np.fromiter(
        (0 < p.public_key.value < group.p for p in proofs),
        dtype=bool, count=B)
    if (knobs.get_flag("EGTPU_VERIFY_BATCH")
            and all(p.commitment_hint is not None
                    and 0 < p.commitment_hint < group.p for p in proofs)):
        return _rlc_schnorr_verify(group, proofs, check_subgroup,
                                   eo, k_l, c_l, in_range)
    if check_subgroup:
        q_rep = np.broadcast_to(bn.int_to_limbs(group.q, ee.ne),
                                c_l.shape)
        pows = np.asarray(eo.multi_powmod(
            k_l, np.stack([c_l, q_rep], axis=1)))
        kc, kq = pows[:, 0], pows[:, 1]
        one = np.zeros_like(kq)
        one[:, 0] = 1
        sub_ok = in_range & (kq == one).all(axis=1)
    else:
        kc = np.asarray(eo.powmod(k_l, c_l))
    gv = np.asarray(eo.g_pow(v_l))
    com = np.asarray(eo.mulmod(gv, kc))
    if sha256_jax.supports(group):
        chal = np.asarray(sha256_jax.batch_challenge_p(
            group, b"", [limbs_to_bytes_be(k_l), limbs_to_bytes_be(com)]))
        ok = (chal == c_l).all(axis=1)
    else:
        com_b = limbs_to_bytes_be(com)
        ok = np.zeros(B, dtype=bool)
        for i, p in enumerate(proofs):
            c = hash_elems(group, p.public_key,
                           group.bytes_to_p(bytes(com_b[i])))
            ok[i] = (c == p.challenge)
    ok = ok & in_range
    return (ok, sub_ok) if check_subgroup else ok


def _rlc_schnorr_verify(group: GroupContext, proofs, check_subgroup,
                        eo, k_l, c_l, in_range):
    """RLC batch path of ``batch_schnorr_verify`` (flag-gated by the
    caller).  Hash-bind every hint, one ``rlc_check_schnorr`` over the
    bound rows, recursive bisection (fresh randomizers per split) on
    reject with per-proof ``is_valid`` at the leaves, and a membership
    RLC for the subgroup mask.  Soundness budget: verify/rlc.py."""
    import numpy as np

    from electionguard_tpu.core import bignum_jax as bn
    from electionguard_tpu.core import sha256_jax
    from electionguard_tpu.core.group_jax import limbs_to_bytes_be
    from electionguard_tpu.obs import REGISTRY, span
    from electionguard_tpu.verify import rlc

    B = len(proofs)
    keys = [p.public_key.value for p in proofs]
    cs = [p.challenge.value for p in proofs]
    vs = [p.response.value for p in proofs]
    hints = [p.commitment_hint for p in proofs]
    sub_ok = None
    with span("verify.batch", {"family": "V2.schnorr", "n": B}):
        REGISTRY.counter("verify_rlc_batches_total").inc()
        h_l = np.asarray(eo.to_limbs_p(hints))
        if sha256_jax.supports(group):
            chal = np.asarray(sha256_jax.batch_challenge_p(
                group, b"",
                [limbs_to_bytes_be(k_l), limbs_to_bytes_be(h_l)]))
            hash_ok = (chal == c_l).all(axis=1)
        else:
            hash_ok = np.zeros(B, dtype=bool)
            for i, p in enumerate(proofs):
                c = hash_elems(group, p.public_key,
                               ElementModP(hints[i], group))
                hash_ok[i] = (c == p.challenge)
        ok = np.array(hash_ok, dtype=bool)
        fell_back = False
        # a hash-red row's hint is not the commitment the challenge was
        # derived from (absent/stale/tampered) — the proof itself may
        # still be valid, so judge it from scratch
        for i in np.nonzero(~hash_ok)[0]:
            fell_back = True
            ok[i] = proofs[int(i)].is_valid()

        def bisect(idxs):
            nonlocal fell_back
            if rlc.rlc_check_schnorr(
                    eo, [keys[i] for i in idxs], [cs[i] for i in idxs],
                    [vs[i] for i in idxs], [hints[i] for i in idxs]):
                return
            fell_back = True
            if len(idxs) == 1:
                ok[idxs[0]] = proofs[idxs[0]].is_valid()
                return
            mid = len(idxs) // 2
            bisect(idxs[:mid])
            bisect(idxs[mid:])

        bisect([int(i) for i in np.nonzero(hash_ok)[0]])
        ok &= in_range
        if check_subgroup:
            if rlc.membership_rlc(eo, keys):
                sub_ok = in_range.copy()
            else:
                fell_back = True
                kq = np.asarray(eo.powmod(
                    k_l, np.broadcast_to(
                        bn.int_to_limbs(group.q, c_l.shape[1]),
                        c_l.shape)))
                one = np.zeros_like(kq)
                one[:, 0] = 1
                sub_ok = in_range & (kq == one).all(axis=1)
        if fell_back:
            REGISTRY.counter("verify_rlc_fallbacks_total").inc()
    return (ok, sub_ok) if check_subgroup else ok

"""Deterministic fault injection for the gRPC planes.

The reference system retries nothing and was never tested against a
failing network (SURVEY.md §5.3); this repo's retry/degradation paths
exist precisely to survive such failures — and untested failure paths
are broken failure paths.  This module injects faults *deterministically*
(on the Nth call of a named method), so the chaos suite
(tests/test_faults.py) can drive every recovery path and assert the
election record still verifies.

A ``FaultPlan`` is a list of rules::

    {"rules": [
        {"method": "registerTrustee", "kind": "unavailable", "on_calls": [1, 2]},
        {"method": "directDecrypt",   "kind": "latency", "latency_s": 0.2},
        {"method": "receiveSecretKeyShare", "kind": "drop_response",
         "on_calls": [1], "where": "server"}
    ]}

Kinds:

* ``unavailable`` / ``deadline`` — client side: the request never reaches
  the peer; the caller sees UNAVAILABLE / DEADLINE_EXCEEDED (a dead or
  unreachable peer).  Server side: the rpc aborts *before* the impl runs.
* ``latency`` — added delay before the call proceeds (either side).
* ``drop_response`` — server side only: the impl RUNS (state commits),
  then the response is dropped and the client sees UNAVAILABLE — the
  idempotency killer.  A retried rpc replays against already-committed
  state; every service must tolerate that.
* ``crash_after`` — server side: the impl runs, then the process "dies"
  before the response goes out.  In-process tests wire ``plan.crash_cb``
  (typically to stop the server); an env-loaded plan in a subprocess
  hard-exits with ``os._exit(137)`` — a genuine crash: no atexit, no
  graceful drain, connection reset.  Deterministic "trustee dies
  mid-ceremony", at an exact protocol point instead of a timer.

Activation:

* in-process tests: ``faults.install(plan)`` / ``faults.clear()``;
* subprocesses: ``EGTPU_FAULT_PLAN`` env var — inline JSON, or
  ``@/path/to/plan.json``.  ``rpc_util.make_channel`` and
  ``rpc_util.generic_service`` consult ``active_plan()`` so every client
  channel and server in the process participates with zero call-site
  changes.

Call counters are per (where, method) and process-local; plans fire the
same way on every run — no randomness, no wall-clock dependence.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

import grpc

from electionguard_tpu.utils import clock


class InjectedRpcError(grpc.RpcError):
    """A client-side injected failure, quacking like a real RpcError."""

    def __init__(self, code: grpc.StatusCode, details: str):
        super().__init__()
        self._code = code
        self._details = details

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._details

    def __str__(self) -> str:
        return f"InjectedRpcError({self._code}, {self._details!r})"


_KINDS = ("unavailable", "deadline", "latency", "drop_response",
          "crash_after")


@dataclass(frozen=True)
class FaultRule:
    method: str                  # method short name; "*" matches every method
    kind: str                    # one of _KINDS
    on_calls: tuple[int, ...] = ()   # 1-based call indices; () = every call
    latency_s: float = 0.0
    where: str = ""              # "client" | "server"; "" = kind default

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")

    @property
    def side(self) -> str:
        if self.where:
            return self.where
        return ("server" if self.kind in ("drop_response", "crash_after")
                else "client")

    def matches(self, method: str, call_index: int) -> bool:
        if self.method != "*" and self.method != method:
            return False
        return not self.on_calls or call_index in self.on_calls


@dataclass
class FaultPlan:
    rules: list[FaultRule] = field(default_factory=list)
    #: wired by in-process tests that use ``crash_after``: called with
    #: the method name; typically stops the server to simulate a death
    crash_cb: Optional[Callable[[str], None]] = None
    #: env-loaded plans set this: ``crash_after`` without a wired cb
    #: hard-exits the process (os._exit(137)) — a genuine crash
    hard_exit: bool = False

    def __post_init__(self):
        self._lock = threading.Lock()
        self._counts: dict[tuple[str, str], int] = {}
        #: audit log of every injected fault: (where, method, call_index,
        #: kind) — the chaos suite asserts its plan actually fired
        self.injected: list[tuple[str, str, int, str]] = []

    @staticmethod
    def from_json(text: str) -> "FaultPlan":
        data = json.loads(text)
        return FaultPlan(rules=[
            FaultRule(method=r["method"], kind=r["kind"],
                      on_calls=tuple(r.get("on_calls", ())),
                      latency_s=float(r.get("latency_s", 0.0)),
                      where=r.get("where", ""))
            for r in data.get("rules", [])])

    @staticmethod
    def from_env() -> Optional["FaultPlan"]:
        spec = os.environ.get("EGTPU_FAULT_PLAN", "")
        if not spec:
            return None
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                spec = f.read()
        plan = FaultPlan.from_json(spec)
        plan.hard_exit = True
        return plan

    # ------------------------------------------------------------------
    def _next_index(self, where: str, method: str) -> int:
        with self._lock:
            n = self._counts.get((where, method), 0) + 1
            self._counts[(where, method)] = n
            return n

    def firing(self, where: str, method: str) -> list[tuple[FaultRule, int]]:
        """Advance the (where, method) call counter and return the rules
        firing on this call (with the call index, for the audit log)."""
        n = self._next_index(where, method)
        out = []
        for r in self.rules:
            if r.side == where and r.matches(method, n):
                with self._lock:
                    self.injected.append((where, method, n, r.kind))
                out.append((r, n))
        return out


# ---------------------------------------------------------------------------
# process-wide active plan
# ---------------------------------------------------------------------------

_active: Optional[FaultPlan] = None
_loaded_env = False
_install_lock = threading.Lock()


def install(plan: FaultPlan) -> FaultPlan:
    """Activate ``plan`` for every channel/server created afterwards."""
    global _active, _loaded_env
    with _install_lock:
        _active = plan
        _loaded_env = True
    return plan


def clear() -> None:
    global _active, _loaded_env
    with _install_lock:
        _active = None
        # keep _loaded_env True: an explicit clear() must not resurrect
        # an env plan mid-test
        _loaded_env = True


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, else one lazily loaded from EGTPU_FAULT_PLAN."""
    global _active, _loaded_env
    with _install_lock:
        if not _loaded_env:
            _loaded_env = True
            _active = FaultPlan.from_env()
        return _active


# ---------------------------------------------------------------------------
# client interceptor
# ---------------------------------------------------------------------------

def apply_client_rules(plan: FaultPlan, method: str) -> None:
    """Run ``plan``'s client-side rules for ``method``: sleep injected
    latency, raise injected errors.  Shared by the real channel
    interceptor and the sim transport (which has no grpc channel to
    intercept)."""
    for rule, _n in plan.firing("client", method):
        if rule.kind == "latency":
            clock.sleep(rule.latency_s)
        elif rule.kind == "unavailable":
            raise InjectedRpcError(
                grpc.StatusCode.UNAVAILABLE,
                f"injected UNAVAILABLE on {method}")
        elif rule.kind == "deadline":
            raise InjectedRpcError(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                f"injected DEADLINE_EXCEEDED on {method}")


class FaultClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    """Applies a plan's client-side rules before the request leaves."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def intercept_unary_unary(self, continuation, client_call_details,
                              request):
        method = client_call_details.method.rsplit("/", 1)[-1]
        apply_client_rules(self.plan, method)
        return continuation(client_call_details, request)


def intercept_channel(channel: grpc.Channel) -> grpc.Channel:
    """Wrap ``channel`` with the active plan's client interceptor (no-op
    without an active plan)."""
    plan = active_plan()
    if plan is None:
        return channel
    return grpc.intercept_channel(channel, FaultClientInterceptor(plan))


# ---------------------------------------------------------------------------
# server wrapper
# ---------------------------------------------------------------------------

def wrap_server_impl(method: str, fn: Callable) -> Callable:
    """Wrap one ``fn(request, context)`` impl with the active plan's
    server-side rules (no-op without an active plan)."""
    plan = active_plan()
    if plan is None:
        return fn

    def wrapped(request, context):
        # context.abort raises, so a firing error rule never reaches the
        # trailing fn call; drop/crash rules run fn exactly once first
        for rule, _n in plan.firing("server", method):
            if rule.kind == "latency":
                clock.sleep(rule.latency_s)
            elif rule.kind in ("unavailable", "deadline"):
                context.abort(
                    grpc.StatusCode.UNAVAILABLE
                    if rule.kind == "unavailable"
                    else grpc.StatusCode.DEADLINE_EXCEEDED,
                    f"injected {rule.kind} on {method}")
            elif rule.kind == "drop_response":
                fn(request, context)          # state COMMITS ...
                context.abort(grpc.StatusCode.UNAVAILABLE,  # ... response lost
                              f"injected response drop on {method}")
            elif rule.kind == "crash_after":
                fn(request, context)
                if plan.crash_cb is not None:
                    plan.crash_cb(method)
                elif plan.hard_exit:
                    logging.getLogger("egtpu.faults").warning(
                        "injected crash after %s: hard process exit",
                        method)
                    os._exit(137)   # no atexit, no drain — a real crash
                context.abort(grpc.StatusCode.UNAVAILABLE,
                              f"injected crash after {method}")
        return fn(request, context)

    return wrapped

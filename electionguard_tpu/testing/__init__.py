"""Deterministic fault injection for the rpc and process planes."""

"""Independent Pedersen commitment bases for the shuffle proof.

The Terelius–Wikström permutation commitment is binding only if nobody
knows discrete logs between the bases, so they cannot be ``g^{x_i}`` for
known ``x_i``.  Standard construction: hash a public seed to candidate
residues and project them into the order-q subgroup with one cofactor
exponentiation ``h_i = t_i^{(p-1)/q} mod p`` — a dlog-free
hash-to-group.  The projection is the only heavy step (a full-width
exponent ladder) and runs as ONE batched device dispatch over all N+1
candidates (``JaxGroupOps.cofactor_pow``); results are cached per
(group, seed, count), so the K stages of one election derive them once.
"""

from __future__ import annotations

import threading

import numpy as np

from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.core.group_jax import jax_ops
from electionguard_tpu.core.hash import hash_digest

_lock = threading.Lock()
#: (group spec name, seed, count) -> [h, h_0, ..., h_{count-1}]
_cache: dict[tuple, list[int]] = {}
_CACHE_MAX = 8


def generator_seed(extended_base_hash) -> bytes:
    """The per-election generator seed: every stage of one election uses
    the same bases, derived from the extended base hash."""
    return hash_digest("mix-generators", extended_base_hash)


def derive_generators(group: GroupContext, seed: bytes,
                      count: int) -> list[int]:
    """``count + 1`` independent subgroup generators [h, h_0..h_{count-1}]
    for ``seed``: candidates t_i = H(seed, i, retry) mod p, projected by
    one batched cofactor exponentiation; candidates that project to the
    identity (probability ~1/q per draw) are re-derived host-side."""
    key = (group.spec.name, seed, count)
    with _lock:
        got = _cache.get(key)
    if got is not None:
        return got
    ops = jax_ops(group)
    p, q = group.p, group.q
    cand = []
    for i in range(count + 1):
        t = int.from_bytes(hash_digest(seed, i, 0), "big") % p
        cand.append(t if t > 1 else t + 2)
    out = ops.from_limbs(np.asarray(ops.cofactor_pow(ops.to_limbs_p(cand))))
    r = (p - 1) // q
    for i, h in enumerate(out):
        retry = 1
        while h == 1:  # negligible-probability path; rehash until useful
            t = int.from_bytes(hash_digest(seed, i, retry), "big") % p
            h = pow(t if t > 1 else t + 2, r, p)
            retry += 1
        out[i] = h
    with _lock:
        while len(_cache) >= _CACHE_MAX:
            _cache.pop(next(iter(_cache)))
        _cache[key] = out
    return out

"""Verifiable re-encryption mixnet plane (Terelius–Wikström).

The ballot-anonymization stage that companions an ElectionGuard record
(PAPERS.md: "A Generalised and Optimised Variant of Wikström's Mixnet",
arxiv 1901.08371): each mix stage re-encrypts and permutes the cast
ballots' ciphertext rows and publishes a proof of shuffle, so the link
between a ballot's position in the record and its position in the mixed
output is destroyed while anyone can verify no ciphertext was dropped,
duplicated, or substituted.

Modules:

* ``generators``  — independent Pedersen bases h, h_0..h_{N-1}
  (hash-to-subgroup via one batched cofactor exponentiation);
* ``shuffle``     — the batched re-encryption shuffle (one fused device
  program per power-of-two bucket, same dispatch discipline as the
  serving plane);
* ``proof``       — the Terelius–Wikström proof of shuffle (permutation
  commitment, Fiat–Shamir challenges via ``core.hash``, commitment-
  consistency and product-argument responses), all commitment
  exponentiations batched on device;
* ``stage``       — the ``MixStage`` record artifact + per-stage
  orchestration (``run_stage``), rows-from-ballots extraction;
* ``verify_mix``  — batched proof verification with layered, DISTINCT
  failure classes (structure / chain / membership / binding /
  permutation / re-encryption), wired into ``verify.verifier`` as the
  V15 check family.

The mixnet is almost entirely batched modexp/multi-exp — the workload
shape SZKP-style ZK accelerators target (arxiv 2408.05890); here the
accelerator is the same fused bignum pipeline the rest of the workflow
drives.  Everything is instrumented with ``obs`` spans (``mix.shuffle``,
``mix.prove``, ``mix.verify``) and registry counters.
"""

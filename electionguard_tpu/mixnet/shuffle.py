"""Batched re-encryption shuffle: the mixnet's data plane.

One mix stage takes N rows of W ElGamal ciphertexts (one row per cast
ballot, one column per selection), samples a permutation π and fresh
re-encryption randomness r̃ on the host, and computes

    Ã_{i,w} = A_{π(i),w} · g^{r̃_{i,w}}      B̃_{i,w} = B_{π(i),w} · K^{r̃_{i,w}}

for every element in ONE fused device program per dispatch: both
fixed-base ladders (g and K PowRadix tables) plus the two Montgomery
combines, compiled once per power-of-two bucket shape via the shared
``run_tiled`` policy — the same one-compile-per-bucket discipline the
serving batcher enforces (serve/batcher.py), so K sequential stages of
the same record never recompile after the first.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np

from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.core.group_jax import jax_exp_ops, jax_ops, \
    run_tiled_multi
from electionguard_tpu.core.hash import hash_digest
from electionguard_tpu.obs import REGISTRY, span


def prf_scalars(seed: bytes, tag: str, count: int, q: int) -> list[int]:
    """Deterministic Z_q scalars from a secret seed: H(seed, tag, i) mod q.
    The mixer's nonce PRF — same posture as the encryptor's seed-derived
    nonces (uniform enough mod q: 256-bit digest, q ≤ 256 bits)."""
    return [int.from_bytes(hash_digest(seed, tag, i), "big") % q
            for i in range(count)]


def prf_permutation(seed: bytes, n: int) -> np.ndarray:
    """Deterministic permutation of range(n) from the seed (Fisher–Yates
    with PRF draws, so a seeded stage is exactly reproducible)."""
    perm = np.arange(n, dtype=np.int64)
    for i in range(n - 1, 0, -1):
        j = int.from_bytes(hash_digest(seed, "perm", i), "big") % (i + 1)
        perm[i], perm[j] = perm[j], perm[i]
    return perm


@functools.lru_cache(maxsize=8)
def get_shuffler(group: GroupContext, public_key: int) -> "Shuffler":
    """Process-wide shuffler per (group, key): the jitted re-encryption
    program is cached on the instance, so K stages (and repeated
    ``run_stage`` calls) share one compiled program set."""
    return Shuffler(group, public_key)


def _fused_reenc(ops):
    """ONE jitted (k_table, a, b, r) → (A·g^r, B·K^r) program per ops
    instance, shared by every Shuffler on that group.  The key table is
    a traced ARGUMENT, not a closure constant — baking K into the
    program would recompile the fused pipeline for every election key
    (a multi-second stall per fresh key ceremony)."""
    jfn = getattr(ops, "_reenc_fused_j", None)
    if jfn is None:
        def _impl(kt, a, b, r):
            gr = ops._fixed_pow_impl(ops.g_table, r)
            kr = ops._fixed_pow_impl(kt, r)
            return ops._mulmod_impl(a, gr), ops._mulmod_impl(b, kr)
        jfn = ops._reenc_fused_j = jax.jit(_impl)
    return jfn


class Shuffler:
    """Re-encryption engine for one (group, public key) pair.

    ``ops`` defaults to the single-device ``JaxGroupOps``; a mix server
    passes a ``parallel.sharded.ShardedGroupOps`` to spread the row axis
    over its device mesh — the sharded path composes the same fixed-base
    ladders and Montgomery combines from the public array API (the fused
    single-program variant closes over single-device internals), so both
    paths are bit-identical for the same seed."""

    def __init__(self, group: GroupContext, public_key: int, ops=None):
        self.group = group
        self.public_key = public_key
        self.ops = ops if ops is not None else jax_ops(group)
        self.eops = jax_exp_ops(group)
        self._sharded = hasattr(self.ops, "mesh")
        self._k_table = self.ops.fixed_table(public_key)
        self._reenc_j = None if self._sharded else _fused_reenc(self.ops)

    def reencrypt(self, pads_l: np.ndarray, datas_l: np.ndarray,
                  r_l: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Batched (M, n) limb re-encryption through the bucketed
        dispatch policy (pad rows are the identity ciphertext (1,1) with
        r = 0, so padding re-encrypts to itself)."""
        if self._sharded:
            ops = self.ops
            gr = ops.g_pow(r_l)
            kr = ops.base_pow(self.public_key, r_l)
            return (np.asarray(ops.mulmod(pads_l, gr)),
                    np.asarray(ops.mulmod(datas_l, kr)))
        kt = self._k_table
        out = run_tiled_multi(lambda a, b, r: self._reenc_j(kt, a, b, r),
                              [pads_l, datas_l, r_l],
                              [True, True, False])
        return np.asarray(out[0]), np.asarray(out[1])

    def shuffle(self, pads: Sequence[Sequence[int]],
                datas: Sequence[Sequence[int]],
                seed: bytes,
                perm: Optional[np.ndarray] = None,
                ) -> tuple[list[list[int]], list[list[int]],
                           np.ndarray, list[list[int]]]:
        """Shuffle N rows of W ciphertexts.  Returns
        ``(out_pads, out_datas, perm, rand)`` where output row i is the
        re-encryption of input row perm[i] under randomness rand[i][w].
        ``perm`` may be injected by a (test-only) caller; honest callers
        leave it None and get the PRF permutation for ``seed``."""
        n = len(pads)
        w = len(pads[0]) if n else 0
        if any(len(r) != w for r in pads) or any(len(r) != w for r in datas):
            raise ValueError("mix rows must have uniform width")
        if perm is None:
            perm = prf_permutation(seed, n)
        flat_r = prf_scalars(seed, "reenc", n * w, self.group.q)
        rand = [flat_r[i * w:(i + 1) * w] for i in range(n)]
        attrs = {"n": n, "w": w}
        with span("mix.shuffle", attrs):
            a_in = [pads[perm[i]][j] for i in range(n) for j in range(w)]
            b_in = [datas[perm[i]][j] for i in range(n) for j in range(w)]
            a_out, b_out = self.reencrypt(
                self.ops.to_limbs_p(a_in), self.ops.to_limbs_p(b_in),
                self.eops.to_limbs(flat_r))
            a_i = self.ops.from_limbs(a_out)
            b_i = self.ops.from_limbs(b_out)
        REGISTRY.counter("mix_rows_shuffled_total").inc(n)
        REGISTRY.counter("mix_ciphertexts_reencrypted_total").inc(n * w)
        out_pads = [a_i[i * w:(i + 1) * w] for i in range(n)]
        out_datas = [b_i[i * w:(i + 1) * w] for i in range(n)]
        return out_pads, out_datas, perm, rand

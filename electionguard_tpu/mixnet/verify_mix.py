"""Batched verification of published mix stages (the V15 check family).

Layered checks, each with its OWN check name so tampering classes are
distinguishable in the verification report (and in tests):

  V15.mix_structure    — stage indices, row counts, vector lengths
  V15.mix_chain        — stage k's input hash == stage k-1's output
                         (stage 0 anchors to the cast ballots); a
                         replayed/forged transcript from another input
                         fails HERE, before any crypto runs
  V15.mix_membership   — outputs + transcript P-elements in the order-q
                         subgroup (batched x^q == 1)
  V15.mix_binding      — the Fiat–Shamir challenge re-derives from the
                         actual record data + transcript; a ciphertext
                         tampered after proving fails HERE
  V15.mix_permutation  — t_1/t_2/t_3 and the t̂ chain equations (the
                         committed exponents form a permutation)
  V15.mix_reencryption — the t_4 column equations (outputs re-encrypt
                         exactly the inputs); a cheating mixer whose
                         outputs don't match its committed permutation
                         fails HERE

Within a stage the layers short-circuit: once a layer fails, deeper
equations are meaningless (their challenges no longer bind) and are
skipped.  All N-wide exponentiations are batched device dispatches.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.core.group_jax import jax_exp_ops, jax_ops
from electionguard_tpu.core.hash import hash_digest
from electionguard_tpu.mixnet.generators import derive_generators, \
    generator_seed
from electionguard_tpu.mixnet.proof import MixProof, _ctx_digest, \
    _main_challenge, _u_challenges, _elems_digest, rows_digest, \
    transcript_digests
from electionguard_tpu.mixnet.stage import MixStage, rows_from_ballots
from electionguard_tpu.obs import REGISTRY, span
from electionguard_tpu.utils import knobs
from electionguard_tpu.verify import rlc

CHECKS = ("mix_structure", "mix_chain", "mix_membership", "mix_binding",
          "mix_permutation", "mix_reencryption")


def _check_structure(stage: MixStage, k: int, n_in: int, w_in: int,
                     res, pfx: str) -> bool:
    pr = stage.proof
    ok = True

    def bad(msg):
        nonlocal ok
        ok = False
        res.record(f"{pfx}.mix_structure", False, f"stage {k}: {msg}")

    if stage.stage_index != k:
        bad(f"header index {stage.stage_index} != position {k}")
    if stage.n_rows != n_in or len(stage.pads) != n_in \
            or len(stage.datas) != n_in:
        bad(f"row count {len(stage.pads)} != input rows {n_in}")
    if stage.width != w_in or any(len(r) != w_in for r in stage.pads) \
            or any(len(r) != w_in for r in stage.datas):
        bad(f"column width != input width {w_in}")
    n, w = n_in, w_in
    if not (len(pr.permutation_commitments) == n
            and len(pr.chain_commitments) == n and len(pr.that) == n
            and len(pr.vhat) == n and len(pr.vprime) == n):
        bad("N-vector length mismatch in proof transcript")
    if not (len(pr.t41) == w and len(pr.t42) == w and len(pr.v4) == w):
        bad("column-vector length mismatch in proof transcript")
    return ok


def verify_stage(group: GroupContext, public_key: int, qbar,
                 stage: MixStage, in_pads, in_datas, input_hash: bytes,
                 res, pfx: str = "V15", ops=None) -> bool:
    """Verify one stage against its (already chain-checked) input rows.
    Records failures into ``res``; returns overall stage validity.
    ``ops`` defaults to the single-device plane; a ``ShardedGroupOps``
    spreads the N-wide verification dispatches over its mesh."""
    n, w = len(in_pads), len(in_pads[0])
    k = stage.stage_index
    pr = stage.proof
    q, p, g = group.q, group.p, group.g
    ops = ops if ops is not None else jax_ops(group)
    eops = jax_exp_ops(group)

    # RLC batching (EGTPU_VERIFY_BATCH): the membership screen, the
    # (2+4w) product groups and the t̂ chain all become MSMs
    # (verify/rlc.py).  Any RLC reject falls back to the exact
    # per-element/per-row computation below for attribution; a sharded
    # ops plane has no MSM entry point, so it keeps the naive dispatch.
    batch = (knobs.get_flag("EGTPU_VERIFY_BATCH")
             and hasattr(ops, "msm_ints"))
    if batch:
        REGISTRY.counter("verify_rlc_batches_total").inc()

    # ---- membership: every P element of outputs + transcript ----------
    flat = ([x for row in stage.pads for x in row]
            + [x for row in stage.datas for x in row]
            + list(pr.permutation_commitments) + list(pr.chain_commitments)
            + list(pr.that)
            + [pr.t1, pr.t2, pr.t3, *pr.t41, *pr.t42])
    mem_ok = False
    if batch:
        with span("verify.batch",
                  {"family": "V15.membership", "n": len(flat)}):
            mem_ok = rlc.membership_rlc(ops, flat)
        if not mem_ok:
            REGISTRY.counter("verify_rlc_fallbacks_total").inc()
    if not mem_ok:
        okm = np.asarray(ops.is_valid_residue(ops.to_limbs_p(flat)))
        if not okm.all():
            res.record(f"{pfx}.mix_membership", False,
                       f"stage {k}: {int((~okm).sum())} transcript/output "
                       f"elements outside the order-q subgroup")
            return False

    # ---- binding: the Fiat–Shamir challenge re-derives ----------------
    output_hash = rows_digest(group, stage.pads, stage.datas)
    ctx = _ctx_digest(group, public_key, qbar, k, n, w, input_hash,
                      output_hash)
    u_seed = hash_digest(
        "mix-u", ctx, _elems_digest(group, pr.permutation_commitments))
    u = _u_challenges(group, u_seed, n)
    chain_digest, t_digest = transcript_digests(group, pr)
    c = _main_challenge(group, u_seed, chain_digest, t_digest)
    if c != pr.challenge:
        res.record(f"{pfx}.mix_binding", False,
                   f"stage {k}: challenge does not re-derive from the "
                   f"published rows and transcript (tampered after "
                   f"proving?)")
        return False

    # ---- batched powers for the permutation + re-encryption layers ----
    cs = list(pr.permutation_commitments)
    chain = list(pr.chain_commitments)
    hs_all = derive_generators(group, generator_seed(qbar), n)
    h, hs = hs_all[0], hs_all[1:]
    negc = (q - c) % q
    vp = list(pr.vprime)

    # one dispatch: ∏c^u, ∏h^{v'}, and per column ∏Ã^{v'}, ∏B̃^{v'},
    # ∏A^u, ∏B^u
    bases = cs + hs
    exps = list(u) + vp
    for col in range(w):
        bases.extend(stage.pads[i][col] for i in range(n))
        exps.extend(vp)
    for col in range(w):
        bases.extend(stage.datas[i][col] for i in range(n))
        exps.extend(vp)
    for col in range(w):
        bases.extend(in_pads[i][col] for i in range(n))
        exps.extend(u)
    for col in range(w):
        bases.extend(in_datas[i][col] for i in range(n))
        exps.extend(u)
    ngroups = 2 + 4 * w
    if batch:
        # each group ∏ base_i^{exp_i} IS a multi-scalar multiplication:
        # Pippenger bucketing replaces n full ladders per group with
        # ~q_bits/w windowed bucket reductions (exact, no randomizers)
        with span("verify.batch", {"family": "V15.msm", "n": n * ngroups}):
            prods = [ops.msm_ints(bases[gi * n:(gi + 1) * n],
                                  exps[gi * n:(gi + 1) * n])
                     for gi in range(ngroups)]
    else:
        pw = np.asarray(ops.powmod(ops.to_limbs_p(bases),
                                   eops.to_limbs(exps)))
        stacked = pw.reshape(ngroups, n, ops.n).transpose(1, 0, 2)
        prods = ops.from_limbs(np.asarray(ops.prod_reduce(stacked)))
    cu, hv = prods[0], prods[1]
    av = prods[2:2 + w]
    bv = prods[2 + w:2 + 2 * w]
    au = prods[2 + 2 * w:2 + 3 * w]
    bu = prods[2 + 3 * w:]

    # t̂ chain: t̂_i == g^{v̂_i} ĉ_{i-1}^{v'_i} ĉ_i^{-c}, one batch
    that_batch_ok = False
    if batch:
        # RLC over the n chain equations: three MSMs + one fixed-base
        # power.  All bases are prover-supplied, so exponents stay exact
        # (only g gets the mod-q reduction) — soundness: verify/rlc.py.
        with span("verify.batch", {"family": "V15.that", "n": n}):
            s = rlc.sample_randomizers(n)
            e_g = sum(si * vi for si, vi in zip(s, pr.vhat)) % q
            lhs = ops.msm_ints(list(pr.that), s, exp_bits=rlc.RLC_BITS)
            rhs = (pow(g, e_g, p)
                   * ops.msm_ints([h] + chain[:-1],
                                  [si * vi for si, vi in zip(s, vp)])
                   * ops.msm_ints(chain, [si * negc for si in s])) % p
            that_batch_ok = lhs == rhs
        if not that_batch_ok:
            REGISTRY.counter("verify_rlc_fallbacks_total").inc()
    if that_batch_ok:
        that_ok = np.ones(n, dtype=bool)
    else:
        ghat = np.asarray(ops.g_pow(eops.to_limbs(pr.vhat)))
        p1 = np.asarray(ops.powmod(ops.to_limbs_p([h] + chain[:-1]),
                                   eops.to_limbs(vp)))
        p2 = np.asarray(ops.powmod(ops.to_limbs_p(chain),
                                   eops.to_limbs([negc] * n)))
        that_rec = np.asarray(
            ops.mulmod(np.asarray(ops.mulmod(ghat, p1)), p2))
        that_ok = (that_rec
                   == np.asarray(ops.to_limbs_p(pr.that))).all(axis=1)

    # scalar combines (host: a handful of single modexps)
    prod_c, prod_h = 1, 1
    for ci in cs:
        prod_c = prod_c * ci % p
    for hi in hs:
        prod_h = prod_h * hi % p
    prod_u = 1
    for ui in u:
        prod_u = prod_u * ui % q
    cbar = prod_c * pow(prod_h, -1, p) % p
    chat_bar = chain[-1] * pow(pow(h, prod_u, p), -1, p) % p
    t1_rec = pow(g, pr.v1, p) * pow(cbar, negc, p) % p
    t2_rec = pow(g, pr.v2, p) * pow(chat_bar, negc, p) % p
    t3_rec = pow(g, pr.v3, p) * hv * pow(cu, negc, p) % p

    perm_ok = (t1_rec == pr.t1 and t2_rec == pr.t2 and t3_rec == pr.t3
               and bool(that_ok.all()))
    if not perm_ok:
        parts = [name for name, bad in
                 (("t1", t1_rec != pr.t1), ("t2", t2_rec != pr.t2),
                  ("t3", t3_rec != pr.t3),
                  ("t-hat chain", not that_ok.all())) if bad]
        res.record(f"{pfx}.mix_permutation", False,
                   f"stage {k}: permutation argument fails "
                   f"({', '.join(parts)}) — committed exponents are not "
                   f"a permutation of the challenges")
        return False

    reenc_ok = True
    for col in range(w):
        t41_rec = pow(public_key, (q - pr.v4[col]) % q, p) \
            * bv[col] % p * pow(bu[col], negc, p) % p
        t42_rec = pow(g, (q - pr.v4[col]) % q, p) \
            * av[col] % p * pow(au[col], negc, p) % p
        if t41_rec != pr.t41[col] or t42_rec != pr.t42[col]:
            reenc_ok = False
            res.record(f"{pfx}.mix_reencryption", False,
                       f"stage {k}: column {col} outputs are not a "
                       f"re-encryption of the inputs under the committed "
                       f"permutation")
    return reenc_ok


def verify_stages(group: GroupContext, init, stages, res,
                  input_fn: Callable[[], tuple[list, list]],
                  pfx: str = "V15") -> bool:
    """Verify a whole mix cascade against the election record.
    ``input_fn`` lazily supplies the stage-0 rows (the cast ballots'
    ciphertexts); each later stage chains off its predecessor's output.
    Records all results into ``res`` (a ``VerificationResult``)."""
    public_key = init.joint_public_key.value
    qbar = init.extended_base_hash
    in_pads, in_datas = input_fn()
    all_ok = True
    with span("mix.verify", {"stages": len(stages)}):
        if not in_pads:
            res.record(f"{pfx}.mix_structure", False,
                       "mix stages published but the record has no cast "
                       "ballots")
            all_ok = False
        n_in = len(in_pads)
        w_in = len(in_pads[0]) if n_in else 0
        if any(len(r) != w_in for r in in_pads):
            res.record(f"{pfx}.mix_structure", False,
                       "cast ballots have non-uniform ciphertext width; "
                       "record cannot be mixed as rows")
            all_ok = False
        input_hash = rows_digest(group, in_pads, in_datas)
        for k, stage in enumerate(stages):
            if not all_ok:
                break
            if not _check_structure(stage, k, n_in, w_in, res, pfx):
                all_ok = False
                break
            if stage.input_hash != input_hash:
                res.record(f"{pfx}.mix_chain", False,
                           f"stage {k}: input hash does not match "
                           f"{'stage %d output' % (k - 1) if k else 'the cast ballots'}"
                           f" (replayed or out-of-order transcript?)")
                all_ok = False
                break
            if not verify_stage(group, public_key, qbar, stage,
                                in_pads, in_datas, input_hash, res,
                                pfx=pfx):
                all_ok = False
                break
            in_pads, in_datas = stage.pads, stage.datas
            input_hash = rows_digest(group, in_pads, in_datas)
        for name in CHECKS:
            res.record(f"{pfx}.{name}", True)
    REGISTRY.counter("mix_stages_verified_total").inc(len(stages))
    return all_ok


__all__ = ["CHECKS", "MixProof", "MixStage", "rows_from_ballots",
           "verify_stage", "verify_stages"]

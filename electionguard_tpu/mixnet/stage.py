"""The ``MixStage`` record artifact + per-stage orchestration.

A stage is what gets published: the stage's output ciphertext rows, the
binding hash of its input rows, and the full shuffle-proof transcript.
Stage k's input is stage k-1's output; stage 0's input is the cast
ballots' selection ciphertexts in record order (``rows_from_ballots``),
so the whole cascade is re-verifiable from the election record alone.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Iterable, Optional

import numpy as np

from electionguard_tpu.ballot.ciphertext import BallotState
from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.mixnet.proof import MixProof, prove_shuffle, \
    rows_digest
from electionguard_tpu.mixnet.shuffle import Shuffler, get_shuffler
from electionguard_tpu.utils import devicetime


@dataclass
class MixStage:
    """One published mix stage: output rows + proof transcript."""

    stage_index: int
    n_rows: int
    width: int
    input_hash: bytes              # rows_digest of this stage's INPUT
    pads: list                     # N x W output α values (ints)
    datas: list                    # N x W output β values (ints)
    proof: MixProof


def rows_from_ballots(ballots: Iterable) -> tuple[list, list]:
    """Stage-0 input rows: one row per CAST ballot (record order), one
    column per selection ciphertext in serialized contest/selection
    order (placeholders included — the mixnet permutes whole ballots)."""
    pads: list = []
    datas: list = []
    for b in ballots:
        if b.state != BallotState.CAST:
            continue
        row_a, row_b = [], []
        for c in b.contests:
            for s in c.selections:
                row_a.append(s.ciphertext.pad.value)
                row_b.append(s.ciphertext.data.value)
        pads.append(row_a)
        datas.append(row_b)
    return pads, datas


def run_stage(group: GroupContext, public_key: int, qbar,
              stage_index: int, in_pads, in_datas,
              seed: Optional[bytes] = None,
              shuffler: Optional[Shuffler] = None,
              perm: Optional[np.ndarray] = None) -> MixStage:
    """Shuffle + prove one stage.  ``seed`` pins the stage (tests,
    reproducible runs); None draws a fresh secret.  ``perm`` is a
    test-only injection point for adversarial permutations."""
    if not in_pads:
        raise ValueError("mix stage needs at least one input row")
    devicetime.charge("mix_stage", len(in_pads))
    seed = seed if seed is not None else secrets.token_bytes(32)
    sh = shuffler if shuffler is not None else get_shuffler(group,
                                                            public_key)
    out_pads, out_datas, perm, rand = sh.shuffle(
        in_pads, in_datas, seed, perm=perm)
    input_hash = rows_digest(group, in_pads, in_datas)
    # the proof dispatches ride the shuffler's batch plane, so a sharded
    # shuffler (mixfed server with -shards) shards the proof too
    proof = prove_shuffle(group, public_key, qbar, stage_index,
                          in_pads, in_datas, out_pads, out_datas,
                          perm, rand, seed, input_hash=input_hash,
                          ops=sh.ops)
    return MixStage(stage_index, len(in_pads), len(in_pads[0]),
                    input_hash, out_pads, out_datas, proof)

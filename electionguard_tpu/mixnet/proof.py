"""Terelius–Wikström proof of shuffle (generalised to W-wide rows).

Proves that output rows ẽ are a permuted re-encryption of input rows e
under public key K without revealing the permutation (PAPERS.md: arxiv
1901.08371; the reference ecosystem's egk-mixnet workload).  Wire form
follows this repo's convention of carrying the full sigma transcript
(commitments AND responses) so the verifier can attribute a failure to a
specific layer — binding, permutation argument, or re-encryption
consistency — instead of collapsing every tamper into one hash mismatch.

Protocol (0-based, row i, column w; ẽ_i = e_{π(i)} · (g, K)^{r̃_{i,w}}):

  permutation commitment   c_i = g^{s_i} · h_{π^{-1}(i)}
  row challenges           u_i = PRF(transcript), ũ_i = u_{π(i)}
  bridging chain           ĉ_i = g^{r̂_i} ĉ_{i-1}^{ũ_i}, ĉ_{-1} = h
                           (closed form ĉ_i = g^{R_i} h^{U_i} with host
                           mod-q recurrences R, U — so the whole chain
                           is ONE dual-fixed-base device dispatch)
  sigma commitments        t_1 = g^{ω_1}; t_2 = g^{ω_2}
                           t_3 = g^{ω_3} ∏ h_i^{ω'_i}
                           t_{41,w} = K^{-ω_{4,w}} ∏ B̃_{i,w}^{ω'_i}
                           t_{42,w} = g^{-ω_{4,w}} ∏ Ã_{i,w}^{ω'_i}
                           t̂_i = g^{ω̂_i} ĉ_{i-1}^{ω'_i}
  challenge                c = PRF(transcript, t's)
  responses                v_1 = ω_1 + c·Σs_i          (∏c_i/∏h_i = g^...)
                           v_2 = ω_2 + c·R_{N-1}       (chain total)
                           v_3 = ω_3 + c·Σs_i u_i      (∏c_i^{u_i})
                           v_{4,w} = ω_{4,w} + c·Σ r̃_{i,w} ũ_i
                           v̂_i = ω̂_i + c·r̂_i,  v'_i = ω'_i + c·ũ_i

Every N-wide exponentiation (chain, t̂, the ∏·^{ω'} products) runs as a
batched device dispatch; host work is mod-q integer algebra and
SHA-256.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.core.group_jax import jax_exp_ops, jax_ops
from electionguard_tpu.core.hash import hash_digest
from electionguard_tpu.mixnet.generators import derive_generators, \
    generator_seed
from electionguard_tpu.mixnet.shuffle import prf_scalars
from electionguard_tpu.obs import REGISTRY, span


@dataclass(frozen=True)
class MixProof:
    """Full shuffle-proof transcript (values as plain ints; wire
    validation happens at the serialize boundary)."""

    permutation_commitments: tuple   # c_i, N ElementModP values
    chain_commitments: tuple         # ĉ_i, N
    t1: int
    t2: int
    t3: int
    t41: tuple                       # per column w, W
    t42: tuple                       # per column w, W
    that: tuple                      # t̂_i, N
    challenge: int
    v1: int
    v2: int
    v3: int
    v4: tuple                        # per column w, W
    vhat: tuple                      # v̂_i, N
    vprime: tuple                    # v'_i, N


# ---------------------------------------------------------------------------
# Fiat–Shamir transcript hashing
# ---------------------------------------------------------------------------

def rows_digest(group: GroupContext, pads, datas) -> bytes:
    """Streaming SHA-256 over a row set's fixed-width byte images — the
    stage input/output binding value (MixStageHeader.input_hash)."""
    h = hashlib.sha256()
    pb = group.spec.p_bytes
    for arow, brow in zip(pads, datas):
        for a, b in zip(arow, brow):
            h.update(a.to_bytes(pb, "big"))
            h.update(b.to_bytes(pb, "big"))
    return h.digest()


def _elems_digest(group: GroupContext, xs) -> bytes:
    h = hashlib.sha256()
    pb = group.spec.p_bytes
    for x in xs:
        h.update(x.to_bytes(pb, "big"))
    return h.digest()


def _ctx_digest(group, public_key: int, qbar, stage_index: int,
                n: int, w: int, input_hash: bytes,
                output_hash: bytes) -> bytes:
    return hash_digest("mix-ctx", qbar, public_key, stage_index, n, w,
                       input_hash, output_hash)


def _u_challenges(group, u_seed: bytes, n: int) -> list[int]:
    q = group.q
    return [int.from_bytes(hash_digest(u_seed, i), "big") % q
            for i in range(n)]


def _main_challenge(group, u_seed: bytes, chain_digest: bytes,
                    t_digest: bytes) -> int:
    return int.from_bytes(
        hash_digest("mix-chal", u_seed, chain_digest, t_digest),
        "big") % group.q


def transcript_digests(group, proof: MixProof) -> tuple[bytes, bytes]:
    """(chain_digest, t_digest) of a transcript — shared by prover and
    verifier so the challenge derivation cannot diverge."""
    chain_digest = _elems_digest(group, proof.chain_commitments)
    t_digest = _elems_digest(
        group, [proof.t1, proof.t2, proof.t3, *proof.t41, *proof.t42,
                *proof.that])
    return chain_digest, t_digest


# ---------------------------------------------------------------------------
# prover
# ---------------------------------------------------------------------------

def prove_shuffle(group: GroupContext, public_key: int, qbar,
                  stage_index: int,
                  in_pads, in_datas, out_pads, out_datas,
                  perm: np.ndarray, rand: Sequence[Sequence[int]],
                  seed: bytes,
                  input_hash: Optional[bytes] = None,
                  ops=None) -> MixProof:
    """Prove ``out = π(in)`` re-encrypted with ``rand`` under ``seed``-
    derived commitment randomness.  All N-wide exponentiations are
    device dispatches; ``qbar`` is the election's extended base hash
    (binds the proof to the election), ``stage_index`` + ``input_hash``
    bind it to its place in the mix cascade.  ``ops`` defaults to the
    single-device plane; a ``ShardedGroupOps`` spreads the N-wide
    dispatches (powmod + product-reduce, fixed_multi_pow chain ladders)
    over its mesh — same public array API, bit-identical transcript."""
    n = len(in_pads)
    w = len(in_pads[0]) if n else 0
    if n < 1:
        raise ValueError("cannot prove an empty shuffle")
    q, p, g = group.q, group.p, group.g
    ops = ops if ops is not None else jax_ops(group)
    eops = jax_exp_ops(group)
    hs_all = derive_generators(group, generator_seed(qbar), n)
    h, hs = hs_all[0], hs_all[1:]

    with span("mix.prove", {"n": n, "w": w}):
        # secret scalars (PRF of the stage seed, like the encryptor's
        # nonce derivation: deterministic under a pinned seed, secret
        # otherwise)
        s = prf_scalars(seed, "s", n, q)
        rhat = prf_scalars(seed, "rhat", n, q)
        om = prf_scalars(seed, "om", 3, q)
        om4 = prf_scalars(seed, "om4", w, q)
        omhat = prf_scalars(seed, "omhat", n, q)
        omp = prf_scalars(seed, "omp", n, q)

        # permutation commitments c_i = g^{s_i} h_{π^{-1}(i)}
        inv_perm = np.argsort(np.asarray(perm))
        gs = np.asarray(ops.g_pow(eops.to_limbs(s)))
        h_perm = ops.to_limbs_p([hs[int(inv_perm[i])] for i in range(n)])
        c_vec = ops.from_limbs(np.asarray(ops.mulmod(gs, h_perm)))

        # row challenges (committed-to: c_vec is hashed before u is drawn)
        if input_hash is None:
            input_hash = rows_digest(group, in_pads, in_datas)
        output_hash = rows_digest(group, out_pads, out_datas)
        ctx = _ctx_digest(group, public_key, qbar, stage_index, n, w,
                          input_hash, output_hash)
        u_seed = hash_digest("mix-u", ctx, _elems_digest(group, c_vec))
        u = _u_challenges(group, u_seed, n)
        ut = [u[int(perm[i])] for i in range(n)]

        # bridging chain ĉ_i = g^{R_i} h^{U_i}: host mod-q recurrences,
        # one dual-fixed-base device dispatch
        R = [0] * n
        U = [0] * n
        r_prev, u_prev = 0, 1
        for i in range(n):
            R[i] = (rhat[i] + ut[i] * r_prev) % q
            U[i] = (ut[i] * u_prev) % q
            r_prev, u_prev = R[i], U[i]
        ch_exps = np.stack([eops.to_limbs(R), eops.to_limbs(U)], axis=1)
        chain = ops.from_limbs(
            np.asarray(ops.fixed_multi_pow([g, h], ch_exps)))

        # sigma commitments t̂_i = g^{ω̂_i} ĉ_{i-1}^{ω'_i}
        #                        = g^{ω̂_i + ω'_i R_{i-1}} h^{ω'_i U_{i-1}}
        e1 = [(omhat[i] + omp[i] * (R[i - 1] if i else 0)) % q
              for i in range(n)]
        e2 = [(omp[i] * (U[i - 1] if i else 1)) % q for i in range(n)]
        th_exps = np.stack([eops.to_limbs(e1), eops.to_limbs(e2)], axis=1)
        that = ops.from_limbs(
            np.asarray(ops.fixed_multi_pow([g, h], th_exps)))

        # ∏ h_i^{ω'_i} and the 2W output-column products ∏ ·^{ω'_i}:
        # one batched powmod + one product-reduce
        bases = list(hs)
        for col in range(w):
            bases.extend(out_pads[i][col] for i in range(n))
        for col in range(w):
            bases.extend(out_datas[i][col] for i in range(n))
        ngroups = 1 + 2 * w
        exps = eops.to_limbs(omp * ngroups)
        pw = np.asarray(ops.powmod(ops.to_limbs_p(bases), exps))
        stacked = pw.reshape(ngroups, n, ops.n).transpose(1, 0, 2)
        prods = ops.from_limbs(np.asarray(ops.prod_reduce(stacked)))
        h_prod = prods[0]
        a_prods = prods[1:1 + w]
        b_prods = prods[1 + w:]

        t1 = pow(g, om[0], p)
        t2 = pow(g, om[1], p)
        t3 = pow(g, om[2], p) * h_prod % p
        t41 = tuple(pow(public_key, (q - om4[col]) % q, p)
                    * b_prods[col] % p for col in range(w))
        t42 = tuple(pow(g, (q - om4[col]) % q, p)
                    * a_prods[col] % p for col in range(w))

        # challenge + responses
        proof0 = MixProof(tuple(c_vec), tuple(chain), t1, t2, t3,
                          t41, t42, tuple(that), 0, 0, 0, 0, (), (), ())
        chain_digest, t_digest = transcript_digests(group, proof0)
        c = _main_challenge(group, u_seed, chain_digest, t_digest)

        rbar = sum(s) % q
        rtilde = sum(si * ui for si, ui in zip(s, u)) % q
        rprime = [sum(rand[i][col] * ut[i] for i in range(n)) % q
                  for col in range(w)]
        v1 = (om[0] + c * rbar) % q
        v2 = (om[1] + c * R[n - 1]) % q
        v3 = (om[2] + c * rtilde) % q
        v4 = tuple((om4[col] + c * rprime[col]) % q for col in range(w))
        vhat = tuple((omhat[i] + c * rhat[i]) % q for i in range(n))
        vprime = tuple((omp[i] + c * ut[i]) % q for i in range(n))

    REGISTRY.counter("mix_stages_proved_total").inc()
    return MixProof(tuple(c_vec), tuple(chain), t1, t2, t3, t41, t42,
                    tuple(that), c, v1, v2, v3, v4, vhat, vprime)

"""Merge N shard records into ONE verifiable election record.

Each fabric worker publishes an ordinary record directory — init, framed
encrypted-ballot stream, admission journal — plus its signed
``shard_manifest.json``.  The merge is deliberately dumb where it can be
and cryptographic where it must be:

* **ballots** concatenate byte-for-byte in shard order (each stream is
  tail-repaired first, so a SIGKILL'd worker's torn final frame never
  reaches the merged record);
* **manifests** are structurally checked (signature, derived chain seed,
  admitted count vs frames, distinct shard ids) and republished together
  as ``shard_manifests.json`` — the verifier's ``V.shard_manifest``
  family re-checks them against the actual ballot stream;
* **sub-tallies** add homomorphically: ElGamal is additively homomorphic
  under ciphertext multiplication, so the fleet tally is the
  component-wise ``mult_p`` of per-shard tallies — bit-identical to
  accumulating the merged stream directly (asserted in tests).
"""

from __future__ import annotations

import logging
import os
import shutil
from dataclasses import dataclass, field
from typing import Optional, Sequence

from electionguard_tpu.ballot.tally import (EncryptedTally,
                                            EncryptedTallyContest,
                                            EncryptedTallySelection)
from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
from electionguard_tpu.fabric import manifest as fab_manifest
from electionguard_tpu.publish.election_record import TallyResult
from electionguard_tpu.publish.publisher import (Consumer, Publisher,
                                                 repair_frame_stream)

log = logging.getLogger("fabric.merge")

_BALLOTS = "encrypted_ballots.pb"


class MergeError(ValueError):
    """A shard record set that must not be merged (forged manifest,
    duplicate shard id, count mismatch, divergent init...)."""


@dataclass
class MergeReport:
    """What one merge did — per-shard counts and the merged totals."""

    out_dir: str
    n_shards: int = 0
    n_ballots: int = 0
    per_shard: list = field(default_factory=list)  # (shard_id, n_ballots)


def merge_shard_records(group: GroupContext, shard_dirs: Sequence[str],
                        out_dir: str, check: bool = True) -> MergeReport:
    """Fold N shard record dirs into one election record at ``out_dir``.

    ``check=True`` refuses structurally bad inputs up front (signature,
    seed derivation, admitted-vs-published count, duplicate shard ids,
    divergent init) — the merged record still goes through the full
    verifier, this just keeps garbage from being published at all.
    """
    if not shard_dirs:
        raise MergeError("no shard record dirs to merge")
    shards = []  # (manifest, dir, n_frames, init_bytes)
    for d in shard_dirs:
        m = fab_manifest.read_shard_manifest(d)
        n_frames, _ = repair_frame_stream(os.path.join(d, _BALLOTS))
        with open(os.path.join(d, "election_initialized.pb"), "rb") as f:
            init_bytes = f.read()
        shards.append((m, d, n_frames, init_bytes))
    shards.sort(key=lambda s: s[0].shard_id)

    if check:
        _check_shards(group, shards)

    pub = Publisher(out_dir)
    with open(os.path.join(out_dir, "election_initialized.pb"), "wb") as f:
        f.write(shards[0][3])
    report = MergeReport(out_dir=out_dir, n_shards=len(shards))
    # framed streams concatenate as raw bytes once each tail is repaired
    with open(os.path.join(out_dir, _BALLOTS), "wb") as dst:
        for m, d, n_frames, _ in shards:
            src_path = os.path.join(d, _BALLOTS)
            if os.path.exists(src_path):
                with open(src_path, "rb") as src:
                    shutil.copyfileobj(src, dst)
            report.n_ballots += n_frames
            report.per_shard.append((m.shard_id, n_frames))
        dst.flush()
        os.fsync(dst.fileno())
    fab_manifest.write_shard_manifests(pub.dir, [s[0] for s in shards])
    log.info("merged %d shards -> %s (%d ballots: %s)", len(shards),
             out_dir, report.n_ballots,
             " ".join(f"s{sid}={n}" for sid, n in report.per_shard))
    return report


def _check_shards(group: GroupContext, shards) -> None:
    manifest_hash = Consumer(
        shards[0][1], group).read_election_initialized().manifest_hash
    seen_ids: set[int] = set()
    init0 = shards[0][3]
    for m, d, n_frames, init_bytes in shards:
        if init_bytes != init0:
            raise MergeError(f"shard {m.shard_id} ({d}): "
                             f"election_initialized differs from shard "
                             f"{shards[0][0].shard_id}")
        if m.shard_id in seen_ids:
            raise MergeError(f"duplicate shard id {m.shard_id} ({d})")
        seen_ids.add(m.shard_id)
        if not fab_manifest.verify_manifest_signature(group, m):
            raise MergeError(f"shard {m.shard_id} ({d}): manifest "
                             f"signature invalid")
        want = fab_manifest.shard_chain_seed(manifest_hash, m.shard_id)
        if m.chain_seed != want:
            raise MergeError(f"shard {m.shard_id} ({d}): chain seed is "
                             f"not H('shard-chain-start', manifest_hash, "
                             f"{m.shard_id})")
        if m.admitted_count != n_frames:
            raise MergeError(f"shard {m.shard_id} ({d}): manifest claims "
                             f"{m.admitted_count} ballots, stream has "
                             f"{n_frames}")


def merge_sub_tallies(group: GroupContext,
                      tallies: Sequence[TallyResult],
                      tally_id: str = "tally",
                      metadata: Optional[dict] = None) -> TallyResult:
    """Homomorphically add per-shard sub-tallies: component-wise
    ``mult_p`` of the ciphertexts, cast counts add.  Equals the tally of
    the concatenated stream because ElGamal accumulation is an abelian
    product — shard order doesn't matter."""
    if not tallies:
        raise MergeError("no sub-tallies to merge")
    base = tallies[0].encrypted_tally
    contests = []
    for ci, c in enumerate(base.contests):
        sels = []
        for si, s in enumerate(c.selections):
            pad, data = s.ciphertext.pad, s.ciphertext.data
            for t in tallies[1:]:
                other = t.encrypted_tally.contests[ci].selections[si]
                if (other.selection_id != s.selection_id
                        or t.encrypted_tally.contests[ci].contest_id
                        != c.contest_id):
                    raise MergeError(
                        f"sub-tally shape mismatch at contest {ci} "
                        f"selection {si}")
                pad = group.mult_p(pad, other.ciphertext.pad)
                data = group.mult_p(data, other.ciphertext.data)
            sels.append(EncryptedTallySelection(
                s.selection_id, s.sequence_order,
                ElGamalCiphertext(pad, data)))
        contests.append(EncryptedTallyContest(
            c.contest_id, c.sequence_order, tuple(sels)))
    n_cast = sum(t.encrypted_tally.cast_ballot_count for t in tallies)
    tally = EncryptedTally(tally_id, tuple(contests),
                           cast_ballot_count=n_cast)
    return TallyResult(tallies[0].election_init, tally, (tally_id,),
                       dict(metadata or {}))

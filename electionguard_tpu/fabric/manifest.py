"""Signed shard manifests: the publishable claim one fleet worker makes
about its slice of the election record.

Each fabric worker runs its own contiguous ballot-code chain, anchored
not at the single-worker anchor ``H("code-chain-start", manifest_hash)``
but at a per-shard seed derivable by anyone holding the election
manifest::

    chain_seed(shard) = H("shard-chain-start", manifest_hash, shard_id)

When the worker drains it signs a manifest — (shard id, worker id, chain
seed, head hash, admitted count), hashed through ``core/hash.py`` and
signed with a Schnorr signature over the election group (same equations
as ``crypto/schnorr.py``, with the manifest digest bound into the
Fiat–Shamir challenge).  The merge step publishes all N manifests next
to the concatenated ballot stream; the verifier's ``V.shard_manifest``
family recomputes the seeds, checks the signatures, and asserts the
chains are individually contiguous, disjoint, and jointly complete — a
gapped, overlapping, or forged-manifest record goes red.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, replace
from typing import Optional, Sequence

from electionguard_tpu.core.group import (ElementModP, ElementModQ,
                                          GroupContext)
from electionguard_tpu.core.hash import hash_digest, hash_elems

#: per-worker manifest in its own shard record dir
MANIFEST_NAME = "shard_manifest.json"
#: all shards' manifests in the merged record dir
MANIFESTS_NAME = "shard_manifests.json"


def shard_chain_seed(manifest_hash: bytes, shard_id: int) -> bytes:
    """The code-chain anchor of one shard — derivable from public data,
    so a forged manifest can't smuggle in an arbitrary seed."""
    return hash_digest("shard-chain-start", manifest_hash, shard_id)


@dataclass(frozen=True)
class ShardSignature:
    """Schnorr signature (c, u) over a manifest digest: with keypair
    ``K = g^s``, sign picks nonce r, ``h = g^r``, ``c = H(K, h, digest)``,
    ``u = r + c·s mod q``; verify recomputes ``h' = g^u · K^(q-c)`` and
    accepts iff ``c == H(K, h', digest)``."""

    challenge: int
    response: int


@dataclass(frozen=True)
class ManifestKeypair:
    """A worker's manifest signing key (secret stays in the worker
    process; only ``public`` travels — registration and manifest)."""

    secret: ElementModQ
    public: ElementModP

    @staticmethod
    def generate(group: GroupContext,
                 secret: Optional[ElementModQ] = None) -> "ManifestKeypair":
        s = secret if secret is not None else group.rand_q()
        return ManifestKeypair(s, group.g_pow_p(s))


@dataclass(frozen=True)
class ShardManifest:
    """One shard's signed claim: chain seed, head, and admitted count."""

    shard_id: int
    worker_id: str
    chain_seed: bytes          # 32B anchor (shard_chain_seed)
    head_hash: bytes           # 32B: last ballot's code; chain_seed if empty
    admitted_count: int
    public_key: int            # signing key K (ElementModP value)
    signature: Optional[ShardSignature] = None

    def digest(self) -> bytes:
        return hash_digest("shard-manifest", self.shard_id, self.worker_id,
                           self.chain_seed, self.head_hash,
                           self.admitted_count, self.public_key)

    # ---- json wire form ----------------------------------------------
    def to_dict(self) -> dict:
        d = {"shard_id": self.shard_id, "worker_id": self.worker_id,
             "chain_seed": self.chain_seed.hex(),
             "head_hash": self.head_hash.hex(),
             "admitted_count": self.admitted_count,
             "public_key": f"{self.public_key:x}"}
        if self.signature is not None:
            d["signature"] = {"challenge": f"{self.signature.challenge:x}",
                              "response": f"{self.signature.response:x}"}
        return d

    @staticmethod
    def from_dict(d: dict) -> "ShardManifest":
        sig = None
        if d.get("signature"):
            sig = ShardSignature(int(d["signature"]["challenge"], 16),
                                 int(d["signature"]["response"], 16))
        return ShardManifest(
            shard_id=int(d["shard_id"]), worker_id=str(d["worker_id"]),
            chain_seed=bytes.fromhex(d["chain_seed"]),
            head_hash=bytes.fromhex(d["head_hash"]),
            admitted_count=int(d["admitted_count"]),
            public_key=int(d["public_key"], 16), signature=sig)


def sign_manifest(group: GroupContext, keypair: ManifestKeypair,
                  manifest: ShardManifest) -> ShardManifest:
    """Attach a Schnorr signature binding ``manifest.digest()`` to the
    worker's keypair (which must match ``manifest.public_key``)."""
    if keypair.public.value != manifest.public_key:
        raise ValueError("manifest public_key does not match the keypair")
    r = group.rand_q(minimum=0)
    h = group.g_pow_p(r)
    c = hash_elems(group, keypair.public, h, manifest.digest())
    u = group.add_q(r, group.mult_q(c, keypair.secret))
    return replace(manifest,
                   signature=ShardSignature(c.value, u.value))


def verify_manifest_signature(group: GroupContext,
                              manifest: ShardManifest) -> bool:
    """Recompute the Fiat–Shamir challenge from the claimed key and the
    manifest digest; also rejects keys outside the order-q subgroup."""
    sig = manifest.signature
    if sig is None:
        return False
    from electionguard_tpu.crypto import validate as vgate
    try:
        K = ElementModP(manifest.public_key, group)
        c = ElementModQ(sig.challenge, group)
        u = ElementModQ(sig.response, group)
        # subgroup membership through the one ingestion gate
        # (crypto/validate): named class, sim-visible detection
        vgate.gate_elements(
            group, [(f"shard {manifest.shard_id} manifest key",
                     K.value)], "fabric")
    except (ValueError, vgate.GateError):
        return False
    # h' = g^u · K^(-c); K has order q, so K^(-c) = K^(q-c)
    h = group.mult_p(group.g_pow_p(u),
                     group.pow_p(K, group.sub_q(group.ZERO_MOD_Q, c)))
    return hash_elems(group, K, h, manifest.digest()) == c


# ---- on-disk forms ----------------------------------------------------

def write_shard_manifest(out_dir: str, manifest: ShardManifest) -> str:
    """One worker's own manifest, in its shard record dir (atomic)."""
    path = os.path.join(out_dir, MANIFEST_NAME)
    _write_json(path, manifest.to_dict())
    return path


def read_shard_manifest(in_dir: str) -> ShardManifest:
    with open(os.path.join(in_dir, MANIFEST_NAME)) as f:
        return ShardManifest.from_dict(json.load(f))


def write_shard_manifests(out_dir: str,
                          manifests: Sequence[ShardManifest]) -> str:
    """All shards' manifests in the merged record dir, shard order."""
    path = os.path.join(out_dir, MANIFESTS_NAME)
    _write_json(path, [m.to_dict()
                       for m in sorted(manifests,
                                       key=lambda m: m.shard_id)])
    return path


def read_shard_manifests(in_dir: str) -> list[ShardManifest]:
    path = os.path.join(in_dir, MANIFESTS_NAME)
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [ShardManifest.from_dict(d) for d in json.load(f)]


def _write_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)

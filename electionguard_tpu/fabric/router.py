"""The fabric router: one front door, N encryption-worker shards.

Speaks the existing ``BallotEncryptionService`` surface — clients built
for the single worker (``EncryptionClient``, ``tools/loadgen_encrypt``)
point at the router unchanged — and fans every request out to the
least-loaded live worker.  Workers reverse-dial the router through
``FabricRegistrationService`` exactly as mix servers reverse-dial their
coordinator (nonce-idempotent: a lost-response retry replays, a
relaunched worker with the same id reclaims its shard and receives the
ballot ids that were requeued away while it was down).

Routing and membership:

* **least queue depth** — each shard's score is its last health-reported
  queue depth plus the router's own in-flight delta, so bursts between
  polls still spread;
* **eviction / readmission** — a background poll drives the ``health``
  rpc; ``EGTPU_FABRIC_EVICT_AFTER`` consecutive failures evict, one
  success readmits.  A transport failure on a live forward evicts
  immediately and requeues the ballot onto a surviving shard, recording
  the id against the dead shard so its journal replay skips it;
* **backpressure** — a worker's RESOURCE_EXHAUSTED moves the request to
  the next shard; the router itself aborts RESOURCE_EXHAUSTED only when
  EVERY live shard is saturated (and UNAVAILABLE when none is live).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import grpc

from electionguard_tpu import obs
from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.crypto import validate
from electionguard_tpu.obs import REGISTRY, election_labels
from electionguard_tpu.obs import tenant as obs_tenant
from electionguard_tpu.publish import pb
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.serve.tenants import TenantQuota, TenantQuotaError
from electionguard_tpu.utils import clock, knobs

log = logging.getLogger("fabric.router")

_FRONT = "BallotEncryptionService"
_REG = "FabricRegistrationService"


class _Shard:
    """Router-side handle for one registered encryption worker."""

    def __init__(self, shard_id: int, worker_id: str, url: str,
                 nonce: bytes, public_key: bytes,
                 elections: frozenset = frozenset()):
        self.shard_id = shard_id
        self.worker_id = worker_id
        self.url = url
        self.reg_nonce = nonce
        self.public_key = public_key
        #: elections this shard serves; empty = every election (shared
        #: pool).  Routing intersects the request's ambient election
        #: with this set, so dedicated and shared shards coexist.
        self.elections = elections
        self.live = False          # at least one health success, not evicted
        self.evicted = False
        self.fail_count = 0
        self.queue_depth = 0       # last health-reported depth
        self.in_flight = 0         # router-tracked delta since that poll
        self.forwarded = 0
        #: admitted-here ballot ids the router moved to surviving shards;
        #: handed back (and kept, for idempotent replays) at re-register
        self.requeued: list[str] = []
        self._channel = None
        self._stub: Optional[rpc_util.Stub] = None

    def serves(self, election: str) -> bool:
        return not self.elections or election in self.elections

    def stub(self) -> rpc_util.Stub:
        if self._stub is None:
            self._channel = rpc_util.make_channel(self.url)
            self._stub = rpc_util.Stub(self._channel, _FRONT)
        return self._stub

    def score(self) -> int:
        return self.queue_depth + self.in_flight

    def close(self):
        if self._channel is not None:
            self._channel.close()
            self._channel = None
            self._stub = None


class EncryptionRouter:
    """Front-door server + registration service + health-poll loop."""

    def __init__(self, group: GroupContext, port: int = 0,
                 health_interval: Optional[float] = None,
                 health_timeout: Optional[float] = None,
                 evict_after: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 max_workers: int = 32):
        self.group = group
        self._health_interval = (
            health_interval if health_interval is not None
            else knobs.get_float("EGTPU_FABRIC_HEALTH_INTERVAL"))
        self._health_timeout = (
            health_timeout if health_timeout is not None
            else knobs.get_float("EGTPU_FABRIC_HEALTH_TIMEOUT"))
        self._evict_after = (evict_after if evict_after is not None
                             else knobs.get_int("EGTPU_FABRIC_EVICT_AFTER"))
        self._max_inflight = (
            max_inflight if max_inflight is not None
            else knobs.get_int("EGTPU_FABRIC_MAX_INFLIGHT"))
        self._lock = threading.Lock()
        self.shards: list[_Shard] = []
        self._rr = 0               # tiebreak rotation for equal scores
        # forwards fail fast (one attempt): failover to another shard IS
        # the router's retry, and the client's own Stub retries the
        # router — stacking a third retry layer inside the forward would
        # multiply worst-case latency for no added delivery guarantee
        self._fwd_policy = rpc_util.RetryPolicy(
            attempts=1, base_wait=0.1, max_wait=0.1,
            connect_window=self._health_timeout, budget=0.0)
        # per-tenant admission quota over the whole fleet (the serving
        # processes enforce their own copy; the router's is the front
        # line, so a flooding election is shed before it ever crosses
        # the wire to a worker)
        self._tenant_quota = TenantQuota()
        self.server, self.port = rpc_util.make_server(
            port, max_workers=max_workers)
        self.url = f"localhost:{self.port}"
        self.server.add_generic_rpc_handlers((
            rpc_util.generic_service(_REG, {
                "registerEncryptionWorker": self._register}),
            rpc_util.generic_service(_FRONT, {
                "encryptBallot": self._encrypt_ballot,
                "encryptBallotBatch": self._encrypt_ballot_batch,
                "health": self._health}),
        ))
        self.server.start()
        self._stop = threading.Event()
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="fabric-health", daemon=True)
        clock.start_thread(self._poller)
        obs.set_phase("routing shards=0/0")
        log.info("fabric router listening on %d (health every %.1fs, "
                 "evict after %d misses)", self.port,
                 self._health_interval, self._evict_after)

    @staticmethod
    def _c(name: str):
        """Fabric counter resolved PER EVENT against the ambient tenant
        context (the registry get-or-creates by flat name), so the same
        event series splits cleanly per election on a shared fleet —
        binding once at __init__ would pin every tenant's events to the
        election the router happened to start under."""
        return REGISTRY.counter(name, election_labels())

    # ---- registration ------------------------------------------------
    def _register(self, request, context):
        Resp = pb.RegisterEncryptionWorkerResponse
        constants = rpc_util.group_constants_msg(self.group)
        with self._lock:
            err = rpc_util.check_group_fingerprint(
                self.group, request.group_fingerprint,
                boundary="fabric")
            if err:
                return Resp(error=err, constants=constants)
            wid = request.worker_id
            # ingestion gate on the manifest signing key (when the
            # worker sends one): a key outside the subgroup must die at
            # registration, not at merge-time signature verification
            if request.manifest_public_key:
                try:
                    validate.gate_elements(
                        self.group,
                        [(f"{wid} manifest key",
                          int.from_bytes(bytes(request.manifest_public_key),
                                         "big"))],
                        "fabric")
                except validate.GateError as e:
                    return Resp(error=str(e), constants=constants)
            nonce = bytes(request.registration_nonce)
            for s in self.shards:
                if s.worker_id != wid:
                    continue
                if s.reg_nonce == nonce:
                    if s.url == request.remote_url:
                        # lost-response retry: replay idempotently,
                        # including the requeued-ids list
                        return Resp(shard_id=s.shard_id,
                                    requeued_ballot_ids=s.requeued,
                                    constants=constants)
                    return Resp(
                        error=f"worker id {wid!r} already registered "
                              f"from {s.url}", constants=constants)
                # same id, fresh nonce: a RELAUNCHED worker reclaims its
                # shard.  The requeued list stays on the handle (never
                # cleared) so a lost response replays identically; ids
                # no longer in the worker's journal are skipped for free.
                log.warning("worker %s re-registered (shard %d, %d "
                            "requeued ids handed back)", wid, s.shard_id,
                            len(s.requeued))
                s.url = request.remote_url
                s.reg_nonce = nonce
                s.public_key = bytes(request.manifest_public_key)
                s.elections = frozenset(request.election_ids)
                s.close()
                s.live = False
                s.evicted = False
                s.fail_count = 0
                s.in_flight = 0
                return Resp(shard_id=s.shard_id,
                            requeued_ballot_ids=s.requeued,
                            constants=constants)
            shard = _Shard(len(self.shards), wid, request.remote_url,
                           nonce, bytes(request.manifest_public_key),
                           elections=frozenset(request.election_ids))
            self.shards.append(shard)
            log.info("registered encryption worker %s as shard %d at %s",
                     wid, shard.shard_id, shard.url)
            return Resp(shard_id=shard.shard_id, constants=constants)

    def wait_for_workers(self, n: int, timeout: float = 300.0,
                         poll: float = 0.25, live: bool = False) -> bool:
        """Block until ``n`` workers are registered (``live=True``: until
        n have answered a health poll and entered the routing set)."""
        deadline = clock.monotonic() + timeout
        while clock.monotonic() < deadline:
            with self._lock:
                ready = sum(1 for s in self.shards
                            if (s.live if live else True))
            if ready >= n:
                return True
            clock.sleep(poll)
        return False

    # ---- health / membership -----------------------------------------
    def _poll_loop(self) -> None:
        while not self._stop.wait(self._health_interval):
            with self._lock:
                shards = list(self.shards)
            for s in shards:
                if self._stop.is_set():
                    return
                self._poll_one(s)
            with self._lock:
                n_live = sum(1 for s in self.shards if s.live)
                n = len(self.shards)
            obs.set_phase(f"routing shards={n_live}/{n}")

    def _poll_one(self, s: _Shard) -> None:
        try:
            h = s.stub().call("health", pb.msg("HealthRequest")(),
                              timeout=self._health_timeout,
                              policy=self._fwd_policy)
        except grpc.RpcError as e:
            with self._lock:
                s.fail_count += 1
                if s.live and s.fail_count >= self._evict_after:
                    self._evict_locked(s, f"health: {e.code()}")
            return
        with self._lock:
            s.fail_count = 0
            s.queue_depth = h.queue_depth
            if s.evicted:
                s.evicted = False
                self._c("fabric_readmissions_total").inc()
                log.info("shard %d readmitted (status=%s depth=%d)",
                         s.shard_id, h.status, h.queue_depth)
            if not s.live:
                s.live = True
                log.info("shard %d live at %s (status=%s)", s.shard_id,
                         s.url, h.status)

    def _evict_locked(self, s: _Shard, reason: str) -> None:
        if not s.live:
            return
        s.live = False
        s.evicted = True
        s.close()
        self._c("fabric_evictions_total").inc()
        log.warning("evicted shard %d (%s): %s", s.shard_id, s.worker_id,
                    reason)

    # ---- routing -----------------------------------------------------
    def _pick(self, tried: set[int],
              election: str = "") -> Optional[_Shard]:
        """Least-loaded live shard serving ``election``, not yet tried
        and under the in-flight cap; claims one in-flight slot under the
        lock."""
        with self._lock:
            candidates = [s for s in self.shards
                          if s.live and s.shard_id not in tried
                          and s.serves(election)
                          and s.in_flight < self._max_inflight]
            if not candidates:
                return None
            # equal scores rotate round-robin so a sequential client
            # doesn't pin the whole stream to shard 0
            self._rr += 1
            rr = self._rr
            best = min(candidates,
                       key=lambda s: (s.score(),
                                      (s.shard_id - rr) % (len(self.shards)
                                                           or 1)))
            best.in_flight += 1
            best.forwarded += 1
            return best

    def _release(self, s: _Shard) -> None:
        with self._lock:
            s.in_flight = max(0, s.in_flight - 1)

    def _route(self, method: str, request, context, ballot_ids,
               timeout: float):
        """Forward ``request`` to shards in load order until one answers.

        The request's ambient election (gRPC metadata → ``obs.tenant``)
        scopes everything: only shards serving it are candidates, and
        the per-tenant admission quota (EGTPU_TENANT_QUOTA) sheds THAT
        election's overflow — RESOURCE_EXHAUSTED naming the tenant —
        before a single forward leaves the router.

        RESOURCE_EXHAUSTED from a worker tries the next shard; a
        transport failure evicts the shard and requeues (recording
        ``ballot_ids`` against it so the worker's recovery skips them).
        Aborts RESOURCE_EXHAUSTED only when every reachable shard is
        saturated, UNAVAILABLE when none is reachable at all.
        """
        election = obs_tenant.current_election()
        try:
            quota_release = self._tenant_quota.acquire(election)
        except TenantQuotaError as e:
            self._c("fabric_rejects_tenant_quota_total").inc()
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        try:
            return self._route_inner(method, request, context,
                                     ballot_ids, timeout, election)
        finally:
            if quota_release is not None:
                quota_release()

    def _route_inner(self, method: str, request, context, ballot_ids,
                     timeout: float, election: str):
        tried: set[int] = set()
        n_exhausted = 0
        while True:
            shard = self._pick(tried, election)
            if shard is None:
                with self._lock:
                    any_live = any(s.live and s.serves(election)
                                   for s in self.shards)
                if n_exhausted or any_live:
                    # a live shard we can't route to is a saturated one:
                    # either its worker said RESOURCE_EXHAUSTED or the
                    # router's own in-flight cap is the bound
                    self._c("fabric_rejects_saturated_total").inc()
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"fleet saturated: {n_exhausted} shard(s) "
                        f"exhausted, none under the in-flight cap")
                self._c("fabric_rejects_no_live_shards_total").inc()
                context.abort(grpc.StatusCode.UNAVAILABLE,
                              "no live encryption workers"
                              + (f" serving election {election!r}"
                                 if election else ""))
            tried.add(shard.shard_id)
            try:
                return shard.stub().call(method, request, timeout=timeout,
                                         policy=self._fwd_policy)
            except grpc.RpcError as e:
                code = e.code()
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    n_exhausted += 1
                    continue
                # transport-level failure mid-forward: the worker may
                # have journaled the admission before dying, so the ids
                # are recorded against this shard — its recovery must
                # NOT replay what surviving shards are about to encrypt
                with self._lock:
                    self._evict_locked(shard, f"{method}: {code}")
                    shard.requeued.extend(ballot_ids)
                    self._c("fabric_requeues_total").inc(len(ballot_ids))
                log.warning("requeued %d ballot(s) away from shard %d "
                            "after %s", len(ballot_ids), shard.shard_id,
                            code)
                continue
            finally:
                self._release(shard)

    def _encrypt_ballot(self, request, context):
        return self._route("encryptBallot", request, context,
                           [request.ballot.ballot_id],
                           timeout=rpc_util.deadline_for("encryptBallot"))

    def _encrypt_ballot_batch(self, request, context):
        return self._route(
            "encryptBallotBatch", request, context,
            [b.ballot_id for b in request.ballots],
            timeout=rpc_util.deadline_for("encryptBallotBatch"))

    def _health(self, request, context):
        with self._lock:
            live = [s for s in self.shards if s.live]
            depth = sum(s.score() for s in live)
        return pb.msg("HealthResponse")(
            status="SERVING" if live else "STARTING",
            ready=bool(live), queue_depth=depth, shard_id=-1)

    # ---- lifecycle ---------------------------------------------------
    def snapshot(self) -> list[dict]:
        """Membership view for CLIs/tests: one dict per shard."""
        with self._lock:
            return [{"shard_id": s.shard_id, "worker_id": s.worker_id,
                     "url": s.url, "live": s.live, "evicted": s.evicted,
                     "elections": sorted(s.elections),
                     "queue_depth": s.queue_depth,
                     "in_flight": s.in_flight, "forwarded": s.forwarded,
                     "requeued": len(s.requeued)}
                    for s in self.shards]

    def shutdown(self, grace: float = 2.0) -> None:
        self._stop.set()
        clock.wait_event(self.server.stop(grace=grace), grace)
        with self._lock:
            for s in self.shards:
                s.close()


def register_worker(router_url: str, group: GroupContext, worker_id: str,
                    serve_port: int, manifest_public_key: bytes = b"",
                    host: str = "localhost",
                    timeout: float = 120.0,
                    election_ids=()) -> tuple[int, list[str]]:
    """Worker-side reverse dial: register with the router (retrying while
    it is unreachable), returning ``(shard_id, requeued_ballot_ids)`` —
    the shard this worker owns and the admissions the router moved to
    surviving shards while a previous incarnation was down.  One nonce
    per process: a lost-response retry replays idempotently, a relaunch
    (fresh nonce, same ``worker_id``) reclaims the shard.
    ``election_ids``: the elections this worker serves (empty = all) —
    the router routes a request only to shards whose set contains its
    ambient election."""
    nonce = os.urandom(16)
    deadline = clock.monotonic() + timeout
    channel = rpc_util.make_channel(router_url)
    stub = rpc_util.Stub(channel, _REG)
    try:
        while True:
            try:
                resp = stub.call(
                    "registerEncryptionWorker",
                    pb.RegisterEncryptionWorkerRequest(
                        worker_id=worker_id,
                        remote_url=f"{host}:{serve_port}",
                        group_fingerprint=group.fingerprint(),
                        registration_nonce=nonce,
                        manifest_public_key=manifest_public_key,
                        election_ids=list(election_ids)))
            except grpc.RpcError:
                if clock.monotonic() >= deadline:
                    raise
                clock.sleep(0.5)
                continue
            if resp.error:
                raise RuntimeError(
                    f"router refused registration: {resp.error}")
            err = rpc_util.check_group_constants(group, resp.constants)
            if err:
                raise RuntimeError(err)
            return resp.shard_id, list(resp.requeued_ballot_ids)
    finally:
        channel.close()

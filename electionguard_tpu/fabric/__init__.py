"""Horizontally sharded serving fabric.

Turns the single encryption worker of ``serve/`` into a fleet: a router
process speaks the ``BallotEncryptionService`` surface as the front door
and fans requests out to N worker processes (``fabric/router.py``), each
running its own contiguous ballot-code chain under a signed shard
manifest (``fabric/manifest.py``); ``fabric/merge.py`` folds the N shard
records back into ONE verifiable election record — sub-tallies add
homomorphically, manifests are published alongside the ballots and
checked by the verifier's ``V.shard_manifest`` family.
"""

from electionguard_tpu.fabric.manifest import (  # noqa: F401
    ManifestKeypair, ShardManifest, shard_chain_seed)

"""Homomorphic tally accumulation: sharded product-reduce over ballots.

Native replacement for the reference's [ext] ``runAccumulateBallots(group,
in, out, name, createdBy)`` (call site:
src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:151 —
``∏ ciphertexts mod p`` 🔥).  The ballot axis is laid out as the leading
array dimension and reduced with a log-depth Montgomery tree on device; on a
multi-chip mesh this axis is sharded and the tree rides ICI
(electionguard_tpu.parallel).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from electionguard_tpu.ballot.ciphertext import BallotState
from electionguard_tpu.ballot.tally import (EncryptedTally,
                                            EncryptedTallyContest,
                                            EncryptedTallySelection)
from electionguard_tpu.core.group import ElementModP
from electionguard_tpu.core.group_jax import jax_ops
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
from electionguard_tpu.publish.election_record import (ElectionInitialized,
                                                       TallyResult)


def accumulate_ballots(
        election_init: ElectionInitialized,
        ballots,
        tally_id: str = "tally",
        metadata: Optional[dict] = None,
        chunk_size: int = 4096) -> TallyResult:
    """Product-reduce all CAST ballots into an EncryptedTally.

    ``ballots`` may be ANY iterable (e.g. a lazy
    ``Consumer.iterate_encrypted_ballots()``): chunks of ``chunk_size``
    are reduced with one device prod-reduce each and combined host-side
    (2·nk modmuls per chunk), so a million-ballot record accumulates with
    O(chunk) host residency (BASELINE.md config 4).
    """
    import itertools

    g = election_init.joint_public_key.group
    ops = jax_ops(g)
    manifest = election_init.config.manifest

    # tally keys in manifest order
    keys = [(c.object_id, s.object_id)
            for c in manifest.contests for s in c.selections]
    key_idx = {k: i for i, k in enumerate(keys)}
    nk = len(keys)

    prod_ints = [1] * (2 * nk)
    n_cast = 0
    it = iter(ballots)
    while True:
        chunk = list(itertools.islice(it, chunk_size))
        if not chunk:
            break
        cast = [b for b in chunk if b.state == BallotState.CAST]
        if not cast:
            continue
        n_cast += len(cast)
        # (M, 2*nk) int matrix of pads|datas, ones where a ballot lacks a key
        rows = np.empty((len(cast), 2 * nk), dtype=object)
        rows[:] = 1
        for bi, b in enumerate(cast):
            for c in b.contests:
                for s in c.selections:
                    if s.is_placeholder:
                        continue
                    i = key_idx.get((c.contest_id, s.selection_id))
                    if i is None:
                        raise ValueError(
                            f"ballot {b.ballot_id} selection "
                            f"({c.contest_id}, {s.selection_id}) not in "
                            f"manifest")
                    rows[bi, i] = s.ciphertext.pad.value
                    rows[bi, nk + i] = s.ciphertext.data.value
        arr = np.stack([ops.to_limbs_p(list(rows[bi]))
                        for bi in range(len(cast))])  # (M, 2nk, n)
        prod = ops.prod_reduce(arr)                   # (2nk, n)
        chunk_ints = ops.from_limbs(np.asarray(prod))
        prod_ints = [a * b % g.p for a, b in zip(prod_ints, chunk_ints)]

    contests = []
    for c in manifest.contests:
        sels = []
        for s in c.selections:
            i = key_idx[(c.object_id, s.object_id)]
            sels.append(EncryptedTallySelection(
                s.object_id, s.sequence_order,
                ElGamalCiphertext(ElementModP(prod_ints[i], g),
                                  ElementModP(prod_ints[nk + i], g))))
        contests.append(EncryptedTallyContest(
            c.object_id, c.sequence_order, tuple(sels)))

    tally = EncryptedTally(tally_id, tuple(contests),
                           cast_ballot_count=n_cast)
    return TallyResult(election_init, tally, (tally_id,),
                       dict(metadata or {}))

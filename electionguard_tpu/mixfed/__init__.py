"""Federated mix plane: one gRPC process per shuffle stage.

The single-process mixnet (cli/run_mixnet) holds EVERY stage's
permutation and re-encryption randomness in one address space, so one
compromised process can unwind the whole cascade.  This plane restores
the mixnet's actual trust model: each ``MixServerServer`` process mixes
exactly ONE stage (it structurally refuses a second assignment), and a
``MixCoordinator`` streams rows between servers, verifying each stage's
Terelius–Wikström proof BEFORE forwarding its output downstream —
a cheating or crashed server costs one requeue, never a tainted record.

Same published artifact, same verifier: the coordinator writes the
standard ``mix_stage_NNN.pb`` streams, so ``run_verifier`` checks a
federated record exactly like a single-process one.
"""

from electionguard_tpu.mixfed.coordinator import MixCoordinator, MixFedError
from electionguard_tpu.mixfed.server import MixServerServer

__all__ = ["MixCoordinator", "MixFedError", "MixServerServer"]

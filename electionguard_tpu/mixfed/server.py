"""One federated mix server: the node that holds ONE stage's secrets.

Lifecycle mirrors the trustee plane (remote/keyceremony_remote.py):
listen first, then reverse-dial the coordinator's registration service
with a per-process nonce (lost-response retries replay idempotently; a
relaunched process — fresh secrets — registers as a new server).  The
coordinator then drives the stage over four rpcs:

  registerStage   assign THIS server its one stage (index, key, qbar)
  pushRows        stream the stage's input ciphertext rows in chunks
  shuffleStage    shuffle + prove, keyed to the coordinator's input hash
  pullRows        stream the shuffled output rows back in chunks

The trust boundary is structural, not behavioural: ``registerStage``
for a second, different stage is refused in-band, so no process ever
sees two stages' permutations or randomness — the property the
federated topology exists to provide (and tests/test_mixfed.py asserts
by inspecting server state).  Every rpc is idempotent: chunks overwrite
by ``chunk_start``, and a repeated ``shuffleStage`` with the same input
hash returns the cached result instead of re-shuffling (a retried rpc
must not mint a second permutation for the same stage).

Sharding: ``shards``/EGTPU_MIX_SHARDS spreads the row axis of the
shuffle AND the N-wide proof dispatches over an in-process device mesh
(parallel/sharded.ShardedGroupOps) — bit-identical transcript, see
tests/test_sharded_fused.py's differential coverage.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Optional

from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.crypto import validate
from electionguard_tpu.mixnet.proof import rows_digest
from electionguard_tpu.mixnet.shuffle import Shuffler
from electionguard_tpu.mixnet.stage import run_stage
from electionguard_tpu.obs import (REGISTRY, election_labels,
                                   set_phase, span)
from electionguard_tpu.publish import pb, serialize
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.utils import clock, knobs

log = logging.getLogger("mixfed.server")


def _adversary_mod():
    """The sim's adversary registry WITHOUT importing the sim package
    into honest processes: present when already imported (a sim run),
    otherwise imported only when the EGTPU_MIX_TAMPER drill knob asks
    for it (the knob is a thin alias for the ``mix_tamper_output``
    adversary)."""
    mod = sys.modules.get("electionguard_tpu.sim.adversary")
    if mod is None and os.environ.get("EGTPU_MIX_TAMPER"):
        from electionguard_tpu.sim import adversary as mod
    return mod


def _env_shards() -> int:
    try:
        return max(0, knobs.get_int("EGTPU_MIX_SHARDS"))
    except ValueError:
        return 0


class MixServerServer:
    """One mix-server process; see module docstring for the protocol."""

    def __init__(self, group: GroupContext, coordinator_url: str,
                 server_id: str, port: int = 0, host: str = "localhost",
                 shards: Optional[int] = None, wp: int = 1,
                 tamper: bool = False, seed: Optional[bytes] = None):
        self.group = group
        self.server_id = server_id
        # tamper hook (tests + drills): corrupt one output ciphertext
        # AFTER proving, so the published transcript no longer binds —
        # the coordinator's pre-forward verification must catch it as a
        # V15.mix_binding failure, never publish it.  The ctor flag is
        # the direct form; the EGTPU_MIX_TAMPER knob and the sim's
        # seeded schedules both mount the same `mix_tamper_output`
        # adversary (sim/adversary.py), consulted per shuffled stage.
        self._tamper = tamper
        self._pinned_seed = seed
        shards = _env_shards() if shards is None else shards
        self._ops = None
        if shards:
            from electionguard_tpu.core.group_jax import jax_ops
            from electionguard_tpu.parallel.mesh import election_mesh
            from electionguard_tpu.parallel.sharded import ShardedGroupOps
            self._ops = ShardedGroupOps(jax_ops(group),
                                        election_mesh(shards, wp=wp))
            log.info("mix server %s sharding over %d devices (wp=%d)",
                     server_id, shards, wp)

        self._lock = threading.Lock()
        self._done = threading.Event()
        self._all_ok: Optional[bool] = None
        # ---- the ONE stage this process may ever hold ----------------
        self.held_stage: Optional[int] = None
        self._public_key: Optional[int] = None
        self._qbar: Optional[bytes] = None
        self._n_rows = 0
        self._width = 0
        self._chunks: dict[int, tuple[list, list]] = {}
        self._result = None          # cached MixStageResult message
        self._result_input_hash: Optional[bytes] = None
        self._out_pads: list = []
        self._out_datas: list = []

        self.server, self.port = rpc_util.make_server(port)
        self.url = f"{host}:{self.port}"
        self.server.add_generic_rpc_handlers((rpc_util.generic_service(
            "MixServerService",
            {"registerStage": self._register_stage,
             "pushRows": self._push_rows,
             "shuffleStage": self._shuffle_stage,
             "pullRows": self._pull_rows,
             "health": self._health,
             "finish": self._finish}),))
        self.server.start()

        self._reg_nonce = os.urandom(16)
        channel = rpc_util.make_channel(coordinator_url,
                                        rpc_util.MAX_REGISTRATION_MESSAGE)
        try:
            resp = rpc_util.Stub(channel, "MixRegistrationService").call(
                "registerMixServer", pb.RegisterMixServerRequest(
                    server_id=server_id, remote_url=self.url,
                    group_fingerprint=group.fingerprint(),
                    registration_nonce=self._reg_nonce))
        finally:
            channel.close()
        err = resp.error or rpc_util.check_group_constants(group,
                                                           resp.constants)
        if err:
            self.server.stop(grace=0)
            raise RuntimeError(f"mix server registration failed: {err}")
        log.info("mix server %s registered at %s", server_id, self.url)

    # ---- rpc impls ---------------------------------------------------

    def _register_stage(self, request, context):
        with self._lock:
            k = int(request.stage_index)
            err = rpc_util.check_group_fingerprint(
                self.group, request.group_fingerprint,
                boundary="mixfed")
            if err:
                return pb.MixStageReady(stage_index=k, error=err)
            if self.held_stage is not None and self.held_stage != k:
                # the trust boundary: this process already holds stage
                # held_stage's secrets and will never hold another's
                return pb.MixStageReady(
                    stage_index=k,
                    error=f"server {self.server_id} already holds stage "
                          f"{self.held_stage}; one stage per process")
            self.held_stage = k
            set_phase(f"hold-stage-{k}")
            self._public_key = serialize._imp_p_int(
                self.group, request.joint_public_key)
            self._qbar = serialize.import_q(self.group,
                                            request.extended_base_hash)
            self._n_rows = int(request.n_rows)
            self._width = int(request.width)
            return pb.MixStageReady(stage_index=k)

    def _push_rows(self, request, context):
        with self._lock:
            if self.held_stage is None \
                    or int(request.stage_index) != self.held_stage:
                return pb.msg("BoolResponse")(
                    ok=False, error=f"server {self.server_id} holds stage "
                                    f"{self.held_stage}, not "
                                    f"{int(request.stage_index)}")
            # ingestion gate: every ciphertext element of the pushed
            # chunk is screened (range + subgroup, RLC-batched) before
            # it can enter this stage's re-encryption arithmetic
            try:
                validate.gate_wire_p(
                    self.group,
                    [(f"row {int(request.chunk_start) + i} ct[{j}].{fld}",
                      bytes(getattr(c, fld).value))
                     for i, rm in enumerate(request.rows)
                     for j, c in enumerate(rm.ciphertexts)
                     for fld in ("pad", "data")],
                    "mixfed", allow_identity=True)
            except validate.GateError as e:
                return pb.msg("BoolResponse")(ok=False, error=str(e))
            pads, datas = [], []
            for rm in request.rows:
                row_a, row_b = serialize.import_mix_row(self.group, rm)
                pads.append(row_a)
                datas.append(row_b)
            # idempotent by chunk_start: a retried chunk overwrites itself
            self._chunks[int(request.chunk_start)] = (pads, datas)
            REGISTRY.counter("mixfed_rows_pushed_total",
                             election_labels()).inc(len(pads))
            return pb.msg("BoolResponse")(ok=True)

    @staticmethod
    def _assemble_rows(chunks, n_rows):
        """Contiguous rows from the pushed chunks, or None + error.
        Pure: the caller passes state it read under ``self._lock``."""
        pads: list = []
        datas: list = []
        for start in sorted(chunks):
            if start != len(pads):
                return None, None, (f"row chunks not contiguous at "
                                    f"{len(pads)} (got chunk {start})")
            p, d = chunks[start]
            pads.extend(p)
            datas.extend(d)
        if len(pads) != n_rows:
            return None, None, (f"{len(pads)} rows pushed != announced "
                                f"{n_rows}")
        return pads, datas, ""

    def _shuffle_stage(self, request, context):
        with self._lock:
            k = int(request.stage_index)
            if self.held_stage is None or k != self.held_stage:
                return pb.MixStageResult(
                    error=f"server {self.server_id} holds stage "
                          f"{self.held_stage}, not {k}")
            want = bytes(request.input_hash)
            if self._result is not None:
                # idempotent retry of a lost response — but ONLY for the
                # same input: re-shuffling would mint a second
                # permutation for the stage
                if want == self._result_input_hash:
                    return self._result
                return pb.MixStageResult(
                    error=f"stage {k} already shuffled for a different "
                          f"input hash")
            pads, datas, err = self._assemble_rows(self._chunks,
                                                   self._n_rows)
            if err:
                return pb.MixStageResult(error=f"stage {k}: {err}")
            got = rows_digest(self.group, pads, datas)
            if want and want != got:
                # the coordinator and this server disagree on the input
                # rows — refuse to mix (a proof over disputed input is
                # unverifiable downstream anyway)
                return pb.MixStageResult(
                    error=f"stage {k}: input hash mismatch — coordinator "
                          f"sent {want.hex()[:16]}…, rows digest to "
                          f"{got.hex()[:16]}…")
            with span("mixfed.stage",
                      {"stage": k, "n": len(pads), "server": self.server_id}):
                sh = Shuffler(self.group, self._public_key, ops=self._ops)
                stage = run_stage(self.group, self._public_key, self._qbar,
                                  k, pads, datas, seed=self._pinned_seed,
                                  shuffler=sh)
            adv = _adversary_mod()
            if self._tamper or (adv is not None
                                and adv.mix_tamper_fires(self.server_id)):
                # corrupt one output AFTER proving: digest matches the
                # rows we hand back, but the Fiat–Shamir challenge no
                # longer re-derives — a mix_binding failure downstream
                log.warning("mix server %s TAMPERING with stage %d "
                            "output (drill)", self.server_id, k)
                stage.pads[0][0] = stage.pads[0][0] * self.group.g \
                    % self.group.p
            self._out_pads, self._out_datas = stage.pads, stage.datas
            out_hash = rows_digest(self.group, stage.pads, stage.datas)
            self._result = pb.MixStageResult(
                header=serialize.publish_mix_header(self.group, stage),
                output_hash=out_hash)
            self._result_input_hash = want or got
            REGISTRY.counter("mixfed_stages_total",
                             election_labels()).inc()
            return self._result

    def _pull_rows(self, request, context):
        with self._lock:
            k = int(request.stage_index)
            if self._result is None or k != self.held_stage:
                return pb.MixRowChunk(
                    error=f"stage {k} not shuffled on server "
                          f"{self.server_id}")
            start = int(request.chunk_start)
            end = min(start + max(1, int(request.max_rows)),
                      len(self._out_pads))
            rows = [serialize.publish_mix_row(
                self.group, self._out_pads[i], self._out_datas[i])
                for i in range(start, end)]
            REGISTRY.counter("mixfed_rows_pulled_total",
                             election_labels()).inc(len(rows))
            return pb.MixRowChunk(stage_index=k, chunk_start=start,
                                  rows=rows)

    def _health(self, request, context):
        with self._lock:
            shuffled = self._result is not None
            return pb.msg("HealthResponse")(
                status=(f"stage={self.held_stage} shuffled={shuffled}"
                        if self.held_stage is not None else "idle"),
                ready=True,
                queue_depth=len(self._chunks))

    def _finish(self, request, context):
        self._all_ok = bool(request.all_ok)
        self._done.set()
        return pb.msg("BoolResponse")(ok=True)

    # ---- process lifecycle -------------------------------------------

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        if not clock.wait_event(self._done, timeout):
            return False
        self.server.stop(grace=1)
        return bool(self._all_ok)

    def stop(self):
        self.server.stop(grace=0)

"""The federated mix coordinator: assigns stages, moves rows, verifies.

Drives K mix stages over K registered ``MixServerServer`` processes
(extra registrations are spares).  Per stage: assign a fresh server —
never one that already holds a stage, so the one-stage-per-process
trust boundary also holds from this side — push the input rows in
chunks, request the shuffle keyed to the coordinator's own input
digest, pull the output rows back, and verify the stage's full
Terelius–Wikström proof LOCALLY before anything is forwarded: a bad
proof or a dead server costs one requeue onto a spare, and a tampered
stage can never reach the published record or the next server's input.

Every chunk rides ``rpc_util.Stub`` (full-jitter retries, per-class
deadlines) and the fault/trace interceptors, so the PR-2 chaos drills
and PR-3 cross-process traces cover this plane for free.

Crash recovery is journal-style: a stage is published (framed, fsync'd
``mix_stage_NNN.pb``) only AFTER it verifies, and a checkpoint file
records the last verified stage + its output digest, so a restarted
coordinator resumes at the first unpublished stage, re-chaining off the
record instead of re-mixing verified work.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional

import grpc

from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.crypto import validate
from electionguard_tpu.mixnet.proof import rows_digest
from electionguard_tpu.mixnet.stage import MixStage
from electionguard_tpu.mixnet.verify_mix import verify_stage
from electionguard_tpu.obs import (REGISTRY, election_labels,
                                   set_phase, span)
from electionguard_tpu.publish import pb, serialize
from electionguard_tpu.publish.publisher import Consumer, Publisher
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.utils import clock, errors, knobs

log = logging.getLogger("mixfed.coordinator")


def _chunk_rows() -> int:
    try:
        return max(1, knobs.get_int("EGTPU_MIX_CHUNK_ROWS"))
    except ValueError:
        return 64


class MixFedError(RuntimeError):
    """A stage could not be completed on ANY available server.  ``check``
    names the verification class that failed ("" for transport-only
    failures), so chaos tests can assert a tampered stage died as
    ``mix_binding`` and not as some generic error."""

    def __init__(self, msg: str, check: str = ""):
        super().__init__(msg)
        self.check = check


class _StageFailed(Exception):
    """Internal: this server failed the stage (transport or in-band);
    requeue on a spare."""

    def __init__(self, msg: str, check: str = ""):
        super().__init__(msg)
        self.check = check


class _MixServer:
    """Coordinator-side handle for one registered mix server."""

    def __init__(self, server_id: str, url: str, nonce: bytes):
        self.id = server_id
        self.url = url
        self.reg_nonce = nonce
        self.stage: Optional[int] = None   # assigned stage, if any
        self.failed = False
        self.fail_cause = ""               # named cause of the eviction
        self._channel = None
        self._stub: Optional[rpc_util.Stub] = None

    def stub(self) -> rpc_util.Stub:
        if self._stub is None:
            self._channel = rpc_util.make_channel(self.url)
            self._stub = rpc_util.Stub(self._channel, "MixServerService")
        return self._stub

    def close(self):
        if self._channel is not None:
            self._channel.close()
            self._channel = None
            self._stub = None


class _Recorder:
    """Minimal VerificationResult stand-in for the pre-forward check."""

    def __init__(self):
        self.failures: list[tuple[str, str]] = []

    def record(self, name: str, ok: bool, msg: str = ""):
        if not ok:
            self.failures.append((name, msg))


class MixCoordinator:
    """Registration service + stage driver; see module docstring."""

    def __init__(self, group: GroupContext, out_dir: str, port: int = 0,
                 checkpoint_file: Optional[str] = None):
        self.group = group
        self.out_dir = out_dir
        self.publisher = Publisher(out_dir)
        self._checkpoint_file = checkpoint_file
        self._lock = threading.Lock()
        self.servers: list[_MixServer] = []
        self.server, self.port = rpc_util.make_server(
            port, rpc_util.MAX_REGISTRATION_MESSAGE)
        self.url = f"localhost:{self.port}"
        self.server.add_generic_rpc_handlers((rpc_util.generic_service(
            "MixRegistrationService",
            {"registerMixServer": self._register}),))
        self.server.start()
        log.info("mix coordinator listening on %d", self.port)

    # ---- registration rpc --------------------------------------------

    def _register(self, request, context):
        with self._lock:
            sid = request.server_id
            err = rpc_util.check_group_fingerprint(
                self.group, request.group_fingerprint,
                boundary="mixfed")
            if err:
                return pb.RegisterMixServerResponse(
                    error=err,
                    constants=rpc_util.group_constants_msg(self.group))
            for s in self.servers:
                if s.id == sid:
                    if (s.url == request.remote_url and s.reg_nonce
                            == bytes(request.registration_nonce)):
                        # lost-response retry: replay idempotently
                        return pb.RegisterMixServerResponse(
                            server_id=sid,
                            constants=rpc_util.group_constants_msg(
                                self.group))
                    msg = f"duplicate mix server id {sid}"
                    errors.reject("rpc.stale_registration", msg)
                    return pb.RegisterMixServerResponse(
                        error=errors.named("rpc.stale_registration", msg))
            self.servers.append(_MixServer(
                sid, request.remote_url,
                bytes(request.registration_nonce)))
            log.info("registered mix server %s at %s", sid,
                     request.remote_url)
            return pb.RegisterMixServerResponse(
                server_id=sid,
                constants=rpc_util.group_constants_msg(self.group))

    def ready(self) -> int:
        with self._lock:
            return len(self.servers)

    def wait_for_servers(self, n: int, timeout: float = 300.0,
                         poll: float = 0.25) -> bool:
        deadline = clock.monotonic() + timeout
        while clock.monotonic() < deadline:
            if self.ready() >= n:
                return True
            clock.sleep(poll)
        return False

    # ---- stage driver ------------------------------------------------

    def _next_server(self) -> Optional[_MixServer]:
        with self._lock:
            for s in self.servers:
                if s.stage is None and not s.failed:
                    return s
        return None

    def _write_checkpoint(self, stage_index: int, output_hash: bytes):
        if not self._checkpoint_file:
            return
        tmp = self._checkpoint_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"verified_stages": stage_index + 1,
                       "output_hash": output_hash.hex()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._checkpoint_file)

    def _resume_point(self, in_pads, in_datas):
        """(next_stage, rows, input_hash) from the published record.
        Published stages were verified before being written, so resume
        trusts the record; the checkpoint file cross-checks the chain
        head so a diverged/stale output dir fails loudly, not subtly."""
        consumer = Consumer(self.out_dir, self.group)
        done = consumer.mix_stage_count()
        if done == 0:
            return 0, in_pads, in_datas, rows_digest(self.group, in_pads,
                                                     in_datas)
        last = consumer.read_mix_stage(done - 1)
        head = rows_digest(self.group, last.pads, last.datas)
        if self._checkpoint_file and os.path.exists(self._checkpoint_file):
            with open(self._checkpoint_file) as f:
                cp = json.load(f)
            if int(cp.get("verified_stages", -1)) == done \
                    and cp.get("output_hash") != head.hex():
                raise MixFedError(
                    f"checkpoint output hash diverges from published "
                    f"stage {done - 1} — output dir and checkpoint are "
                    f"from different runs")
        log.info("resuming after %d published stage(s)", done)
        return done, last.pads, last.datas, head

    def _run_stage_on(self, srv: _MixServer, k: int, pads, datas,
                      input_hash: bytes, public_key: int, qbar,
                      n: int, w: int) -> MixStage:
        """Drive one stage on one server; raises _StageFailed on any
        transport or in-band failure (caller requeues on a spare)."""
        stub = srv.stub()
        ready = stub.call("registerStage", pb.MixStageRequest(
            stage_index=k,
            joint_public_key=serialize._pub_p_int(self.group, public_key),
            extended_base_hash=serialize.publish_q(qbar),
            n_rows=n, width=w,
            group_fingerprint=self.group.fingerprint()))
        if ready.error:
            raise _StageFailed(f"registerStage: {ready.error}",
                               check="refused")
        chunk = _chunk_rows()
        for start in range(0, n, chunk):
            rows = [serialize.publish_mix_row(self.group, pads[i], datas[i])
                    for i in range(start, min(start + chunk, n))]
            resp = stub.call("pushRows", pb.MixRowChunk(
                stage_index=k, chunk_start=start, rows=rows))
            if not resp.ok:
                raise _StageFailed(f"pushRows@{start}: {resp.error}",
                                   check="refused")
        result = stub.call("shuffleStage", pb.MixShuffleRequest(
            stage_index=k, input_hash=input_hash))
        if result.error:
            # the server refused to shuffle: disputed input rows or a
            # transcript replayed against a different input
            raise _StageFailed(f"shuffleStage: {result.error}",
                               check="input_mismatch")
        out_pads: list = []
        out_datas: list = []
        while len(out_pads) < n:
            got = stub.call("pullRows", pb.MixRowRequest(
                stage_index=k, chunk_start=len(out_pads), max_rows=chunk))
            if got.error:
                raise _StageFailed(f"pullRows: {got.error}",
                                   check="transfer")
            if not got.rows:
                raise _StageFailed(
                    f"pullRows: server returned {len(out_pads)} of {n} "
                    f"rows then went empty", check="transfer")
            # ingestion gate on the pulled output rows: a defective
            # element dies HERE with its named class, before the digest
            # check and before verify-before-forward touches it
            try:
                validate.gate_wire_p(
                    self.group,
                    [(f"out row {len(out_pads) + i} ct[{j}].{fld}",
                      bytes(getattr(c, fld).value))
                     for i, rm in enumerate(got.rows)
                     for j, c in enumerate(rm.ciphertexts)
                     for fld in ("pad", "data")],
                    "mixfed", allow_identity=True)
            except validate.GateError as e:
                raise _StageFailed(str(e), check="transfer")
            for rm in got.rows:
                row_a, row_b = serialize.import_mix_row(self.group, rm)
                out_pads.append(row_a)
                out_datas.append(row_b)
        if rows_digest(self.group, out_pads, out_datas) \
                != bytes(result.output_hash):
            raise _StageFailed(
                f"stage {k}: pulled rows do not digest to the server's "
                f"output hash (corrupted transfer?)", check="transfer")
        hdr = result.header
        if (int(hdr.stage_index) != k or int(hdr.n_rows) != n
                or int(hdr.width) != w
                or serialize.import_u256(hdr.input_hash) != input_hash):
            # a replayed transcript: the result describes some OTHER
            # stage (wrong index / rows / input hash)
            raise _StageFailed(
                f"stage {k}: result header does not describe the "
                f"requested stage", check="replay")
        proof = serialize.import_mix_proof(self.group, hdr.proof)
        return MixStage(k, n, w, input_hash, out_pads, out_datas, proof)

    def run_mix(self, public_key: int, qbar, n_stages: int,
                in_pads, in_datas) -> int:
        """Mix ``n_stages`` stages starting from the given input rows
        (the record's cast-ballot ciphertexts for a fresh run); returns
        the number of stages published THIS call (resume skips verified
        ones).  Raises ``MixFedError`` when a stage cannot be completed
        on any remaining server."""
        if not in_pads:
            raise MixFedError("no input rows to mix")
        n, w = len(in_pads), len(in_pads[0])
        k, pads, datas, input_hash = self._resume_point(in_pads, in_datas)
        published = 0
        while k < n_stages:
            srv = self._next_server()
            if srv is None:
                # exhaustion discovered a stage AFTER the evictions that
                # caused it; re-surface their named causes so the abort
                # text says WHY every server is gone (and so a sound
                # abort under attack stays attributable to the attack)
                with self._lock:
                    causes = [f"{s.id}: {s.fail_cause}"
                              for s in self.servers
                              if s.failed and s.fail_cause]
                raise MixFedError(
                    f"stage {k}: no registered mix server left to run it "
                    f"(all assigned or failed"
                    + (f"; evictions: {'; '.join(causes)}" if causes
                       else "") + ")")
            srv.stage = k
            set_phase(f"mix-stage-{k}")
            with span("mixfed.forward", {"stage": k, "server": srv.id}):
                try:
                    stage = self._run_stage_on(srv, k, pads, datas,
                                               input_hash, public_key,
                                               qbar, n, w)
                except (grpc.RpcError, _StageFailed) as e:
                    detail = (f"{e.code().name}: {e.details()}"
                              if isinstance(e, grpc.RpcError) else str(e))
                    cls = getattr(e, "check", "")
                    if cls:
                        # in-band refusal with a named cause: a
                        # contained detection even when a spare absorbs
                        # the requeue
                        errors.reject(f"mix.{cls}",
                                      f"stage {k} on {srv.id}: {detail}")
                    log.warning("stage %d failed on server %s (%s); "
                                "requeueing on a spare", k, srv.id, detail)
                    srv.failed = True
                    srv.fail_cause = (errors.named(f"mix.{cls}", detail)
                                      if cls else detail)
                    srv.close()
                    REGISTRY.counter("mixfed_stage_requeues_total",
                                     election_labels()).inc()
                    if self._next_server() is None:
                        msg = (f"stage {k} failed on server {srv.id} "
                               f"({detail}) and no spare server remains")
                        if cls:
                            msg = errors.named(f"mix.{cls}", msg)
                        raise MixFedError(msg, check=cls)
                    continue
                # ---- verify BEFORE forwarding ------------------------
                rec = _Recorder()
                ok = verify_stage(self.group, public_key, qbar, stage,
                                  pads, datas, input_hash, rec)
                if not ok:
                    check, msg = (rec.failures[0] if rec.failures
                                  else ("mix_verify", "unknown"))
                    check = check.split(".")[-1]
                    short = check[4:] if check.startswith("mix_") else check
                    errors.reject(f"mix.{short}",
                                  f"stage {k} on {srv.id}: {msg}")
                    log.error("stage %d from server %s FAILED pre-forward "
                              "verification [%s]: %s — requeueing", k,
                              srv.id, check, msg)
                    srv.failed = True
                    srv.fail_cause = errors.named(f"mix.{short}", msg)
                    srv.close()
                    REGISTRY.counter("mixfed_bad_proofs_total",
                                     election_labels()).inc()
                    REGISTRY.counter("mixfed_stage_requeues_total",
                                     election_labels()).inc()
                    if self._next_server() is None:
                        raise MixFedError(errors.named(
                            f"mix.{short}",
                            f"stage {k} from server {srv.id} failed "
                            f"verification ({check}: {msg}) and no spare "
                            f"server remains"), check=check)
                    continue
            path = self.publisher.write_mix_stage(self.group, stage)
            output_hash = rows_digest(self.group, stage.pads, stage.datas)
            self._write_checkpoint(k, output_hash)
            log.info("stage %d verified on server %s and published -> %s",
                     k, srv.id, path)
            pads, datas = stage.pads, stage.datas
            input_hash = output_hash
            published += 1
            k += 1
        set_phase("mix-complete")
        return published

    def shutdown(self, all_ok: bool):
        with self._lock:
            servers = list(self.servers)
        for s in servers:
            try:
                s.stub().call("finish", pb.msg("FinishRequest")(
                    all_ok=all_ok), timeout=5.0)
            except grpc.RpcError:
                pass   # a crashed server has nothing to finish
            s.close()
        self.server.stop(grace=1)

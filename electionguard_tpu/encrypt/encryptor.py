"""Batch ballot encryption: the TPU-vmapped replacement for the reference's
[ext] ``batchEncryption(group, in, out, ballots, invalid, fixedNonces,
nthreads=11, createdBy, check)`` (call site:
src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:140 — the
reference scales this with an 11-thread CPU pool; we scale it with the batch
axis on the chip, SURVEY.md §5.7).

TPU-first structure: because the encryptor KNOWS every nonce R, *every*
group exponentiation in the pipeline — ciphertext pads/datas, real proof
commitments, and even the simulated-branch commitments
``a_f = g^{v_f} α^{c_f} = g^{v_f + R c_f}`` — is a fixed-base power of g or
K.  One batched PowRadix pass over [all ballots × contests × selections]
computes everything; host work is only SHA-256 challenges and bookkeeping.

Per selection: 4 g-powers + 3 K-powers + 2 modmuls.
Per contest:   2 g-powers + 2 K-powers (limit proof + direct accumulation
               A = g^{ΣR}, B = g^{ΣV} K^{ΣR}).

Contests are padded with ``votes_allowed`` placeholder selections so the
selection sum always equals the limit; overvoted ballots are returned on the
invalid list (the reference's invalidDir)."""

from __future__ import annotations

import dataclasses
import hashlib
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from electionguard_tpu.ballot.ciphertext import (BallotState, EncryptedBallot,
                                                 EncryptedContest,
                                                 EncryptedSelection)
from electionguard_tpu.ballot.plaintext import PlaintextBallot
from electionguard_tpu.core.group import ElementModP, ElementModQ
from electionguard_tpu.core.group_jax import (JaxExponentOps, JaxGroupOps,
                                              jax_exp_ops, jax_ops,
                                              limbs_to_bytes_be)
from electionguard_tpu.core import sha256_jax
from electionguard_tpu.core.hash import _encode, hash_digest, hash_elems
from electionguard_tpu.crypto.chaum_pedersen import (
    ConstantChaumPedersenProof, DisjunctiveChaumPedersenProof)
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
from electionguard_tpu.publish.election_record import ElectionInitialized
from electionguard_tpu.utils import clock, devicetime, knobs


@dataclass
class _FlatSelections:
    """Columnar view of one batch: all selections of all ballots."""

    ballot_idx: list[int]
    contest_idx: list[int]          # index into per-ballot contest list
    selection_ids: list[str]
    sequence_orders: list[int]
    votes: list[int]
    is_placeholder: list[bool]


class BatchEncryptor:
    def __init__(self, election_init: ElectionInitialized,
                 group=None, mesh=None):
        """``mesh``: optional device mesh — shards the fused selection/
        contest encryption programs' batch axis over dp (production
        group only; see encrypt/fused.py)."""
        self.init = election_init
        self.mesh = mesh
        self.group = group if group is not None else \
            election_init.joint_public_key.group
        self.manifest = election_init.config.manifest
        self.K = election_init.joint_public_key
        self.qbar = election_init.extended_base_hash
        self.ops: JaxGroupOps = jax_ops(self.group)
        self.eops: JaxExponentOps = jax_exp_ops(self.group)
        # build/cache the K fixed-base table once
        self.ops.fixed_table(self.K.value)
        # ballot ids seen across ALL encrypt_ballots calls on this
        # encryptor: identity keys the nonce PRF, so a repeated id in a
        # later chunk would reuse pads — reject it in any chunk.  Stored
        # as 16-byte digest prefixes: ~24 MB of payload per 1M ballots,
        # the one per-ballot residual on the otherwise O(chunk) path.
        self._seen_ids: set[bytes] = set()

    # ------------------------------------------------------------------
    def encrypt_ballots(
            self, ballots: Sequence[PlaintextBallot],
            seed: Optional[ElementModQ] = None,
            code_seed: Optional[bytes] = None,
            ballot_index_base: int = 0,
            spoiled_ids: Optional[set] = None,
            timestamp: Optional[int] = None,
    ) -> tuple[list[EncryptedBallot], list[tuple[PlaintextBallot, str]]]:
        from electionguard_tpu.obs import trace
        devicetime.charge("encrypt", len(ballots))
        attrs = {"n": len(ballots)} if trace.enabled() else None
        with trace.span("encrypt.batch", attrs):
            return self._encrypt_ballots(
                ballots, seed=seed, code_seed=code_seed,
                ballot_index_base=ballot_index_base,
                spoiled_ids=spoiled_ids, timestamp=timestamp)

    def _encrypt_ballots(
            self, ballots: Sequence[PlaintextBallot],
            seed: Optional[ElementModQ] = None,
            code_seed: Optional[bytes] = None,
            ballot_index_base: int = 0,
            spoiled_ids: Optional[set] = None,
            timestamp: Optional[int] = None,
    ) -> tuple[list[EncryptedBallot], list[tuple[PlaintextBallot, str]]]:
        """Encrypt a batch.  Returns (encrypted, invalid) where invalid is
        [(ballot, reason)] — mirroring batchEncryption's invalidDir.

        Nonces are keyed by BALLOT IDENTITY (SHA-256 of ballot_id), never
        by batch position, so encrypting chunk-by-chunk under one seed can
        never reuse a pad across chunks — ballots with distinct ids get
        distinct nonces no matter how the stream is split.  Duplicate ids
        within a batch are rejected to the invalid list (and ballot ids
        must be unique election-wide, as the code chain already requires).
        ``ballot_index_base`` is retained for API compatibility but no
        longer participates in nonce derivation.
        ``spoiled_ids``: ballot ids to mark SPOILED instead of CAST — they
        stay in the code chain but are excluded from the tally and become
        eligible for spoiled-ballot decryption (reference:
        RunRemoteDecryptor.java:264-269).
        ``timestamp``: ballot timestamp (defaults to now); the
        confirmation code commits to it, so a caller replaying a stream
        for bit-identical codes (serve differential tests) must pin it.
        """
        g = self.group
        seed = seed if seed is not None else g.rand_q()
        spoiled_ids = spoiled_ids or set()
        code_seed = code_seed if code_seed is not None else \
            hash_digest("code-chain-start", self.init.manifest_hash)

        # ---- flatten: selections (with placeholders) and contests -------
        valid: list[PlaintextBallot] = []
        invalid: list[tuple[PlaintextBallot, str]] = []
        flat = _FlatSelections([], [], [], [], [], [])
        sel_ord: list[int] = []       # selection ordinal within its ballot
        contest_rows: list[tuple[int, int, str, int, int]] = []
        # (ballot_idx, contest_ordinal, contest_id, seq, limit)
        contests_by_id = {c.object_id: c for c in self.manifest.contests}
        # stage this batch's ids locally; merge into the cross-call set
        # only on success, so a caller retrying a failed dispatch doesn't
        # see its own ballots as duplicates
        batch_ids: set[bytes] = set()
        valid_digests: list[bytes] = []   # full 32-byte identity digests

        for pos, b in enumerate(ballots):
            reason = None
            bid_digest = hashlib.sha256(b.ballot_id.encode()).digest()
            bid_key = bid_digest[:16]
            if bid_key in self._seen_ids or bid_key in batch_ids:
                # identity keys the nonce PRF: a second ballot under the
                # same id would reuse its pads and leak vote equality
                invalid.append((b, f"duplicate ballot id {b.ballot_id}"))
                continue
            cids = [c.contest_id for c in b.contests]
            if len(set(cids)) != len(cids):
                invalid.append((b, "duplicate contest ids"))
                continue
            for c in b.contests:
                desc = contests_by_id.get(c.contest_id)
                if desc is None:
                    reason = f"unknown contest {c.contest_id}"
                    break
                sids = [s.selection_id for s in c.selections]
                if len(set(sids)) != len(sids):
                    reason = f"duplicate selection ids in {c.contest_id}"
                    break
                known_sels = {s.object_id for s in desc.selections}
                bad = [s.selection_id for s in c.selections
                       if s.selection_id not in known_sels]
                if bad:
                    reason = f"unknown selection {bad[0]} in {c.contest_id}"
                    break
                votes = [s.vote for s in c.selections]
                if any(v not in (0, 1) for v in votes):
                    reason = f"non-binary vote in {c.contest_id}"
                    break
                if sum(votes) > desc.votes_allowed:
                    reason = f"overvote in {c.contest_id}"
                    break
            if reason is not None:
                invalid.append((b, reason))
                continue
            bi = len(valid)
            valid.append(b)
            batch_ids.add(bid_key)
            valid_digests.append(bid_digest)
            sel_ordinal = 0
            for ci, c in enumerate(b.contests):
                desc = contests_by_id[c.contest_id]
                limit = desc.votes_allowed
                votes = [s.vote for s in c.selections]
                n_real = len(votes)
                pad_votes = [0] * limit
                for j in range(limit - sum(votes)):
                    pad_votes[j] = 1  # placeholders top the sum up to limit
                contest_rows.append((bi, ci, c.contest_id,
                                     desc.sequence_order, limit))
                for si, s in enumerate(c.selections):
                    flat.ballot_idx.append(bi)
                    flat.contest_idx.append(len(contest_rows) - 1)
                    flat.selection_ids.append(s.selection_id)
                    flat.sequence_orders.append(si)
                    flat.votes.append(s.vote)
                    flat.is_placeholder.append(False)
                    sel_ord.append(sel_ordinal)
                    sel_ordinal += 1
                for j, pv in enumerate(pad_votes):
                    flat.ballot_idx.append(bi)
                    flat.contest_idx.append(len(contest_rows) - 1)
                    flat.selection_ids.append(
                        f"{c.contest_id}-placeholder-{j}")
                    flat.sequence_orders.append(n_real + j)
                    flat.votes.append(pv)
                    flat.is_placeholder.append(True)
                    sel_ord.append(sel_ordinal)
                    sel_ordinal += 1

        S = len(flat.votes)
        C = len(contest_rows)
        if S == 0:
            self._seen_ids |= batch_ids
            return [], invalid

        # ---- per-selection scalars + group math -------------------------
        # The four per-selection scalars (R, U, CF, VF) are internal
        # secrets: they must be deterministic in the seed, unique per
        # (ballot identity, position-in-ballot), and uniform mod q —
        # nothing external ever re-derives them.  On the production group
        # the ENTIRE pipeline (nonce PRF, exponent algebra, fixed-base
        # passes, Fiat–Shamir, responses) runs as one fused device
        # program per tile (encrypt/fused.py); other groups fall back to
        # host hashing with batched group math.
        q = g.q
        bid_digests = np.frombuffer(
            b"".join(valid_digests), np.uint8).reshape(-1, 32)
        votes = np.array(flat.votes, dtype=np.int64)
        eo = self.ops
        ee = self.eops
        V_sum = [0] * C
        for i in range(S):
            V_sum[flat.contest_idx[i]] += flat.votes[i]

        # With EGTPU_VERIFY_BATCH on, the prover's commitment values ride
        # along as unserialized verification hints so the RLC batch
        # verifier can skip recomputing them (they are produced by both
        # pipelines anyway; the flag only gates transfer/attachment).
        with_hints = knobs.get_flag("EGTPU_VERIFY_BATCH")
        ar_l = br_l = af_l = bf_l = ac_l = bc_l = None
        if sha256_jax.supports(g):
            bids_con = bid_digests[
                np.asarray([row[0] for row in contest_rows], np.int64)]
            ords_con = np.asarray([row[1] for row in contest_rows],
                                  dtype=np.uint32)
            by_limit: dict[int, list[int]] = {}
            for ci, row in enumerate(contest_rows):
                by_limit.setdefault(row[4], []).append(ci)
            from electionguard_tpu.encrypt.fused import get_fused_encryptor
            fe = get_fused_encryptor(eo, ee, self.mesh)
            seed_row = np.frombuffer(seed.to_bytes(), np.uint8)
            sel_outs = fe.encrypt_selections(
                seed_row,
                bid_digests[np.asarray(flat.ballot_idx, np.int64)],
                np.asarray(sel_ord, np.uint32), votes,
                self.K.value, _encode(self.qbar), with_hints=with_hints)
            alpha, beta, R_l, CR_l, VR_l, CF_l, VF_l = sel_outs[:7]
            if with_hints:
                ar_l, br_l, af_l, bf_l = sel_outs[7:]
            # per-contest ΣR mod q from the nonce limbs: unsorted-safe
            # segment sum (a contest with zero selection rows — possible
            # only for an unvalidated votes_allowed=0 manifest — still
            # lands ΣR=0 at its own index instead of shifting the rest)
            sums = np.zeros((C, R_l.shape[1]), dtype=np.uint64)
            np.add.at(sums, np.asarray(flat.contest_idx, np.int64),
                      R_l.astype(np.uint64))
            R_sum = [int(sum(int(v) << (16 * k)
                             for k, v in enumerate(row))) % q
                     for row in sums]
            RS_l = np.asarray(ee.to_limbs(R_sum))
            VS_l = np.asarray(ee.to_limbs(V_sum))
            A_c = np.empty((C, eo.n), dtype=np.uint32)
            B_c = np.empty((C, eo.n), dtype=np.uint32)
            C2_l = np.empty((C, ee.ne), dtype=np.uint32)
            V2_l = np.empty((C, ee.ne), dtype=np.uint32)
            if with_hints:
                ac_l = np.empty((C, eo.n), dtype=np.uint32)
                bc_l = np.empty((C, eo.n), dtype=np.uint32)
            for limit, idxs in by_limit.items():
                ix = np.asarray(idxs)
                con_outs = fe.encrypt_contests(
                    seed_row, bids_con[ix], ords_con[ix],
                    RS_l[ix], VS_l[ix], self.K.value,
                    _encode(self.qbar) + _encode(limit),
                    with_hints=with_hints)
                A_c[ix], B_c[ix] = con_outs[0], con_outs[1]
                C2_l[ix], V2_l[ix] = con_outs[2], con_outs[3]
                if with_hints:
                    ac_l[ix], bc_l[ix] = con_outs[4], con_outs[5]
        else:
            R = np.empty(S, dtype=object)
            U = np.empty(S, dtype=object)
            CF = np.empty(S, dtype=object)
            VF = np.empty(S, dtype=object)
            for i in range(S):
                # keyed by (identity, per-ballot contest ordinal,
                # selection id) — like the fused path, invariant to how
                # the stream is chunked into encrypt_ballots calls, so
                # online batching and offline runs produce identical
                # ciphertexts for the same seed
                h = hash_elems(g, seed, valid[flat.ballot_idx[i]].ballot_id,
                               contest_rows[flat.contest_idx[i]][1],
                               flat.selection_ids[i])
                R[i] = h.value
                U[i] = hash_elems(g, h, "u").value
                CF[i] = hash_elems(g, h, "cf").value
                VF[i] = hash_elems(g, h, "vf").value

            # batched group math on device, Fiat–Shamir on host
            R_l = ee.to_limbs(R)
            U_l = ee.to_limbs(U)
            CF_l = np.asarray(ee.to_limbs(CF))
            VF_l = np.asarray(ee.to_limbs(VF))
            # w = v_f + R*c_f mod q
            W_l = np.asarray(ee.add(VF_l, ee.mul(R_l, CF_l)))
            # s = +c_f (vote==1) or q - c_f (vote==0): exponent of g in
            # the fake-branch commitment b_f
            negCF = np.asarray(ee.sub(ee.to_limbs([0] * S), CF_l))
            S_l = np.where((votes == 1)[:, None], CF_l,
                           negCF).astype(np.uint32)

            g_exps = np.concatenate([R_l, U_l, W_l, S_l])      # (4S, ne)
            k_exps = np.concatenate([R_l, U_l, W_l])           # (3S, ne)
            g_pows = np.asarray(eo.g_pow(g_exps))
            k_pows = np.asarray(eo.base_pow(self.K.value, k_exps))
            alpha = g_pows[:S]
            a_real = g_pows[S:2 * S]
            a_fake = g_pows[2 * S:3 * S]
            g_s = g_pows[3 * S:]
            beta_k = k_pows[:S]
            b_real = k_pows[S:2 * S]
            k_w = k_pows[2 * S:]

            g_limbs = eo.to_limbs_p([g.g])[0]
            beta1 = np.asarray(eo.mulmod(
                beta_k, np.broadcast_to(g_limbs, beta_k.shape)))
            beta = np.where((votes == 1)[:, None], beta1,
                            beta_k).astype(np.uint32)
            b_fake = np.asarray(eo.mulmod(g_s, k_w))

            alpha_b = limbs_to_bytes_be(alpha)
            beta_b = limbs_to_bytes_be(beta)
            a_real_b = limbs_to_bytes_be(a_real)
            b_real_b = limbs_to_bytes_be(b_real)
            a_fake_b = limbs_to_bytes_be(a_fake)
            b_fake_b = limbs_to_bytes_be(b_fake)
            C_chal = np.empty(S, dtype=object)
            for i in range(S):
                if votes[i] == 0:
                    a0, b0, a1, b1 = (a_real_b[i], b_real_b[i],
                                      a_fake_b[i], b_fake_b[i])
                else:
                    a0, b0, a1, b1 = (a_fake_b[i], b_fake_b[i],
                                      a_real_b[i], b_real_b[i])
                C_chal[i] = _hash_disjunctive(
                    g, self.qbar, alpha_b[i], beta_b[i], a0, b0, a1, b1)
            C_l = ee.to_limbs(C_chal)

            # c_real = c - c_f ; v_real = u - c_real * R  (device, mod q)
            CR_l = np.asarray(ee.sub(C_l, CF_l))
            VR_l = np.asarray(ee.a_minus_bc(U_l, CR_l, R_l))

            # contests: accumulation + limit proof
            R_sum = [0] * C
            for i in range(S):
                R_sum[flat.contest_idx[i]] = \
                    (R_sum[flat.contest_idx[i]] + R[i]) % q
            U2 = [hash_elems(g, seed, "contest-u", row[1],
                             valid[row[0]].ballot_id).value
                  for row in contest_rows]
            RS_l = ee.to_limbs(R_sum)
            U2_l = ee.to_limbs(U2)
            VS_l = ee.to_limbs(V_sum)
            g_exps2 = np.concatenate([RS_l, U2_l, VS_l])
            k_exps2 = np.concatenate([RS_l, U2_l])
            g_pows2 = np.asarray(eo.g_pow(g_exps2))
            k_pows2 = np.asarray(eo.base_pow(self.K.value, k_exps2))
            A_c = g_pows2[:C]
            a_c = g_pows2[C:2 * C]
            gV = g_pows2[2 * C:]
            BK_c = k_pows2[:C]
            b_c = k_pows2[C:2 * C]
            B_c = np.asarray(eo.mulmod(gV, BK_c))

            A_b = limbs_to_bytes_be(A_c)
            B_b = limbs_to_bytes_be(B_c)
            a_cb = limbs_to_bytes_be(a_c)
            b_cb = limbs_to_bytes_be(b_c)
            C2 = np.empty(C, dtype=object)
            for ci, row in enumerate(contest_rows):
                C2[ci] = _hash_constant(g, self.qbar, row[4], A_b[ci],
                                        B_b[ci], a_cb[ci], b_cb[ci])
            C2_l = ee.to_limbs(C2)
            V2_l = np.asarray(ee.a_minus_bc(U2_l, C2_l, RS_l))
            if with_hints:
                ar_l, br_l, af_l, bf_l = a_real, b_real, a_fake, b_fake
                ac_l, bc_l = a_c, b_c

        # ---- materialize ballots ---------------------------------------
        alpha_i = self.ops.from_limbs(alpha)
        beta_i = self.ops.from_limbs(beta)
        A_i = self.ops.from_limbs(A_c)
        B_i = self.ops.from_limbs(B_c)
        CR = ee.from_limbs(CR_l)
        VR = ee.from_limbs(VR_l)
        CF_i = ee.from_limbs(CF_l)
        VF_i = ee.from_limbs(VF_l)
        C2_i = ee.from_limbs(C2_l)
        V2 = ee.from_limbs(V2_l)
        if with_hints:
            ar_i = self.ops.from_limbs(ar_l)
            br_i = self.ops.from_limbs(br_l)
            af_i = self.ops.from_limbs(af_l)
            bf_i = self.ops.from_limbs(bf_l)
            ac_i = self.ops.from_limbs(ac_l)
            bc_i = self.ops.from_limbs(bc_l)

        sel_by_contest: dict[int, list[EncryptedSelection]] = {}
        for i in range(S):
            ct = ElGamalCiphertext(ElementModP(alpha_i[i], g),
                                   ElementModP(beta_i[i], g))
            if votes[i] == 0:
                # hints in hash/proof order (a0, b0, a1, b1): the real
                # branch is the zero branch here, the simulated branch
                # the one branch (and vice versa below)
                hints = ((ar_i[i], br_i[i], af_i[i], bf_i[i])
                         if with_hints else None)
                proof = DisjunctiveChaumPedersenProof(
                    g.int_to_q(CR[i]), g.int_to_q(VR[i]),
                    g.int_to_q(CF_i[i]), g.int_to_q(VF_i[i]),
                    commitment_hints=hints)
            else:
                hints = ((af_i[i], bf_i[i], ar_i[i], br_i[i])
                         if with_hints else None)
                proof = DisjunctiveChaumPedersenProof(
                    g.int_to_q(CF_i[i]), g.int_to_q(VF_i[i]),
                    g.int_to_q(CR[i]), g.int_to_q(VR[i]),
                    commitment_hints=hints)
            sel = EncryptedSelection(
                flat.selection_ids[i], flat.sequence_orders[i], ct, proof,
                flat.is_placeholder[i])
            sel_by_contest.setdefault(flat.contest_idx[i], []).append(sel)

        contests_by_ballot: dict[int, list[EncryptedContest]] = {}
        for ci, row in enumerate(contest_rows):
            bi, _, contest_id, seq, limit = row[:5]
            proof = ConstantChaumPedersenProof(
                g.int_to_q(C2_i[ci]), g.int_to_q(V2[ci]), limit,
                commitment_hints=((ac_i[ci], bc_i[ci])
                                  if with_hints else None))
            contests_by_ballot.setdefault(bi, []).append(
                EncryptedContest(contest_id, seq,
                                 tuple(sel_by_contest[ci]), proof))

        out: list[EncryptedBallot] = []
        prev_code = code_seed
        timestamp = int(clock.now()) if timestamp is None else int(timestamp)
        # the ballot crypto hash is chain-independent, so the whole batch
        # hashes in a few device dispatches; only the (cheap) code chain
        # itself is sequential
        from electionguard_tpu.ballot.code_batch import batch_crypto_hashes
        structured = []
        for bi, b in enumerate(valid):
            contests = tuple(contests_by_ballot.get(bi, []))
            state = (BallotState.SPOILED if b.ballot_id in spoiled_ids
                     else BallotState.CAST)
            structured.append(EncryptedBallot(
                b.ballot_id, b.ballot_style_id, self.init.manifest_hash,
                b"", b"", timestamp, contests, state))
        hashes = batch_crypto_hashes(structured)
        for i, partial in enumerate(structured):
            code = EncryptedBallot.make_code(prev_code, timestamp,
                                             hashes[i].tobytes())
            out.append(dataclasses.replace(
                partial, code_seed=prev_code, code=code))
            prev_code = code
        self._seen_ids |= batch_ids
        return out, invalid


def _nonce_rows(seed: ElementModQ, tags: np.ndarray, bids: np.ndarray,
                ords: np.ndarray) -> np.ndarray:
    """Fixed-width SHA-256 input rows:
    seed(32) || tag(1) || SHA-256(ballot_id)(32) || ordinal(4 BE).

    Keying by ballot identity (not batch position) makes cross-chunk
    nonce reuse structurally impossible: no matter how a caller splits a
    ballot stream into encrypt_ballots() calls under one seed, distinct
    ballots hash distinct rows."""
    n = tags.shape[0]
    msgs = np.zeros((n, 69), np.uint8)
    msgs[:, :32] = np.frombuffer(seed.to_bytes(), np.uint8)
    msgs[:, 32] = tags
    msgs[:, 33:65] = bids
    msgs[:, 65:] = ords.astype(">u4").view(np.uint8).reshape(n, 4)
    return msgs


def _derive_nonce_ints(g, ee, msgs: np.ndarray) -> list[int]:
    """Host-visible twin of the fused pipeline's nonce PRF (hash rows on
    device, reduce mod q, return ints).  The fused programs derive these
    in-dispatch (encrypt/fused.py _nonce_mod_q); this twin exists for
    differential tests pinning the two byte-identical."""
    from electionguard_tpu.core.group_jax import run_tiled
    limbs = run_tiled(
        lambda m: sha256_jax.digest_to_q_limbs(g, sha256_jax.sha256_rows(m)),
        [msgs], [False])
    return ee.from_limbs(np.asarray(limbs))


def _hash_disjunctive(g, qbar, alpha_b, beta_b, a0, b0, a1, b1) -> int:
    """Challenge c = H(Q̄, α, β, a0, b0, a1, b1) over byte images; must match
    DisjunctiveChaumPedersenProof.is_valid's hash_elems call exactly."""
    return hash_elems(g, qbar, *(g.bytes_to_p(bytes(x)) for x in
                                 (alpha_b, beta_b, a0, b0, a1, b1))).value


def _hash_constant(g, qbar, constant, A_b, B_b, a_b, b_b) -> int:
    return hash_elems(g, qbar, constant,
                      *(g.bytes_to_p(bytes(x)) for x in
                        (A_b, B_b, a_b, b_b))).value

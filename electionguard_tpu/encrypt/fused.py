"""Fused on-device ballot encryption programs.

Round-4 hardware profiling left encryption as the slowest phase (88.5
ballots/s vs 188.5 verify, TPU_RESULTS.md): the columnar encryptor ran
~12 separate device dispatches per chunk — nonce SHA, five Z_q algebra
ops, two fixed-base passes, two Montgomery products, the challenge SHA,
and two response ops — each a synchronous host round-trip over the
single-chip tunnel, with the nonces even pulled to host ints and pushed
straight back as limbs.

These programs keep the ENTIRE selection / contest encryption pipeline
on device in one jitted dispatch per tile: nonce PRF (SHA-256 rows),
exponent algebra in Z_q, PowRadix fixed-base passes in the Montgomery
domain, ciphertext assembly, byte imaging, the device Fiat–Shamir
challenge, and the response equations.  The host uploads ballot-identity
digests + ordinals + votes and downloads the finished columns (α, β,
proof scalars) once.

Byte-identical to the unfused path: the nonce rows replay
``encryptor._nonce_rows`` exactly and the challenge framing replays
``sha256_jax.batch_challenge_p``; the differential test
(tests/test_fused_encrypt.py) pins ciphertext-for-ciphertext equality.

Applies to groups supported by the device SHA path (production
4096/256-bit geometry); other groups keep the host-hash fallback.
Reference analogue of the whole pipeline: ``batchEncryption(...,
nthreads=11, ...)`` — src/test/java/electionguard/workflow/
RunRemoteWorkflowTest.java:140.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.core import sha256_jax
from electionguard_tpu.core.group_jax import (JaxExponentOps, JaxGroupOps,
                                              run_tiled_multi)
from electionguard_tpu.verify.fused import (challenge_rows, fixed_pow_mont,
                                            k_tables, limbs_to_bytes_j)

_P_HDR = np.frombuffer(sha256_jax._TAG_P_HDR, np.uint8)


def get_fused_encryptor(ops: JaxGroupOps, eops: JaxExponentOps,
                        mesh=None) -> "FusedEncryptor":
    """One FusedEncryptor per (batch plane, mesh), stored ON the plane
    (same lifetime/aliasing rationale as verify.fused.get_fused)."""
    cache = getattr(ops, "_fused_encryptors", None)
    if cache is None:
        cache = ops._fused_encryptors = {}
    key = None if mesh is None else id(mesh)
    fe = cache.get(key)
    if fe is None:
        fe = FusedEncryptor(ops, eops, mesh)
        cache[key] = fe
    return fe


class FusedEncryptor:
    """Jitted selection/contest encryption for one group's batch planes.

    Group constants (g table, g in Montgomery form, q limbs) are closure
    constants; the election key table, seed row, and hash prefix are
    runtime arguments, so compiled programs survive election turnover.
    """

    def __init__(self, ops: JaxGroupOps, eops: JaxExponentOps, mesh=None):
        self.ops = ops
        self.eops = eops
        self.mesh = mesh
        g = ops.group
        self.qctx = eops.ctx
        self._q_limbs = jnp.asarray(bn.int_to_limbs(g.q, eops.ne))
        self._hdr = jnp.asarray(_P_HDR)
        self._g_mont = jnp.asarray(
            bn.int_to_limbs(g.g * ops._R % g.p, ops.n))
        # NTT-evaluated table twins (None on the cios backend)
        self._g_hat = ops.fixed_table_hat(g.g)
        if mesh is None:
            self.ndp = 1
            self._sel_j = jax.jit(self._sel_impl)
            self._con_j = jax.jit(self._con_impl)
        else:
            from electionguard_tpu.parallel.mesh import DP_AXIS
            from electionguard_tpu.verify.fused import shard_rows
            self.ndp = mesh.shape[DP_AXIS]
            self._sel_j = jax.jit(
                shard_rows(self._sel_impl, mesh, 3, 4, n_out=11))
            self._con_j = jax.jit(
                shard_rows(self._con_impl, mesh, 4, 4, n_out=6))


    # -- shared helpers (device) ---------------------------------------
    def _challenge(self, prefix_row, elem_bytes):
        return challenge_rows(self._hdr, self._q_limbs, prefix_row,
                              elem_bytes)

    def _nonce_mod_q(self, seed_row, tags, bids, ords):
        """Device twin of encryptor._nonce_rows + digest mod q:
        seed(32) || tag(1) || bid-digest(32) || ordinal(4 BE)."""
        t = bids.shape[0]
        ordb = jnp.stack([(ords >> 24) & 0xFF, (ords >> 16) & 0xFF,
                          (ords >> 8) & 0xFF, ords & 0xFF],
                         axis=1).astype(jnp.uint8)
        msgs = jnp.concatenate(
            [jnp.broadcast_to(seed_row, (t, 32)),
             tags[:, None].astype(jnp.uint8), bids, ordb], axis=1)
        return sha256_jax._digest_mod_q(sha256_jax.sha256_rows(msgs),
                                        self._q_limbs)

    # -- selections ----------------------------------------------------
    def _sel_impl(self, bids, ords, votes, seed_row, k_table, k_hat,
                  prefix_row):
        """One dispatch for a tile of selections.

        α = g^R, β = K^R g^v; real commitments a=g^U, b=K^U; fake branch
        a_f = g^{V_F + R C_F}, b_f = g^{±C_F} K^{V_F + R C_F};
        c = H(Q̄, α, β, a0, b0, a1, b1) with branch order by vote;
        c_r = c - C_F, v_r = U - c_r R   (all mod q).
        Returns (α, β, R, c_r, v_r, C_F, V_F, a_r, b_r, a_f, b_f) —
        α/β and the four commitment rows (the RLC verifier's hints,
        already computed for the challenge hash — returning them is
        free) canonical limbs, scalars as Z_q limbs.
        """
        ops, qc = self.ops, self.qctx
        mm = ops._mm
        t = bids.shape[0]
        tags = jnp.repeat(jnp.arange(4, dtype=jnp.uint32), t)
        d = self._nonce_mod_q(seed_row, tags, jnp.tile(bids, (4, 1)),
                              jnp.tile(ords, 4))
        R, U, CF, VF = d[:t], d[t:2 * t], d[2 * t:3 * t], d[3 * t:]

        W = bn.add_mod(VF, bn.mulmod(qc, R, CF), qc.p_limbs)
        negCF = bn.sub_mod(jnp.zeros_like(CF), CF, qc.p_limbs)
        v1 = (votes == 1)[:, None]
        Sx = jnp.where(v1, CF, negCF)

        gp = fixed_pow_mont(ops, ops.g_table,
                            jnp.concatenate([R, U, W, Sx]), self._g_hat)
        kp = fixed_pow_mont(ops, k_table, jnp.concatenate([R, U, W]),
                            k_hat)
        alpha_m, a_real_m, a_fake_m, gS_m = (
            gp[:t], gp[t:2 * t], gp[2 * t:3 * t], gp[3 * t:])
        betak_m, b_real_m, kW_m = kp[:t], kp[t:2 * t], kp[2 * t:]
        beta_m = jnp.where(
            v1, mm(betak_m, jnp.broadcast_to(self._g_mont, betak_m.shape)),
            betak_m)
        b_fake_m = mm(gS_m, kW_m)

        com = bn.from_mont_via(mm, jnp.concatenate(
            [alpha_m, beta_m, a_real_m, b_real_m, a_fake_m, b_fake_m]))
        cb = limbs_to_bytes_j(com)
        arb, brb = cb[2 * t:3 * t], cb[3 * t:4 * t]
        afb, bfb = cb[4 * t:5 * t], cb[5 * t:]
        chal = self._challenge(
            prefix_row,
            [cb[:t], cb[t:2 * t],
             jnp.where(v1, afb, arb), jnp.where(v1, bfb, brb),
             jnp.where(v1, arb, afb), jnp.where(v1, brb, bfb)])
        CR = bn.sub_mod(chal, CF, qc.p_limbs)
        VR = bn.sub_mod(U, bn.mulmod(qc, CR, R), qc.p_limbs)
        return (com[:t], com[t:2 * t], R, CR, VR, CF, VF,
                com[2 * t:3 * t], com[3 * t:4 * t],
                com[4 * t:5 * t], com[5 * t:])

    def encrypt_selections(self, seed_row: np.ndarray, bids: np.ndarray,
                           ords: np.ndarray, votes: np.ndarray,
                           K: int, prefix: bytes,
                           with_hints: bool = False):
        """Host entry: (S,32) identity digests + ordinals + votes ->
        [α, β, R, c_real, v_real, c_fake, v_fake] np arrays via the
        shared tiling policy, plus the four commitment-hint columns
        (a_real, b_real, a_fake, b_fake) when ``with_hints`` — the
        device computes them either way; the flag only gates the
        device->host transfer.  ``K`` is the election public key."""
        from electionguard_tpu.verify.fused import pad_to_dp
        k_table, k_hat = k_tables(self.ops, K)
        prefix_row = jnp.asarray(np.frombuffer(prefix, np.uint8))
        seed_j = jnp.asarray(seed_row)
        arrays, n = pad_to_dp(
            [bids, ords.astype(np.uint32), votes.astype(np.int32)],
            self.ndp)
        outs = run_tiled_multi(
            lambda b, o, v: self._sel_j(b, o, v, seed_j, k_table, k_hat,
                                        prefix_row),
            arrays, [False, False, False])
        if not with_hints:
            outs = outs[:7]
        return [np.asarray(o)[:n] for o in outs]

    # -- contests ------------------------------------------------------
    def _con_impl(self, bids, ords, RS, VS, seed_row, k_table, k_hat,
                  prefix_row):
        """One dispatch for a tile of contests sharing one vote limit:
        A = g^ΣR, B = g^ΣV K^ΣR, a = g^{U₂}, b = K^{U₂};
        c₂ = H(Q̄, L, A, B, a, b); v₂ = U₂ - c₂ ΣR.
        Returns (A, B, c₂, v₂, a, b) — the (a, b) commitment rows are
        the constant proof's RLC verification hints."""
        ops, qc = self.ops, self.qctx
        mm = ops._mm
        t = bids.shape[0]
        U2 = self._nonce_mod_q(seed_row,
                               jnp.full((t,), 4, jnp.uint32), bids, ords)
        gp = fixed_pow_mont(ops, ops.g_table,
                            jnp.concatenate([RS, U2, VS]), self._g_hat)
        kp = fixed_pow_mont(ops, k_table, jnp.concatenate([RS, U2]),
                            k_hat)
        A_m, a_m, gV_m = gp[:t], gp[t:2 * t], gp[2 * t:]
        B_m = mm(gV_m, kp[:t])
        b_m = kp[t:2 * t]
        com = bn.from_mont_via(mm, jnp.concatenate([A_m, B_m, a_m, b_m]))
        cb = limbs_to_bytes_j(com)
        C2 = self._challenge(
            prefix_row, [cb[:t], cb[t:2 * t], cb[2 * t:3 * t], cb[3 * t:]])
        V2 = bn.sub_mod(U2, bn.mulmod(qc, C2, RS), qc.p_limbs)
        return (com[:t], com[t:2 * t], C2, V2,
                com[2 * t:3 * t], com[3 * t:])

    def encrypt_contests(self, seed_row: np.ndarray, bids: np.ndarray,
                         ords: np.ndarray, RS_l: np.ndarray,
                         VS_l: np.ndarray, K: int, prefix: bytes,
                         with_hints: bool = False):
        """Host entry for one vote-limit group (the limit is encoded in
        ``prefix``): -> [A, B, c₂, v₂] np arrays, plus the (a, b)
        commitment-hint columns when ``with_hints``."""
        from electionguard_tpu.verify.fused import pad_to_dp
        k_table, k_hat = k_tables(self.ops, K)
        prefix_row = jnp.asarray(np.frombuffer(prefix, np.uint8))
        seed_j = jnp.asarray(seed_row)
        arrays, n = pad_to_dp(
            [bids, ords.astype(np.uint32), RS_l, VS_l], self.ndp)
        outs = run_tiled_multi(
            lambda b, o, rs, vs: self._con_j(b, o, rs, vs, seed_j,
                                             k_table, k_hat, prefix_row),
            arrays, [False, False, False, False])
        if not with_hints:
            outs = outs[:4]
        return [np.asarray(o)[:n] for o in outs]

"""The wall-clock seam every library component tells time through.

Production code never calls ``time.time()`` / ``time.monotonic()`` /
``time.sleep()`` directly (the eglint ``wall-clock-discipline`` pass
enforces this outside ``cli/`` and benches); it calls the module
functions here, which delegate to the installed :class:`Clock`.  In
production that is :data:`SYSTEM` — a thin pass-through to ``time`` —
so the seam costs one attribute hop.  The deterministic simulator
(``electionguard_tpu/sim``) installs a virtual clock instead, so the
entire multi-node workflow runs on simulated time: sleeps are free,
schedules are reproducible from a seed, and "wait ten minutes" tests
finish in microseconds.

Blocking primitives are part of the seam too.  A cooperative simulator
can only interleave tasks at points it controls, so code that would
otherwise park a thread in the kernel — ``Event.wait``,
``Condition.wait``, ``Future.result(timeout)``, ``Thread.start`` /
``join`` — routes through :func:`wait_event` / :func:`cv_wait` /
:func:`wait_future` / :func:`start_thread` / :func:`join_thread`.
The system clock forwards each to the real primitive; the sim clock
turns each into a virtual-time poll.  Every call site in the codebase
sits inside a predicate-rechecking loop (or tolerates spurious
wakeups), which is exactly the contract that makes the poll-based sim
implementation sound.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Optional


class Clock:
    """The real clock: a pass-through to ``time`` and the genuine
    blocking primitives.  Subclass and :func:`install` to virtualize
    (see ``sim/scheduler.py``)."""

    def time(self) -> float:
        return _time.time()

    def monotonic(self) -> float:
        return _time.monotonic()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)

    # ---- blocking primitives ----------------------------------------
    def wait_event(self, event: threading.Event,
                   timeout: Optional[float] = None) -> bool:
        return event.wait(timeout)

    def cv_wait(self, cv: threading.Condition,
                timeout: Optional[float] = None) -> bool:
        """Wait on ``cv`` (held by the caller).  May return before the
        timeout without a notify — callers must recheck their
        predicate, the standard condition-variable contract."""
        return cv.wait(timeout)

    def wait_future(self, future, timeout: Optional[float] = None):
        """``future.result(timeout)`` through the seam: returns the
        result, re-raises the future's exception, or raises
        ``concurrent.futures.TimeoutError``."""
        return future.result(timeout)

    def start_thread(self, thread: threading.Thread) -> None:
        thread.start()

    def join_thread(self, thread: threading.Thread,
                    timeout: Optional[float] = None) -> None:
        thread.join(timeout)


SYSTEM = Clock()

_lock = threading.Lock()
_installed: Clock = SYSTEM


def install(clock: Clock) -> None:
    """Make ``clock`` the process-wide clock (the simulator's entry
    point).  Callers pair this with :func:`uninstall` in a finally."""
    global _installed
    with _lock:
        _installed = clock


def uninstall() -> None:
    global _installed
    with _lock:
        _installed = SYSTEM


def installed() -> Clock:
    return _installed


# ---- module-level conveniences (the seam call sites use) ------------

def now() -> float:
    """Wall-clock seconds (``time.time`` semantics)."""
    return _installed.time()


def monotonic() -> float:
    return _installed.monotonic()


def sleep(seconds: float) -> None:
    _installed.sleep(seconds)


def wait_event(event: threading.Event,
               timeout: Optional[float] = None) -> bool:
    return _installed.wait_event(event, timeout)


def cv_wait(cv: threading.Condition,
            timeout: Optional[float] = None) -> bool:
    return _installed.cv_wait(cv, timeout)


def wait_future(future, timeout: Optional[float] = None):
    return _installed.wait_future(future, timeout)


def start_thread(thread: threading.Thread) -> None:
    _installed.start_thread(thread)


def join_thread(thread: threading.Thread,
                timeout: Optional[float] = None) -> None:
    _installed.join_thread(thread, timeout)

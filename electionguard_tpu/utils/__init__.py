"""Shared runtime utilities."""

from __future__ import annotations

import contextlib
import os


def batch_bucket(b: int) -> int:
    """Power-of-two batch rounding (16 minimum) — the small-batch half of
    the dispatch policy; every plane reaches it through
    ``core.group_jax.dispatch_bucket``/``run_tiled``, which cap large
    batches at the fixed tile so the compiled shape set stays bounded."""
    return 16 if b <= 16 else 1 << (b - 1).bit_length()


@contextlib.contextmanager
def maybe_profile(tag: str):
    """JAX profiler trace for one workflow phase when EGTPU_PROFILE=<dir>
    is set (the TPU equivalent of the reference's Guava Stopwatch prints —
    reference: RunRemoteWorkflowTest.java:125,145,153,174; SURVEY.md §5.1)."""
    out = os.environ.get("EGTPU_PROFILE")
    if not out:
        yield
        return
    import jax
    with jax.profiler.trace(os.path.join(out, tag)):
        yield


def enable_compile_cache(path: str | None = None) -> str:
    """Turn on JAX's persistent compilation cache (best-effort); returns
    the resolved cache directory so callers can inspect it.

    The MXU NTT programs are expensive to compile (~minutes for the full
    modexp ladder); caching makes every process after the first warm.
    Call before the first jit dispatch.
    """
    cache = (path or os.environ.get("JAX_COMPILATION_CACHE_DIR")
             or os.path.expanduser("~/.cache/egtpu_jax"))
    try:
        os.makedirs(cache, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache is an optimization; never fail the workload for it
    return cache

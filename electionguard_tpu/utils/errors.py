"""Named error classes for in-band rejections.

Every rejection path a Byzantine adversary can trigger carries a stable
machine-matchable class token of the form ``[plane.reason]`` embedded in
the human-readable error string (``named``), so the sim's soundness
oracle can assert *which* defense fired without string-matching prose
(``classes_in`` extracts the tokens back out of any error text).

Rejections that are *contained* — the protocol recovers in-band and the
run stays green (a challenged key share, a requeued mix stage, a
discarded duplicate ballot) — never surface in an error string at all,
so containment sites additionally call ``reject`` which fans out to
registered listeners.  The sim mounts a listener per run to collect
these detections; outside the sim the list is empty and ``reject`` is a
cheap no-op.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Iterable

_CLASS_RE = re.compile(r"\[([a-z][a-z0-9_]*\.[a-z][a-z0-9_]*)\]")

_lock = threading.Lock()
_listeners: list[Callable[[str, str], None]] = []


def named(cls: str, msg: str) -> str:
    """Prefix ``msg`` with the class token ``[cls]``."""
    return f"[{cls}] {msg}"


def classes_in(text: str) -> set[str]:
    """All ``[plane.reason]`` class tokens embedded in ``text``."""
    return set(_CLASS_RE.findall(text or ""))


def listen(cb: Callable[[str, str], None]) -> None:
    with _lock:
        _listeners.append(cb)


def unlisten(cb: Callable[[str, str], None]) -> None:
    with _lock:
        if cb in _listeners:
            _listeners.remove(cb)


def reject(cls: str, detail: str = "") -> None:
    """Record an in-band rejection (detection) with class ``cls``.

    Called at every site that *contains* a malicious input — listeners
    (the sim's detection log) see it even when no error string ever
    reaches the workflow."""
    with _lock:
        cbs = list(_listeners)
    for cb in cbs:
        cb(cls, detail)


def classes_over(texts: Iterable[str]) -> set[str]:
    out: set[str] = set()
    for t in texts:
        out |= classes_in(t)
    return out

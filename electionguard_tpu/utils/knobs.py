"""Central registry of every ``EGTPU_*`` environment knob.

Every env var the codebase reads is declared here with its type, the
default the read site uses, and one line of doc.  The eglint pass
``env-knob-registry`` enforces the contract in both directions:

* an ``os.environ`` read of an undeclared ``EGTPU_*`` name is a finding
  (so a knob can't ship undocumented), and
* a read site whose literal default disagrees with the declared default
  is a finding (so this table can't silently drift from the code).

``ENV_KNOBS.md`` at the repo root is generated from this registry
(``python tools/eglint.py --write-knobs``) and the same pass fails on
drift between the committed table and ``render_table()``.

Code may read knobs either directly (``os.environ.get(name, default)``)
or through the typed getters below; the getters centralize the default
so the read site can't contradict the declaration.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class Knob:
    """One declared env knob.  ``default`` is the literal string the
    read sites pass to ``os.environ.get`` (None = no default: the knob
    is an opt-in switch or has a context-dependent fallback)."""

    name: str
    type: str               # int | float | str | path | json | flag
    default: Optional[str]
    doc: str


KNOBS: tuple[Knob, ...] = (
    Knob("EGTPU_BENCH_BASELINE", "path", None,
         "Default baseline artifact for the perf-regression gate: a "
         "bench.py RESULT json, a BENCH_r*.json wrapper, BASELINE.json, "
         "or a PROGRESS.jsonl trajectory (tools/bench_diff; falls back "
         "to the repo BASELINE.json)."),
    Knob("EGTPU_BIGNUM", "str", "auto",
         "Bignum kernel backend: auto|pallas|ntt|cios; auto = pallas on "
         "TPU, cios elsewhere (core/group_jax)."),
    Knob("EGTPU_CAPACITY_BALLOTS", "int", "1000000",
         "Election size of the headline capacity question: chips needed "
         "to finish this many ballots under EGTPU_CAPACITY_DEADLINE_S "
         "(obs/capacity; tools/egplan)."),
    Knob("EGTPU_CAPACITY_DEADLINE_S", "float", "60.0",
         "Wall-clock deadline of the headline capacity question, "
         "seconds (obs/capacity; tools/egplan)."),
    Knob("EGTPU_CAPACITY_TOL", "float", "0.25",
         "Predicted-vs-measured relative error band of the capacity "
         "model validation gate: egplan --validate and the bench "
         "capacity phase fail past it (obs/capacity)."),
    Knob("EGTPU_CAPACITY_VALIDATE_N", "str", "128,512,384",
         "Ballot counts of the traced e2e validation elections: two "
         "calibration sizes bracketing the held-out predicted size "
         "(obs/capacity.validate_e2e)."),
    Knob("EGTPU_CHAOS_HOLD_AFTER_BALLOTS", "int", None,
         "Chaos hook: the serving worker holds the device after N "
         "ballots so a SIGKILL lands mid-batch (cli/run_encryption_"
         "service; tests/test_faults)."),
    Knob("EGTPU_COORDINATOR", "str", None,
         "jax.distributed coordinator address host:port "
         "(parallel/distributed)."),
    Knob("EGTPU_DISPATCH_HOST_PAD", "str", "1",
         "Host-side numpy bucket padding in the tiled dispatch policy "
         "(default on; 0 reverts to eager device-op padding) — removes "
         "the per-call zeros/scatter/concatenate dispatch tax on "
         "host-resident batches; tools/sim_matrix measures seeds/s "
         "both ways (core/group_jax.run_tiled)."),
    Knob("EGTPU_DRYRUN_INLINE", "flag", None,
         "Harness-internal: run the smoke dry-run inline instead of "
         "re-exec'ing (repo entry shim)."),
    Knob("EGTPU_DRYRUN_TIMEOUT", "float", "900",
         "Harness-internal: dry-run subprocess timeout, seconds (repo "
         "entry shim)."),
    Knob("EGTPU_FABRIC_EMULATE_DEVICE_MS", "float", "0",
         "Pad each encryption batch's device leg to this wall-clock "
         "duration — the per-chip-device-time regime of a real fleet — "
         "so a single-host fabric scale curve measures routing-plane "
         "scaling instead of host-core contention; 0 = off "
         "(serve/worker, set by tools/scale_run --fabric)."),
    Knob("EGTPU_ELECTION", "str", "default",
         "Election id stamped as the {election=...} label on the "
         "serve/fabric/mixfed per-election metric series — the "
         "per-tenant seed for multi-election fleets (serve/metrics; "
         "fabric/router; mixfed)."),
    Knob("EGTPU_FABRIC_EVICT_AFTER", "int", "2",
         "Consecutive failed health polls before the router evicts a "
         "worker from routing (fabric/router)."),
    Knob("EGTPU_FABRIC_HEALTH_INTERVAL", "float", "1.0",
         "Router health-poll period, seconds (fabric/router)."),
    Knob("EGTPU_FABRIC_HEALTH_TIMEOUT", "float", "2.0",
         "Per-worker health rpc deadline inside the router's poll loop, "
         "seconds (fabric/router)."),
    Knob("EGTPU_FABRIC_MAX_INFLIGHT", "int", "128",
         "Router-side in-flight request cap per shard; a shard at the "
         "cap is skipped, and a whole fleet at the cap is saturation "
         "(fabric/router)."),
    Knob("EGTPU_FAULT_PLAN", "json", "",
         "Fault-injection plan: inline JSON or @file "
         "(testing/faults; workflow chaos modes set it per process)."),
    Knob("EGTPU_FEEDER_PLATFORM", "str", "cpu",
         "Verifier feeder-pool child JAX platform (cli/run_verifier)."),
    Knob("EGTPU_FLIGHT_STRAGGLER_RATIO", "float", "1.5",
         "A fabric worker whose mean device-batch duration exceeds this "
         "multiple of the fleet median is named a straggler in the "
         "flight report (obs/analyze)."),
    Knob("EGTPU_FLIGHT_TOP_N", "int", "10",
         "Rows in the flight report's top-self-time table "
         "(obs/analyze; tools/egreport -topN overrides)."),
    Knob("EGTPU_LIVE_AUDIT_LAG_MAX", "int", "4096",
         "Audit-lag SLO objective: frames published but not yet "
         "live-verified before the audit_lag alert fires (obs/slo; "
         "verify/live sets the live_audit_lag_frames gauge)."),
    Knob("EGTPU_LIVE_CHECKPOINT", "path", None,
         "Live-verifier checkpoint file (cursor + aggregates + "
         "commitment ledger); defaults to live_checkpoint.json inside "
         "the record dir (verify/live)."),
    Knob("EGTPU_LIVE_CHUNK", "int", "512",
         "Ballot frames per live-verification chunk — the commitment "
         "granularity of the bulletin board (verify/live)."),
    Knob("EGTPU_LIVE_MAX_FRAME", "int", "67108864",
         "Sanity bound on one framed-record frame, bytes: a header "
         "above it is a corrupt frame (red), not a torn tail "
         "(verify/live; publish/framing default)."),
    Knob("EGTPU_LIVE_POLL_S", "float", "0.25",
         "Live-verifier tail poll period, seconds "
         "(cli/run_live_verifier)."),
    Knob("EGTPU_LOG", "str", "INFO",
         "Root log level for every CLI (cli/common)."),
    Knob("EGTPU_MIX_CHUNK_ROWS", "int", "64",
         "Row-chunk size for the mixfed pushRows/pullRows paging "
         "(mixfed/coordinator)."),
    Knob("EGTPU_MIX_SHARDS", "int", "0",
         "Mix-server row-axis shard count; 0 = single device "
         "(mixfed/server)."),
    Knob("EGTPU_MIX_TAMPER", "flag", None,
         "Drill hook: mounts the mix_tamper_output adversary "
         "(sim/adversary registry) so one mix stage's output is "
         "corrupted after proving and verification must catch it; "
         "1 = any server, any other value = that server id "
         "(mixfed/server)."),
    Knob("EGTPU_MSM_WINDOW", "int", "8",
         "Pippenger window width in bits for JaxGroupOps.msm; must "
         "divide 16 (the bignum limb width): 4, 8 or 16 "
         "(core/group_jax)."),
    Knob("EGTPU_NUM_PROCESSES", "int", None,
         "jax.distributed process count (parallel/distributed)."),
    Knob("EGTPU_OBS_COLLECTOR", "str", "",
         "Obs collector address host:port; enables the per-process "
         "telemetry push client (obs/collector)."),
    Knob("EGTPU_OBS_HTTP", "int", "",
         "Prometheus /metrics port; 0 = ephemeral (obs/httpd)."),
    Knob("EGTPU_OBS_LOG", "path", None,
         "JSONL log-mirror dir; defaults to the trace dir (obs/slog)."),
    Knob("EGTPU_OBS_PARENT_SPAN", "str", "",
         "Parent span id for this process's root span; set by the "
         "workflow driver (obs/trace)."),
    Knob("EGTPU_OBS_PROC", "str", None,
         "Process name stamped on spans/logs (obs/trace)."),
    Knob("EGTPU_OBS_PUSH_INTERVAL", "float", "1.0",
         "Telemetry push interval, seconds (obs/collector)."),
    Knob("EGTPU_OBS_RETAIN", "str", "",
         "Collector receive-dir retention cap: 'SIZE[,AGE]' with "
         "KB/MB/GB and s/m/h/d suffixes (e.g. '256MB,24h'); "
         "oldest-first rotation, counted by obs_rotated_files_total; "
         "empty = unbounded (obs/collector)."),
    Knob("EGTPU_OBS_SLO", "json", "",
         "SLO config override: inline JSON or @file (obs/slo)."),
    Knob("EGTPU_OBS_TRACE", "path", None,
         "Span-export dir; enables tracing (obs/trace)."),
    Knob("EGTPU_OBS_TRACE_ID", "str", None,
         "Join an existing trace id instead of minting one (obs/trace)."),
    Knob("EGTPU_PALLAS_BLOCK", "int", "128",
         "Rows per Pallas kernel grid step; bounds the fused kernels' "
         "VMEM working set (core/pallas)."),
    Knob("EGTPU_PALLAS_INTERPRET", "flag", None,
         "Allow the pallas backend off-TPU by running its kernels in "
         "interpret mode (slow; for differential testing — "
         "core/group_jax)."),
    Knob("EGTPU_PROCESS_ID", "int", None,
         "jax.distributed process id (parallel/distributed)."),
    Knob("EGTPU_PROFILE", "path", None,
         "JAX profiler trace dir, one subdir per workflow phase "
         "(utils.profile_phase)."),
    Knob("EGTPU_RACE", "flag", None,
         "Enable the dynamic race detector on every sim run: guarded "
         "attribute accesses are instrumented and checked by the "
         "happens-before + lockset monitor (sim/explore; "
         "analysis/race)."),
    Knob("EGTPU_RACE_WATCH", "str", "",
         "Extra race-monitor targets beyond ANALYSIS_GUARDS.json: "
         "'pkg.mod:Class=attr1+attr2;pkg.other:Cls=attr' "
         "(analysis/race_instrument)."),
    Knob("EGTPU_RPC_CONNECT_WINDOW", "float", "5.0",
         "Max seconds one wait_for_ready retry may block "
         "(remote/rpc_util)."),
    Knob("EGTPU_RPC_RETRIES", "int", "3",
         "RPC tries per call; 1 restores the reference's no-retry "
         "posture (remote/rpc_util)."),
    Knob("EGTPU_RPC_RETRY_BUDGET", "float", "120.0",
         "Total backoff-sleep seconds one Stub may spend before "
         "fail-fast (remote/rpc_util)."),
    Knob("EGTPU_RPC_RETRY_CAP", "float", "8.0",
         "Retry backoff ceiling, seconds (remote/rpc_util)."),
    Knob("EGTPU_RPC_RETRY_WAIT", "float", "0.5",
         "Retry backoff base, seconds (remote/rpc_util)."),
    Knob("EGTPU_RPC_TIMEOUT_CONTROL", "float", "30.0",
         "Deadline for control-class rpcs (remote/rpc_util)."),
    Knob("EGTPU_RPC_TIMEOUT_DATA", "float", "600.0",
         "Deadline for data-plane rpcs (51 MB batches; "
         "remote/rpc_util)."),
    Knob("EGTPU_RPC_TIMEOUT_EXCHANGE", "float", "120.0",
         "Deadline for key-exchange rpcs (seconds of crypto; "
         "remote/rpc_util)."),
    Knob("EGTPU_RPC_TIMEOUT_REGISTRATION", "float", "30.0",
         "Deadline for registration rpcs (remote/rpc_util)."),
    Knob("EGTPU_SHA_DEVICE_MIN", "int", "65536",
         "Min rows before the ballot-code SHA batch runs on the device "
         "(ballot/code_batch)."),
    Knob("EGTPU_SIM_ADV_MAX", "int", "2",
         "Max in-protocol attacks drawn per adversary schedule (always "
         "at least one; sim/schedule)."),
    Knob("EGTPU_SIM_ADV_SEEDS", "int", "200",
         "Seed count of the default adversary sweep "
         "(tools/sim_matrix --adversaries)."),
    Knob("EGTPU_SIM_HORIZON", "float", "600.0",
         "Virtual-time horizon for one deterministic simulation run, "
         "seconds; exceeding it is a liveness violation (sim/cluster)."),
    Knob("EGTPU_SIM_PARAM_SEEDS", "int", "200",
         "Seed count of the default parameter-adversary sweep "
         "(tools/sim_matrix --param-adversaries)."),
    Knob("EGTPU_SIM_PROC_DOWNTIME_S", "float", "1.0",
         "Virtual downtime between a simulated process's exit and its "
         "restart_on_exit replay — the in-sim twin of the guardian "
         "restart drill's real sleep (sim/procmodel)."),
    Knob("EGTPU_SIM_PCT_DEPTH", "int", "3",
         "PCT bug depth d under EGTPU_SIM_STRATEGY=pct: d-1 priority "
         "change points are drawn per run (sim/explore; "
         "sim/scheduler)."),
    Knob("EGTPU_SIM_SCALE_BALLOTS", "int", "1000000",
         "Virtual electorate size of the default virtual election "
         "(sim/election)."),
    Knob("EGTPU_SIM_SCALE_BATCH", "int", "8192",
         "Admission micro-batch (journal unit) of the virtual "
         "election; one scheduler event cluster per batch "
         "(sim/election)."),
    Knob("EGTPU_SIM_SCALE_CHIPS", "int", "8",
         "Accelerator chips the virtual election's device-time model "
         "divides rooflined work across (sim/election; "
         "sim/devicemodel)."),
    Knob("EGTPU_SIM_SCALE_REP", "int", "64",
         "Real-arithmetic cap per distinct batch shape: how many "
         "representative ballots actually run on the tiny group "
         "(sim/election)."),
    Knob("EGTPU_SIM_SCALE_WORKERS", "int", "16",
         "Serve-worker SimProcess count of the virtual election "
         "(sim/election)."),
    Knob("EGTPU_SIM_SEED", "int", "0",
         "First seed of the default simulation sweep range "
         "(sim/explore; tools/sim_matrix)."),
    Knob("EGTPU_SIM_SEEDS", "int", "20",
         "Seed count of the default simulation sweep range "
         "(sim/explore; tools/sim_matrix)."),
    Knob("EGTPU_SIM_WATCHDOG_S", "float", "60.0",
         "Real-time seconds a sim task may run without yielding before "
         "the liveness watchdog declares it stuck; sweep drivers raise "
         "it so cold jit compiles under CPU contention are not "
         "misdiagnosed as deadlocks (sim/scheduler; tools/race_matrix "
         "sets 300 for its workers)."),
    Knob("EGTPU_SIM_SHRINK_BUDGET", "int", "60",
         "Max probe runs the failing-schedule shrinker may spend "
         "(sim/shrink)."),
    Knob("EGTPU_SIM_STRATEGY", "str", "random",
         "Scheduler exploration strategy: 'random' (uniform over "
         "runnable tasks) or 'pct' (priority-based probabilistic "
         "concurrency testing, own RNG stream) (sim/explore; "
         "sim/scheduler)."),
    Knob("EGTPU_TENANT_MAX", "int", "64",
         "Max distinct election ids one process will label metric "
         "series with — the label-cardinality bound; past it "
         "tenant_scope raises the named tenant.cardinality error "
         "(obs/tenant)."),
    Knob("EGTPU_TENANT_NOISY_SHARE", "float", "0.5",
         "Noisy-neighbor detection threshold: a tenant whose share of "
         "fleet device time over the trailing window exceeds this while "
         "ANOTHER tenant burns its SLO is named the offender "
         "(obs/slo)."),
    Knob("EGTPU_TENANT_NOISY_WINDOW", "float", "30.0",
         "Trailing window, seconds, over which per-tenant device-time "
         "share is computed for noisy-neighbor attribution (obs/slo)."),
    Knob("EGTPU_TENANT_QUOTA", "int", "0",
         "Per-tenant admission quota: max in-flight encrypt requests "
         "one election may hold in a serving process or router shard "
         "before its OWN requests are rejected RESOURCE_EXHAUSTED "
         "(other tenants keep flowing); 0 = no per-tenant cap "
         "(serve/tenants; fabric/router)."),
    Knob("EGTPU_TABLE_CACHE", "path", None,
         "On-disk cache dir for host-precomputed setup tables (NttCtx "
         "constants, PowRadix tables), keyed by group fingerprint; "
         "empty/unset = rebuild every process (core/table_cache)."),
    Knob("EGTPU_TILE", "int", "4096",
         "Row cap per device dispatch; bounds compile count AND peak "
         "memory (core/group_jax)."),
    Knob("EGTPU_VALIDATE", "str", "on",
         "Ingestion validation gate mode: on = RLC-batched subgroup "
         "screen + range/identity/small-order checks at every trust "
         "boundary, strict = exact per-element residue test, off = "
         "no-op (terminal verifier still re-checks) (crypto/validate)."),
    Knob("EGTPU_VERIFY_BATCH", "flag", None,
         "Random-linear-combination batch verification: encryptors "
         "attach commitment hints to proofs and verifiers collapse "
         "per-proof modexps into fused MSMs, falling back to the naive "
         "per-proof path on any batch failure (encrypt/encryptor; "
         "verify/verifier; mixnet/verify_mix; crypto/schnorr)."),
)

_BY_NAME = {k.name: k for k in KNOBS}


def declared(name: str) -> Optional[Knob]:
    return _BY_NAME.get(name)


def _declared_or_raise(name: str) -> Knob:
    k = _BY_NAME.get(name)
    if k is None:
        raise KeyError(f"{name} is not declared in utils/knobs.py — add "
                       f"it there (eglint env-knob-registry enforces "
                       f"this)")
    return k


def get_str(name: str) -> str:
    k = _declared_or_raise(name)
    return os.environ.get(name, k.default or "")


def get_int(name: str) -> int:
    k = _declared_or_raise(name)
    return int(os.environ.get(name, k.default))


def get_float(name: str) -> float:
    k = _declared_or_raise(name)
    return float(os.environ.get(name, k.default))


def get_flag(name: str) -> bool:
    _declared_or_raise(name)
    return os.environ.get(name, "") not in ("", "0")


def render_table(knobs=KNOBS) -> str:
    """The markdown knob table (``ENV_KNOBS.md``), generated so docs
    can't drift from the registry."""
    lines = [
        "<!-- Generated from electionguard_tpu/utils/knobs.py by",
        "     `python tools/eglint.py --write-knobs` — do not edit;",
        "     the eglint env-knob-registry pass fails on drift. -->",
        "# `EGTPU_*` environment knobs",
        "",
        "| Knob | Type | Default | Description |",
        "|------|------|---------|-------------|",
    ]
    for k in sorted(knobs, key=lambda k: k.name):
        default = f"`{k.default}`" if k.default else "(unset)"
        lines.append(f"| `{k.name}` | {k.type} | {default} | {k.doc} |")
    return "\n".join(lines) + "\n"

"""Bottom-layer hook point for the sim's virtual device-time model.

The batch crypto entry points (encrypt / mix / decrypt / verify) call
:func:`charge` with a semantic op name and a ballot count.  Outside the
sim nothing is installed and the call is a no-op costing one attribute
read; under ``sim/devicemodel`` the installed charger advances the
virtual clock by the fitted per-op device cost.  This module exists so
those crypto modules never import the sim package (``sim/__init__``
pulls in the whole exploration stack) — same layering trick as the
``utils.clock`` seam.
"""

from __future__ import annotations

from typing import Callable, Optional

_CHARGER: Optional[Callable[[str, float], None]] = None


def set_charger(fn: Optional[Callable[[str, float], None]]) -> None:
    """Install (or, with None, remove) the ambient device-time charger.
    One sim at a time, like ``utils.clock.install``."""
    global _CHARGER
    _CHARGER = fn


def active() -> bool:
    return _CHARGER is not None


def charge(op: str, ballots: float) -> None:
    """Charge ``ballots`` worth of semantic op ``op`` ("encrypt",
    "mix_stage", "decrypt", "verify", "verify_batch") to the installed
    device-time model, if any."""
    if _CHARGER is not None:
        _CHARGER(op, float(ballots))

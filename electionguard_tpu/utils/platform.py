"""Platform selection for driver entry points (bench.py, __graft_entry__).

The single real TPU chip is reached through the experimental ``axon`` PJRT
tunnel, which dials its relay at backend init regardless of
``JAX_PLATFORMS`` and can wedge for long stretches — a bare ``import jax``
then HANGS rather than erroring.  These helpers decide the platform with a
bounded subprocess probe BEFORE the first ``import jax`` in the calling
process, falling back to CPU by stripping the tunnel env (the same escape
hatch tests/conftest.py uses).

Pure stdlib: importing this module must never touch jax.
"""

from __future__ import annotations

import contextlib
import os
import subprocess
import sys

from electionguard_tpu.utils import clock
from typing import Mapping, MutableMapping, Optional

#: Env-var name prefixes that attach the process to the axon TPU tunnel.
#: Prefix-matched (not substring) so unrelated vars that merely contain
#: one of these words (e.g. JAX_PALLAS_* debug knobs or third-party
#: *_AXON_* settings) are never scrubbed from subprocess envs.
_TUNNEL_PREFIXES = ("AXON_", "PALLAS_", "TPU_")


def _is_tunnel_var(key: str) -> bool:
    return key.startswith(_TUNNEL_PREFIXES) or key in ("AXON", "TPU")


def detach_axon(env: Optional[MutableMapping[str, str]] = None) -> None:
    """Strip the axon/TPU tunnel env and pin JAX to CPU.

    Mutates ``os.environ`` unless an explicit mapping is given.  In this
    environment a site hook pre-imports jax at interpreter startup, so the
    ``JAX_PLATFORMS`` env var alone comes too late for the current
    process — when mutating ``os.environ`` we also flip the live jax
    config (safe: it does not initialize any backend).
    """
    env = os.environ if env is None else env
    for k in list(env):
        if _is_tunnel_var(k):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    if env is os.environ and "jax" in sys.modules:
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def cpu_mesh_env(n_devices: int,
                 base: Optional[Mapping[str, str]] = None) -> dict:
    """A detached copy of the env with ``n_devices`` virtual CPU devices —
    the same configuration tests/conftest.py forces for sharding tests."""
    env = dict(os.environ if base is None else base)
    detach_axon(env)
    flags = env.get("XLA_FLAGS", "")
    # drop any stale forced-count flag, then set ours
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    return env


def probe_tpu(timeout: float = 90.0) -> bool:
    """True iff a fresh subprocess (inheriting this env) can initialise the
    TPU backend within ``timeout`` seconds.  A wedged relay hangs the
    child — the timeout kills it; a backend setup error exits nonzero."""
    code = ("import jax; d = jax.devices(); "
            "assert d and d[0].platform != 'cpu', d; print(d[0].platform)")
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def ensure_tpu_or_cpu(probe_timeout: float = 90.0,
                      retries: int = 2,
                      retry_wait: float = 20.0,
                      log=print) -> str:
    """Decide the platform for this process, mutating ``os.environ``.

    If no tunnel env is present, leaves everything alone.  Otherwise probes
    TPU reachability in a subprocess up to ``retries`` times (bounded —
    never hangs the caller); on failure detaches the tunnel and pins CPU.
    Returns ``"tpu"`` or ``"cpu"``.  Call before the first backend touch
    (never calls ``jax.devices()``/``default_backend()`` in this process —
    with a wedged tunnel those hang).
    """
    if not any(_is_tunnel_var(k) for k in os.environ):
        return "cpu"
    for attempt in range(max(1, retries)):
        if attempt:
            clock.sleep(retry_wait)
        if probe_tpu(probe_timeout):
            return "tpu"
        log(f"# tpu probe {attempt + 1}/{retries} failed "
            f"(timeout={probe_timeout:.0f}s)", file=sys.stderr)
    log("# falling back to CPU: axon tunnel unreachable", file=sys.stderr)
    detach_axon()
    return "cpu"


@contextlib.contextmanager
def pinned_child_platform(platform: str = "cpu"):
    """Temporarily shape ``os.environ`` so SPAWNED children initialize
    jax on ``platform`` — and restore it on exit.

    Env assignment inside an already-running child comes TOO LATE: the
    module import chain (and on some machines a site hook) imports jax
    before any worker body runs, so ``JAX_PLATFORMS`` must be in the
    environment the child INHERITS at interpreter startup.  For
    ``platform="cpu"`` the tunnel vars are scrubbed too (detach_axon
    semantics) so the axon plugin never dials the relay from a feeder;
    for any other platform the tunnel env is left intact and only
    ``JAX_PLATFORMS`` is pinned.  The PARENT's live jax config is never
    touched — a TPU-resident parent keeps its backend.
    """
    snapshot = dict(os.environ)
    try:
        if platform == "cpu":
            for k in list(os.environ):
                if _is_tunnel_var(k):
                    del os.environ[k]
        os.environ["JAX_PLATFORMS"] = platform
        yield
    finally:
        os.environ.clear()
        os.environ.update(snapshot)

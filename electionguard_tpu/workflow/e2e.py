"""The 5-phase multi-process workflow driver.

Mirror of the reference's ``RunRemoteWorkflowTest``
(src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:83-194):

  1. key ceremony   — coordinator + nguardians guardian processes (gRPC)
  2. encrypt        — RandomBallotProvider fake ballots + batch encryption
  3. tally          — homomorphic accumulation
  4. decrypt        — decryptor + navailable trustee processes (gRPC)
  5. verify         — full record verification (the ground truth)

Every node is a subprocess on localhost with captured output, exactly the
reference's multi-node-without-a-cluster mechanism; phases communicate only
through the election-record directory (the checkpoint system).

Run:  python -m electionguard_tpu.workflow.e2e -out /tmp/eg -nballots 20 \
          -nguardians 3 -quorum 2 -navailable 2 -group tiny
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from electionguard_tpu.ballot.manifest import (BallotStyle, Candidate,
                                               ContestDescription,
                                               GeopoliticalUnit, Manifest,
                                               Party, SelectionDescription)
from electionguard_tpu.ballot.plaintext import RandomBallotProvider
from electionguard_tpu.cli.common import setup_logging
from electionguard_tpu.obs import collector as obs_collector
from electionguard_tpu.obs import trace as obs_trace
from electionguard_tpu.publish.publisher import Publisher
from electionguard_tpu.remote.rpc_util import (Stub, find_free_port,
                                               make_plain_channel)
from electionguard_tpu.utils import clock
from electionguard_tpu.workflow.run_command import RunCommand, wait_all


class _PhaseTracer:
    """Driver-side phase spans.  ``begin`` closes the previous phase,
    opens the next, and exports the new span id as
    ``EGTPU_OBS_PARENT_SPAN`` so every subprocess launched during the
    phase roots its own span tree under that phase.  No-op (and env
    untouched) when tracing is off."""

    def __init__(self):
        self._cur = None

    def begin(self, name: str) -> None:
        self.end()
        obs_collector.set_phase(name)   # mission-control heartbeat
        if not obs_trace.enabled():
            return
        self._cur = obs_trace.span(name)
        self._cur.__enter__()
        os.environ["EGTPU_OBS_PARENT_SPAN"] = self._cur.span_id

    def end(self) -> None:
        if self._cur is not None:
            self._cur.__exit__(None, None, None)
            self._cur = None
            os.environ.pop("EGTPU_OBS_PARENT_SPAN", None)


def sample_manifest(ncontests: int = 1, nselections: int = 2) -> Manifest:
    contests = []
    candidates = []
    for c in range(ncontests):
        sels = []
        for s in range(nselections):
            cid = f"cand-{c}-{s}"
            candidates.append(Candidate(cid, f"Candidate {c}/{s}"))
            sels.append(SelectionDescription(f"contest{c}-sel{s}", s, cid))
        contests.append(ContestDescription(
            f"contest-{c}", c, "gp-0", "one_of_m", 1,
            f"Contest {c}", tuple(sels)))
    return Manifest(
        election_scope_id="e2e-election", spec_version="tpu-1.0",
        start_date="2026-07-01", end_date="2026-07-29",
        geopolitical_units=(GeopoliticalUnit("gp-0", "District 0"),),
        parties=(Party("party-0", "The Party"),),
        candidates=tuple(candidates),
        contests=tuple(contests),
        ballot_styles=(BallotStyle("style-0", ("gp-0",)),),
    )


def _watch_log(path: str, needle: bytes, count: int = 1,
               timeout: float = 60.0) -> bool:
    """Poll a subprocess's captured stdout until ``needle`` appears at
    least ``count`` times (registration/liveness markers)."""
    deadline = clock.now() + timeout
    while clock.now() < deadline:
        try:
            with open(path, "rb") as f:
                if f.read().count(needle) >= count:
                    return True
        except OSError:
            pass
        clock.sleep(0.25)
    return False


def _fabric_encrypt_phase(args, out, record_dir, cmd_out, group_flags,
                          manifest, log, procs, phase_fail):
    """Phase 2 through the sharded serving fabric: router + N worker
    subprocesses, the driver as gRPC client, shard merge at the end.
    Returns True, or the run's failing exit code."""
    import threading

    from electionguard_tpu.cli.common import resolve_group
    from electionguard_tpu.fabric.merge import merge_shard_records
    from electionguard_tpu.serve import journal as wal
    from electionguard_tpu.serve.service import EncryptionClient

    group = resolve_group(argparse.Namespace(group=args.group))
    n = args.fabric_workers
    shards_root = os.path.join(out, "shards")
    router_port = find_free_port()
    router_cmd = RunCommand.python_module(
        "fabric-router", "electionguard_tpu.cli.run_router",
        ["-port", str(router_port)] + group_flags, cmd_out)
    procs.append(router_cmd)
    clock.sleep(1.5)  # let the front door bind

    def launch_worker(i, env=None):
        return RunCommand.python_module(
            f"encryption-worker-{i}",
            "electionguard_tpu.cli.run_encryption_service",
            ["-in", record_dir, "-out",
             os.path.join(shards_root, f"shard-w{i}"),
             "-port", "0", "-router", f"localhost:{router_port}",
             "-workerId", f"w{i}", "-fixedNonces",
             "-timestamp", "1754000000", "-maxBatch", "8",
             "-maxWaitMs", "15"] + group_flags, cmd_out, env=env)

    workers = []
    for i in range(n):
        env = {}
        if args.chaos_fabric and i == 0:
            env["EGTPU_CHAOS_HOLD_AFTER_BALLOTS"] = "2"
        if args.fabric_skew_ms > 0 and i == 0:
            # seeded straggler: worker 0's device leg is padded so the
            # flight report's straggler section has something to name
            env["EGTPU_FABRIC_EMULATE_DEVICE_MS"] = \
                str(args.fabric_skew_ms)
        workers.append(launch_worker(i, env=env or None))
    procs.extend(workers)
    # every shard must be in the routing set before load starts
    if not _watch_log(router_cmd.stdout_path, b" live at ", count=n,
                      timeout=180):
        return phase_fail("fabric-startup", [router_cmd] + workers)
    log.info("[2] fabric up: router :%d routing %d shards", router_port, n)
    if args.chaos_fabric:
        log.info("CHAOS: worker 0 wedges after 2 ballots and is "
                 "SIGKILL'd mid-load; its admissions must requeue onto "
                 "surviving shards")

    ballots = list(RandomBallotProvider(manifest, args.nballots,
                                        seed=11).ballots())
    results: dict[str, object] = {}
    errors: list[BaseException] = []
    lock = threading.Lock()

    def client_run(idx):
        client = EncryptionClient(f"localhost:{router_port}", group)
        try:
            for bi in range(idx, len(ballots), 4):
                b = ballots[bi]
                spoil = (args.spoil_every > 0
                         and (bi + 1) % args.spoil_every == 0)
                enc = client.encrypt(b, spoil=spoil, timeout=300)
                with lock:
                    results[b.ballot_id] = enc
        except BaseException as e:  # noqa: BLE001 — collected, asserted below
            with lock:
                errors.append(e)
        finally:
            client.close()

    threads = [threading.Thread(target=client_run, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()

    if args.chaos_fabric:
        # wait for the wedge to bite AND for an admission to land behind
        # it: a post-wedge admission is journaled but can never publish,
        # so pending>=1 here is stable, not a publish race — the SIGKILL
        # is guaranteed to strand admitted-but-unpublished work
        if not _watch_log(workers[0].stdout_path, b"worker wedged",
                          timeout=120):
            return phase_fail("fabric-chaos-arm", [router_cmd] + workers)
        w0_journal = os.path.join(shards_root, "shard-w0",
                                  wal.JOURNAL_NAME)
        deadline = clock.now() + 120
        while clock.now() < deadline:
            try:
                if len(wal.replay(w0_journal)) >= 1:
                    break
            except OSError:
                pass
            clock.sleep(0.2)
        else:
            return phase_fail("fabric-chaos-arm", [router_cmd] + workers)
        workers[0].kill_hard()   # SIGKILL: no drain, torn stream allowed
        log.info("CHAOS: worker 0 SIGKILL'd; load must complete on the "
                 "surviving %d shard(s)", n - 1)
        # the router requeues the dead shard's in-flight admissions; the
        # stuck client calls complete on survivors
        if not _watch_log(router_cmd.stdout_path, b"requeued ",
                          timeout=120):
            return phase_fail("fabric-chaos-requeue",
                              [router_cmd] + workers)
        workers[0]._env.pop("EGTPU_CHAOS_HOLD_AFTER_BALLOTS", None)
        workers[0].restart()
        # the relaunch reclaims shard 0 (same -workerId), tombstones the
        # requeued ids instead of replaying them, and serves again (the
        # second "serving on port" in its appended log): only a worker
        # that finished recovery can drain and sign its shard manifest
        if not _watch_log(router_cmd.stdout_path, b"re-registered",
                          timeout=120):
            return phase_fail("fabric-chaos-rejoin",
                              [router_cmd] + workers)
        if not _watch_log(workers[0].stdout_path, b"serving on port",
                          count=2, timeout=120):
            return phase_fail("fabric-chaos-rejoin",
                              [router_cmd] + workers)

    for t in threads:
        t.join(timeout=600)
    if errors or len(results) != args.nballots:
        for e in errors[:5]:
            log.error("fabric client error: %r", e)
        log.error("fabric load: %d/%d ballots admitted", len(results),
                  args.nballots)
        return phase_fail("fabric-load", [router_cmd] + workers)
    log.info("[2] fabric load done: %d/%d ballots admitted, zero lost",
             len(results), args.nballots)

    # graceful drain: every worker closes its stream and signs its shard
    # manifest; then the router goes down and the driver merges
    for w in workers:
        w.process.terminate()
    if not wait_all(workers, timeout=180):
        return phase_fail("fabric-drain", [router_cmd] + workers)
    router_cmd.process.terminate()
    if router_cmd.wait_for(30) is None:
        router_cmd.kill()
    shard_dirs = sorted(
        os.path.join(shards_root, d) for d in os.listdir(shards_root))
    rep = merge_shard_records(group, shard_dirs, record_dir)
    log.info("[2] merged %d shard records -> %s (%s)", rep.n_shards,
             record_dir, " ".join(f"s{sid}={cnt}"
                                  for sid, cnt in rep.per_shard))
    if rep.n_ballots != args.nballots:
        log.error("merged record has %d ballots, expected %d",
                  rep.n_ballots, args.nballots)
        return phase_fail("fabric-merge", [router_cmd] + workers)
    return True


def main(argv=None) -> int:
    log = setup_logging("RunRemoteWorkflow")
    ap = argparse.ArgumentParser("RunRemoteWorkflow")
    ap.add_argument("-out", dest="output", required=True,
                    help="working dir (record + process logs)")
    ap.add_argument("-nballots", type=int, default=20)
    ap.add_argument("-nguardians", type=int, default=3)
    ap.add_argument("-quorum", type=int, default=2)
    ap.add_argument("-navailable", type=int, default=2)
    ap.add_argument("-ncontests", type=int, default=1)
    ap.add_argument("-nselections", type=int, default=2)
    ap.add_argument("-group", choices=["production", "tiny"],
                    default="tiny")
    ap.add_argument("-mix", type=int, default=0,
                    help="run N re-encryption mix stages between tally "
                         "accumulation and decryption (0 = none); the "
                         "published mix cascade is checked by the "
                         "verifier's V15 family in phase 5")
    ap.add_argument("-mixServers", dest="mix_servers", type=int, default=0,
                    help="run N mix stages FEDERATED: one mix-server "
                         "subprocess per stage plus a coordinator that "
                         "verifies every stage before forwarding it "
                         "(mutually exclusive with -mix; same published "
                         "artifact, same V15 checks in phase 5)")
    ap.add_argument("-chaosKillMixServer", dest="chaos_mix",
                    action="store_true",
                    help="chaos hook for -mixServers: mix-server-0 "
                         "hard-crashes (EGTPU_FAULT_PLAN crash_after) "
                         "right after its first shuffle commits; the "
                         "coordinator must requeue the stage on the "
                         "extra spare this flag also launches")
    ap.add_argument("-fabricWorkers", dest="fabric_workers", type=int,
                    default=0,
                    help="run phase 2 through the sharded serving fabric: "
                         "a router subprocess plus N encryption-worker "
                         "subprocesses, each publishing its own shard "
                         "record under a signed manifest; the driver "
                         "merges the shards into the one verifiable "
                         "record (fabric/merge.py) before phase 3")
    ap.add_argument("-chaosKillEncryptionWorker", dest="chaos_fabric",
                    action="store_true",
                    help="chaos hook for -fabricWorkers: worker 0 wedges "
                         "after 2 ballots (EGTPU_CHAOS_HOLD_AFTER_"
                         "BALLOTS) and is SIGKILL'd mid-load; the router "
                         "must requeue its in-flight admissions onto "
                         "surviving shards, the relaunched worker must "
                         "reclaim its shard without double-publishing, "
                         "and the merged record must verify green")
    ap.add_argument("-spoilEvery", dest="spoil_every", type=int, default=5,
                    help="spoil every Nth ballot (0 = none); spoiled "
                         "ballots are decrypted in phase 4 and checked by "
                         "verifier V13 in phase 5")
    ap.add_argument("-keep", action="store_true",
                    help="keep going past failures and dump all output")
    ap.add_argument("-trace", action="store_true",
                    help="trace the whole run: every process exports "
                         "spans under <out>/trace (EGTPU_OBS_TRACE), "
                         "and the driver merges them into <out>/"
                         "trace.json (Chrome-trace/Perfetto) at the end")
    ap.add_argument("-flightReport", dest="flight_report",
                    action="store_true",
                    help="implies -trace: after the run (pass OR fail) "
                         "analyze the trace and write <out>/FLIGHT_"
                         "REPORT.md — critical path, wall-clock "
                         "attribution, shard balance/stragglers, SLO "
                         "verdicts (obs/analyze + obs/flight)")
    ap.add_argument("-fabricSkewMs", dest="fabric_skew_ms", type=float,
                    default=0.0,
                    help="straggler drill for -fabricWorkers: worker 0 "
                         "alone runs under EGTPU_FABRIC_EMULATE_DEVICE_"
                         "MS of this much device-leg padding, so the "
                         "flight report must name it in the straggler "
                         "section")
    ap.add_argument("-obsCollector", dest="obs_collector",
                    action="store_true",
                    help="launch the run's obs collector FIRST and point "
                         "every process at it (EGTPU_OBS_COLLECTOR): live "
                         "telemetry under <out>/obs (fleet /metrics, "
                         "trace_live.json, SLO engine); the driver "
                         "asserts fleet-green at the end")
    ap.add_argument("-chaosRestartGuardian", dest="chaos_guardian",
                    type=int, default=-1,
                    help="chaos hook: this guardian hard-crashes "
                         "(EGTPU_FAULT_PLAN crash_after) right after it "
                         "commits its first received key share, then "
                         "restarts from its resume file; the ceremony "
                         "must still complete (fault-injection harness)")
    ap.add_argument("-liveVerify", dest="live_verify", action="store_true",
                    help="launch the live verifier (verify/live) right "
                         "after the key ceremony: it tails the record's "
                         "ballot stream while phases 2-4 write it, serves "
                         "a BulletinBoardService, and must end with <5%% "
                         "of the verification work left when the "
                         "decryption result lands")
    args = ap.parse_args(argv)
    if args.mix > 0 and args.mix_servers > 0:
        log.error("-mix and -mixServers are mutually exclusive (same "
                  "artifact, different topology)")
        return 1
    if args.live_verify and args.fabric_workers > 0:
        log.error("-liveVerify tails the ballot stream as it is written; "
                  "-fabricWorkers materializes it only at the final shard "
                  "merge, so there is nothing to tail mid-election")
        return 1
    if args.chaos_fabric and args.fabric_workers < 2:
        log.error("-chaosKillEncryptionWorker needs -fabricWorkers >= 2 "
                  "(someone has to survive)")
        return 1
    if args.flight_report:
        args.trace = True   # a flight report is analytics over a trace

    out = args.output
    record_dir = os.path.join(out, "record")
    ballots_dir = os.path.join(out, "plaintext_ballots")
    cmd_out = os.path.join(out, "logs")
    trustee_dir = os.path.join(record_dir, "private", "trustees")
    os.makedirs(record_dir, exist_ok=True)
    os.makedirs(ballots_dir, exist_ok=True)
    group_flags = ["-group", args.group]

    # one trace for the whole run: the driver enables tracing on itself
    # and exports the trace dir + trace id so every subprocess of every
    # phase joins the same timeline (see obs.trace)
    trace_dir = os.environ.get("EGTPU_OBS_TRACE", "")
    if args.trace and not trace_dir:
        trace_dir = os.path.join(out, "trace")
        os.environ["EGTPU_OBS_TRACE"] = trace_dir
    if trace_dir:
        os.environ.setdefault("EGTPU_OBS_TRACE_ID", os.urandom(16).hex())
        os.environ.setdefault("EGTPU_OBS_PROC", "workflow-driver")
        obs_trace.enable_from_env()
        log.info("tracing to %s (trace_id=%s)", trace_dir,
                 obs_trace.trace_id())
    phases = _PhaseTracer()

    t_all = clock.now()
    procs: list[RunCommand] = []

    def phase_fail(name, cmds):
        for c in cmds:
            c.show()
        log.error("phase %s FAILED", name)
        return 1

    # ---- phase 0.5 (optional): the obs collector, launched FIRST ---------
    # so its fleet view covers every other process from its first
    # heartbeat.  The env var is set only AFTER the collector child is
    # up, so the collector itself never self-pushes.
    obs_cmd = None
    obs_stub = None
    if args.obs_collector:
        from electionguard_tpu.publish import pb
        obs_dir = os.path.join(out, "obs")
        obs_port, obs_http = find_free_port(), find_free_port()
        obs_cmd = RunCommand.python_module(
            "obs-collector", "electionguard_tpu.cli.run_obs_collector",
            ["-port", str(obs_port), "-metricsPort", str(obs_http),
             "-out", obs_dir], cmd_out)
        obs_stub = Stub(make_plain_channel(f"localhost:{obs_port}"),
                        "ObsCollectorService")
        deadline = clock.now() + 30
        while True:
            try:
                obs_stub.call("getFleetStatus",
                              pb.msg("FleetStatusRequest")(), timeout=2.0)
                break
            except Exception:  # noqa: BLE001 — still binding
                if clock.now() > deadline or obs_cmd.poll() is not None:
                    obs_cmd.kill()
                    return phase_fail("obs-collector", [obs_cmd])
                clock.sleep(0.25)
        os.environ["EGTPU_OBS_COLLECTOR"] = f"localhost:{obs_port}"
        obs_collector.client_from_env()   # the driver streams too
        procs.append(obs_cmd)
        log.info("[0.5] obs collector up: rpc :%d, fleet /metrics on "
                 "http://localhost:%d/metrics, live timeline %s",
                 obs_port, obs_http,
                 os.path.join(obs_dir, "trace_live.json"))

    try:
        # ---- phase 0: write the manifest -------------------------------------
        manifest = sample_manifest(args.ncontests, args.nselections)
        input_dir = os.path.join(out, "input")
        os.makedirs(input_dir, exist_ok=True)
        with open(os.path.join(input_dir, "manifest.json"), "w") as f:
            f.write(manifest.to_json())

        # ---- phase 1: key ceremony (multi-process) ---------------------------
        t0 = clock.now()
        phases.begin("phase.key-ceremony")
        if args.chaos_guardian >= 0:
            # the COORDINATOR (launched next) needs a retry window wide
            # enough to bridge the guardian's kill→restart gap
            os.environ.setdefault("EGTPU_RPC_RETRIES", "8")
            os.environ.setdefault("EGTPU_RPC_RETRY_BUDGET", "300")
        kc_port = find_free_port()
        coord = RunCommand.python_module(
            "keyceremony-coordinator",
            "electionguard_tpu.cli.run_remote_keyceremony",
            ["-in", input_dir, "-out", record_dir,
             "-nguardians", str(args.nguardians), "-quorum", str(args.quorum),
             "-port", str(kc_port), "-trusteeDir", trustee_dir,
             "-timeout", "90"] + group_flags,
            cmd_out)
        procs.append(coord)
        clock.sleep(1.5)  # let the coordinator bind
        chaos_dir = os.path.join(out, "chaos")
        guardians = []
        for i in range(args.nguardians):
            flags = ["-name", f"guardian-{i}", "-serverPort", str(kc_port),
                     "-out", trustee_dir] + group_flags
            env = None
            if args.chaos_guardian >= 0:
                # resume files make every guardian restartable; only the
                # chaos target actually crashes
                os.makedirs(chaos_dir, exist_ok=True)
                flags += ["-resumeFile",
                          os.path.join(chaos_dir, f"guardian-{i}.resume")]
                if i == args.chaos_guardian:
                    # deterministic death at a protocol point, not a timer:
                    # the guardian hard-exits (os._exit) right after it
                    # commits + checkpoints its first received key share,
                    # so the retried rpc must replay against restored state
                    env = {"EGTPU_FAULT_PLAN": json.dumps({"rules": [
                        {"method": "receiveSecretKeyShare",
                         "kind": "crash_after", "on_calls": [1]}]})}
            guardians.append(RunCommand.python_module(
                f"guardian-{i}", "electionguard_tpu.cli.run_remote_trustee",
                flags, cmd_out, env=env))
        procs.extend(guardians)
        chaos_thread = None
        if 0 <= args.chaos_guardian < len(guardians):
            log.info("CHAOS: guardian-%d dies after its first committed key "
                     "share and restarts from its resume file",
                     args.chaos_guardian)
            chaos_thread = guardians[args.chaos_guardian].restart_on_exit(
                strip_env=("EGTPU_FAULT_PLAN",), downtime_s=1.0)
        if not wait_all([coord] + guardians, timeout=240):
            return phase_fail("key-ceremony", [coord] + guardians)
        if chaos_thread is not None:
            chaos_thread.join(timeout=10)
            log.info("[1] key ceremony survived the guardian-%d chaos "
                     "restart", args.chaos_guardian)
        log.info("[1] key ceremony took %.1fs", clock.now() - t0)

        # ---- phase 1.5 (optional): live verifier tails the record ------------
        # launched BEFORE any ballot exists so the whole stream is
        # verified as it lands; it self-terminates once the decryption
        # result is published and the stream goes quiet (gated in 5.5)
        lv_cmd = None
        lv_audit = os.path.join(out, "live_audit.json")
        if args.live_verify:
            from electionguard_tpu.publish import pb
            lv_port = find_free_port()
            lv_cmd = RunCommand.python_module(
                "live-verifier", "electionguard_tpu.cli.run_live_verifier",
                ["-in", record_dir, "-port", str(lv_port),
                 "-chunk", str(max(1, args.nballots // 16)),
                 "-audit", lv_audit, "-timeout", "900"] + group_flags,
                cmd_out)
            procs.append(lv_cmd)
            lv_stub = Stub(make_plain_channel(f"localhost:{lv_port}"),
                           "BulletinBoardService")
            deadline = clock.now() + 60
            while True:
                try:
                    lv_stub.call("getRoot",
                                 pb.msg("BulletinRootRequest")(),
                                 timeout=2.0)
                    break
                except Exception:  # noqa: BLE001 — still binding
                    if clock.now() > deadline or lv_cmd.poll() is not None:
                        return phase_fail("live-verify", [lv_cmd])
                    clock.sleep(0.25)
            log.info("[1.5] live verifier tailing %s (bulletin board on "
                     "port %d)", record_dir, lv_port)

        # ---- phase 2: fake ballots + batch encryption ------------------------
        t0 = clock.now()
        phases.begin("phase.encrypt")
        pub = Publisher(out)
        for b in RandomBallotProvider(manifest, args.nballots, seed=11).ballots():
            pub.write_plaintext_ballot("plaintext_ballots", b)
        if args.fabric_workers > 0:
            ok = _fabric_encrypt_phase(args, out, record_dir, cmd_out,
                                       group_flags, manifest, log, procs,
                                       phase_fail)
            if ok is not True:
                return ok
        else:
            enc = RunCommand.python_module(
                "batch-encryption", "electionguard_tpu.cli.run_batch_encryption",
                ["-in", record_dir, "-ballots", ballots_dir, "-out", record_dir,
                 "-fixedNonces", "-spoilEvery", str(args.spoil_every)] + group_flags,
                cmd_out)
            if not wait_all([enc], timeout=600):
                return phase_fail("encryption", [enc])
        dt = clock.now() - t0
        log.info("[2] encrypted %d ballots in %.1fs (%.3fs/ballot)",
                 args.nballots, dt, dt / max(args.nballots, 1))

        # ---- phase 3: accumulate --------------------------------------------
        t0 = clock.now()
        phases.begin("phase.tally")
        acc = RunCommand.python_module(
            "accumulate", "electionguard_tpu.cli.run_accumulate_tally",
            ["-in", record_dir, "-out", record_dir] + group_flags, cmd_out)
        if not wait_all([acc], timeout=300):
            return phase_fail("accumulate", [acc])
        log.info("[3] tally accumulation took %.1fs", clock.now() - t0)
        if lv_cmd is not None:
            # mid-election probe: the bulletin board must already be
            # serving a commitment over the landed ballots (the root it
            # serves here is later pinned by the inclusion proofs)
            st = lv_stub.call("getAuditState",
                              pb.msg("AuditStateRequest")(), timeout=30.0)
            rt = lv_stub.call("getRoot", pb.msg("BulletinRootRequest")(),
                              timeout=30.0)
            log.info("[3] live audit mid-election: %s, %d/%d frames "
                     "verified (lag %d), root=%s", st.status,
                     st.frames_verified, st.frames_published,
                     st.audit_lag_frames, rt.root.hex()[:16])

        # ---- phase 3.5: mixnet (optional) -------------------------------------
        if args.mix > 0:
            t0 = clock.now()
            phases.begin("phase.mix")
            mix = RunCommand.python_module(
                "mixnet", "electionguard_tpu.cli.run_mixnet",
                ["-in", record_dir, "-out", record_dir,
                 "-stages", str(args.mix)] + group_flags, cmd_out)
            if not wait_all([mix], timeout=600):
                return phase_fail("mixnet", [mix])
            log.info("[3.5] %d mix stages took %.1fs", args.mix,
                     clock.now() - t0)

        # ---- phase 3.5 (federated): one mix-server process per stage ---------
        if args.mix_servers > 0:
            t0 = clock.now()
            phases.begin("phase.mixfed")
            mix_port = find_free_port()
            n_servers = args.mix_servers + (1 if args.chaos_mix else 0)
            mcoord = RunCommand.python_module(
                "mix-coordinator", "electionguard_tpu.cli.run_mix_coordinator",
                ["-in", record_dir, "-out", record_dir,
                 "-stages", str(args.mix_servers),
                 "-servers", str(n_servers), "-port", str(mix_port),
                 "-registrationTimeout", "90",
                 "-checkpointFile", os.path.join(out, "mix_checkpoint.json")]
                + group_flags, cmd_out)
            clock.sleep(1.5)  # let the registration service bind

            def launch_mix_server(i, env=None):
                return RunCommand.python_module(
                    f"mix-server-{i}", "electionguard_tpu.cli.run_mix_server",
                    ["-name", f"mix-{i}", "-serverPort", str(mix_port)]
                    + group_flags, cmd_out, env=env)

            mix_servers = []
            if args.chaos_mix:
                # deterministic death at a protocol point: the victim
                # hard-exits right after its first shuffle commits (the
                # result is lost with the process); the coordinator's
                # bounded retries must requeue the stage on the spare.
                # The coordinator assigns stages in REGISTRATION order, so
                # the victim launches alone and must be registered before
                # the honest servers start — otherwise it could end up an
                # unused spare and the drill would silently test nothing.
                log.info("CHAOS: mix-server-0 dies after its first shuffle "
                         "commits; its stage must requeue on the spare")
                victim = launch_mix_server(0, env={
                    "EGTPU_FAULT_PLAN": json.dumps({"rules": [
                        {"method": "shuffleStage", "kind": "crash_after",
                         "on_calls": [1]}]})})
                mix_servers.append(victim)
                deadline = clock.now() + 60
                while clock.now() < deadline:
                    with open(mcoord.stdout_path, "rb") as f:
                        if b"registered mix server mix-0" in f.read():
                            break
                    clock.sleep(0.25)
                else:
                    return phase_fail("mixfed", [mcoord, victim])
            for i in range(len(mix_servers), n_servers):
                mix_servers.append(launch_mix_server(i))
            procs.extend([mcoord] + mix_servers)
            # the chaos victim dies by design (exit 137) — don't gate the
            # phase on its exit code
            waited = [mcoord] + (mix_servers[1:] if args.chaos_mix
                                 else mix_servers)
            if not wait_all(waited, timeout=600):
                return phase_fail("mixfed", [mcoord] + mix_servers)
            log.info("[3.5] %d federated mix stages over %d server "
                     "processes took %.1fs", args.mix_servers, n_servers,
                     clock.now() - t0)

        # ---- phase 4: remote decryption (multi-process) ----------------------
        t0 = clock.now()
        phases.begin("phase.decrypt")
        dec_port = find_free_port()
        decryptor = RunCommand.python_module(
            "decryptor", "electionguard_tpu.cli.run_remote_decryptor",
            ["-in", record_dir, "-out", record_dir,
             "-navailable", str(args.navailable), "-port", str(dec_port),
             "-timeout", "90"]
            + (["-decryptSpoiled"] if args.spoil_every else []) + group_flags,
            cmd_out)
        clock.sleep(1.5)
        dec_trustees = []
        trustee_files = sorted(os.listdir(trustee_dir))[:args.navailable]
        for name in trustee_files:
            dec_trustees.append(RunCommand.python_module(
                f"dec-{name}", "electionguard_tpu.cli.run_remote_decrypting_trustee",
                ["-trusteeFile", os.path.join(trustee_dir, name),
                 "-serverPort", str(dec_port)] + group_flags,
                cmd_out))
        if not wait_all([decryptor] + dec_trustees, timeout=300):
            return phase_fail("decryption", [decryptor] + dec_trustees)
        log.info("[4] decryption took %.1fs", clock.now() - t0)

        # ---- phase 5: verify --------------------------------------------------
        t0 = clock.now()
        phases.begin("phase.verify")
        ver = RunCommand.python_module(
            "verifier", "electionguard_tpu.cli.run_verifier",
            ["-in", record_dir] + group_flags, cmd_out)
        code = ver.wait_for(timeout=600)
        ver.show()
        if code != 0:
            return phase_fail("verify", [ver])
        log.info("[5] verification took %.1fs", clock.now() - t0)

        # ---- phase 5.5 (optional): live verifier convergence gate ------------
        # the live verifier saw the decryption result land; it drains its
        # residual tail, finalizes, and exits with the verifier's verdict
        # contract.  Acceptance: green, and <5% of the stream was still
        # unverified at the moment the election closed.
        if lv_cmd is not None:
            t0 = clock.now()
            phases.begin("phase.live-verify")
            code = lv_cmd.wait_for(timeout=300)
            lv_cmd.show()
            if code != 0:
                return phase_fail("live-verify", [lv_cmd])
            with open(lv_audit) as f:
                audit = json.load(f)
            if not audit["verdict_ok"]:
                log.error("live verifier ended red: %s", audit["errors"])
                return phase_fail("live-verify", [lv_cmd])
            if audit["residual_fraction"] >= 0.05:
                log.error("live verifier left %.1f%% of the stream "
                          "unverified when the election closed (gate is "
                          "<5%%)", 100 * audit["residual_fraction"])
                return phase_fail("live-verify", [lv_cmd])
            log.info("[5.5] live verification converged: root=%s chunks=%d "
                     "residual=%.2f%% (%d frames, drained in %.2fs)",
                     audit["root"][:16], audit["n_chunks"],
                     100 * audit["residual_fraction"],
                     audit["residual_frames_at_close"],
                     audit["residual_verify_s"])

        phases.end()

        # ---- obs epilogue: the fleet must be green ----------------------------
        if obs_stub is not None:
            st = obs_stub.call("getFleetStatus",
                               pb.msg("FleetStatusRequest")())
            for p in st.processes:
                log.info("fleet: %-26s %-6s %-8s hb=%5.1fs phase=%-18s "
                         "spans=%d", f"{p.proc}:{p.pid}", p.state, p.status,
                         p.heartbeat_age_s, p.phase or "-", p.spans)
            log.info("[obs] fleet %s: %d spans ingested, %d slo evals, %d "
                     "alerts", st.health, st.spans_total, st.slo_evals,
                     len(st.alerts))
            if st.health != "green":
                log.error("fleet health is %s at end of run: %s", st.health,
                          "; ".join(st.alerts))
                return phase_fail("obs-fleet", [obs_cmd])

        log.info("WORKFLOW PASS: 5 phases, %d ballots, %.1fs total",
                 args.nballots, clock.now() - t_all)
        return 0
    finally:
        # best-effort teardown on EVERY exit path — including a phase
        # failure or an exception mid-run: close any open phase span,
        # say goodbye to (and stop) the collector so it flushes a final
        # live assembly, and merge whatever span files exist so a died
        # run still yields a timeline.
        phases.end()
        if obs_cmd is not None and obs_cmd.poll() is None:
            try:
                client = obs_collector._client
                if client is not None:
                    client.close()   # the driver's EXITING goodbye
                obs_stub.call("finish", pb.msg("FinishRequest")(),
                              timeout=10.0)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                log.warning("obs collector finish rpc failed; killing")
            if obs_cmd.wait_for(15) is None:
                obs_cmd.kill()
        if obs_trace.enabled():
            # close the driver's own span file first so its spans
            # (phases, root) land in the merge, then assemble everything
            # into one Perfetto-openable timeline.  In-flight spans of
            # processes that never exited cleanly are tolerated by the
            # assembler (reported as open_spans).
            obs_trace.shutdown()
            try:
                from electionguard_tpu.obs import assemble
                report = assemble.merge_dir(
                    trace_dir, os.path.join(out, "trace.json"))
                log.info("TRACE: %d spans / %d processes / trace_ids=%s "
                         "rpc_pairs=%d orphans=%d gaps=%d open=%d -> %s",
                         report["n_spans"], len(report["processes"]),
                         report["trace_ids"], report["rpc_pairs"],
                         len(report["orphans"]), len(report["gaps"]),
                         len(report["open_spans"]), report["out"])
            except (OSError, ValueError):
                log.exception("trace merge failed")
            if args.flight_report:
                # even on a failed/chaos run: the report degrades to
                # partial-with-warnings, never blocks teardown
                try:
                    from electionguard_tpu.obs import flight
                    rpt_path, analysis = flight.write_report(
                        trace_dir,
                        os.path.join(out, "FLIGHT_REPORT.md"))
                    log.info(
                        "FLIGHT REPORT: %s (wall=%.1fs path=%.1fs "
                        "coverage=%.1f%% stragglers=%d warnings=%d)",
                        rpt_path, analysis.wall_us / 1e6,
                        analysis.path_total_us / 1e6,
                        analysis.coverage * 100,
                        len(analysis.stragglers),
                        len(analysis.warnings))
                except Exception:  # noqa: BLE001 — report is best-effort
                    log.exception("flight report generation failed")


if __name__ == "__main__":
    sys.exit(main())

"""Async subprocess runner for multi-process workflow tests.

Mirror of the reference's ``RunCommand`` (src/test/java/electionguard/
workflow/RunCommand.java:19-117): starts a process detached, captures
stdout/stderr to ``<output_dir>/<name>.std{out,err}`` files, supports
wait-with-timeout, kill, and ``show()`` dumping the captured output —
the reference's multi-node-without-a-cluster mechanism (SURVEY.md §4).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
from typing import Optional

from electionguard_tpu.utils import clock


class RunCommand:
    def __init__(self, name: str, args: list[str], output_dir: str,
                 env: Optional[dict] = None):
        self.name = name
        self.args = list(args)
        self._env = dict(env or {})
        os.makedirs(output_dir, exist_ok=True)
        self.stdout_path = os.path.join(output_dir, f"{name}.stdout")
        self.stderr_path = os.path.join(output_dir, f"{name}.stderr")
        self._stdout_f = open(self.stdout_path, "wb")
        self._stderr_f = open(self.stderr_path, "wb")
        self.process = subprocess.Popen(
            self.args, stdout=self._stdout_f, stderr=self._stderr_f,
            env={**os.environ, **self._env})

    @staticmethod
    def python_module(name: str, module: str, flags: list[str],
                      output_dir: str, env: Optional[dict] = None
                      ) -> "RunCommand":
        """Launch ``python -m module flags...`` (the fatJar equivalent).
        The child's obs process name defaults to its RunCommand name, so
        a traced run exports one span/log file per ROLE (guardian-1,
        decryptor, ...) instead of one per interpreter path."""
        env = dict(env or {})
        env.setdefault("EGTPU_OBS_PROC", name)
        return RunCommand(name, [sys.executable, "-m", module] + flags,
                          output_dir, env)

    def wait_for(self, timeout: float) -> Optional[int]:
        """Wait up to timeout seconds; returns exit code or None."""
        try:
            return self.process.wait(timeout)
        except subprocess.TimeoutExpired:
            return None

    def poll(self) -> Optional[int]:
        return self.process.poll()

    def kill(self):
        if self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(5)
            except subprocess.TimeoutExpired:
                self.process.kill()
        self._close()

    # ---- chaos hooks (fault-injection harness, ISSUE 2) --------------
    def kill_hard(self):
        """SIGKILL — no signal handlers, no atexit, no graceful drain:
        the genuine crash the recovery paths must survive."""
        if self.process.poll() is None:
            self.process.kill()
            self.process.wait(10)
        self._close()

    def restart(self) -> None:
        """Relaunch the SAME argv (e.g. a trustee pointed at its resume
        file); captured output appends so one log tells the whole
        story.  The previous process must have exited."""
        if self.process.poll() is None:
            raise RuntimeError(f"{self.name} still running; kill first")
        self._close()
        self._stdout_f = open(self.stdout_path, "ab")
        self._stderr_f = open(self.stderr_path, "ab")
        self.process = subprocess.Popen(
            self.args, stdout=self._stdout_f, stderr=self._stderr_f,
            env={**os.environ, **self._env})

    def restart_on_exit(self, strip_env: tuple[str, ...] = (),
                        downtime_s: float = 1.0) -> threading.Thread:
        """Watch for the process's FIRST exit (e.g. an EGTPU_FAULT_PLAN
        crash_after hard-exit at a deterministic protocol point) and
        relaunch it once, ``downtime_s`` later, with ``strip_env`` keys
        removed so the fault does not re-fire.  Returns the daemon
        watcher thread so callers can join it."""
        def fire():
            self.process.wait()
            for k in strip_env:
                self._env.pop(k, None)
            clock.sleep(downtime_s)
            self.restart()

        t = threading.Thread(target=fire, daemon=True,
                             name=f"chaos-{self.name}")
        t.start()
        return t

    def _close(self):
        for f in (self._stdout_f, self._stderr_f):
            try:
                f.close()
            except OSError:
                pass

    def show(self, stream=sys.stdout):
        """Dump captured output (reference: RunCommand.show :84-99)."""
        self._close()
        print(f"----- {self.name} " + "-" * 40, file=stream)
        print(f"  args: {' '.join(self.args)}", file=stream)
        print(f"  exit: {self.process.poll()}", file=stream)
        for label, path in (("stdout", self.stdout_path),
                            ("stderr", self.stderr_path)):
            with open(path, "rb") as f:
                data = f.read().decode(errors="replace")
            if data.strip():
                print(f"  --- {label} ---", file=stream)
                for line in data.splitlines():
                    print(f"  {line}", file=stream)


def wait_all(commands: list[RunCommand], timeout: float) -> bool:
    """Wait for all commands; kill stragglers at the deadline."""
    deadline = clock.monotonic() + timeout
    ok = True
    for c in commands:
        remaining = max(0.1, deadline - clock.monotonic())
        code = c.wait_for(remaining)
        if code is None:
            c.kill()
            ok = False
        elif code != 0:
            ok = False
    return ok

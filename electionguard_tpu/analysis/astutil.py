"""Small AST helpers shared by the eglint passes."""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def call_name(node: ast.Call) -> Optional[str]:
    """The terminal name of a call: ``f(...)`` -> "f",
    ``a.b.f(...)`` -> "f", anything else (lambda, subscript) -> None."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` -> "a.b.c" when the chain is Names/Attributes only."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr(node: ast.expr) -> Optional[str]:
    """``self.X`` -> "X", else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def walk_functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    """Every (sync or async) function definition, at any nesting."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def str_const(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names

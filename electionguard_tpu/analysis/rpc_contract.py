"""rpc-contract / raw-channel: the .proto is the contract, rpc_util the
only transport.

Contract checks (``rpc-contract``), driven by the runtime-descriptor
toolchain itself — the proto files are compiled with the same
``protoc_mini`` that builds the production descriptors:

* every rpc method in ``remote_rpc.proto`` has a deadline class in
  ``rpc_util._DEADLINE_CLASS_OF`` (otherwise ``Stub.call`` silently
  falls back to the exchange default);
* every rpc method has a server impl in some
  ``rpc_util.generic_service("Svc", {...})`` registration
  (``getMetrics`` has a registry-backed default);
* chunked rpcs — those whose request message carries ``chunk_start`` —
  have impls that actually read ``chunk_start`` (the idempotency
  contract: a retried chunk must overwrite, not append).

Channel discipline (``raw-channel``): ``grpc.insecure_channel`` /
``grpc.server`` may only be created inside ``remote/rpc_util.py``
(``make_channel`` / ``make_plain_channel`` / ``make_server``).  Those
hooks are what make tracing and fault injection universal — a raw
channel is invisible to both, so the baseline for this rule must stay
EMPTY.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from electionguard_tpu.analysis import astutil, core

RULE_CONTRACT = "rpc-contract"
RULE_CHANNEL = "raw-channel"

PROTO_SUFFIX = "publish/proto/remote_rpc.proto"
RPC_UTIL_SUFFIX = "remote/rpc_util.py"

#: methods generic_service supplies a default impl for
_DEFAULT_IMPLS = {"getMetrics"}


def _compile_protos(project: core.Project):
    """FileDescriptorSet of every .proto beside the contract file, via
    protoc_mini (pure python); None when the project has no contract."""
    main = None
    for p in sorted(project.package_dir.rglob("*.proto")):
        if p.as_posix().endswith(PROTO_SUFFIX):
            main = p
    if main is None:
        return None, None
    try:
        from electionguard_tpu.publish import protoc_mini
    except Exception:       # descriptor runtime unavailable: skip
        return None, None
    texts = [(p.name, p.read_text())
             for p in sorted(main.parent.glob("*.proto"))]
    try:
        return protoc_mini.compile_files(texts), main
    except Exception:
        return None, None


def _deadline_classes(src: core.SourceFile
                      ) -> tuple[dict[str, int], Optional[int]]:
    """method -> lineno of its entry in _DEADLINE_CLASS_OF, + dict line."""
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "_DEADLINE_CLASS_OF"
                for t in node.targets):
            if isinstance(node.value, ast.Dict):
                entries = {}
                for k in node.value.keys:
                    name = astutil.str_const(k) if k is not None else None
                    if name:
                        entries[name] = k.lineno
                return entries, node.lineno
    return {}, None


def _service_registrations(project: core.Project
                           ) -> dict[str, list[tuple[core.SourceFile, int,
                                                     set[str]]]]:
    """service name -> [(file, line, literal impl-dict keys)]."""
    regs: dict[str, list] = {}
    for f in project.files():
        # module-level NAME = "literal" constants (serve/service.py
        # registers via a _SERVICE constant, not an inline literal)
        consts: dict[str, str] = {}
        for stmt in f.tree.body:
            if isinstance(stmt, ast.Assign):
                lit = astutil.str_const(stmt.value)
                if lit is not None:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            consts[t.id] = lit
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call)
                    and astutil.call_name(node) == "generic_service"
                    and len(node.args) >= 2):
                continue
            svc = astutil.str_const(node.args[0])
            if svc is None and isinstance(node.args[0], ast.Name):
                svc = consts.get(node.args[0].id)
            if svc is None:
                continue
            impls: set[str] = set()
            if isinstance(node.args[1], ast.Dict):
                impls = {astutil.str_const(k) for k in node.args[1].keys
                         if k is not None and astutil.str_const(k)}
            regs.setdefault(svc, []).append((f, node.lineno, impls))
    return regs


def _impl_reads_chunk_start(project: core.Project, reg_file: core.SourceFile,
                            reg_line: int, method: str) -> Optional[bool]:
    """Does the registered impl for ``method`` reference .chunk_start?
    None when the impl expression isn't statically resolvable."""
    impl_name = None
    for node in ast.walk(reg_file.tree):
        if (isinstance(node, ast.Call)
                and astutil.call_name(node) == "generic_service"
                and node.lineno == reg_line
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Dict)):
            for k, v in zip(node.args[1].keys, node.args[1].values):
                if k is not None and astutil.str_const(k) == method:
                    if isinstance(v, ast.Name):
                        impl_name = v.id
                    else:
                        impl_name = astutil.self_attr(v) or (
                            v.attr if isinstance(v, ast.Attribute)
                            else None)
    if impl_name is None:
        return None
    for fn in astutil.walk_functions(reg_file.tree):
        if fn.name == impl_name:
            return any(isinstance(n, ast.Attribute)
                       and n.attr == "chunk_start"
                       for n in ast.walk(fn))
    return None


def _proto_line(text: str, method: str) -> int:
    m = re.search(rf"^\s*rpc\s+{re.escape(method)}\b", text, re.MULTILINE)
    return text[:m.start()].count("\n") + 1 if m else 1


@core.register(RULE_CONTRACT, rules=(RULE_CONTRACT, RULE_CHANNEL),
               doc="proto/deadline/impl/idempotency contract + the "
                   "rpc_util-only channel discipline")
def run(project: core.Project) -> Iterator[core.Finding]:
    # ---- raw-channel: grpc.insecure_channel / grpc.server outside
    # rpc_util's factory functions
    for f in project.files():
        if f.rel.endswith(RPC_UTIL_SUFFIX):
            continue
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("insecure_channel", "server",
                                           "secure_channel")
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "grpc"):
                yield core.Finding(
                    RULE_CHANNEL, f.rel, node.lineno,
                    f"raw grpc.{node.func.attr}() bypasses rpc_util."
                    f"make_channel/make_server — invisible to tracing "
                    f"and fault injection")

    # ---- contract checks need the proto + rpc_util in the project
    fds, proto_path = _compile_protos(project)
    rpc_util = project.file(RPC_UTIL_SUFFIX)
    if fds is None or rpc_util is None:
        return
    proto_rel = proto_path.relative_to(project.root).as_posix()
    proto_text = proto_path.read_text()
    classes, dict_line = _deadline_classes(rpc_util)
    regs = _service_registrations(project)

    msg_fields: dict[str, set[str]] = {}
    for fl in fds.file:
        for m in fl.message_type:
            msg_fields[m.name] = {fld.name for fld in m.field}

    for fl in fds.file:
        for svc in fl.service:
            svc_regs = regs.get(svc.name, [])
            if not svc_regs:
                yield core.Finding(
                    RULE_CONTRACT, proto_rel, 1,
                    f"service {svc.name} has no rpc_util."
                    f"generic_service registration in the package")
            for m in svc.method:
                line = _proto_line(proto_text, m.name)
                if m.name not in classes:
                    yield core.Finding(
                        RULE_CONTRACT, proto_rel, line,
                        f"rpc {svc.name}.{m.name} has no deadline class "
                        f"in rpc_util._DEADLINE_CLASS_OF (Stub.call "
                        f"would silently use the exchange default)")
                impl_regs = [(f, ln) for f, ln, impls in svc_regs
                             if m.name in impls]
                if svc_regs and not impl_regs \
                        and m.name not in _DEFAULT_IMPLS:
                    yield core.Finding(
                        RULE_CONTRACT, proto_rel, line,
                        f"rpc {svc.name}.{m.name} has no server impl in "
                        f"any generic_service({svc.name!r}, ...) "
                        f"registration")
                req = m.input_type.rsplit(".", 1)[-1]
                if "chunk_start" in msg_fields.get(req, set()):
                    for f, ln in impl_regs:
                        ok = _impl_reads_chunk_start(project, f, ln,
                                                     m.name)
                        if ok is False:
                            yield core.Finding(
                                RULE_CONTRACT, f.rel, ln,
                                f"chunked rpc {svc.name}.{m.name}: impl "
                                f"never reads chunk_start — a retried "
                                f"chunk would append instead of "
                                f"overwrite (idempotency contract)")

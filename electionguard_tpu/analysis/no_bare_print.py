"""no-bare-print: library telemetry goes through ``logging``.

A bare ``print()`` is invisible to the observability plane (``obs.slog``
mirrors logging, not stdout) and unattributable to a trace.  CLI entry
points (``electionguard_tpu/cli/``) are exempt — their stdout IS their
user interface — and ``print(..., file=...)`` writing to an explicitly
chosen stream is display plumbing, not telemetry.

Migrated from the seed lint ``tests/test_lint_print.py`` (which is now a
thin wrapper over this pass, pinning the walked packages).
"""

from __future__ import annotations

import ast
from typing import Iterator

from electionguard_tpu.analysis import core

#: subpackages whose stdout is their interface (pinned by
#: tests/test_lint_print.py so coverage can't silently shrink)
EXEMPT_DIRS = ("cli",)

RULE = "no-bare-print"


@core.register(RULE, doc="bare print() in library code (use logging; "
                         "obs.slog mirrors it with trace context)")
def run(project: core.Project) -> Iterator[core.Finding]:
    for f in project.files():
        parts = project.package_rel_parts(f)
        if parts and parts[0] in EXEMPT_DIRS:
            continue
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    and not any(kw.arg == "file" for kw in node.keywords)):
                yield core.Finding(
                    RULE, f.rel, node.lineno,
                    "bare print() in library code: use logging so "
                    "obs.slog mirrors it as structured JSONL with "
                    "trace context")

"""Analysis framework: findings, pass registry, parse cache, suppressions.

A *pass* is a function ``(Project) -> Iterable[Finding]`` registered
under its primary rule id.  ``run_passes`` walks the package once,
caches each file's AST, runs every requested pass, then applies the two
suppression layers:

* inline ``# eglint: disable=RULE[,RULE2]`` on the offending line
  silences exactly that line (counted per rule, so tests can assert a
  disable suppressed exactly one finding);
* ``analysis/baseline.json`` entries — ``{rule, path, line, note}`` —
  park known findings; every entry MUST carry a non-empty ``note``
  explaining why it is baselined rather than fixed.

Both layers are visible in the report (and the ``ANALYSIS.json``
artifact), never silently dropped.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Optional

PACKAGE_ROOT = Path(__file__).resolve().parents[1]
REPO_ROOT = PACKAGE_ROOT.parent
DEFAULT_BASELINE = Path(__file__).resolve().with_name("baseline.json")

#: rules whose baseline must stay EMPTY: a finding here is a secret leak
#: or an untraced/unfaultable channel — fixed, never parked.
NO_BASELINE_RULES = ("secret-taint", "raw-channel")

_DISABLE_RE = re.compile(r"#\s*eglint:\s*disable=([A-Za-z0-9_\-, ]+)")


@dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one source line."""

    rule: str
    path: str      # posix path relative to the project root
    line: int
    message: str

    @property
    def key(self) -> tuple[str, str, int]:
        return (self.rule, self.path, self.line)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One scanned file: text + lazily parsed AST + inline disables."""

    def __init__(self, abspath: Path, rel: str):
        self.abspath = abspath
        self.rel = rel
        self.text = abspath.read_text()
        self._tree: Optional[ast.Module] = None
        self._disables: Optional[dict[int, set[str]]] = None

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            self._tree = ast.parse(self.text, filename=str(self.abspath))
        return self._tree

    @property
    def disables(self) -> dict[int, set[str]]:
        """line number -> rule ids disabled on that line."""
        if self._disables is None:
            self._disables = {}
            for i, line in enumerate(self.text.splitlines(), start=1):
                m = _DISABLE_RE.search(line)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    self._disables[i] = {r for r in rules if r}
        return self._disables


class Project:
    """A scanned source tree: every ``*.py`` under ``package_dir``.

    ``root`` (default: the package's parent) anchors the relative paths
    findings report; passes locate contract files (the .proto, the knob
    registry) by suffix inside the same tree, so a temp-dir fixture
    project with the same relative layout exercises every pass without
    the real package walk ever seeing it.
    """

    def __init__(self, package_dir: Optional[Path] = None,
                 root: Optional[Path] = None):
        self.package_dir = Path(package_dir or PACKAGE_ROOT).resolve()
        self.root = Path(root).resolve() if root else self.package_dir.parent
        self._files: Optional[list[SourceFile]] = None

    def files(self) -> list[SourceFile]:
        if self._files is None:
            self._files = []
            for p in sorted(self.package_dir.rglob("*.py")):
                if "__pycache__" in p.parts:
                    continue
                rel = p.relative_to(self.root).as_posix()
                self._files.append(SourceFile(p, rel))
        return self._files

    def file(self, rel_suffix: str) -> Optional[SourceFile]:
        """The scanned file whose path ends with ``rel_suffix``, if any."""
        for f in self.files():
            if f.rel.endswith(rel_suffix):
                return f
        return None

    def package_rel_parts(self, f: SourceFile) -> tuple[str, ...]:
        """Path parts relative to the package dir (for dir exemptions)."""
        return f.abspath.relative_to(self.package_dir).parts


# ---------------------------------------------------------------------------
# pass registry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PassInfo:
    name: str                      # primary rule id == pass name
    rules: tuple[str, ...]         # every rule id the pass may emit
    doc: str
    fn: Callable[[Project], Iterable[Finding]] = field(compare=False)


PASSES: dict[str, PassInfo] = {}


def register(name: str, rules: Optional[tuple[str, ...]] = None,
             doc: str = ""):
    """Decorator registering an analysis pass under ``name``."""
    def deco(fn):
        PASSES[name] = PassInfo(name, tuple(rules or (name,)),
                                doc or (fn.__doc__ or "").strip(), fn)
        return fn
    return deco


def load_default_passes() -> None:
    """Import every built-in pass module (idempotent: registry keyed)."""
    from electionguard_tpu.analysis import (env_knobs,  # noqa: F401
                                            ingestion_validation,
                                            jit_hygiene, lock_discipline,
                                            no_bare_print, rpc_contract,
                                            secret_taint, tenant_label,
                                            trace_coverage, wall_clock)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: Optional[Path] = None) -> list[dict]:
    """Baseline entries ``{rule, path, line, note}``; every entry must
    carry a non-empty ``note`` (the tracking rationale)."""
    p = Path(path) if path else DEFAULT_BASELINE
    if not p.exists():
        return []
    entries = json.loads(p.read_text())
    for e in entries:
        for k in ("rule", "path", "line"):
            if k not in e:
                raise ValueError(f"baseline entry missing {k!r}: {e}")
        if not str(e.get("note", "")).strip():
            raise ValueError(
                f"baseline entry for {e['rule']} at {e['path']}:{e['line']} "
                f"has no note: every baselined finding needs a rationale")
        if e["rule"] in NO_BASELINE_RULES:
            raise ValueError(
                f"rule {e['rule']!r} may not be baselined (fix it): {e}")
    return entries


def write_baseline(path: Path, findings: Iterable[Finding],
                   note: str) -> None:
    """Baseline ``findings`` with one shared rationale ``note``."""
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "note": note} for f in sorted(findings)]
    Path(path).write_text(json.dumps(entries, indent=2) + "\n")


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

@dataclass
class Report:
    findings: list[Finding]            # live: unsuppressed, unbaselined
    baselined: list[Finding]
    suppressed: dict[str, int]         # rule -> inline-disable count
    stale_baseline: list[dict]         # entries matching nothing anymore
    files_scanned: list[str]
    passes_run: list[str]

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        by_pass: dict[str, dict] = {}
        load_default_passes()
        rule_to_pass = {r: info.name for info in PASSES.values()
                        for r in info.rules}
        for name in self.passes_run:
            by_pass[name] = {"findings": 0, "baselined": 0, "suppressed": 0}
        for f in self.findings:
            by_pass.setdefault(rule_to_pass.get(f.rule, f.rule),
                               {"findings": 0, "baselined": 0,
                                "suppressed": 0})["findings"] += 1
        for f in self.baselined:
            by_pass.setdefault(rule_to_pass.get(f.rule, f.rule),
                               {"findings": 0, "baselined": 0,
                                "suppressed": 0})["baselined"] += 1
        for rule, n in self.suppressed.items():
            by_pass.setdefault(rule_to_pass.get(rule, rule),
                               {"findings": 0, "baselined": 0,
                                "suppressed": 0})["suppressed"] += n
        return {
            "version": 1,
            "files_scanned": len(self.files_scanned),
            "passes": {k: by_pass[k] for k in sorted(by_pass)},
            "suppressed_total": sum(self.suppressed.values()),
            "stale_baseline": self.stale_baseline,
            "findings": [{"rule": f.rule, "path": f.path, "line": f.line,
                          "message": f.message}
                         for f in sorted(self.findings)],
            "baselined": [{"rule": f.rule, "path": f.path, "line": f.line}
                          for f in sorted(self.baselined)],
        }


def run_passes(project: Optional[Project] = None,
               passes: Optional[Iterable[str]] = None,
               baseline: Optional[list[dict]] = None) -> Report:
    """Run ``passes`` (default: all registered) over ``project``."""
    load_default_passes()
    project = project or Project()
    names = list(passes) if passes else sorted(PASSES)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise KeyError(f"unknown passes: {unknown}; "
                       f"have {sorted(PASSES)}")
    raw: list[Finding] = []
    for name in names:
        raw.extend(PASSES[name].fn(project))

    by_rel = {f.rel: f for f in project.files()}
    live: list[Finding] = []
    suppressed: dict[str, int] = {}
    for f in sorted(set(raw)):
        src = by_rel.get(f.path)
        if src is not None and f.rule in src.disables.get(f.line, set()):
            suppressed[f.rule] = suppressed.get(f.rule, 0) + 1
            continue
        live.append(f)

    baseline = baseline if baseline is not None else load_baseline()
    bkeys = {(e["rule"], e["path"], int(e["line"])) for e in baseline}
    hit: set[tuple] = set()
    findings, baselined = [], []
    for f in live:
        if f.key in bkeys:
            baselined.append(f)
            hit.add(f.key)
        else:
            findings.append(f)
    stale = [e for e in baseline
             if (e["rule"], e["path"], int(e["line"])) not in hit]
    return Report(findings=findings, baselined=baselined,
                  suppressed=suppressed, stale_baseline=stale,
                  files_scanned=[f.rel for f in project.files()],
                  passes_run=names)

"""jit-hygiene: jitted code must stay compile-once and device-resident.

The serving plane's ``device_compiles``-flat guarantee (power-of-two
bucket padding, construction-time ``jax.jit``) dies from four habits:

* **host sync inside jit** — ``.item()``, ``.tolist()``,
  ``.block_until_ready()``, or ``int()``/``float()``/``complex()`` on a
  traced value: a blocking device->host transfer per call (or a tracer
  error at runtime);
* **per-call jit construction** — ``jax.jit(fn)(x)`` builds and throws
  away the compiled callable every call;
* **unhashable static/container args** — calling a jitted callable with
  a list/dict/set literal retraces per call (or fails to hash);
* **dynamic shapes** — ``jnp.arange(n)``/``zeros(n)`` where ``n`` is a
  function parameter (a tracer under jit) keys a fresh compile per
  value or errors outright.

Jitted functions are found by decorator (``@jax.jit``, ``@jit``,
``@partial(jax.jit, ...)``) and by call-site registration: any name
passed (however deeply: ``jax.jit(shard_map(self._f_impl, ...))``) into
a ``jax.jit(...)`` call is looked up among the module's function defs.

Pallas kernel bodies are jitted code too — stricter, even: Mosaic
compiles them, so a host sync or dynamic shape is a guaranteed error,
not just a performance bug.  The kernel handed to ``pl.pallas_call``
(directly, or via the factory idiom ``self._k = make_kernel(...)`` →
``pallas_call(ctx._k, ...)``) is resolved against the module's function
defs and walked with the same checks; a factory match walks the factory
whole, nested kernel def included.
"""

from __future__ import annotations

import ast
from typing import Iterator

from electionguard_tpu.analysis import astutil, core

RULE = "jit-hygiene"

_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_HOST_CASTS = {"int", "float", "complex"}
_SHAPE_BUILDERS = {"arange", "zeros", "ones", "empty", "full"}
#: static accessors whose result is a python int even under jit
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}


def _is_jit(node: ast.expr) -> bool:
    """``jit`` / ``jax.jit`` (as a name or the function of a call)."""
    return ((isinstance(node, ast.Name) and node.id == "jit")
            or (isinstance(node, ast.Attribute) and node.attr == "jit"))


def _is_pallas_call(node: ast.expr) -> bool:
    """``pallas_call`` / ``pl.pallas_call`` — its first argument is a
    kernel body that must obey the jitted-code rules."""
    return ((isinstance(node, ast.Name) and node.id == "pallas_call")
            or (isinstance(node, ast.Attribute)
                and node.attr == "pallas_call"))


def _jit_decorated(fn: ast.FunctionDef) -> bool:
    for d in fn.decorator_list:
        if _is_jit(d):
            return True
        if isinstance(d, ast.Call):
            if _is_jit(d.func):
                return True
            if astutil.call_name(d) == "partial" and any(
                    _is_jit(a) for a in d.args):
                return True
    return False


def _leaf_names(node: ast.expr) -> Iterator[str]:
    """Every Name id / Attribute attr inside an expression — the
    candidate function references handed to ``jax.jit``."""
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            yield n.id
        elif isinstance(n, ast.Attribute):
            yield n.attr


def _is_static_value(node: ast.expr) -> bool:
    """Values that are python ints under jit: literals, ``len(...)``,
    ``x.shape[...]`` / ``x.ndim`` chains."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call) and astutil.call_name(node) == "len":
        return True
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return True
    return False


def _check_jitted_body(fn: ast.FunctionDef, rel: str
                       ) -> Iterator[core.Finding]:
    params = set(astutil.param_names(fn))
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _HOST_SYNC_METHODS:
            yield core.Finding(
                RULE, rel, node.lineno,
                f".{f.attr}() inside jitted code forces a device->host "
                f"sync (or fails on a tracer)")
        elif (isinstance(f, ast.Name) and f.id in _HOST_CASTS
              and len(node.args) == 1
              and not _is_static_value(node.args[0])):
            yield core.Finding(
                RULE, rel, node.lineno,
                f"{f.id}() on a traced value inside jitted code is a "
                f"host sync; keep it an array (or hoist to a static "
                f"arg)")
        elif (isinstance(f, ast.Attribute) and f.attr in _SHAPE_BUILDERS
              and node.args
              and isinstance(node.args[0], ast.Name)
              and node.args[0].id in params):
            yield core.Finding(
                RULE, rel, node.lineno,
                f"jnp.{f.attr}({node.args[0].id}) sizes an array from a "
                f"traced parameter: dynamic shapes defeat the "
                f"compile-once guarantee")


@core.register(RULE, doc="host syncs, per-call jit construction, "
                         "container args, and dynamic shapes in jitted "
                         "code")
def run(project: core.Project) -> Iterator[core.Finding]:
    for src in project.files():
        fns = list(astutil.walk_functions(src.tree))
        by_name: dict[str, list[ast.FunctionDef]] = {}
        for fn in fns:
            by_name.setdefault(fn.name, []).append(fn)

        jitted: set[str] = set()          # function names
        jitted_callables: set[str] = set()  # names bound to jax.jit(...)
        # one-level indirection: mapped = shard_map(kernel, ...) or
        # self._k = make_kernel(...); jax.jit(mapped) /
        # pallas_call(ctx._k, ...) must still mark the def as jitted
        indirect: dict[str, set[str]] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                leaves = set(_leaf_names(node.value))
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        indirect[t.id] = leaves
                    else:
                        a = astutil.self_attr(t)
                        if a:
                            indirect[a] = leaves
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and _is_jit(node.func):
                args = node.args
            elif (isinstance(node, ast.Call)
                  and _is_pallas_call(node.func)):
                args = node.args[:1]      # the kernel body argument
            else:
                args = ()
            for arg in args:
                leaves = set(_leaf_names(arg))
                for n in list(leaves):
                    leaves |= indirect.get(n, set())
                jitted.update(n for n in leaves if n in by_name)
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and _is_jit(node.value.func):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted_callables.add(t.id)
                    else:
                        a = astutil.self_attr(t)
                        if a:
                            jitted_callables.add(a)

        # per-call construction: jax.jit(fn)(x) builds + discards the
        # compiled callable every call
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Call)
                    and _is_jit(node.func.func)):
                yield core.Finding(
                    RULE, src.rel, node.lineno,
                    "jax.jit(fn)(...) constructs and discards the "
                    "compiled callable per call; bind it once at "
                    "construction time")
            # container literal handed to a known-jitted callable:
            # retraces per call (unhashable if static)
            elif isinstance(node, ast.Call):
                name = astutil.call_name(node)
                if name in jitted_callables and any(
                        isinstance(a, (ast.List, ast.Dict, ast.Set))
                        for a in node.args):
                    yield core.Finding(
                        RULE, src.rel, node.lineno,
                        f"list/dict/set literal passed to jitted "
                        f"callable {name!r}: container args retrace "
                        f"per call (and can't hash as statics)")

        seen: set[int] = set()
        for fn in fns:
            if fn.name in jitted or _jit_decorated(fn):
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                yield from _check_jitted_body(fn, src.rel)

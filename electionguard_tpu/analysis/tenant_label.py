"""tenant-label: serving-plane metric series must carry the election label.

Every counter/histogram registered in the multi-tenant planes (serve,
fabric, mixfed, verify) must pass ``election_labels(...)`` — directly, or
via a local variable assigned from it in the same function — so the
series splits per election on a shared fleet.  An unlabeled series
silently merges every tenant's traffic into one line: per-tenant SLOs
read garbage, the noisy-neighbor join has nothing to attribute, and the
cross-tenant blindness only shows up during the first real incident.

Gauges are exempt: the existing gauge series are process-scoped facts
(queue depth, compile counts, audit lag) that the collector already
namespaces with ``proc=``; counters and histograms are the event/latency
series per-tenant SLOs are computed from.
"""

from __future__ import annotations

import ast
from typing import Iterator

from electionguard_tpu.analysis import core

#: subpackages whose metric series MUST be election-labeled
TENANT_DIRS = ("serve", "fabric", "mixfed", "verify")
#: registry factory method names that create per-tenant series
_FACTORIES = ("counter", "histogram")

RULE = "tenant-label"


def _is_election_labels_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    return (isinstance(fn, ast.Name) and fn.id == "election_labels") or \
        (isinstance(fn, ast.Attribute) and fn.attr == "election_labels")


def _labeled_names(scope: ast.AST) -> set[str]:
    """Names assigned from ``election_labels(...)`` anywhere in the
    enclosing function scope (the ``labels = election_labels()`` idiom)."""
    names: set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) \
                and _is_election_labels_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


def _carries_labels(call: ast.Call, labeled: set[str]) -> bool:
    args = list(call.args) + [kw.value for kw in call.keywords]
    for a in args:
        if _is_election_labels_call(a):
            return True
        if isinstance(a, ast.Name) and a.id in labeled:
            return True
    return False


@core.register(RULE, doc="metric series in serve/fabric/mixfed/verify "
                         "missing election_labels() (cross-tenant blind "
                         "spot on a shared fleet)")
def run(project: core.Project) -> Iterator[core.Finding]:
    for f in project.files():
        parts = project.package_rel_parts(f)
        if not parts or parts[0] not in TENANT_DIRS:
            continue
        # function scopes first, so variable-indirection resolves; the
        # module body is its own scope for module-level registrations
        scopes = [n for n in ast.walk(f.tree)
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))]
        scopes.append(f.tree)
        seen: set[int] = set()
        for scope in scopes:
            labeled = _labeled_names(scope)
            walker = (ast.walk(scope) if not isinstance(scope, ast.Module)
                      else iter(ast.iter_child_nodes(scope)))
            for node in walker:
                for call in ast.walk(node):
                    if not (isinstance(call, ast.Call)
                            and isinstance(call.func, ast.Attribute)
                            and call.func.attr in _FACTORIES):
                        continue
                    if id(call) in seen:
                        continue
                    seen.add(id(call))
                    if _carries_labels(call, labeled):
                        continue
                    yield core.Finding(
                        RULE, f.rel, call.lineno,
                        f"registry.{call.func.attr}() without "
                        f"election_labels(): this series merges every "
                        f"tenant's traffic on a shared fleet — pass "
                        f"election_labels() (or a local assigned from "
                        f"it) so per-tenant SLOs and noisy-neighbor "
                        f"attribution can split it")
    return

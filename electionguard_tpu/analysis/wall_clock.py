"""wall-clock-discipline: library code reads time through ``utils/clock``.

A direct ``time.time()`` / ``time.monotonic()`` / ``time.sleep()`` in
library code bypasses the clock seam, which makes that code path
invisible to the deterministic simulator (``electionguard_tpu/sim``):
under sim it would read the REAL clock and sleep REAL seconds, breaking
both determinism and the no-real-sleeps speed contract.  Route through
``utils.clock`` (``clock.now() / clock.monotonic() / clock.sleep()`` and
the waiting helpers) instead.

Exempt: ``utils/clock.py`` itself (the seam's one legitimate home),
``cli/`` entry points (process lifetime is outside any simulation), and
bench harnesses (``*bench*.py`` — they measure the real wall clock by
definition).  The ns/perf-counter variants are flagged too: a library
timestamp is a library timestamp regardless of unit.
"""

from __future__ import annotations

import ast
from typing import Iterator

from electionguard_tpu.analysis import core

#: subpackages that legitimately touch the real clock
EXEMPT_DIRS = ("cli",)

#: the seam itself — the only library file allowed direct access
SEAM_SUFFIX = "utils/clock.py"

#: ``time`` module members that read or consume wall time
BANNED = frozenset({"time", "monotonic", "sleep", "time_ns",
                    "monotonic_ns", "perf_counter", "perf_counter_ns"})

RULE = "wall-clock-discipline"


def _time_aliases(tree: ast.Module) -> tuple[set[str], dict[str, str]]:
    """(module aliases of ``time``, local name -> banned member) from
    the file's imports."""
    mod_aliases: set[str] = set()
    from_names: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mod_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in BANNED:
                    from_names[a.asname or a.name] = a.name
    return mod_aliases, from_names


@core.register(RULE, doc="direct time.time/monotonic/sleep in library "
                         "code (route through the utils/clock seam so "
                         "the deterministic simulator controls it)")
def run(project: core.Project) -> Iterator[core.Finding]:
    for f in project.files():
        parts = project.package_rel_parts(f)
        if parts and parts[0] in EXEMPT_DIRS:
            continue
        if f.rel.endswith(SEAM_SUFFIX):
            continue
        if "bench" in parts[-1]:
            continue
        mod_aliases, from_names = _time_aliases(f.tree)
        if not mod_aliases and not from_names:
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            member = None
            if (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in mod_aliases
                    and node.func.attr in BANNED):
                member = node.func.attr
            elif (isinstance(node.func, ast.Name)
                    and node.func.id in from_names):
                member = from_names[node.func.id]
            if member is not None:
                yield core.Finding(
                    RULE, f.rel, node.lineno,
                    f"direct time.{member}() in library code: use the "
                    f"utils/clock seam so the deterministic simulator "
                    f"can virtualize it")

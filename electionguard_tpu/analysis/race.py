"""egrace — dynamic happens-before + lockset race detection over the
deterministic sim.

The cooperative scheduler (``sim/scheduler.py``) runs exactly one task
at a time, so two accesses race iff no *explicit* happens-before edge
orders them.  Because every interleaving point is owned by the
scheduler, the HB relation here is precise — there are no accidental
real-time orderings to hide a race the way they do under a wall-clock
runtime.  Edges:

=====================  ==============================================
edge                   drawn at
=====================  ==============================================
spawn                  child's clock starts as a copy of the parent's
task finish            finisher publishes into the global seam clock
lock release→acquire   ``TrackedLock``/``TrackedCondition`` proxies
                       (release publishes the holder's clock to the
                       lock; acquire joins it)
message send→receive   inherited: sim-transport RPC handlers run
                       inline on the sender's task, so the edge is a
                       program-order edge by construction
server start→dispatch  ``SimServer.start()`` publishes the starting
                       task's clock; every dispatch to that port joins
                       it (models ``grpc.Server.start()``'s handler
                       publication — handlers and their captured state
                       are built before ``start()``)
clock-seam wait        a predicate wait that *succeeds* joins the
                       global seam clock (every task publishes into
                       it at each yield); plain sleeps and timeouts
                       create no edge
=====================  ==============================================

Two detectors share the event stream:

* **FastTrack-style HB** — per-variable last-write epoch + read map;
  fires only on accesses genuinely unordered in *this* schedule.
* **Eraser-style lockset** — candidate-lockset intersection with a
  one-time ownership transfer (a handoff that happens-after the
  variable's whole history re-assigns the owner once).  Predictive:
  it can flag a pair that this schedule happened to order via a seam
  wait but that no common lock protects.

Races are waivable only via ``analysis/race_waivers.json`` (each entry
needs a ``note``); the file ships empty and the tier-1 gate keeps it
that way.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass, field
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
WAIVERS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "race_waivers.json")

#: monitor/instrumentation frames are skipped when attributing a site
_SKIP_FRAME_FILES = ("analysis/race.py", "analysis/race_instrument.py",
                     "sim/scheduler.py")

MAX_RACES = 50          # stop recording (not detecting) past this
_STACK_DEPTH = 4


# ---------------------------------------------------------------- reports

@dataclass
class RaceSide:
    task: str
    op: str                      # "read" | "write"
    site: str                    # repo-relative file:line
    stack: list = field(default_factory=list)
    locks: list = field(default_factory=list)
    rpc: Optional[str] = None    # rpc method the access ran under

    def to_dict(self) -> dict:
        return {"task": self.task, "op": self.op, "site": self.site,
                "stack": list(self.stack), "locks": list(self.locks),
                "rpc": self.rpc}


@dataclass
class RaceReport:
    kind: str                    # "hb" | "lockset"
    var: str                     # "Class.attr"
    pair: str                    # "w/w" | "r/w" | "w/r"
    prior: RaceSide
    current: RaceSide
    vtime: float

    def key(self) -> tuple:
        return (self.kind, self.var, self.pair,
                self.prior.site, self.current.site)

    def summary(self) -> str:
        return (f"{self.kind} {self.pair} {self.var} "
                f"{self.prior.task}@{self.prior.site} vs "
                f"{self.current.task}@{self.current.site}")

    def to_dict(self) -> dict:
        return {"kind": self.kind, "var": self.var, "pair": self.pair,
                "prior": self.prior.to_dict(),
                "current": self.current.to_dict(),
                "vtime": round(self.vtime, 6)}


# ---------------------------------------------------------------- waivers

def load_waivers(path: str = None) -> list[dict]:
    """``race_waivers.json`` entries; every entry must carry a note."""
    path = path or WAIVERS_PATH
    if not os.path.exists(path):
        return []
    with open(path) as f:
        doc = json.load(f)
    waivers = doc.get("waivers", [])
    for w in waivers:
        if not str(w.get("note", "")).strip():
            raise ValueError(
                f"race waiver for {w.get('var')!r} has no note — every "
                f"waiver needs a rationale")
        if "var" not in w:
            raise ValueError(f"race waiver missing 'var': {w!r}")
    return waivers


def waived(report: RaceReport, waivers: list[dict]) -> bool:
    for w in waivers:
        if w["var"] != report.var:
            continue
        if w.get("kind", "*") in ("*", report.kind):
            return True
    return False


# ---------------------------------------------------------------- state

class _Var:
    """Per-variable FastTrack + Eraser state."""

    __slots__ = ("name", "wtask", "wclock", "wmeta", "reads", "rmeta",
                 "state", "owner", "creator", "transferred", "cand",
                 "last", "written", "ls_reported")

    def __init__(self, name: str):
        self.name = name
        # FastTrack: last-write epoch + per-task read clocks
        self.wtask: Optional[int] = None
        self.wclock = 0
        self.wmeta: Optional[RaceSide] = None
        self.reads: dict[int, int] = {}
        self.rmeta: dict[int, RaceSide] = {}
        # Eraser: exclusive -> shared -> shared-mod, one transfer
        self.state = "virgin"
        self.owner: Optional[int] = None
        self.creator: Optional[int] = None
        self.transferred = False
        self.cand: Optional[frozenset] = None
        self.last: Optional[tuple] = None   # (locks frozenset, RaceSide)
        self.written = False
        self.ls_reported = False

    def covered_by(self, vc: dict[int, int]) -> bool:
        """Does ``vc`` happen-after every recorded access?"""
        if self.wtask is not None and vc.get(self.wtask, 0) < self.wclock:
            return False
        return all(vc.get(t, 0) >= c for t, c in self.reads.items())


class RaceMonitor:
    """Consumes scheduler + instrumentation events, produces reports.

    Attaches itself as ``sched.monitor``; the scheduler calls the
    ``on_*`` hooks at its synchronization points and the instrumented
    classes report attribute accesses through :meth:`on_access`.  The
    monitor adds no yield points and never touches the scheduler RNG,
    so a race-enabled run is bit-for-bit the same schedule as a plain
    one (asserted in tests via the trace hash).
    """

    def __init__(self, sched):
        self.sched = sched
        sched.monitor = self
        self._vc: dict[int, dict[int, int]] = {}      # task seq -> VC
        self._lock_vc: dict[int, dict[int, int]] = {}  # lock id -> VC
        self._chan: dict[object, dict[int, int]] = {}  # publication VCs
        self._global: dict[int, int] = {}             # seam clock
        self._held: dict[int, list] = {}              # task -> locks
        self._rpc: dict[int, list[str]] = {}          # task -> rpc stack
        self._vars: dict[tuple, _Var] = {}
        self._pins: dict[int, object] = {}            # keep ids stable
        self._seen: set = set()
        self.races: list[RaceReport] = []
        self.dropped = 0
        self.events = 0
        self._busy = False
        self._retired = False

    # ---------------- clocks

    def _clock(self, seq: int) -> dict[int, int]:
        vc = self._vc.get(seq)
        if vc is None:
            vc = self._vc[seq] = dict(self._global)
            vc[seq] = vc.get(seq, 0) + 1
        return vc

    @staticmethod
    def _join(into: dict[int, int], other: dict[int, int]) -> None:
        for t, c in other.items():
            if into.get(t, 0) < c:
                into[t] = c

    def _task(self):
        if self._retired:
            return None
        return self.sched.current_task()

    # ---------------- scheduler hooks

    def on_spawn(self, parent, child) -> None:
        if parent is not None:
            pvc = self._clock(parent.seq)
            cvc = dict(pvc)
            pvc[parent.seq] += 1
        else:
            cvc = dict(self._global)
        cvc[child.seq] = cvc.get(child.seq, 0) + 1
        self._vc[child.seq] = cvc

    def on_yield(self, task) -> None:
        vc = self._clock(task.seq)
        self._join(self._global, vc)
        vc[task.seq] += 1

    def on_wait_ok(self, task) -> None:
        self._join(self._clock(task.seq), self._global)

    def on_finish(self, task) -> None:
        self._join(self._global, self._clock(task.seq))

    # ---------------- lock hooks (from Tracked proxies)

    def on_acquire(self, lock) -> None:
        task = self._task()
        if task is None:
            return
        self._held.setdefault(task.seq, []).append(lock)
        lvc = self._lock_vc.get(id(lock))
        if lvc:
            self._join(self._clock(task.seq), lvc)
        self._pins[id(lock)] = lock

    def on_release(self, lock) -> None:
        task = self._task()
        if task is None:
            return
        held = self._held.get(task.seq, [])
        if lock in held:
            held.remove(lock)
        vc = self._clock(task.seq)
        self._lock_vc[id(lock)] = dict(vc)
        vc[task.seq] += 1

    # ---------------- publication channels (server start → dispatch)

    def on_publish(self, key) -> None:
        """One-way edge source: merge the current task's clock into
        channel ``key`` (e.g. a sim server starting on a port)."""
        task = self._task()
        if task is None:
            return
        vc = self._clock(task.seq)
        self._join(self._chan.setdefault(key, {}), vc)
        vc[task.seq] += 1

    def on_subscribe(self, key) -> None:
        """Edge sink: the current task happens-after every publish to
        ``key`` (e.g. dispatching an rpc to a started server)."""
        task = self._task()
        if task is None:
            return
        ch = self._chan.get(key)
        if ch:
            self._join(self._clock(task.seq), ch)

    # ---------------- rpc context (from sim transport)

    def rpc_begin(self, method: str) -> None:
        task = self._task()
        if task is not None:
            self._rpc.setdefault(task.seq, []).append(method)

    def rpc_end(self) -> None:
        task = self._task()
        if task is not None:
            stack = self._rpc.get(task.seq)
            if stack:
                stack.pop()

    # ---------------- access events (from race_instrument)

    def on_access(self, obj, cname: str, attr: str, is_write: bool) -> None:
        if self._busy or self._retired:
            return
        task = self._task()
        if task is None:
            return          # scheduler-thread pred eval, or outside sim
        self._busy = True
        try:
            self.events += 1
            self._record(obj, cname, attr, is_write, task)
        finally:
            self._busy = False

    def _record(self, obj, cname, attr, is_write, task) -> None:
        key = (id(obj), attr)
        var = self._vars.get(key)
        if var is None:
            var = self._vars[key] = _Var(f"{cname}.{attr}")
            self._pins[id(obj)] = obj
        t = task.seq
        vc = self._clock(t)
        locks = frozenset(id(k) for k in self._held.get(t, ()))
        meta = self._side(task, is_write, t)

        # --- FastTrack happens-before
        if is_write:
            if (var.wtask is not None and var.wtask != t
                    and vc.get(var.wtask, 0) < var.wclock):
                self._report("hb", var, "w/w", var.wmeta, meta)
            else:
                for rt, rc in var.reads.items():
                    if rt != t and vc.get(rt, 0) < rc:
                        self._report("hb", var, "r/w", var.rmeta[rt], meta)
                        break
            var.wtask, var.wclock, var.wmeta = t, vc[t], meta
            var.reads, var.rmeta = {}, {}
        else:
            if (var.wtask is not None and var.wtask != t
                    and vc.get(var.wtask, 0) < var.wclock):
                self._report("hb", var, "w/r", var.wmeta, meta)
            var.reads[t] = vc[t]
            var.rmeta[t] = meta

        # --- Eraser lockset (with ownership transfer: the creating
        # task hands off for free — construction precedes sharing —
        # and ONE further happens-after-all-history handoff is allowed
        # before the variable counts as shared)
        if var.state == "virgin":
            var.state, var.owner, var.creator = "exclusive", t, t
        elif var.state == "exclusive" and t != var.owner:
            if var.covered_by(vc) and (var.owner == var.creator
                                       or not var.transferred):
                if var.owner != var.creator:
                    var.transferred = True
                var.owner = t
            else:
                var.state = ("shared-mod"
                             if (is_write or var.written) else "shared")
                prev = var.last[0] if var.last else frozenset()
                var.cand = prev & locks
        elif var.state != "exclusive":
            var.cand = (var.cand if var.cand is not None
                        else locks) & locks
            if is_write:
                var.state = "shared-mod"
        if (var.state == "shared-mod" and not var.cand
                and not var.ls_reported):
            var.ls_reported = True
            prior = var.last[1] if var.last else meta
            self._report("lockset", var,
                         "w/w" if is_write else "w/r", prior, meta)
        if is_write:
            var.written = True
        var.last = (locks, meta)

    # ---------------- reporting

    def _side(self, task, is_write: bool, seq: int) -> RaceSide:
        stack = self._stack()
        rpc = self._rpc.get(seq)
        names = [getattr(k, "_name", "?")
                 for k in self._held.get(seq, ())]
        return RaceSide(
            task=task.name, op="write" if is_write else "read",
            site=stack[0] if stack else "?", stack=stack,
            locks=sorted(names), rpc=rpc[-1] if rpc else None)

    def _stack(self) -> list[str]:
        out = []
        f = sys._getframe(2)
        while f is not None and len(out) < _STACK_DEPTH:
            fn = f.f_code.co_filename
            rel = os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
            if not rel.startswith("..") and not any(
                    rel.endswith(s) for s in _SKIP_FRAME_FILES):
                out.append(f"{rel}:{f.f_lineno}:{f.f_code.co_name}")
            f = f.f_back
        return out

    def _report(self, kind: str, var: _Var, pair: str,
                prior: Optional[RaceSide], current: RaceSide) -> None:
        r = RaceReport(kind=kind, var=var.name, pair=pair,
                       prior=prior or current, current=current,
                       vtime=self.sched.now)
        if r.key() in self._seen:
            return
        self._seen.add(r.key())
        if len(self.races) >= MAX_RACES:
            self.dropped += 1
            return
        self.races.append(r)

    # ---------------- lifecycle

    def retire(self) -> None:
        """Detach: later events (e.g. from still-wrapped singleton
        locks) become no-ops."""
        self._retired = True
        if getattr(self.sched, "monitor", None) is self:
            self.sched.monitor = None

"""eglint: project-native static analysis.

The repo's trust boundaries (secrets stay in-process, all rpc traffic
flows through ``rpc_util``, device code never host-syncs, shared state
stays behind its lock, every ``EGTPU_*`` knob is documented) were
established PR by PR as *conventions*.  This package machine-checks
them: an AST pass registry (``core``), six project-specific passes, and
a ``tools/eglint.py`` CLI.  Run it with::

    python tools/eglint.py -strict

See README "Static analysis" for the pass catalog and the suppression
story (inline ``# eglint: disable=RULE`` / ``analysis/baseline.json``).
"""

from electionguard_tpu.analysis.core import (Finding,  # noqa: F401
                                             Project, Report,
                                             load_baseline, run_passes,
                                             write_baseline)

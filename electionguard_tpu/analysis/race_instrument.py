"""Attribute-access instrumentation for the dynamic race monitor.

The static lock-discipline pass exports, per class, the attributes it
inferred to be lock-guarded (``ANALYSIS_GUARDS.json``).  This module
patches exactly those classes' ``__getattribute__`` / ``__setattr__``
so every touch of a guarded attribute reports a read/write event —
carrying the held lockset and the current vector-clock epoch — to a
:class:`~electionguard_tpu.analysis.race.RaceMonitor`.  The static pass
*seeds* the dynamic monitor; the monitor then validates (a schedule
exhibits the race) or refutes (every schedule orders the accesses)
what lexical analysis could only suspect.

Locks are observed by proxy: assigning a ``threading`` Lock/RLock/
Condition to a lock-ish attribute of an instrumented class stores a
``TrackedLock`` / ``TrackedCondition`` wrapper instead, whose
acquire/release notify the monitor (release→acquire is an HB edge and
the held set feeds the Eraser lockset).  Instances created *before*
installation (module singletons) get their locks wrapped lazily on
first attribute read.

Infrastructure packages are excluded at runtime — the sim scheduler,
the analysis layer, and the fault machinery implement the watching and
must not watch themselves.  ``EGTPU_RACE_WATCH`` extends the surface:
``pkg.mod:Class=attr1+attr2;pkg.other:Cls=attr``.
"""

from __future__ import annotations

import importlib
import json
import os
import threading
from typing import Iterable, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
GUARDS_PATH = os.path.join(REPO_ROOT, "ANALYSIS_GUARDS.json")

#: the machinery implementing the sim/monitor cannot be watched by it
EXCLUDE_PREFIXES = (
    "electionguard_tpu.sim.", "electionguard_tpu.analysis.",
    "electionguard_tpu.testing.", "electionguard_tpu.utils.",
)

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))


class TrackedLock:
    """Forwarding proxy for ``threading.Lock``/``RLock`` that reports
    acquire/release to the monitor.  ``release`` notifies *before*
    releasing so the holder publishes its clock while still exclusive."""

    def __init__(self, inner, name: str, monitor):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_name", name)
        object.__setattr__(self, "_mon", monitor)

    def acquire(self, *a, **kw):
        got = self._inner.acquire(*a, **kw)
        if got:
            self._mon.on_acquire(self)
        return got

    def release(self):
        self._mon.on_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TrackedCondition:
    """Forwarding proxy for ``threading.Condition``.  The condition
    object itself is the tracked lock; ``wait`` reports the implicit
    release/reacquire pair.  (In the sim, CV waits go through the clock
    seam's explicit release/sleep/acquire, which hits the same hooks.)"""

    def __init__(self, inner, name: str, monitor):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_name", name)
        object.__setattr__(self, "_mon", monitor)

    def acquire(self, *a, **kw):
        got = self._inner.acquire(*a, **kw)
        if got:
            self._mon.on_acquire(self)
        return got

    def release(self):
        self._mon.on_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        self._mon.on_release(self)
        try:
            return self._inner.wait(timeout)
        finally:
            self._mon.on_acquire(self)

    def wait_for(self, predicate, timeout=None):
        self._mon.on_release(self)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._mon.on_acquire(self)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _wrap_lock(value, name: str, monitor):
    """Wrap a raw lock in a tracked proxy; rewrap a proxy left behind by
    a previous (retired) monitor; pass everything else through."""
    if isinstance(value, (TrackedLock, TrackedCondition)):
        if value._mon is monitor:
            return value
        value = value._inner            # previous run's wrapper: peel
    if isinstance(value, _LOCK_TYPES):
        return TrackedLock(value, name, monitor)
    if isinstance(value, threading.Condition):
        return TrackedCondition(value, name, monitor)
    return value


# ---------------------------------------------------------------- config

def load_guards(path: Optional[str] = None) -> list[dict]:
    path = path or GUARDS_PATH
    with open(path) as f:
        return json.load(f)["classes"]


def parse_watch(spec: str) -> list[dict]:
    """``pkg.mod:Class=attr1+attr2;...`` → guard entries (no lock attrs:
    extension targets are watched, their locks inferred by name)."""
    out = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        try:
            modcls, attrs = part.split("=", 1)
            module, cls = modcls.rsplit(":", 1)
        except ValueError:
            raise ValueError(
                f"bad EGTPU_RACE_WATCH entry {part!r} "
                f"(want pkg.mod:Class=attr1+attr2)") from None
        out.append({"module": module, "class": cls,
                    "lock_attrs": [],
                    "guarded": [a for a in attrs.split("+") if a]})
    return out


# ---------------------------------------------------------------- patching

class Instrumentation:
    """Handle over a set of patched classes; ``uninstall`` restores the
    original descriptors and retires the monitor."""

    def __init__(self, monitor):
        self.monitor = monitor
        self._patched: list[tuple[type, object, object]] = []
        self.classes: list[str] = []

    def add(self, cls: type, watched: Iterable[str],
            lock_attrs: Iterable[str]) -> None:
        watched = frozenset(watched)
        lock_attrs = frozenset(lock_attrs)
        monitor = self.monitor
        orig_get = cls.__getattribute__
        orig_set = cls.__setattr__
        cname = cls.__name__

        def __getattribute__(self, name):
            val = orig_get(self, name)
            if name in lock_attrs and not (
                    isinstance(val, (TrackedLock, TrackedCondition))
                    and val._mon is monitor):
                # lazy wrap: instance predates install() (singleton) or
                # carries a retired wrapper from an earlier run
                wrapped = _wrap_lock(val, f"{cname}.{name}", monitor)
                if wrapped is not val:
                    orig_set(self, name, wrapped)
                return wrapped
            if name in watched:
                monitor.on_access(self, cname, name, False)
            return val

        def __setattr__(self, name, value):
            if name in lock_attrs:
                value = _wrap_lock(value, f"{cname}.{name}", monitor)
            orig_set(self, name, value)
            if name in watched:
                monitor.on_access(self, cname, name, True)

        cls.__getattribute__ = __getattribute__
        cls.__setattr__ = __setattr__
        self._patched.append((cls, orig_get, orig_set))
        self.classes.append(f"{cls.__module__}.{cname}")

    def uninstall(self) -> None:
        for cls, orig_get, orig_set in self._patched:
            cls.__getattribute__ = orig_get
            cls.__setattr__ = orig_set
        self._patched.clear()
        self.monitor.retire()


def install(monitor, guards: Optional[list[dict]] = None,
            watch: Optional[str] = None,
            extra: Optional[list[tuple[type, Iterable[str],
                                       Iterable[str]]]] = None
            ) -> Instrumentation:
    """Patch every non-excluded guarded class (plus ``EGTPU_RACE_WATCH``
    entries and explicit ``extra`` (cls, attrs, lock_attrs) triples)."""
    from electionguard_tpu.utils import knobs

    if guards is None:
        guards = load_guards()
    if watch is None:
        watch = knobs.get_str("EGTPU_RACE_WATCH")
    entries = [g for g in guards
               if not any(g["module"].startswith(p)
                          for p in EXCLUDE_PREFIXES)]
    entries += parse_watch(watch)

    inst = Instrumentation(monitor)
    for g in entries:
        try:
            mod = importlib.import_module(g["module"])
            cls = getattr(mod, g["class"])
        except (ImportError, AttributeError) as e:
            raise RuntimeError(
                f"race watch target {g['module']}:{g['class']} not "
                f"importable: {e}") from e
        inst.add(cls, g["guarded"], g["lock_attrs"] or _infer_locks(cls))
    for cls, attrs, lock_attrs in (extra or ()):
        inst.add(cls, attrs, lock_attrs)
    return inst


def _infer_locks(cls: type) -> list[str]:
    """Best-effort lock attrs for EGTPU_RACE_WATCH targets (no static
    inference available): any init-assigned attr with a lock-ish name."""
    import re
    pat = re.compile(r"lock|mutex|cv|cond", re.IGNORECASE)
    init = getattr(cls, "__init__", None)
    names = set()
    code = getattr(init, "__code__", None)
    if code is not None:
        names = {n for n in code.co_names if pat.search(n)}
    return sorted(names)

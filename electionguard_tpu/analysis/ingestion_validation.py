"""ingestion-validation: every proto→group-element conversion runs
behind the crypto/validate gate.

The serialize importers (``import_p``, ``import_ciphertext``, …) turn
wire bytes into ``ElementModP``/``ElementModQ`` with only a width/range
check — no subgroup membership, no identity/small-order screening.
That is fine for the terminal verifier (it re-proves everything) and
for the publisher (reading back its own artifacts), but any OTHER call
site is an ingestion boundary where an adversarial peer's forged
parameters enter arithmetic, and must sit behind
``crypto/validate.gate_*`` (ISSUE 17; the Moscow break, arxiv
1908.09170, was exactly unvalidated parameters).

Two findings:

* an importer call in a file that is NOT a registered boundary and not
  exempt — a new conversion site snuck in outside the gate's reach;
* an importer call in a registered boundary file that contains NO gate
  call — the boundary lost its gate.

The baseline for this rule must stay EMPTY: a new conversion site is
either a verifier/publisher path (add it to the exemptions WITH review)
or a trust boundary (wire the gate and register it in BOUNDARIES).
"""

from __future__ import annotations

import ast
from typing import Iterator

from electionguard_tpu.analysis import astutil, core

RULE = "ingestion-validation"

#: serialize functions that construct group elements from wire messages
IMPORTERS = frozenset({
    "import_p", "import_q", "import_ciphertext", "import_generic_proof",
    "import_disjunctive_proof", "import_constant_proof",
    "import_hashed_ciphertext", "import_schnorr", "import_guardian_record",
    "import_election_initialized", "import_encrypted_ballot",
    "import_encrypted_tally", "import_tally_result",
    "import_plaintext_tally", "import_decryption_result",
    "import_mix_proof", "import_mix_row", "_imp_p_int", "_imp_q_int",
})

#: the gate's entry points (crypto/validate.py)
GATE_CALLS = frozenset({"gate_elements", "gate_wire_p", "gate_wire_q",
                        "gate_fingerprint"})

#: registered ingestion boundaries: package-relative file -> boundary
#: label the file's gate calls are tagged with
BOUNDARIES = {
    "remote/keyceremony_remote.py": "keyceremony",
    "remote/decrypting_remote.py": "decrypt",
    "mixfed/server.py": "mixfed",
    "mixfed/coordinator.py": "mixfed",
    "fabric/router.py": "fabric",
    "serve/service.py": "serve",
    "verify/live/verifier.py": "live",
}

#: subtrees that re-verify (or produced) what they deserialize:
#: the terminal verifier proves every element's membership itself, the
#: publisher round-trips its own artifacts, the gate is the gate
EXEMPT_DIRS = ("publish", "verify", "sim", "testing", "analysis")
EXEMPT_FILES = ("crypto/validate.py",)


def _importer_calls(f: core.SourceFile) -> Iterator[int]:
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Call) \
                and astutil.call_name(node) in IMPORTERS:
            yield node.lineno


def _has_gate_call(f: core.SourceFile) -> bool:
    return any(isinstance(n, ast.Call)
               and astutil.call_name(n) in GATE_CALLS
               for n in ast.walk(f.tree))


@core.register(RULE, doc="proto→group-element conversion sites must "
                         "flow through the crypto/validate ingestion "
                         "gate (registered-boundary allowlist)")
def run(project: core.Project) -> Iterator[core.Finding]:
    for f in project.files():
        rel = "/".join(project.package_rel_parts(f))
        boundary = BOUNDARIES.get(rel)
        if boundary is None:
            parts = project.package_rel_parts(f)
            if rel in EXEMPT_FILES or (parts and parts[0] in EXEMPT_DIRS):
                continue
            for line in _importer_calls(f):
                yield core.Finding(
                    RULE, f.rel, line,
                    "proto→group-element conversion outside a registered "
                    "ingestion boundary: wire crypto/validate.gate_* "
                    "here and register the file in ingestion_validation."
                    "BOUNDARIES (or exempt it as a verifier path)")
            continue
        if _has_gate_call(f):
            continue
        for line in _importer_calls(f):
            yield core.Finding(
                RULE, f.rel, line,
                f"registered ingestion boundary '{boundary}' has no "
                f"crypto/validate.gate_* call left in the file — the "
                f"conversion on this line is ungated")

"""trace-coverage: every gRPC servicer method must be trace-wrapped.

The obs plane's cross-process timeline only works because EVERY server
method goes through ``obs.trace.wrap_server_method`` (it opens the
``rpc.server.*`` span and adopts the caller's trace context from the
request metadata).  The one blessed path is
``remote/rpc_util.generic_service``, which wraps each method before
building its ``unary_unary_rpc_method_handler``; a service registered
any other way ships an untraced RPC surface that silently breaks rpc
client/server pairing in every flight report.

Three shapes are flagged:

* a ``unary_unary_rpc_method_handler(...)`` whose behavior is not a
  ``wrap_server_method(...)`` result (directly or via a local name);
* an ``add_generic_rpc_handlers(...)`` registration whose handlers are
  built by something other than ``generic_service(...)`` or a
  collector-style ``.service()`` factory;
* any ``method_handlers_generic_handler`` call outside
  ``remote/rpc_util.py`` itself (hand-rolling the handler map bypasses
  the wrap entirely).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from electionguard_tpu.analysis import astutil, core

RULE = "trace-coverage"

#: handler factories that wrap every method via wrap_server_method
_BLESSED_FACTORIES = ("generic_service", "service")


def _assigned_calls(tree: ast.Module) -> dict[str, str]:
    """name -> terminal call name of the last ``name = call(...)`` at
    any nesting level (enough to resolve the one-hop local aliases the
    registration idiom uses)."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = astutil.call_name(node.value)
            if name is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = name
    return out


def _resolved(node: ast.AST, assigns: dict[str, str]) -> Optional[str]:
    """Terminal call name an expression provably evaluates to; None
    when it can't be proven (the pass stays lenient on those)."""
    if isinstance(node, ast.Call):
        return astutil.call_name(node)
    if isinstance(node, ast.Name):
        return assigns.get(node.id)
    return None


@core.register(RULE, doc="gRPC servicer method registered without "
                         "obs.trace.wrap_server_method (use "
                         "rpc_util.generic_service)")
def run(project: core.Project) -> Iterator[core.Finding]:
    for f in project.files():
        assigns = _assigned_calls(f.tree)
        in_rpc_util = f.rel.endswith("remote/rpc_util.py")
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node)
            if name == "method_handlers_generic_handler" and not in_rpc_util:
                yield core.Finding(
                    RULE, f.rel, node.lineno,
                    "hand-rolled method_handlers_generic_handler "
                    "bypasses obs.trace.wrap_server_method: register "
                    "via rpc_util.generic_service")
            elif name == "unary_unary_rpc_method_handler":
                behavior = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "behavior"), None)
                if behavior is None:
                    continue
                got = _resolved(behavior, assigns)
                if got != "wrap_server_method":
                    yield core.Finding(
                        RULE, f.rel, node.lineno,
                        "rpc method handler behavior is not a "
                        "wrap_server_method(...) result: this method "
                        "would serve untraced")
            elif name == "add_generic_rpc_handlers":
                for arg in node.args:
                    elts = arg.elts if isinstance(
                        arg, (ast.Tuple, ast.List)) else [arg]
                    for e in elts:
                        got = _resolved(e, assigns)
                        if got is not None and \
                                got not in _BLESSED_FACTORIES:
                            yield core.Finding(
                                RULE, f.rel, e.lineno,
                                f"handlers built by {got}() instead of "
                                f"rpc_util.generic_service: methods "
                                f"would serve untraced")
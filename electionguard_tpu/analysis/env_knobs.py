"""env-knob-registry: every ``EGTPU_*`` read is declared and documented.

Three checks, all against ``utils/knobs.py``:

* a read of an undeclared ``EGTPU_*`` name (``os.environ.get``/``[]``/
  ``in``, ``os.getenv``, the typed ``knobs.get_*`` getters, or the
  ``_env_float``/``_env_int`` helpers) is a finding;
* a read site whose inline literal default disagrees with the declared
  default is a finding (the registry can't drift from the code);
* the committed ``ENV_KNOBS.md`` table must equal ``render_table()`` of
  the declarations (docs can't drift from the registry).

Dynamic names are supported for declared prefixes: an f-string knob
name whose literal head is ``EGTPU_RPC_TIMEOUT_`` is covered because
declared knobs with that prefix exist.  Writes (``os.environ[...] =``,
``setdefault``, ``pop``) never count as reads; ``setdefault`` is
declaration-checked but not default-checked (workflow posture overrides
intentionally differ from the process default).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from electionguard_tpu.analysis import astutil, core
from electionguard_tpu.utils import knobs as knobs_mod

RULE = "env-knob-registry"

#: helper callables whose literal first argument is an env-knob read
_GETTERS_CHECKED = {"_env_float", "_env_int"}          # default-checked
_GETTERS_DECLARED = {"get_str", "get_int", "get_float", "get_flag"}

KNOBS_SUFFIX = "utils/knobs.py"
TABLE_NAME = "ENV_KNOBS.md"


def _is_environ(node: ast.expr) -> bool:
    """``os.environ`` (or a bare ``environ`` import)."""
    return ((isinstance(node, ast.Attribute) and node.attr == "environ")
            or (isinstance(node, ast.Name) and node.id == "environ"))


def _literal_default(node: ast.Call) -> Optional[str]:
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        return str(node.args[1].value)
    return None


def _declarations(project: core.Project) -> list[knobs_mod.Knob]:
    src = project.file(KNOBS_SUFFIX)
    if src is None:
        return []
    decls = []
    for node in ast.walk(src.tree):
        if (isinstance(node, ast.Call)
                and astutil.call_name(node) == "Knob"
                and len(node.args) >= 4):
            name = astutil.str_const(node.args[0])
            ktype = astutil.str_const(node.args[1])
            default = (node.args[2].value
                       if isinstance(node.args[2], ast.Constant) else None)
            doc = astutil.str_const(node.args[3])
            if name and ktype and doc is not None:
                decls.append(knobs_mod.Knob(name, ktype, default, doc))
    return decls


def _reads(tree: ast.AST) -> Iterator[tuple[str, int, Optional[str], bool]]:
    """Yield (name, line, literal_default_or_None, default_checked) for
    every EGTPU_* read; prefix reads yield the literal f-string head."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            # os.environ.get(NAME[, default]) / os.getenv / setdefault
            if isinstance(fn, ast.Attribute) and _is_environ(fn.value):
                if fn.attr not in ("get", "setdefault"):
                    continue   # pop etc: a write
                name_node = node.args[0] if node.args else None
                checked = fn.attr == "get"
            elif (isinstance(fn, ast.Attribute) and fn.attr == "getenv"
                  and isinstance(fn.value, ast.Name)
                  and fn.value.id == "os"):
                name_node, checked = (node.args[0] if node.args else None,
                                      True)
            else:
                cname = astutil.call_name(node)
                if cname in _GETTERS_CHECKED or cname in _GETTERS_DECLARED:
                    name_node = node.args[0] if node.args else None
                    checked = cname in _GETTERS_CHECKED
                else:
                    continue
            if name_node is None:
                continue
            lit = astutil.str_const(name_node)
            if lit is not None and lit.startswith("EGTPU_"):
                yield (lit, node.lineno, _literal_default(node), checked)
            elif isinstance(name_node, ast.JoinedStr) and name_node.values:
                head = name_node.values[0]
                if isinstance(head, ast.Constant) and str(
                        head.value).startswith("EGTPU_"):
                    yield (str(head.value) + "*", node.lineno, None, False)
        elif (isinstance(node, ast.Subscript)
              and _is_environ(node.value)
              and isinstance(node.ctx, ast.Load)):
            lit = astutil.str_const(node.slice)
            if lit is not None and lit.startswith("EGTPU_"):
                yield (lit, node.lineno, None, False)
        elif isinstance(node, ast.Compare):
            if (len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))
                    and _is_environ(node.comparators[0])):
                lit = astutil.str_const(node.left)
                if lit is not None and lit.startswith("EGTPU_"):
                    yield (lit, node.lineno, None, False)


@core.register(RULE, doc="undeclared/undocumented EGTPU_* env reads and "
                         "registry/docs drift")
def run(project: core.Project) -> Iterator[core.Finding]:
    decls = _declarations(project)
    by_name = {k.name: k for k in decls}
    names = sorted(by_name)

    for f in project.files():
        for name, line, site_default, checked in _reads(f.tree):
            if name.endswith("*"):    # declared-prefix dynamic read
                prefix = name[:-1]
                if not any(n.startswith(prefix) for n in names):
                    yield core.Finding(
                        RULE, f.rel, line,
                        f"dynamic env knob {name} matches no declared "
                        f"knob prefix in utils/knobs.py")
                continue
            k = by_name.get(name)
            if k is None:
                yield core.Finding(
                    RULE, f.rel, line,
                    f"{name} is read here but not declared in "
                    f"utils/knobs.py (type/default/doc)")
                continue
            if (checked and k.default is not None
                    and site_default is not None
                    and site_default != str(k.default)):
                yield core.Finding(
                    RULE, f.rel, line,
                    f"{name} read with default {site_default!r} but "
                    f"utils/knobs.py declares {k.default!r}")

    # docs drift: ENV_KNOBS.md must equal the rendered registry
    if decls:
        table = project.root / TABLE_NAME
        rendered = knobs_mod.render_table(decls)
        knobs_src = project.file(KNOBS_SUFFIX)
        rel = knobs_src.rel if knobs_src else KNOBS_SUFFIX
        if not table.exists():
            yield core.Finding(
                RULE, rel, 1,
                f"{TABLE_NAME} missing: run `python tools/eglint.py "
                f"--write-knobs`")
        elif table.read_text() != rendered:
            yield core.Finding(
                RULE, TABLE_NAME, 1,
                f"{TABLE_NAME} is out of sync with utils/knobs.py: "
                f"run `python tools/eglint.py --write-knobs`")

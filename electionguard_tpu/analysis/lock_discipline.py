"""lock-discipline: guarded attributes stay behind their lock.

Per class: find the lock-ish attributes (``with self._lock: ...`` where
the attr name matches lock/mutex/cv/cond), compute the *guarded set* —
``self.X`` attributes WRITTEN under such a ``with`` (assignment,
augmented assignment, item store, del, or a mutating method call like
``self._q.append``), then flag every lexically lock-free touch (read or
write) of a guarded attribute in any other method.  ``__init__`` is
exempt: construction happens before the object is shared.

This is exactly the race class the serving/obs planes are exposed to:
request threads, the device-owner worker, the SLO eval loop, and
drain/shutdown paths all share ``self`` state (serve/batcher,
serve/service, obs/collector).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from electionguard_tpu.analysis import astutil, core

RULE = "lock-discipline"

_LOCK_NAME = re.compile(r"lock|mutex|cv|cond", re.IGNORECASE)

#: method calls that mutate their receiver (``self.X.append(...)``
#: counts as a write to ``X``)
_MUTATORS = {"append", "appendleft", "extend", "insert", "add", "update",
             "remove", "discard", "pop", "popleft", "popitem", "clear",
             "setdefault", "append_drop"}

_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}


def _lock_attrs_of(cls: ast.ClassDef) -> set[str]:
    attrs = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.With):
            for item in node.items:
                a = astutil.self_attr(item.context_expr)
                if a and _LOCK_NAME.search(a):
                    attrs.add(a)
    return attrs


def _touches(method: ast.FunctionDef, lock_attrs: set[str]
             ) -> Iterator[tuple[str, int, bool, bool]]:
    """Yield (attr, line, is_write, under_lock) for every ``self.X``
    touch in ``method`` (lexical: a with-lock in the same method)."""

    def visit(node: ast.AST, under: bool) -> Iterator:
        if isinstance(node, ast.With):
            locked = under or any(
                (astutil.self_attr(i.context_expr) or "") in lock_attrs
                for i in node.items)
            for item in node.items:
                yield from visit(item.context_expr, under)
            for child in node.body:
                yield from visit(child, locked)
            return
        if isinstance(node, ast.Attribute):
            a = astutil.self_attr(node)
            if a and a not in lock_attrs:
                yield (a, node.lineno,
                       isinstance(node.ctx, (ast.Store, ast.Del)), under)
            yield from visit(node.value, under)
            return
        if isinstance(node, ast.Call):
            # self.X.mutator(...) writes X; self.X[k] = v handled via ctx
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS):
                a = astutil.self_attr(fn.value)
                if a and a not in lock_attrs:
                    yield (a, node.lineno, True, under)
        elif isinstance(node, ast.Subscript):
            a = astutil.self_attr(node.value)
            if a and a not in lock_attrs and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                yield (a, node.lineno, True, under)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            return   # nested defs run later, under their own discipline
        for child in ast.iter_child_nodes(node):
            yield from visit(child, under)

    for stmt in method.body:
        yield from visit(stmt, False)


def _class_inference(cls: ast.ClassDef) -> tuple[
        set[str], dict[str, list[tuple[str, int, bool, bool]]], set[str]]:
    """Shared inference: (lock_attrs, per-method touches, guarded set)."""
    lock_attrs = _lock_attrs_of(cls)
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    per_method = {m.name: list(_touches(m, lock_attrs)) for m in methods}
    guarded: set[str] = set()
    for touches in per_method.values():
        for attr, _line, is_write, under in touches:
            if is_write and under:
                guarded.add(attr)
    return lock_attrs, per_method, guarded


def infer_guards(project: core.Project) -> list[dict]:
    """Machine-readable per-class guard sets for the dynamic race
    monitor (``analysis/race_instrument.py``): every class with at
    least one guarded attribute, keyed by import path, sorted."""
    out = []
    for f in project.files():
        module = f.rel[:-3].replace("/", ".")
        if module.endswith(".__init__"):
            module = module[: -len(".__init__")]
        for cls in [n for n in ast.walk(f.tree)
                    if isinstance(n, ast.ClassDef)]:
            lock_attrs, _per_method, guarded = _class_inference(cls)
            if not guarded:
                continue
            out.append({"module": module, "class": cls.name,
                        "lock_attrs": sorted(lock_attrs),
                        "guarded": sorted(guarded)})
    return sorted(out, key=lambda g: (g["module"], g["class"]))


def render_guards(project: core.Project) -> str:
    """ANALYSIS_GUARDS.json content (drift-gated like ENV_KNOBS.md)."""
    import json
    doc = {"generated_by": "python tools/eglint.py --write-guards",
           "rule": RULE, "classes": infer_guards(project)}
    return json.dumps(doc, indent=2, sort_keys=False) + "\n"


@core.register(RULE, doc="attributes written under a lock in one method "
                         "but touched lock-free in another")
def run(project: core.Project) -> Iterator[core.Finding]:
    for f in project.files():
        for cls in [n for n in ast.walk(f.tree)
                    if isinstance(n, ast.ClassDef)]:
            lock_attrs, per_method, guarded = _class_inference(cls)
            if not lock_attrs:
                continue
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            for m in methods:
                if m.name in _EXEMPT_METHODS:
                    continue
                # dedupe per (attr, line): self._q.append(x) is both a
                # read of _q and a mutation of it — one finding
                merged: dict[tuple[str, int], tuple[bool, bool]] = {}
                for attr, line, is_write, under in per_method[m.name]:
                    w, u = merged.get((attr, line), (False, False))
                    merged[(attr, line)] = (w or is_write, u or under)
                for (attr, line), (is_write, under) in sorted(
                        merged.items()):
                    if attr in guarded and not under:
                        kind = "written" if is_write else "read"
                        yield core.Finding(
                            RULE, f.rel, line,
                            f"{cls.name}.{attr} is written under a lock "
                            f"elsewhere but {kind} lock-free in "
                            f"{m.name}()")

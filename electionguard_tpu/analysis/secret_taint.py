"""secret-taint: secret material must never reach a telemetry sink.

Intraprocedural dataflow.  Sources are registered three ways:

* **call-site**: any call of ``rand_q`` / ``prf_scalars`` /
  ``prf_permutation`` / ``compute_polynomial`` is secret everywhere in
  the package (fresh randomness, polynomial secrets, mix permutations);
* **attribute**, path-scoped: ``self._coefficients`` in
  ``keyceremony/trustee.py``, ``self._pinned_seed`` in
  ``mixfed/server.py``;
* **parameter name**, path-scoped: ``nonce``/``secret`` in
  ``crypto/elgamal.py``, ``seed``/``perm`` in ``mixnet/shuffle.py``, ...

Taint propagates through assignments, arithmetic, f-strings,
containers, comprehensions (a loop var over a tainted iterable is
tainted), and through ANY call that takes a tainted argument — except
the registered *declassifiers*, the one-way functions whose outputs are
the published record (``g_pow_p``, ``elgamal_encrypt``,
``make_schnorr_proof``, ... and ``len``: sizes are public).

Sinks are the telemetry plane PR 4/7 built: ``logging`` calls (mirrored
fleet-wide by ``obs.slog``), span attributes (``obs.span(...)`` dicts /
``span.set``), metric names/labels, exception messages (they cross the
rpc boundary in-band), and protobuf message construction outside the
published-record allowlist.  One careless ``log.info("%s", seed)``
would broadcast a trustee's secret to the collector; this pass makes
that a build failure.  The baseline for this rule must stay EMPTY.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from electionguard_tpu.analysis import astutil, core

RULE = "secret-taint"

#: calls whose RESULT is secret, package-wide
SOURCE_CALLS = {"rand_q", "prf_scalars", "prf_permutation",
                "compute_polynomial"}

#: path-suffix -> name-registered sources in that module
PATH_SOURCES: dict[str, dict[str, set[str]]] = {
    "keyceremony/trustee.py": {"attrs": {"_coefficients"},
                               "params": {"nonce", "seed"}},
    "mixnet/shuffle.py": {"attrs": set(), "params": {"seed", "perm"}},
    "mixfed/server.py": {"attrs": {"_pinned_seed"}, "params": {"seed"}},
    "crypto/elgamal.py": {"attrs": set(), "params": {"nonce", "secret"}},
    "crypto/hashed_elgamal.py": {"attrs": set(), "params": {"nonce"}},
}

#: one-way publicization: the output is (part of) the published record,
#: so taint stops here.  Everything else that consumes a secret returns
#: a secret.
DECLASSIFIERS = {
    "g_pow_p", "pow_p",                    # discrete exp: public keys
    "elgamal_encrypt", "hashed_elgamal_encrypt",   # ciphertexts
    "encrypt_ballots", "encrypt_ballot",   # encrypted record + audit rows
    "make_schnorr_proof", "make_chaum_pedersen",   # ZK proofs
    "commitment_product",                  # public commitment algebra
    "run_stage",                           # mix stage -> public transcript
    "len", "type", "isinstance", "bool",   # shape/size/type are public
    "range", "enumerate",
}

_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}

#: proto fields that may carry secret-derived values by design (the
#: encrypted/proof channels of the record).  Everything else tainted in
#: a ``pb.msg(...)``/``pb.X(...)`` constructor is a finding.
PROTO_ALLOWLIST = {"encrypted_coordinate", "ciphertext", "proof"}


def _sources_for(rel: str) -> dict[str, set[str]]:
    for suffix, cfg in PATH_SOURCES.items():
        if rel.endswith(suffix):
            return cfg
    return {"attrs": set(), "params": set()}


class _FnTaint:
    """Taint evaluation for one function body (intraprocedural)."""

    def __init__(self, fn: ast.FunctionDef, attrs: set[str],
                 params: set[str]):
        self.fn = fn
        self.source_attrs = set(attrs)
        self.names: set[str] = {p for p in astutil.param_names(fn)
                                if p in params}
        self.attrs: set[str] = set()    # self.X assigned from taint here

    # -- expression taint ------------------------------------------------
    def tainted(self, node: Optional[ast.expr],
                extra: frozenset = frozenset()) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.names or node.id in extra
        if isinstance(node, ast.Attribute):
            a = astutil.self_attr(node)
            if a is not None:
                return a in self.source_attrs or a in self.attrs
            return self.tainted(node.value, extra)
        if isinstance(node, ast.Call):
            name = astutil.call_name(node)
            if name in DECLASSIFIERS:
                return False
            if name in SOURCE_CALLS:
                return True
            parts = ([node.func.value] if isinstance(node.func,
                                                     ast.Attribute) else [])
            parts += list(node.args)
            parts += [kw.value for kw in node.keywords]
            return any(self.tainted(p, extra) for p in parts)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            bound = set(extra)
            for gen in node.generators:
                if self.tainted(gen.iter, frozenset(bound)):
                    for t in ast.walk(gen.target):
                        if isinstance(t, ast.Name):
                            bound.add(t.id)
            inner = frozenset(bound)
            if isinstance(node, ast.DictComp):
                return (self.tainted(node.key, inner)
                        or self.tainted(node.value, inner))
            return self.tainted(node.elt, inner)
        if isinstance(node, ast.Compare):
            return False          # a comparison result is one public bit
        if isinstance(node, ast.Lambda):
            return False
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr) and self.tainted(child, extra):
                return True
        return False

    # -- propagation -----------------------------------------------------
    def _bind(self, target: ast.expr) -> None:
        if isinstance(target, ast.Name):
            self.names.add(target.id)
        elif isinstance(target, ast.Attribute):
            a = astutil.self_attr(target)
            if a is not None:
                self.attrs.add(a)
        elif isinstance(target, ast.Subscript):
            # a tainted store into a container taints the container,
            # never the names used to index it
            self._bind(target.value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt)
        elif isinstance(target, ast.Starred):
            self._bind(target.value)

    def propagate(self) -> None:
        """Two monotone passes (taint only grows) reach a fixpoint for
        straight-line code and simple loops."""
        for _ in range(2):
            for node in ast.walk(self.fn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not self.fn:
                    continue
                if isinstance(node, ast.Assign):
                    if self.tainted(node.value):
                        for t in node.targets:
                            self._bind(t)
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    if node.value is not None and self.tainted(node.value):
                        self._bind(node.target)
                elif isinstance(node, ast.NamedExpr):
                    if self.tainted(node.value):
                        self._bind(node.target)
                elif isinstance(node, ast.For):
                    if self.tainted(node.iter):
                        self._bind(node.target)


def _is_logger_base(node: ast.expr) -> bool:
    """Heuristic: ``log.info``/``logger.x``/``logging.getLogger(..).x``."""
    if isinstance(node, ast.Name):
        return "log" in node.id.lower()
    if isinstance(node, ast.Attribute):
        return "log" in node.attr.lower()
    if isinstance(node, ast.Call):
        return astutil.call_name(node) == "getLogger"
    return False


def _is_pb_ctor(node: ast.Call) -> bool:
    """``pb.msg("X")(...)`` or ``pb.X(...)``."""
    fn = node.func
    if isinstance(fn, ast.Call) and astutil.call_name(fn) == "msg":
        return True
    return (isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name) and fn.value.id == "pb")


def _sinks(ft: _FnTaint, rel: str) -> Iterator[core.Finding]:
    for node in ast.walk(ft.fn):
        if isinstance(node, ast.Raise):
            exc = node.exc
            if isinstance(exc, ast.Call) and any(
                    ft.tainted(a) for a in
                    list(exc.args) + [k.value for k in exc.keywords]):
                yield core.Finding(
                    RULE, rel, node.lineno,
                    "secret-derived value in an exception message "
                    "(errors travel in-band over rpc and into logs)")
            continue
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        argvals = list(node.args) + [k.value for k in node.keywords]
        if isinstance(fn, ast.Attribute) and fn.attr in _LOG_METHODS \
                and _is_logger_base(fn.value):
            if any(ft.tainted(a) for a in argvals):
                yield core.Finding(
                    RULE, rel, node.lineno,
                    "secret-derived value reaches a logging call "
                    "(obs.slog mirrors logs to the fleet collector)")
        elif astutil.call_name(node) == "span":
            if any(ft.tainted(a) for a in argvals):
                yield core.Finding(
                    RULE, rel, node.lineno,
                    "secret-derived value in span attributes (spans "
                    "are exported and pushed to the collector)")
        elif (isinstance(fn, ast.Attribute) and fn.attr == "set"
              and len(node.args) == 2):
            if ft.tainted(node.args[1]):
                yield core.Finding(
                    RULE, rel, node.lineno,
                    "secret-derived value in a span attribute "
                    "(span.set exports it with the trace)")
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in ("counter", "gauge", "histogram")):
            if any(ft.tainted(a) for a in argvals):
                yield core.Finding(
                    RULE, rel, node.lineno,
                    "secret-derived value in a metric name/labels "
                    "(scraped and pushed fleet-wide)")
        elif isinstance(fn, ast.Attribute) and fn.attr == "Err":
            if any(ft.tainted(a) for a in argvals):
                yield core.Finding(
                    RULE, rel, node.lineno,
                    "secret-derived value in a Result.Err message "
                    "(errors are logged and cross process boundaries)")
        elif _is_pb_ctor(node):
            for kw in node.keywords:
                if kw.arg and kw.arg not in PROTO_ALLOWLIST \
                        and ft.tainted(kw.value):
                    yield core.Finding(
                        RULE, rel, node.lineno,
                        f"secret-derived value in proto field "
                        f"{kw.arg!r} outside the published-record "
                        f"allowlist")


@core.register(RULE, doc="dataflow from secret sources (key shares, "
                         "permutations, nonces) to telemetry sinks")
def run(project: core.Project) -> Iterator[core.Finding]:
    for f in project.files():
        cfg = _sources_for(f.rel)
        for fn in astutil.walk_functions(f.tree):
            ft = _FnTaint(fn, cfg["attrs"], cfg["params"])
            ft.propagate()
            yield from _sinks(ft, f.rel)

"""Remote decryption: coordinator + decrypting-trustee servers and proxies.

Mirrors the reference's four decryption classes (SURVEY.md §2 rows 5,7-9):

* ``DecryptionCoordinator`` — registration service + decryption driver
  (reference: RunRemoteDecryptor.java:55-373): waits for ``navailable``
  registrations (quorum ≤ navailable ≤ nguardians), computes the missing-
  guardian list from the election record, runs ``Decryption`` over proxies,
  publishes ``DecryptionResult``.
* ``RemoteDecryptingTrusteeProxy`` — coordinator-resident
  ``DecryptingTrusteeIF`` over gRPC (reference:
  RemoteDecryptingTrusteeProxy.java:30-212).  Unlike the reference, errors
  are surfaced as Result values, not silently mapped to empty lists
  (the reference's silent-degrade quirk at :66,74).
* ``DecryptingTrusteeServer`` — guardian process serving batch
  direct/compensated decryption around a ``DecryptingTrustee`` loaded from
  its ceremony state file (reference: RunRemoteDecryptingTrustee.java:28-279).
* ``RemoteDecryptorProxy`` — trustee-side registration client
  (reference: RemoteDecryptorProxy.java:15-66).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence, Union

import grpc

from electionguard_tpu.core.group import ElementModP, ElementModQ, GroupContext
from electionguard_tpu.crypto import validate
from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
from electionguard_tpu.decrypt.interface import (
    CompensatedDecryptionAndProof, DecryptingTrusteeIF,
    DirectDecryptionAndProof)
from electionguard_tpu.decrypt.trustee import DecryptingTrustee
from electionguard_tpu.keyceremony.interface import Result
from electionguard_tpu.publish import pb, serialize
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.utils import clock

log = logging.getLogger("egtpu.remote.decrypt")


# ---------------------------------------------------------------------------
# coordinator side
# ---------------------------------------------------------------------------

class RemoteDecryptingTrusteeProxy(DecryptingTrusteeIF):
    def __init__(self, group: GroupContext, guardian_id: str,
                 x_coordinate: int, public_key: ElementModP, url: str):
        self.group = group
        self._id = guardian_id
        self._x = x_coordinate
        self._public_key = public_key
        self.url = url
        self._channel = rpc_util.make_channel(url)
        self._stub = rpc_util.Stub(self._channel, "DecryptingTrusteeService")

    @property
    def id(self) -> str:
        return self._id

    @property
    def x_coordinate(self) -> int:
        return self._x

    @property
    def election_public_key(self) -> ElementModP:
        return self._public_key

    def direct_decrypt(self, texts: Sequence[ElGamalCiphertext],
                       extended_base_hash: ElementModQ
                       ) -> Union[list[DirectDecryptionAndProof], Result]:
        req = pb.msg("DirectDecryptionRequest")(
            texts=[serialize.publish_ciphertext(t) for t in texts],
            extended_base_hash=serialize.publish_q(extended_base_hash))
        try:
            resp = self._stub.call("directDecrypt", req, timeout=600.0)
        except grpc.RpcError as e:
            return Result.TransportErr(
                f"directDecrypt rpc to {self._id}: {e.code()}")
        if resp.error:
            return Result.Err(resp.error)
        # ingestion gate on the shares BEFORE they touch the combine:
        # an identity / small-order / non-subgroup share is a named
        # rejection here, never an arithmetic artifact in the tally
        try:
            validate.gate_wire_p(
                self.group,
                [(f"{self._id} share[{j}]", bytes(r.partial_decryption.value))
                 for j, r in enumerate(resp.results)],
                "decrypt")
        except validate.GateError as e:
            return Result.Err(str(e))
        return [DirectDecryptionAndProof(
            serialize.import_p(self.group, r.partial_decryption),
            serialize.import_generic_proof(self.group, r.proof))
            for r in resp.results]

    def compensated_decrypt(self, missing_guardian_id: str,
                            texts: Sequence[ElGamalCiphertext],
                            extended_base_hash: ElementModQ
                            ) -> Union[list[CompensatedDecryptionAndProof], Result]:
        req = pb.msg("CompensatedDecryptionRequest")(
            missing_guardian_id=missing_guardian_id,
            texts=[serialize.publish_ciphertext(t) for t in texts],
            extended_base_hash=serialize.publish_q(extended_base_hash))
        try:
            resp = self._stub.call("compensatedDecrypt", req, timeout=600.0)
        except grpc.RpcError as e:
            return Result.TransportErr(
                f"compensatedDecrypt rpc to {self._id}: {e.code()}")
        if resp.error:
            return Result.Err(resp.error)
        try:
            validate.gate_wire_p(
                self.group,
                [(f"{self._id} comp[{j}].{fld}",
                  bytes(getattr(r, fld).value))
                 for j, r in enumerate(resp.results)
                 for fld in ("partial_decryption",
                             "recovered_public_key_share")],
                "decrypt")
        except validate.GateError as e:
            return Result.Err(str(e))
        return [CompensatedDecryptionAndProof(
            serialize.import_p(self.group, r.partial_decryption),
            serialize.import_generic_proof(self.group, r.proof),
            serialize.import_p(self.group, r.recovered_public_key_share))
            for r in resp.results]

    def finish(self, all_ok: bool) -> Result:
        try:
            resp = self._stub.call("finish",
                                   pb.msg("FinishRequest")(all_ok=all_ok))
            return Result(resp.ok, resp.error)
        except grpc.RpcError as e:
            return Result.TransportErr(f"finish rpc to {self._id}: {e.code()}")

    def shutdown(self):
        self._channel.close()


class DecryptionCoordinator:
    """Registration server for decrypting trustees
    (reference: RunRemoteDecryptor.java:164-182,325-369)."""

    def __init__(self, group: GroupContext, navailable: int,
                 port: int = 17711):
        self.group = group
        self.navailable = navailable
        self.proxies: list[RemoteDecryptingTrusteeProxy] = []
        self._lock = threading.Lock()
        self._started = False
        self.server, self.port = rpc_util.make_server(
            port, rpc_util.MAX_REGISTRATION_MESSAGE)
        self.server.add_generic_rpc_handlers((rpc_util.generic_service(
            "DecryptingRegistrationService",
            {"registerTrustee": self._register_trustee}),))
        self.server.start()
        log.info("decryption coordinator listening on %d", self.port)

    def _register_trustee(self, request, context):
        Resp = pb.msg("RegisterDecryptingTrusteeResponse")
        with self._lock:
            gid = request.guardian_id
            # fingerprint first: a cross-group trustee must get the
            # negotiation error (+ constants), not a decode failure
            err = rpc_util.check_group_fingerprint(
                self.group, request.group_fingerprint,
                boundary="decrypt")
            if err:
                return Resp(
                    error=err,
                    constants=rpc_util.group_constants_msg(self.group))
            try:
                validate.gate_wire_p(
                    self.group,
                    [(f"{gid} public key", bytes(request.public_key.value))],
                    "decrypt")
                pubkey = serialize.import_p(self.group, request.public_key)
            except validate.GateError as e:
                return Resp(error=str(e))
            except ValueError as e:
                return Resp(error=f"bad public key: {e}")
            for p in self.proxies:
                if p.id == gid:
                    if (p.url == request.remote_url
                            and p.x_coordinate == int(request.x_coordinate)
                            and p.election_public_key == pubkey):
                        # idempotent re-registration after a lost
                        # response (retried by rpc_util.Stub.call);
                        # checked BEFORE the started guard (the last
                        # registration's lost response races the start)
                        # and only for a FULL identity match — a trustee
                        # relaunched with a different state file must
                        # not silently keep the stale proxy
                        return Resp(constants=rpc_util.group_constants_msg(
                            self.group))
                    return Resp(error=f"duplicate guardian id {gid}")
            if self._started:
                return Resp(error="decryption already started")
            if len(self.proxies) >= self.navailable:
                return Resp(error="enough guardians already registered")
            proxy = RemoteDecryptingTrusteeProxy(
                self.group, gid, int(request.x_coordinate), pubkey,
                request.remote_url)
            self.proxies.append(proxy)
            log.info("registered decrypting trustee %s x=%d url=%s",
                     gid, request.x_coordinate, request.remote_url)
            return Resp(constants=rpc_util.group_constants_msg(self.group))

    def ready(self) -> int:
        with self._lock:
            return len(self.proxies)

    def registered(self) -> list:
        """Lock-held snapshot of the registered proxies.  External
        callers must use this instead of reading ``proxies`` directly:
        registration handlers mutate the list under ``_lock`` on other
        threads (found by the egrace monitor as a lockset violation on
        DecryptionCoordinator.proxies — ready() vs the sim driver's
        lock-free read)."""
        with self._lock:
            return list(self.proxies)

    def wait_for_registrations(self, timeout: float = 300.0,
                               poll: float = 0.25) -> bool:
        deadline = clock.monotonic() + timeout
        while clock.monotonic() < deadline:
            if self.ready() == self.navailable:
                return True
            clock.sleep(poll)
        return False

    def mark_started(self):
        with self._lock:
            self._started = True

    def shutdown(self, all_ok: bool):
        with self._lock:
            proxies = list(self.proxies)
        for p in proxies:
            p.finish(all_ok)
            p.shutdown()
        self.server.stop(grace=1)


# ---------------------------------------------------------------------------
# trustee side
# ---------------------------------------------------------------------------

class RemoteDecryptorProxy:
    """Trustee-side registration client (reference: RemoteDecryptorProxy.java)."""

    def __init__(self, coordinator_url: str):
        self._channel = rpc_util.make_channel(
            coordinator_url, rpc_util.MAX_REGISTRATION_MESSAGE)
        self._stub = rpc_util.Stub(self._channel,
                                   "DecryptingRegistrationService")

    def register_trustee(self, guardian_id: str, remote_url: str,
                         x_coordinate: int, public_key: ElementModP,
                         group: Optional[GroupContext] = None):
        return self._stub.call("registerTrustee",
                               pb.msg("RegisterDecryptingTrusteeRequest")(
                                   guardian_id=guardian_id,
                                   remote_url=remote_url,
                                   x_coordinate=x_coordinate,
                                   public_key=serialize.publish_p(public_key),
                                   group_fingerprint=(group.fingerprint()
                                                      if group else b"")))

    def close(self):
        self._channel.close()


class DecryptingTrusteeServer:
    """One decryption guardian process: loads its trustee state, registers
    with its identity (id, url, x, public key), serves batch rpcs."""

    def __init__(self, group: GroupContext, trustee: DecryptingTrustee,
                 coordinator_url: str, port: int = 0,
                 host: str = "localhost"):
        self.group = group
        self.trustee = trustee
        self._all_ok: Optional[bool] = None
        self._done = threading.Event()

        self.server, self.port = rpc_util.make_server(port)
        self.url = f"{host}:{self.port}"
        self.server.add_generic_rpc_handlers((rpc_util.generic_service(
            "DecryptingTrusteeService",
            {"directDecrypt": self._direct_decrypt,
             "compensatedDecrypt": self._compensated_decrypt,
             "finish": self._finish}),))
        self.server.start()

        reg = RemoteDecryptorProxy(coordinator_url)
        try:
            resp = reg.register_trustee(
                trustee.id, self.url, trustee.x_coordinate,
                trustee.election_public_key, group)
        finally:
            reg.close()
        err = resp.error or rpc_util.check_group_constants(
            group, resp.constants)
        if err:
            self.server.stop(grace=0)
            raise RuntimeError(f"registration failed: {err}")
        log.info("decrypting trustee %s registered url=%s",
                 trustee.id, self.url)

    # ---- rpc impls (reference: RunRemoteDecryptingTrustee.java:181-257) --
    def _direct_decrypt(self, request, context):
        Resp = pb.msg("DirectDecryptionResponse")
        try:
            texts = [serialize.import_ciphertext(self.group, t)
                     for t in request.texts]
            qbar = serialize.import_q(self.group, request.extended_base_hash)
        except ValueError as e:
            return Resp(error=f"malformed request: {e}")
        res = self.trustee.direct_decrypt(texts, qbar)
        if isinstance(res, Result):
            return Resp(error=res.error)
        return Resp(results=[pb.msg("DirectDecryptionResult")(
            partial_decryption=serialize.publish_p(d.partial_decryption),
            proof=serialize.publish_generic_proof(d.proof))
            for d in res])

    def _compensated_decrypt(self, request, context):
        Resp = pb.msg("CompensatedDecryptionResponse")
        try:
            texts = [serialize.import_ciphertext(self.group, t)
                     for t in request.texts]
            qbar = serialize.import_q(self.group, request.extended_base_hash)
        except ValueError as e:
            return Resp(error=f"malformed request: {e}")
        res = self.trustee.compensated_decrypt(
            request.missing_guardian_id, texts, qbar)
        if isinstance(res, Result):
            return Resp(error=res.error)
        return Resp(results=[pb.msg("CompensatedDecryptionResult")(
            partial_decryption=serialize.publish_p(c.partial_decryption),
            proof=serialize.publish_generic_proof(c.proof),
            recovered_public_key_share=serialize.publish_p(
                c.recovered_public_key_share))
            for c in res])

    def _finish(self, request, context):
        # the reference's trustee exits the whole process here
        # (RunRemoteDecryptingTrustee.java:274-276); we signal the host
        # binary instead, which exits after wait_until_finished.
        self._all_ok = bool(request.all_ok)
        self._done.set()
        return pb.msg("BoolResponse")(ok=True)

    def wait_until_finished(self, timeout: Optional[float] = None) -> Optional[bool]:
        if not clock.wait_event(self._done, timeout):
            return None
        self.server.stop(grace=1)
        return self._all_ok

    def shutdown(self):
        self._done.set()
        self.server.stop(grace=0)

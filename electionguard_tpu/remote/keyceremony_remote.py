"""Remote key ceremony: coordinator + trustee servers and their proxies.

Mirrors the reference's four key-ceremony classes (SURVEY.md §2 rows 1-4):

* ``KeyCeremonyCoordinator`` — registration service + ceremony driver
  (reference: RunRemoteKeyCeremony.java:86-313): waits for ``n_guardians``
  registrations, assigns x-coordinates from a counter, dials each trustee
  back, runs the exchange over proxies, orders remote save, publishes
  ``ElectionInitialized``.
* ``RemoteTrusteeProxy`` — coordinator-resident ``KeyCeremonyTrusteeIF``
  over gRPC (reference: RemoteTrusteeProxy.java:28-256).
* ``KeyCeremonyTrusteeServer`` — guardian process: serves the trustee rpcs
  around an in-process ``KeyCeremonyTrustee`` delegate (reference:
  RunRemoteTrustee.java:33-361).  Guardian secrets never cross the wire
  except encrypted shares / challenged coordinates.
* ``RemoteKeyCeremonyProxy`` — trustee-side registration client
  (reference: RemoteKeyCeremonyProxy.java:16-59).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional, Union

import grpc

from electionguard_tpu.core.group import GroupContext
from electionguard_tpu.crypto import validate
from electionguard_tpu.keyceremony.exchange import (KeyCeremonyResults,
                                                    key_ceremony_exchange)
from electionguard_tpu.keyceremony.interface import (KeyCeremonyTrusteeIF,
                                                     KeyShareChallengeResponse,
                                                     PublicKeys, Result,
                                                     SecretKeyShare)
from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
from electionguard_tpu.publish import pb, serialize
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.utils import clock, errors

log = logging.getLogger("egtpu.remote.keyceremony")


# ---------------------------------------------------------------------------
# coordinator side
# ---------------------------------------------------------------------------

class RemoteTrusteeProxy(KeyCeremonyTrusteeIF):
    """Coordinator-resident client for one remote trustee."""

    def __init__(self, group: GroupContext, guardian_id: str,
                 x_coordinate: int, url: str):
        self.group = group
        self._id = guardian_id
        self._x = x_coordinate
        self.url = url
        self.reg_nonce = b""   # set by the coordinator at registration
        self._channel = rpc_util.make_channel(url)
        self._stub = rpc_util.Stub(self._channel,
                                   "RemoteKeyCeremonyTrusteeService")

    @property
    def id(self) -> str:
        return self._id

    @property
    def x_coordinate(self) -> int:
        return self._x

    def _call(self, method, request):
        try:
            return self._stub.call(method, request)
        except grpc.RpcError as e:
            # transport-level: the rpc died after its bounded retries —
            # the peer's answer is unknown (vs. an in-band rejection)
            return Result.TransportErr(
                f"rpc {method} to {self._id}: {e.code()}")

    def send_public_keys(self) -> Union[PublicKeys, Result]:
        resp = self._call("sendPublicKeys", pb.msg("PublicKeySetRequest")())
        if isinstance(resp, Result):
            return resp
        if resp.error:
            return Result.Err(resp.error)
        # ingestion gate BEFORE construction: a non-canonical or
        # non-subgroup commitment dies here with its named class, not
        # as an anonymous decode error deeper in
        gid = resp.guardian_id or self._id
        try:
            validate.gate_wire_p(
                self.group,
                [(f"{gid} commitment[{j}]", bytes(k.value))
                 for j, k in enumerate(resp.coefficient_commitments)],
                "keyceremony")
            validate.gate_wire_q(
                self.group,
                [(f"{gid} proof[{j}].{fld}", bytes(getattr(pr, fld).value))
                 for j, pr in enumerate(resp.coefficient_proofs)
                 for fld in ("challenge", "response")],
                "keyceremony")
        except validate.GateError as e:
            return Result.Err(str(e))
        commitments = tuple(serialize.import_p(self.group, k)
                            for k in resp.coefficient_commitments)
        return PublicKeys(
            resp.guardian_id, int(resp.x_coordinate),
            commitments,
            tuple(serialize.import_schnorr(self.group, p, k)
                  for p, k in zip(resp.coefficient_proofs, commitments)))

    def receive_public_keys(self, keys: PublicKeys) -> Result:
        m = pb.msg("PublicKeySet")(
            guardian_id=keys.guardian_id, x_coordinate=keys.x_coordinate,
            coefficient_commitments=[serialize.publish_p(k)
                                     for k in keys.coefficient_commitments],
            coefficient_proofs=[serialize.publish_schnorr(p)
                                for p in keys.coefficient_proofs])
        resp = self._call("receivePublicKeys", m)
        if isinstance(resp, Result):
            return resp
        return Result(resp.ok, resp.error)

    def send_secret_key_share(self, other_id: str) -> Union[SecretKeyShare, Result]:
        resp = self._call("sendSecretKeyShare",
                          pb.msg("PartialKeyBackupRequest")(
                              designated_guardian_id=other_id))
        if isinstance(resp, Result):
            return resp
        if resp.error:
            return Result.Err(resp.error)
        return SecretKeyShare(
            resp.generating_guardian_id, resp.designated_guardian_id,
            int(resp.designated_guardian_x),
            serialize.import_hashed_ciphertext(self.group,
                                               resp.encrypted_coordinate))

    def receive_secret_key_share(self, share: SecretKeyShare) -> Result:
        m = pb.msg("PartialKeyBackup")(
            generating_guardian_id=share.generating_guardian_id,
            designated_guardian_id=share.designated_guardian_id,
            designated_guardian_x=share.designated_guardian_x,
            encrypted_coordinate=serialize.publish_hashed_ciphertext(
                share.encrypted_coordinate))
        resp = self._call("receiveSecretKeyShare", m)
        if isinstance(resp, Result):
            return resp
        return Result(resp.ok, resp.error)

    def challenge_share(self, challenger_id: str) -> Union[KeyShareChallengeResponse, Result]:
        resp = self._call("challengeShare", pb.msg("PartialKeyChallenge")(
            challenger_guardian_id=challenger_id))
        if isinstance(resp, Result):
            return resp
        if resp.error:
            return Result.Err(resp.error)
        return KeyShareChallengeResponse(
            resp.generating_guardian_id, resp.designated_guardian_id,
            serialize.import_q(self.group, resp.coordinate))

    def receive_challenged_share(self, response: KeyShareChallengeResponse) -> Result:
        m = pb.msg("PartialKeyChallengeResponse")(
            generating_guardian_id=response.generating_guardian_id,
            designated_guardian_id=response.designated_guardian_id,
            coordinate=serialize.publish_q(response.coordinate))
        resp = self._call("receiveChallengedShare", m)
        if isinstance(resp, Result):
            return resp
        return Result(resp.ok, resp.error)

    def save_state(self, out_dir: str) -> Result:
        resp = self._call("saveState",
                          pb.msg("SaveStateRequest")(out_dir=out_dir))
        if isinstance(resp, Result):
            return resp
        return Result(resp.ok, resp.error)

    def finish(self, all_ok: bool) -> Result:
        resp = self._call("finish", pb.msg("FinishRequest")(all_ok=all_ok))
        if isinstance(resp, Result):
            return resp
        return Result(resp.ok, resp.error)

    def shutdown(self):
        self._channel.close()


class KeyCeremonyCoordinator:
    """The ceremony server + driver (reference: RunRemoteKeyCeremony.java)."""

    def __init__(self, group: GroupContext, n_guardians: int, quorum: int,
                 port: int = 17111):
        self.group = group
        self.n = n_guardians
        self.quorum = quorum
        self.proxies: list[RemoteTrusteeProxy] = []
        self._lock = threading.Lock()
        self._next_coordinate = 0
        self._started_ceremony = False
        self.server, self.port = rpc_util.make_server(
            port, rpc_util.MAX_REGISTRATION_MESSAGE)
        self.server.add_generic_rpc_handlers((rpc_util.generic_service(
            "RemoteKeyCeremonyService",
            {"registerTrustee": self._register_trustee}),))
        self.server.start()
        log.info("key ceremony coordinator listening on %d", self.port)

    # -- registration rpc (reference: RunRemoteKeyCeremony.java:258-276) --
    def _register_trustee(self, request, context):
        Resp = pb.msg("RegisterKeyCeremonyTrusteeResponse")
        with self._lock:
            gid = request.guardian_id
            # fingerprint first: a cross-group trustee must get the
            # negotiation error (+ constants), never a duplicate/replay
            # answer (same ordering as the decryption coordinator)
            err = rpc_util.check_group_fingerprint(
                self.group, request.group_fingerprint,
                boundary="keyceremony")
            if err:
                return Resp(
                    error=err,
                    constants=rpc_util.group_constants_msg(self.group))
            for p in self.proxies:
                if p.id == gid:
                    if (p.url == request.remote_url
                            and p.reg_nonce == bytes(
                                request.registration_nonce)):
                        # idempotent re-registration: the response to a
                        # processed registration can be lost to a
                        # transport drop and retried (rpc_util.Stub.call)
                        # — hand back the coordinate already assigned.
                        # Checked BEFORE the started guard: the lost
                        # response of the LAST registration races the
                        # ceremony start.  The per-process nonce keeps a
                        # RELAUNCHED trustee (fresh secret polynomial)
                        # from silently keeping its stale registration.
                        return Resp(guardian_id=gid,
                                    x_coordinate=p.x_coordinate,
                                    quorum=self.quorum,
                                    constants=rpc_util.group_constants_msg(
                                        self.group))
                    msg = f"duplicate guardian id {gid}"
                    errors.reject("rpc.stale_registration", msg)
                    return Resp(error=errors.named(
                        "rpc.stale_registration", msg))
            if self._started_ceremony:
                return Resp(error="ceremony already started")
            if len(self.proxies) >= self.n:
                return Resp(error="all guardians already registered")
            self._next_coordinate += 1
            x = self._next_coordinate
            proxy = RemoteTrusteeProxy(self.group, gid, x, request.remote_url)
            proxy.reg_nonce = bytes(request.registration_nonce)
            self.proxies.append(proxy)
            log.info("registered trustee %s x=%d url=%s", gid, x,
                     request.remote_url)
            return Resp(guardian_id=gid, x_coordinate=x, quorum=self.quorum,
                        constants=rpc_util.group_constants_msg(self.group))

    def ready(self) -> int:
        with self._lock:
            return len(self.proxies)

    def wait_for_registrations(self, timeout: float = 300.0,
                               poll: float = 0.25) -> bool:
        deadline = clock.monotonic() + timeout
        while clock.monotonic() < deadline:
            if self.ready() == self.n:
                return True
            clock.sleep(poll)
        return False

    def run_key_ceremony(self, trustee_out_dir: str) -> Union[KeyCeremonyResults, Result]:
        with self._lock:
            self._started_ceremony = True
            # snapshot: a late registerTrustee racing the ceremony must
            # not mutate the list we are iterating
            proxies = list(self.proxies)
        results = key_ceremony_exchange(proxies, self.group)
        if isinstance(results, Result):
            return results
        for p in proxies:
            res = p.save_state(trustee_out_dir)
            if not res.ok:
                return Result.Err(f"saveState({p.id}): {res.error}")
        return results

    def shutdown(self, all_ok: bool):
        with self._lock:
            proxies = list(self.proxies)
        for p in proxies:
            p.finish(all_ok)
            p.shutdown()
        self.server.stop(grace=1)


# ---------------------------------------------------------------------------
# trustee side
# ---------------------------------------------------------------------------

class RemoteKeyCeremonyProxy:
    """Trustee-side registration client (reference: RemoteKeyCeremonyProxy.java)."""

    def __init__(self, coordinator_url: str):
        self._channel = rpc_util.make_channel(
            coordinator_url, rpc_util.MAX_REGISTRATION_MESSAGE)
        self._stub = rpc_util.Stub(self._channel, "RemoteKeyCeremonyService")

    def register_trustee(self, guardian_id: str, remote_url: str,
                         group: Optional[GroupContext] = None,
                         nonce: bytes = b""):
        return self._stub.call("registerTrustee",
                               pb.msg("RegisterKeyCeremonyTrusteeRequest")(
                                   guardian_id=guardian_id,
                                   remote_url=remote_url,
                                   group_fingerprint=(group.fingerprint()
                                                      if group else b""),
                                   registration_nonce=nonce))

    def close(self):
        self._channel.close()


class KeyCeremonyTrusteeServer:
    """One guardian process: registers, then serves the trustee rpcs.

    ``resume_file`` enables mid-ceremony crash recovery: every mutating
    rpc checkpoints the trustee's full ceremony state (secret polynomial,
    received keys/shares) plus this server's identity (port, registration
    nonce) to the file BEFORE the response is sent.  A relaunched process
    pointed at the same file re-listens on the SAME port, re-registers
    with the SAME nonce (the coordinator's idempotent replay path hands
    back the original x-coordinate), restores the trustee, and the
    coordinator's bounded-retry rpcs (rpc_util.Stub.call) pick up where
    the dead process stopped.  The file holds the secret polynomial —
    same sensitivity as the saved decrypting-trustee state.
    """

    def __init__(self, group: GroupContext, guardian_id: str,
                 coordinator_url: str, out_dir: Optional[str] = None,
                 port: int = 0, host: str = "localhost",
                 resume_file: Optional[str] = None):
        self.group = group
        self.guardian_id = guardian_id
        self.out_dir = out_dir
        self.trustee: Optional[KeyCeremonyTrustee] = None
        self._all_ok: Optional[bool] = None
        self._done = threading.Event()
        self._ready = threading.Event()
        self._resume_file = resume_file

        resume = None
        if resume_file and os.path.exists(resume_file):
            with open(resume_file) as f:
                resume = json.load(f)
            if resume["guardian_id"] != guardian_id:
                raise RuntimeError(
                    f"resume file is for {resume['guardian_id']}, "
                    f"not {guardian_id}")
            port = int(resume["port"])  # the url the coordinator dials

        self.server, self.port = rpc_util.make_server(port)
        self.url = f"{host}:{self.port}"
        self.server.add_generic_rpc_handlers((rpc_util.generic_service(
            "RemoteKeyCeremonyTrusteeService",
            {"sendPublicKeys": self._send_public_keys,
             "receivePublicKeys": self._receive_public_keys,
             "sendSecretKeyShare": self._send_secret_key_share,
             "receiveSecretKeyShare": self._receive_secret_key_share,
             "challengeShare": self._challenge_share,
             "receiveChallengedShare": self._receive_challenged_share,
             "saveState": self._save_state,
             "finish": self._finish}),))
        self.server.start()

        # register with the coordinator; it assigns our x-coordinate.
        # The nonce identifies THIS ceremony participation: a transport-
        # level retry of a lost response replays idempotently, and a
        # resumed process re-registers with its checkpointed nonce to
        # reclaim its registration; a relaunch WITHOUT state does not.
        self._reg_nonce = (bytes.fromhex(resume["nonce"]) if resume
                           else os.urandom(16))
        # Registration rides out more than one rpc's bounded retries:
        # dying here wedges the WHOLE ceremony — the coordinator may
        # already have committed this registration (lost response) and
        # will dial back into a server whose trustee never materializes
        # (deterministic-simulation seed 108).  The nonce makes every
        # re-attempt an idempotent replay, so keep trying on a fresh
        # channel with a pause that covers a coordinator still starting.
        resp = None
        last_err: Optional[Exception] = None
        for round_no in range(4):
            if round_no:
                clock.sleep(1.5 * round_no)
            reg = RemoteKeyCeremonyProxy(coordinator_url)
            try:
                resp = reg.register_trustee(guardian_id, self.url, group,
                                            nonce=self._reg_nonce)
                break
            except grpc.RpcError as e:
                last_err = e
                log.warning("trustee %s registration attempt %d died "
                            "(%s); re-registering", guardian_id,
                            round_no + 1, e.code())
            finally:
                reg.close()
        if resp is None:
            self.server.stop(grace=0)
            raise RuntimeError(
                f"registration failed after retries: {last_err}")
        err = resp.error or rpc_util.check_group_constants(
            group, resp.constants)
        if err:
            self.server.stop(grace=0)
            raise RuntimeError(f"registration failed: {err}")
        self.x_coordinate = int(resp.x_coordinate)
        self.quorum = int(resp.quorum)
        if resume is not None:
            self.trustee = KeyCeremonyTrustee.from_ceremony_state(
                group, resume["trustee"])
            if self.trustee.x_coordinate != self.x_coordinate:
                self.server.stop(grace=0)
                raise RuntimeError(
                    f"resumed x={self.trustee.x_coordinate} but "
                    f"coordinator assigned x={self.x_coordinate}")
            log.info("trustee %s RESUMED mid-ceremony: %d key sets, %d "
                     "shares restored", guardian_id,
                     len(self.trustee.other_public_keys),
                     len(self.trustee.received_shares))
        else:
            self.trustee = KeyCeremonyTrustee(
                group, guardian_id, self.x_coordinate, self.quorum)
        self._checkpoint()
        self._ready.set()
        log.info("trustee %s registered: x=%d quorum=%d url=%s",
                 guardian_id, self.x_coordinate, self.quorum, self.url)

    def _checkpoint(self) -> None:
        """Durably persist the resume state (atomic replace + fsync) —
        called BEFORE a mutating rpc's response goes out, so an ack'd
        mutation is always recoverable (WAL discipline)."""
        if not self._resume_file or self.trustee is None:
            return
        tmp = self._resume_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"guardian_id": self.guardian_id,
                       "port": self.port,
                       "nonce": self._reg_nonce.hex(),
                       "trustee": self.trustee.ceremony_state()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._resume_file)

    def _delegate(self) -> Optional[KeyCeremonyTrustee]:
        """The server must listen BEFORE registering (the coordinator
        dials back), but the delegate is only built after the
        registration response assigns x/quorum — and on the production
        group that construction (polynomial commitments + Schnorr proofs)
        takes long enough that the coordinator's first sendPublicKeys can
        land in the gap.  Block the rpc briefly instead of racing."""
        if clock.wait_event(self._ready, timeout=60.0):
            return self.trustee
        return None

    # ---- rpc impls ---------------------------------------------------
    def _send_public_keys(self, request, context):
        trustee = self._delegate()
        if trustee is None:
            return pb.msg("PublicKeySet")(error="trustee not ready")
        keys = trustee.send_public_keys()
        if isinstance(keys, Result):
            return pb.msg("PublicKeySet")(error=keys.error)
        return pb.msg("PublicKeySet")(
            guardian_id=keys.guardian_id, x_coordinate=keys.x_coordinate,
            coefficient_commitments=[serialize.publish_p(k)
                                     for k in keys.coefficient_commitments],
            coefficient_proofs=[serialize.publish_schnorr(p)
                                for p in keys.coefficient_proofs])

    def _receive_public_keys(self, request, context):
        Resp = pb.msg("BoolResponse")
        try:
            validate.gate_wire_p(
                self.group,
                [(f"{request.guardian_id} commitment[{j}]",
                  bytes(k.value))
                 for j, k in enumerate(request.coefficient_commitments)],
                "keyceremony")
            commitments = tuple(serialize.import_p(self.group, k)
                                for k in request.coefficient_commitments)
            keys = PublicKeys(
                request.guardian_id, int(request.x_coordinate),
                commitments,
                tuple(serialize.import_schnorr(self.group, p, k)
                      for p, k in zip(request.coefficient_proofs,
                                      commitments)))
        except validate.GateError as e:
            return Resp(ok=False, error=str(e))
        except ValueError as e:
            return Resp(ok=False, error=f"malformed keys: {e}")
        trustee = self._delegate()
        if trustee is None:
            return Resp(ok=False, error="trustee not ready")
        res = trustee.receive_public_keys(keys)
        if res.ok:
            self._checkpoint()
        return Resp(ok=res.ok, error=res.error)

    def _send_secret_key_share(self, request, context):
        trustee = self._delegate()
        if trustee is None:
            return pb.msg("PartialKeyBackup")(error="trustee not ready")
        share = trustee.send_secret_key_share(
            request.designated_guardian_id)
        if isinstance(share, Result):
            return pb.msg("PartialKeyBackup")(error=share.error)
        return pb.msg("PartialKeyBackup")(
            generating_guardian_id=share.generating_guardian_id,
            designated_guardian_id=share.designated_guardian_id,
            designated_guardian_x=share.designated_guardian_x,
            encrypted_coordinate=serialize.publish_hashed_ciphertext(
                share.encrypted_coordinate))

    def _receive_secret_key_share(self, request, context):
        Resp = pb.msg("BoolResponse")
        try:
            share = SecretKeyShare(
                request.generating_guardian_id,
                request.designated_guardian_id,
                int(request.designated_guardian_x),
                serialize.import_hashed_ciphertext(
                    self.group, request.encrypted_coordinate))
        except ValueError as e:
            return Resp(ok=False, error=f"malformed share: {e}")
        trustee = self._delegate()
        if trustee is None:
            return Resp(ok=False, error="trustee not ready")
        res = trustee.receive_secret_key_share(share)
        if res.ok:
            self._checkpoint()
        return Resp(ok=res.ok, error=res.error)

    def _challenge_share(self, request, context):
        trustee = self._delegate()
        if trustee is None:
            return pb.msg("PartialKeyChallengeResponse")(
                error="trustee not ready")
        resp = trustee.challenge_share(request.challenger_guardian_id)
        if isinstance(resp, Result):
            return pb.msg("PartialKeyChallengeResponse")(error=resp.error)
        self._checkpoint()   # the reveal audit trail is durable state
        return pb.msg("PartialKeyChallengeResponse")(
            generating_guardian_id=resp.generating_guardian_id,
            designated_guardian_id=resp.designated_guardian_id,
            coordinate=serialize.publish_q(resp.coordinate))

    def _receive_challenged_share(self, request, context):
        Resp = pb.msg("BoolResponse")
        try:
            resp = KeyShareChallengeResponse(
                request.generating_guardian_id,
                request.designated_guardian_id,
                serialize.import_q(self.group, request.coordinate))
        except ValueError as e:
            return Resp(ok=False, error=f"malformed challenge response: {e}")
        trustee = self._delegate()
        if trustee is None:
            return Resp(ok=False, error="trustee not ready")
        res = trustee.receive_challenged_share(resp)
        if res.ok:
            self._checkpoint()
        return Resp(ok=res.ok, error=res.error)

    def _save_state(self, request, context):
        out = request.out_dir or self.out_dir
        if not out:
            return pb.msg("BoolResponse")(ok=False,
                                          error="no output dir configured")
        trustee = self._delegate()
        if trustee is None:
            return pb.msg("BoolResponse")(ok=False,
                                          error="trustee not ready")
        res = trustee.save_state(out)
        return pb.msg("BoolResponse")(ok=res.ok, error=res.error)

    def _finish(self, request, context):
        self._all_ok = bool(request.all_ok)
        self._done.set()
        return pb.msg("BoolResponse")(ok=True)

    # ------------------------------------------------------------------
    def wait_until_finished(self, timeout: Optional[float] = None) -> Optional[bool]:
        """Block until the coordinator calls finish (reference:
        blockUntilShutdown, RunRemoteTrustee.java:141-172)."""
        if not clock.wait_event(self._done, timeout):
            return None
        self.server.stop(grace=1)
        return self._all_ok

    def shutdown(self):
        self._done.set()
        self.server.stop(grace=0)
